"""Transformer language model family (BERT-base-shaped encoder or GPT-style
causal decoder) built from the seq op family with full SOAP strategies:
sample (n), heads/channels (h/c tensor parallelism), and sequence (s,
ring-attention context parallelism) per layer.

BASELINE.json config: "Transformer/BERT-base via linear+softmax ops, full
SOAP strategy search".  This is new model capability beyond the reference
(which predates transformers)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from flexflow_tpu.config import FFConfig
from flexflow_tpu.machine import MachineModel
from flexflow_tpu.model import FFModel
from flexflow_tpu.strategy import Strategy


@dataclasses.dataclass
class TransformerConfig:
    batch_size: int = 16
    seq_length: int = 512
    num_layers: int = 12           # BERT-base
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    vocab_size: int = 32768
    causal: bool = False           # True = GPT-style next-token LM
    # Mixture-of-Experts (EP — new SOAP axis beyond the reference):
    # num_experts > 0 replaces the dense FFN of every ``moe_every``-th
    # block with a top-k-routed MoE (ops/moe.py)
    num_experts: int = 0
    moe_every: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2
    learning_rate: float = 1e-3
    num_iterations: int = 10
    compute_dtype: str = "float32"
    # parameter storage dtype ("bfloat16" = mixed precision with f32
    # masters in the optimizer state; forwarded to FFConfig)
    param_dtype: str = "float32"
    # Pallas kernel routing policy auto|on|off (forwarded to FFConfig;
    # ops/pallas/__init__.set_policy)
    pallas: str = "auto"
    seed: int = 0
    # verification mechanisms (forwarded to FFConfig; SURVEY.md §4)
    params_init: str = "default"
    print_intermediates: bool = False
    dry_compile: bool = False
    # run telemetry (forwarded to FFConfig; obs subsystem)
    obs_dir: str = ""
    run_id: str = ""
    # sampled per-op timing + live metrics export (MFU-waterfall round)
    op_time_every: int = 0
    metrics_path: str = ""
    # execution performance (forwarded to FFConfig; round 6)
    regrid_planner: str = "on"
    prefetch_depth: int = 2
    placed_overlap: str = "on"
    # fault tolerance (forwarded to FFConfig; robustness round)
    ckpt_dir: str = ""
    ckpt_freq: int = 0
    on_divergence: str = "halt"
    max_rollbacks: int = 3
    fault_spec: str = ""
    # elastic training + async checkpointing (forwarded to FFConfig)
    elastic: bool = False
    min_devices: int = 1
    research_budget_s: float = 30.0
    # decomposed re-search (round 19, forwarded to FFConfig)
    decompose: bool = False
    block_budget_s: float = 0.0
    boundary_refine_iters: int = 0
    ckpt_async: bool = False
    # elastic re-expansion / graceful drain / step watchdog (round 9)
    max_regrows: int = 1
    regrow_probes: int = 2
    drain_budget_s: float = 60.0
    hang_factor: float = 0.0
    hang_min_s: float = 60.0
    transient_reset_steps: int = 16
    # static plan analyzer (verify/plan.py): demote degradation
    # diagnostics to warnings (old degrade-and-continue behavior)
    allow_degraded: bool = False


class TransformerLM(FFModel):
    """Token-level LM: embeddings -> N pre-norm blocks -> vocab projection
    -> per-token CE (labels = tokens shifted when causal, else identity —
    masked-LM-style denoising is a data-pipeline concern)."""

    def __init__(self, t_config: TransformerConfig = None,
                 machine: Optional[MachineModel] = None,
                 strategies: Optional[Strategy] = None):
        self.t = t_config or TransformerConfig()
        ff_cfg = FFConfig(
            batch_size=self.t.batch_size,
            learning_rate=self.t.learning_rate,
            weight_decay=0.0,
            num_iterations=self.t.num_iterations,
            compute_dtype=self.t.compute_dtype,
            param_dtype=self.t.param_dtype,
            pallas=self.t.pallas,
            seed=self.t.seed,
            params_init=self.t.params_init,
            print_intermediates=self.t.print_intermediates,
            dry_compile=self.t.dry_compile,
            obs_dir=self.t.obs_dir,
            run_id=self.t.run_id,
            op_time_every=self.t.op_time_every,
            metrics_path=self.t.metrics_path,
            regrid_planner=self.t.regrid_planner,
            prefetch_depth=self.t.prefetch_depth,
            placed_overlap=self.t.placed_overlap,
            ckpt_dir=self.t.ckpt_dir,
            ckpt_freq=self.t.ckpt_freq,
            on_divergence=self.t.on_divergence,
            max_rollbacks=self.t.max_rollbacks,
            fault_spec=self.t.fault_spec,
            elastic=self.t.elastic,
            min_devices=self.t.min_devices,
            research_budget_s=self.t.research_budget_s,
            decompose=self.t.decompose,
            block_budget_s=self.t.block_budget_s,
            boundary_refine_iters=self.t.boundary_refine_iters,
            ckpt_async=self.t.ckpt_async,
            max_regrows=self.t.max_regrows,
            regrow_probes=self.t.regrow_probes,
            drain_budget_s=self.t.drain_budget_s,
            hang_factor=self.t.hang_factor,
            hang_min_s=self.t.hang_min_s,
            transient_reset_steps=self.t.transient_reset_steps,
            allow_degraded=self.t.allow_degraded,
            strategies=strategies or Strategy(),
        )
        super().__init__(ff_cfg, machine)
        self._build()

    def _build(self):
        t = self.t
        self.tokens = self.create_input((t.batch_size, t.seq_length),
                                        "int32", "tokens")
        self.labels = self.create_input((t.batch_size, t.seq_length),
                                        "int32", "labels")
        x = self.embed("embed", self.tokens, t.vocab_size, t.d_model)
        x = self.pos_embed("pos_embed", x)
        self._moe_aux_tids = []
        for i in range(t.num_layers):
            h = self.layer_norm(f"blk{i}_ln1", x)
            h = self.attention(f"blk{i}_attn", h, t.num_heads,
                               causal=t.causal)
            x = self.add_seq(f"blk{i}_res1", x, h)
            h = self.layer_norm(f"blk{i}_ln2", x)
            if t.num_experts > 0 and i % t.moe_every == 0:
                h = self.moe(f"blk{i}_moe", h, t.num_experts, t.d_ff,
                             t.moe_top_k, t.moe_capacity_factor)
                self._moe_aux_tids.append(self.layers[-1].aux.tid)
            else:
                h = self.seq_linear(f"blk{i}_ff1", h, t.d_ff)
                h = self._gelu(f"blk{i}_gelu", h)
                h = self.seq_linear(f"blk{i}_ff2", h, t.d_model)
            x = self.add_seq(f"blk{i}_res2", x, h)
        x = self.layer_norm("final_ln", x)
        logits = self.seq_linear("lm_head", x, t.vocab_size)
        self.softmax_seq("softmax", logits, self.labels)
        self.loss_op = self.layers[-1]

    def _gelu(self, name, x):
        from flexflow_tpu.ops.seq_common import GeluSeq

        return self._add(GeluSeq(name, self._pc(name, 2), x))

    # ------------------------------------------------------------------

    def loss_fn(self, params, state, tokens, labels, train: bool = True):
        import jax.numpy as jnp

        if self.t.causal:
            # next-token objective: position i predicts labels[i+1]; the
            # final position has no target (-1 = ignore, masked in
            # SoftmaxDP.loss).  Without this shift a causal model would
            # train on the degenerate copy task labels[i] = tokens[i].
            labels = jnp.concatenate(
                [labels[:, 1:],
                 jnp.full((labels.shape[0], 1), -1, labels.dtype)], axis=1)
        inputs = {self.tokens.tid: tokens, self.labels.tid: labels}
        values, new_state = self.apply(params, state, inputs, train)
        op = self.loss_op
        total = op.loss(values[op.output.tid], values[op.labels_tensor.tid])
        n_targets = self.t.batch_size * (self.t.seq_length - 1
                                         if self.t.causal
                                         else self.t.seq_length)
        loss = total / n_targets
        if train:  # aux balance term is a training regularizer only;
            # eval loss stays plain CE (comparable across configs)
            for tid in getattr(self, "_moe_aux_tids", ()):
                loss = loss + self.t.moe_aux_weight * values[tid]
        return loss, new_state

    def make_train_step(self):
        return self.make_sgd_step(self.t.learning_rate)

    def init_opt_state(self, params):
        # plain SGD carries no momentum buffers; mixed-precision mode
        # still needs the float32 masters (None in float32 mode)
        return self.master_opt_state(params)


def build_bert_base(machine=None, strategies=None,
                    **overrides) -> TransformerLM:
    cfg = TransformerConfig(**overrides)
    return TransformerLM(cfg, machine, strategies)


def build_gpt_style(machine=None, strategies=None,
                    **overrides) -> TransformerLM:
    overrides.setdefault("causal", True)
    cfg = TransformerConfig(**overrides)
    return TransformerLM(cfg, machine, strategies)
