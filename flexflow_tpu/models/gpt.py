"""Scalable GPT-style shadow graphs for strategy search (round 19).

Size presets parameterize the existing :class:`TransformerLM` builder up
to 1B+ parameters — hundreds-to-thousands of ops that are *searched*
(priced by the native simulator on a virtual mesh) but never trained.
The decomposed search in ``sim/search.py`` partitions these graphs by
the ``blk{i}_*`` layer-name prefixes the builder already emits.

Presets (param counts from :func:`gpt_param_count`, embeddings + lm_head
included, f32):

    0.1b       12 x  768, ff  3072, vocab 32768  -> ~0.14 B params
    0.4b       24 x 1024, ff  4096, vocab 32768  -> ~0.37 B params
    1.3b       24 x 2048, ff  8192, vocab 32768  -> ~1.34 B params
    1.3b-deep  96 x 1024, ff  4096, vocab 32768  -> ~1.28 B params

``1.3b`` is the acceptance-criteria row of SEARCH_r01.json; ``1.3b-deep``
is the op-count stress shape (~775 ops at depth 96).
"""

from __future__ import annotations

from typing import Dict

from flexflow_tpu.models.transformer import (TransformerConfig,
                                             TransformerLM)

# name -> TransformerConfig field overrides (always causal; batch/seq
# chosen so the DP baseline still fits one 16 GB chip per shard_hbm_bytes)
GPT_SIZES: Dict[str, dict] = {
    "0.1b": dict(num_layers=12, d_model=768, num_heads=12, d_ff=3072,
                 vocab_size=32768, seq_length=512, batch_size=16),
    "0.4b": dict(num_layers=24, d_model=1024, num_heads=16, d_ff=4096,
                 vocab_size=32768, seq_length=1024, batch_size=16),
    # the 1B+ rows run the small per-step token budget (batch 16 x seq
    # 512) where DP's whole-replica gradient sync dominates the step —
    # the regime the paper's per-op search targets (at 16k+ tokens/step
    # activation collectives rival the sync and DP is near-optimal;
    # SEARCH_r01.json's 0.4b row shows that thinner-win regime)
    "1.3b": dict(num_layers=24, d_model=2048, num_heads=16, d_ff=8192,
                 vocab_size=32768, seq_length=512, batch_size=16),
    # seq 256 at depth 96: the activation stack is 96 layers deep, and
    # the plan gate vets the full training peak per device — longer
    # sequences push searched (partially replicated) plans past 16 GB
    "1.3b-deep": dict(num_layers=96, d_model=1024, num_heads=16, d_ff=4096,
                      vocab_size=32768, seq_length=256, batch_size=16),
}


def gpt_config(size: str, **overrides) -> TransformerConfig:
    """TransformerConfig for a named preset; overrides win (e.g.
    ``num_experts=8`` turns the dense FFN stack into MoE)."""
    if size not in GPT_SIZES:
        raise KeyError(
            f"unknown GPT size {size!r}; have {sorted(GPT_SIZES)}")
    kw = dict(GPT_SIZES[size])
    kw.setdefault("causal", True)
    kw.update(overrides)
    return TransformerConfig(**kw)


def build_gpt(size: str, machine=None, strategies=None,
              **overrides) -> TransformerLM:
    """Build the shadow graph for a preset (search-only: callers price it
    on a virtual machine; nothing here allocates device arrays)."""
    return TransformerLM(gpt_config(size, **overrides), machine, strategies)


def gpt_param_count(cfg: TransformerConfig) -> int:
    """Analytic parameter count (matches the op builders: fused 4d^2 QKV+O
    attention, 2-matmul FFN with biases, 2 LN gains/biases per block)."""
    d, ff, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    per_block = 4 * d * d + 4 * d          # attention (QKV + out proj)
    per_block += 2 * 2 * d                 # ln1 + ln2
    if cfg.num_experts > 0:
        moe = cfg.num_experts * (d * ff + ff + ff * d + d) + d * cfg.num_experts
        dense = d * ff + ff + ff * d + d
        n_moe = len([i for i in range(cfg.num_layers)
                     if i % cfg.moe_every == 0])
        total_blocks = (cfg.num_layers - n_moe) * (per_block + dense) \
            + n_moe * (per_block + moe)
    else:
        per_block += d * ff + ff + ff * d + d
        total_blocks = cfg.num_layers * per_block
    embed = v * d + cfg.seq_length * d     # token + learned positional
    head = d * v + v                       # lm_head (untied)
    final_ln = 2 * d
    return embed + total_blocks + final_ln + head
