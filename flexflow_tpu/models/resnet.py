"""ResNet-101 — parity with the reference's USE_RESNET model (cnn.cc:239-260,
BottleneckBlock inception.h:122-132).

The reference's BottleneckBlock has its batch-norms commented out and NO
residual add (the framework has no elementwise op), so its "ResNet-101" is a
plain bottleneck-conv stack.  ``residual=False`` (default) reproduces that
topology exactly; ``residual=True`` builds a true pre-activation-free
ResNet-101 with identity/projection shortcuts via the Add op extension."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.ops.pool import POOL_AVG


def bottleneck_block(ff: FFModel, p: str, input: Tensor, out_channels: int,
                     bn_channels: int, stride: int,
                     residual: bool = False) -> Tensor:
    t = ff.conv2d(f"{p}_conv1", input, bn_channels, 1, 1, 1, 1, 0, 0,
                  relu=True)
    t = ff.conv2d(f"{p}_conv2", t, bn_channels, 3, 3, stride, stride, 1, 1,
                  relu=True)
    t = ff.conv2d(f"{p}_conv3", t, out_channels, 1, 1, 1, 1, 0, 0,
                  relu=not residual)
    if residual:
        if input.shape != t.shape:
            shortcut = ff.conv2d(f"{p}_proj", input, out_channels, 1, 1,
                                 stride, stride, 0, 0, relu=False)
        else:
            shortcut = input
        t = ff.add(f"{p}_add", t, shortcut, relu=True)
    return t


def add_resnet101_layers(ff: FFModel, image: Tensor,
                         residual: bool = False) -> Tensor:
    t = ff.conv2d("conv1", image, 64, 7, 7, 2, 2, 3, 3, relu=True)
    t = ff.pool2d("pool1", t, 3, 3, 2, 2, 1, 1)
    for i in range(3):
        t = bottleneck_block(ff, f"res2_{i}", t, 256, 64, 1, residual)
    for i in range(4):
        t = bottleneck_block(ff, f"res3_{i}", t, 512, 128,
                             2 if i == 0 else 1, residual)
    for i in range(23):
        t = bottleneck_block(ff, f"res4_{i}", t, 1024, 256,
                             2 if i == 0 else 1, residual)
    for i in range(3):
        t = bottleneck_block(ff, f"res5_{i}", t, 2048, 512,
                             2 if i == 0 else 1, residual)
    t = ff.pool2d("pool2", t, 7, 7, 1, 1, 0, 0, pool_type=POOL_AVG,
                  relu=False)
    t = ff.flat("flat", t)
    t = ff.linear("linear1", t, 1000, relu=False)
    return ff.softmax("softmax", t)


def build_resnet101(config: FFConfig = None, machine=None,
                    residual: bool = False) -> FFModel:
    ff = FFModel(config, machine)
    cfg = ff.config
    image = ff.create_input(
        (cfg.batch_size, cfg.input_height, cfg.input_width, 3), name="image")
    add_resnet101_layers(ff, image, residual)
    return ff
