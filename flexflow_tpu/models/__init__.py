"""Model zoo (reference §2.8): AlexNet, VGG-16, Inception-v3, ResNet-101,
DenseNet-121, NMT seq2seq — built through the FFModel layer API so every
layer picks up its strategy entry."""

from flexflow_tpu.models.alexnet import add_alexnet_layers, build_alexnet
from flexflow_tpu.models.vgg import add_vgg16_layers, build_vgg16
from flexflow_tpu.models.inception import (add_inception_v3_layers,
                                           build_inception_v3)
from flexflow_tpu.models.resnet import add_resnet101_layers, build_resnet101
from flexflow_tpu.models.densenet import (add_densenet121_layers,
                                          build_densenet121)
from flexflow_tpu.models.gpt import (GPT_SIZES, build_gpt, gpt_config,
                                     gpt_param_count)

__all__ = [
    "add_alexnet_layers", "build_alexnet",
    "add_vgg16_layers", "build_vgg16",
    "add_inception_v3_layers", "build_inception_v3",
    "add_resnet101_layers", "build_resnet101",
    "add_densenet121_layers", "build_densenet121",
    "GPT_SIZES", "build_gpt", "gpt_config", "gpt_param_count",
]
