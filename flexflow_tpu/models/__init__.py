"""Model zoo (reference §2.8): AlexNet, VGG-16, Inception-v3, ResNet-101,
DenseNet-121, NMT seq2seq — built through the FFModel layer API so every
layer picks up its strategy entry."""

from flexflow_tpu.models.alexnet import add_alexnet_layers, build_alexnet

__all__ = ["add_alexnet_layers", "build_alexnet"]
