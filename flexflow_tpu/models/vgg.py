"""VGG-16 — layer parity with the reference's USE_VGG model (cnn.cc:164-188;
legacy API add_conv_layer defaults relu=true)."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel, Tensor


def add_vgg16_layers(ff: FFModel, image: Tensor) -> Tensor:
    t = image
    plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
    li = 0
    for bi, (ch, reps) in enumerate(plan):
        for _ in range(reps):
            li += 1
            t = ff.conv2d(f"conv{li}", t, ch, 3, 3, 1, 1, 1, 1, relu=True)
        t = ff.pool2d(f"pool{bi + 1}", t, 2, 2, 2, 2, 0, 0)
    t = ff.flat("flat", t)
    t = ff.linear("linear1", t, 4096)
    t = ff.linear("linear2", t, 4096)
    t = ff.linear("linear3", t, 1000, relu=False)
    return ff.softmax("softmax", t)


def build_vgg16(config: FFConfig = None, machine=None) -> FFModel:
    ff = FFModel(config, machine)
    cfg = ff.config
    image = ff.create_input(
        (cfg.batch_size, cfg.input_height, cfg.input_width, 3), name="image")
    add_vgg16_layers(ff, image)
    return ff
