"""DenseNet-121 — parity with the reference's USE_DENSENET model
(cnn.cc:217-236; DenseBlock/Transition inception.h:100-120)."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.ops.pool import POOL_AVG


def dense_block(ff: FFModel, p: str, input: Tensor, num_layers: int,
                growth_rate: int) -> Tensor:
    last = input
    for i in range(num_layers):
        t = ff.batch_norm(f"{p}_l{i}_bn1", last, relu=True)
        t = ff.conv2d(f"{p}_l{i}_conv1", t, 4 * growth_rate, 1, 1, 1, 1,
                      0, 0, relu=False)
        t = ff.batch_norm(f"{p}_l{i}_bn2", t, relu=True)
        t = ff.conv2d(f"{p}_l{i}_conv2", t, growth_rate, 3, 3, 1, 1, 1, 1,
                      relu=False)
        last = ff.concat(f"{p}_l{i}_concat", [last, t])
    return last


def transition(ff: FFModel, p: str, input: Tensor, output_size: int) -> Tensor:
    t = ff.conv2d(f"{p}_conv", input, output_size, 1, 1, 1, 1, 0, 0,
                  relu=True)
    return ff.pool2d(f"{p}_pool", t, 2, 2, 2, 2, 0, 0, pool_type=POOL_AVG,
                     relu=False)


def add_densenet121_layers(ff: FFModel, image: Tensor) -> Tensor:
    t = ff.conv2d("conv1", image, 64, 7, 7, 2, 2, 3, 3, relu=False)
    t = ff.batch_norm("bn1", t, relu=True)
    t = ff.pool2d("pool1", t, 3, 3, 2, 2, 1, 1)
    num_features = 64
    t = dense_block(ff, "dense1", t, 6, 32)
    num_features = (num_features + 32 * 6) // 2
    t = transition(ff, "trans1", t, num_features)
    t = dense_block(ff, "dense2", t, 12, 32)
    num_features = (num_features + 32 * 12) // 2
    t = transition(ff, "trans2", t, num_features)
    t = dense_block(ff, "dense3", t, 24, 32)
    num_features = (num_features + 32 * 24) // 2
    t = transition(ff, "trans3", t, num_features)
    t = dense_block(ff, "dense4", t, 16, 32)
    t = ff.pool2d("pool2", t, 7, 7, 1, 1, 0, 0, pool_type=POOL_AVG,
                  relu=False)
    t = ff.flat("flat", t)
    t = ff.linear("linear1", t, 1000, relu=False)
    return ff.softmax("softmax", t)


def build_densenet121(config: FFConfig = None, machine=None) -> FFModel:
    ff = FFModel(config, machine)
    cfg = ff.config
    image = ff.create_input(
        (cfg.batch_size, cfg.input_height, cfg.input_width, 3), name="image")
    add_densenet121_layers(ff, image)
    return ff
