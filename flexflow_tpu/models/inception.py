"""Inception-v3 — block/layer parity with the reference (inception.h:18-98
block functions; driver cnn.cc:191-214).  Standard 299x299 input (the
reference's default 224 makes its own final 8x8 avg-pool impossible — its
inception path was built for 299)."""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.ops.pool import POOL_AVG


def _conv(ff, name, t, ch, kh, kw, sh=1, sw=1, ph=0, pw=0, relu=True):
    return ff.conv2d(name, t, ch, kh, kw, sh, sw, ph, pw, relu=relu)


def inception_a(ff: FFModel, p: str, input: Tensor,
                pool_features: int) -> Tensor:
    t1 = _conv(ff, f"{p}_b1_1x1", input, 64, 1, 1)
    t2 = _conv(ff, f"{p}_b2_1x1", input, 48, 1, 1)
    t2 = _conv(ff, f"{p}_b2_5x5", t2, 64, 5, 5, 1, 1, 2, 2)
    t3 = _conv(ff, f"{p}_b3_1x1", input, 64, 1, 1)
    t3 = _conv(ff, f"{p}_b3_3x3a", t3, 96, 3, 3, 1, 1, 1, 1)
    t3 = _conv(ff, f"{p}_b3_3x3b", t3, 96, 3, 3, 1, 1, 1, 1)
    t4 = ff.pool2d(f"{p}_b4_pool", input, 3, 3, 1, 1, 1, 1,
                   pool_type=POOL_AVG)
    t4 = _conv(ff, f"{p}_b4_1x1", t4, pool_features, 1, 1)
    return ff.concat(f"{p}_concat", [t1, t2, t3, t4])


def inception_b(ff: FFModel, p: str, input: Tensor) -> Tensor:
    t1 = _conv(ff, f"{p}_b1_3x3", input, 384, 3, 3, 2, 2, 0, 0)
    t2 = _conv(ff, f"{p}_b2_1x1", input, 64, 1, 1)
    t2 = _conv(ff, f"{p}_b2_3x3a", t2, 96, 3, 3, 1, 1, 1, 1)
    t2 = _conv(ff, f"{p}_b2_3x3b", t2, 96, 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(f"{p}_b3_pool", input, 3, 3, 2, 2, 0, 0)
    return ff.concat(f"{p}_concat", [t1, t2, t3])


def inception_c(ff: FFModel, p: str, input: Tensor, channels: int) -> Tensor:
    t1 = _conv(ff, f"{p}_b1_1x1", input, 192, 1, 1)
    t2 = _conv(ff, f"{p}_b2_1x1", input, channels, 1, 1)
    t2 = _conv(ff, f"{p}_b2_1x7", t2, channels, 1, 7, 1, 1, 0, 3)
    t2 = _conv(ff, f"{p}_b2_7x1", t2, 192, 7, 1, 1, 1, 3, 0)
    t3 = _conv(ff, f"{p}_b3_1x1", input, channels, 1, 1)
    t3 = _conv(ff, f"{p}_b3_7x1a", t3, channels, 7, 1, 1, 1, 3, 0)
    t3 = _conv(ff, f"{p}_b3_1x7a", t3, channels, 1, 7, 1, 1, 0, 3)
    t3 = _conv(ff, f"{p}_b3_7x1b", t3, channels, 7, 1, 1, 1, 3, 0)
    t3 = _conv(ff, f"{p}_b3_1x7b", t3, 192, 1, 7, 1, 1, 0, 3)
    t4 = ff.pool2d(f"{p}_b4_pool", input, 3, 3, 1, 1, 1, 1,
                   pool_type=POOL_AVG)
    t4 = _conv(ff, f"{p}_b4_1x1", t4, 192, 1, 1)
    return ff.concat(f"{p}_concat", [t1, t2, t3, t4])


def inception_d(ff: FFModel, p: str, input: Tensor) -> Tensor:
    t1 = _conv(ff, f"{p}_b1_1x1", input, 192, 1, 1)
    t1 = _conv(ff, f"{p}_b1_3x3", t1, 320, 3, 3, 2, 2, 0, 0)
    t2 = _conv(ff, f"{p}_b2_1x1", input, 192, 1, 1)
    t2 = _conv(ff, f"{p}_b2_1x7", t2, 192, 1, 7, 1, 1, 0, 3)
    t2 = _conv(ff, f"{p}_b2_7x1", t2, 192, 7, 1, 1, 1, 3, 0)
    t2 = _conv(ff, f"{p}_b2_3x3", t2, 192, 3, 3, 2, 2, 0, 0)
    t3 = ff.pool2d(f"{p}_b3_pool", input, 3, 3, 2, 2, 0, 0)
    return ff.concat(f"{p}_concat", [t1, t2, t3])


def inception_e(ff: FFModel, p: str, input: Tensor) -> Tensor:
    t1 = _conv(ff, f"{p}_b1_1x1", input, 320, 1, 1)
    t2i = _conv(ff, f"{p}_b2_1x1", input, 384, 1, 1)
    t2 = _conv(ff, f"{p}_b2_1x3", t2i, 384, 1, 3, 1, 1, 0, 1)
    t3 = _conv(ff, f"{p}_b2_3x1", t2i, 384, 3, 1, 1, 1, 1, 0)
    t3i = _conv(ff, f"{p}_b3_1x1", input, 448, 1, 1)
    t3i = _conv(ff, f"{p}_b3_3x3", t3i, 384, 3, 3, 1, 1, 1, 1)
    t4 = _conv(ff, f"{p}_b3_1x3", t3i, 384, 1, 3, 1, 1, 0, 1)
    t5 = _conv(ff, f"{p}_b3_3x1", t3i, 384, 3, 1, 1, 1, 1, 0)
    t6 = ff.pool2d(f"{p}_b4_pool", input, 3, 3, 1, 1, 1, 1,
                   pool_type=POOL_AVG)
    t6 = _conv(ff, f"{p}_b4_1x1", t6, 192, 1, 1)
    return ff.concat(f"{p}_concat", [t1, t2, t3, t4, t5, t6])


def add_inception_v3_layers(ff: FFModel, image: Tensor) -> Tensor:
    t = _conv(ff, "conv1", image, 32, 3, 3, 2, 2, 0, 0)
    t = _conv(ff, "conv2", t, 32, 3, 3, 1, 1, 0, 0)
    t = _conv(ff, "conv3", t, 64, 3, 3, 1, 1, 1, 1)
    t = ff.pool2d("pool1", t, 3, 3, 2, 2, 0, 0)
    t = _conv(ff, "conv4", t, 80, 1, 1, 1, 1, 0, 0)
    t = _conv(ff, "conv5", t, 192, 3, 3, 1, 1, 1, 1)
    t = ff.pool2d("pool2", t, 3, 3, 2, 2, 0, 0)
    t = inception_a(ff, "incA1", t, 32)
    t = inception_a(ff, "incA2", t, 64)
    t = inception_a(ff, "incA3", t, 64)
    t = inception_b(ff, "incB1", t)
    t = inception_c(ff, "incC1", t, 128)
    t = inception_c(ff, "incC2", t, 160)
    t = inception_c(ff, "incC3", t, 160)
    t = inception_c(ff, "incC4", t, 192)
    t = inception_d(ff, "incD1", t)
    t = inception_e(ff, "incE1", t)
    t = inception_e(ff, "incE2", t)
    t = ff.pool2d("pool3", t, 8, 8, 1, 1, 0, 0, pool_type=POOL_AVG,
                  relu=False)
    t = ff.flat("flat", t)
    t = ff.linear("linear1", t, 1000, relu=False)
    return ff.softmax("softmax", t)


def build_inception_v3(config: FFConfig = None, machine=None) -> FFModel:
    config = config or FFConfig(input_height=299, input_width=299)
    ff = FFModel(config, machine)
    cfg = ff.config
    image = ff.create_input(
        (cfg.batch_size, cfg.input_height, cfg.input_width, 3), name="image")
    add_inception_v3_layers(ff, image)
    return ff
