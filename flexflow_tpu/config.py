"""Training/run configuration, equivalent of the reference's ``FFConfig``
(config.h:41-56) with CLI parity with ``parse_input_args`` (cnn.cc:539-582)
and ``DefaultConfig`` (cnn.cc:23-35)."""

from __future__ import annotations

import dataclasses
from typing import Sequence

from flexflow_tpu.strategy import Strategy


def _checked_pallas(v: str) -> str:
    """Validate a --pallas value at parse time (like --on-divergence)."""
    if v not in ("auto", "on", "off"):
        raise SystemExit(f"--pallas must be auto|on|off, got {v!r}")
    return v


def _checked_policy(v: str) -> str:
    """Validate an --on-divergence value at parse time (like -delta)."""
    if v not in ("halt", "warn", "rollback"):
        raise SystemExit(
            f"--on-divergence must be halt|warn|rollback, got {v!r}")
    return v


def _checked_fault_spec(v: str) -> str:
    """Validate a --fault-spec string at parse time so a typo'd kind
    fails loudly instead of never firing."""
    from flexflow_tpu.utils.faultinject import FaultSpecError, \
        parse_fault_spec

    try:
        parse_fault_spec(v)
    except FaultSpecError as e:
        raise SystemExit(f"--fault-spec: {e}")
    return v


@dataclasses.dataclass
class FFConfig:
    # DefaultConfig parity (cnn.cc:23-35)
    epochs: int = 10
    batch_size: int = 64
    num_iterations: int = 10
    print_freq: int = 10
    input_height: int = 224
    input_width: int = 224
    learning_rate: float = 0.01
    weight_decay: float = 1e-4
    momentum: float = 0.0
    num_nodes: int = 1
    workers_per_node: int = 0      # -ll:gpu analog; 0 = use all local chips
    loaders_per_node: int = 4      # -ll:cpu analog (data-loader threads)
    profiling: bool = False
    trace_dir: str = ""            # jax.profiler trace output (-lg:prof analog)
    ckpt_dir: str = ""             # checkpoint/resume directory (TPU-native)
    ckpt_freq: int = 0             # save every N iters (0 = final only)
    synthetic_input: bool = True   # reference default when -d absent (README.md:68)
    dataset_path: str = ""
    strategy_file: str = ""
    # Verification mechanisms (SURVEY.md §4 parity)
    params_init: str = "default"   # "ones" = PARAMETER_ALL_ONES (conv_2d.cu:393-398)
    print_intermediates: bool = False  # PRINT_INTERMEDIATE_RESULT (nmt/rnn.h:25)
    dry_compile: bool = False      # DISABLE_COMPUTATION analog (ops.h:19):
                                   # build+partition+compile, execute nothing
    # TPU-native additions
    compute_dtype: str = "float32"   # "bfloat16" for MXU-friendly training
    # mixed-precision policy (perf round): param_dtype is the STORAGE
    # dtype of the parameters ("bfloat16" halves parameter/gradient HBM
    # and collective traffic).  Anything other than float32 switches
    # the optimizer to master-weight mode: a float32 master copy of
    # every parameter lives in the optimizer state, the update runs in
    # float32 against the masters, and the stored params are re-cast
    # from the masters on write-back (checkpoints carry the masters, so
    # resume is bit-exact).  Compute dtype stays an independent knob —
    # the step casts params to compute_dtype before the forward pass.
    param_dtype: str = "float32"
    seed: int = 0
    num_classes: int = 1000
    # run telemetry (obs subsystem): when obs_dir is set, every surface
    # (fit / search / bench) appends structured JSONL records to
    # <obs_dir>/<run_id>.jsonl; unset = telemetry fully disabled (the step
    # loop pays a single predicate check).  run_id defaults to a fresh
    # time+pid id; set it to join several processes into one stream.
    obs_dir: str = ""
    run_id: str = ""
    # size cap of one obs JSONL file before rollover to a numbered
    # sibling (<run>.jsonl.1, .2, ...); 0 = never rotate
    obs_max_bytes: int = 64 * 1024 * 1024
    # always-on live metrics export (obs/metrics.py): when set, fit()
    # atomically rewrites a Prometheus textfile at this path (plus a
    # <path>.json snapshot) at its existing host-sync boundaries —
    # throughput, MFU, HBM peak/live bytes, rollback/fault counters,
    # prefetch stall.  Independent of obs_dir; empty = disabled.
    metrics_path: str = ""
    # sampled per-op timing in fit() (obs/trace.py's measured side): every
    # Nth step the run syncs and times forward/backward/optimizer
    # sections (plus jax.profiler annotations), and isolated per-op shard
    # timings are emitted post-loop — all as op_time records.  0 = off
    # (the default; sampling perturbs the device pipeline on sampled
    # steps).  Requires obs_dir.
    op_time_every: int = 0
    # strategy search (sim/search.py): number of parallel MCMC chains and
    # the delta re-simulation mode — "on" (default), "off" (every proposal
    # pays a full re-simulation) or "check" (delta cross-checked against
    # full, aborting on divergence; debug only)
    search_chains: int = 1
    search_delta: str = "on"
    # execution performance (round 6): the whole-graph regrid planner
    # (parallel/regrid.py) — "on" (default) resolves every
    # producer->consumer reshard once at plan time with coalescing and
    # cost-aware hop selection; "off" keeps the legacy per-trace path
    # (loss-bit-identical — the equivalence tests compare the two).
    regrid_planner: str = "on"
    # heterogeneous placed-op overlap (perf round): "on" (default) fuses
    # independent same-level placed ops that legacy scheduling would
    # dispatch as SEQUENTIAL shard_maps into one grouped dispatch whose
    # body branches on the group axis, so XLA runs the disjoint device
    # blocks concurrently; "off" keeps the legacy one-dispatch-per-op
    # path (loss-bit-identical — the equivalence tests compare the two,
    # mirroring the regrid-planner pattern above).
    placed_overlap: str = "on"
    # double-buffered device prefetch (data/prefetch.py): queue depth of
    # batches staged on device ahead of the training loop; 0 disables
    # (the legacy synchronous pull inside the timed loop)
    prefetch_depth: int = 2
    # fault tolerance (robustness round): what the step health guard does
    # when a loss window turns non-finite — "halt" (raise TrainingDiverged,
    # the default), "warn" (log + obs record, keep training), "rollback"
    # (restore the last VERIFIED checkpoint and continue on fresh data,
    # at most max_rollbacks times).  Checks run only at print/checkpoint
    # boundaries on already-accumulated device losses — zero per-step
    # host syncs (utils/health.py).
    on_divergence: str = "halt"
    max_rollbacks: int = 3
    # deterministic fault injection (utils/faultinject.py), e.g.
    # "loss_nan@120,data_io@50x3,ckpt_truncate@2"; empty = disabled
    fault_spec: str = ""
    # retrying data sources (utils/retry.py): total read/decode attempts
    # per item, and how many permanently-bad items a run may skip before
    # giving up (data/hdf5.py, data/imagenet.py)
    data_retry_attempts: int = 4
    data_skip_budget: int = 16
    # elastic training (utils/elastic.py): --elastic turns permanent
    # device loss into recovery on the surviving mesh (re-search + live
    # regrid, checkpoint fallback) instead of a fatal error; a shrink
    # below --min-devices raises ElasticShrinkRefused instead of limping.
    # --research-budget-s caps the surviving-mesh re-search wall clock;
    # elastic_search_iters its proposal count.
    elastic: bool = False
    min_devices: int = 1
    research_budget_s: float = 30.0
    elastic_search_iters: int = 2000
    # decomposed strategy search (round 19): --decompose makes every
    # re-search (elastic recovery included) run the block-decomposed
    # path — per-layer sub-searches with shared-block memoization and a
    # boundary-refinement pass.  --research-budget-s then caps the
    # TOTAL wall across all sub-searches (one shared deadline), while
    # --block-budget-s additionally caps each sub-search (0 = proposal-
    # count bound only); --boundary-refine-iters reserves proposals for
    # the post-stitch refinement pass (0 = 20% of the budget).
    decompose: bool = False
    block_budget_s: float = 0.0
    boundary_refine_iters: int = 0
    # elastic re-expansion (round 9): after a shrink, previously-dead
    # ordinals are probed at existing boundaries; --regrow-probes
    # consecutive healthy probes trigger recover_grow (debounce), and a
    # run grows back at most --max-regrows times (flapping cap; 0
    # disables re-expansion entirely)
    max_regrows: int = 1
    regrow_probes: int = 2
    # preemption-aware graceful drain: wall budget for committing the
    # final verified checkpoint after SIGTERM/SIGINT (async writer wait,
    # best-effort sync save fallback past the budget)
    drain_budget_s: float = 60.0
    # step watchdog (utils/health.StepWatchdog): hang deadline =
    # hang_factor x rolling per-step estimate, floored at hang_min_s;
    # 0 = watchdog off (the default — no timer threads in healthy runs)
    hang_factor: float = 0.0
    hang_min_s: float = 60.0
    # transient-retry budget window: probe_devices transient verdicts
    # consume a budget of 3; this many CONSECUTIVE healthy steps refill
    # it, so a long run absorbs spread-out hiccups while rapid flapping
    # still exhausts the cap
    transient_reset_steps: int = 16
    # async checkpointing (utils/checkpoint.AsyncCheckpointWriter):
    # serialization/digest/commit on a background writer, at most one
    # save in flight; fit blocks only on the final save and before a
    # rollback restore.  Off by default — the sync path is unchanged.
    ckpt_async: bool = False
    # buffer donation (round 13): "on" (default) threads donate_argnums
    # through every jitted train step — params, optimizer state, and the
    # mixed-precision __master leaves alias their outputs, so the
    # steady-state step allocates only the batch and the loss; "off" is
    # the A/B arm of the bit-identity contract (tests/test_donation.py)
    # and a debug escape for buffer-reuse investigations.  No CLI flag on
    # purpose: donation is a compilation property, not a training knob.
    donate: str = "on"
    # branch-gradient accumulation (round 13): "tree" (default) hands
    # each consumer of a multi-consumer tensor its own alias
    # (ops/fanout.grad_fanout), so the n branch cotangents re-join as
    # one balanced n-ary sum XLA fuses into a single (n+1)-operand pass
    # instead of the profile's chain of 2-operand add_any fusions
    # (3(n-1) -> n+1 HBM traffic units); "off" keeps JAX's pairwise
    # chain.  Bit-identical for fan-out <= 3, reassociates (tolerance-
    # level) beyond.  No CLI flag: a compilation property, like donate.
    grad_fanout: str = "tree"
    # Pallas kernel policy (round 13): one switch over the per-kernel
    # env gates (FLEXFLOW_TPU_{FLASH,MAXPOOL,AVGPOOL,BNRELU}, which
    # still override per-kernel for tests/experiments).  "auto" (the
    # default) routes a kernel only when its supported() gate holds AND
    # the HBM cost model predicts a win on the concrete geometry
    # (ops/pallas/__init__.set_policy); "on" forces every supported
    # kernel; "off" keeps everything on the stock XLA path.
    pallas: str = "auto"
    # serving runtime (serve/ package, apps/serve.py): --max-batch caps
    # the continuous batcher's decode slots (0 = the model's batch_size);
    # --serve-queue-hi is the queue-depth watermark that triggers a
    # regrow of parked devices; --serve-idle-boundaries is how many
    # consecutive idle decode boundaries trigger a shrink (0 disables
    # autoscaling in that direction)
    max_batch: int = 0
    serve_queue_hi: int = 0
    serve_idle_boundaries: int = 0
    # disaggregated serving (serve/router.py): --serve-prefill-devices
    # > 0 carves the mesh into a prefill pool (the first N devices,
    # split across --serve-prefill-replicas engines searched under the
    # latency objective) and a decode pool (the rest, split across
    # --serve-decode-replicas engines searched under the decode
    # objective); 0 keeps the single-pool engine
    serve_prefill_devices: int = 0
    serve_prefill_replicas: int = 1
    serve_decode_replicas: int = 1
    # fleet coordinator (fleet/ package, apps/fleet.py): --fleet-quantum
    # is how many steps (train iterations / decode boundaries) each
    # running job gets per round-robin turn before the coordinator
    # re-evaluates the packing; --fleet-search-budget-s caps each
    # arbiter pricing re-search's wall clock (generous by default so
    # the fixed iteration bound binds and packing stays reproducible)
    fleet_quantum: int = 4
    fleet_search_budget_s: float = 30.0
    # static plan analyzer (verify/plan.py, round 12): the drivers fail
    # fast on a strategy whose plan check reports errors; --allow-degraded
    # demotes the promoted degradation diagnostics (replicated/normalized
    # execution the machine previously only warned about) back to
    # warnings, restoring the old degrade-and-continue behavior
    allow_degraded: bool = False

    strategies: Strategy = dataclasses.field(default_factory=Strategy)

    def __post_init__(self):
        if self.strategy_file:
            self.load_strategy_file(self.strategy_file)

    # FFConfig::load/save_strategy_file parity (strategy.cc:62-86)
    def load_strategy_file(self, filename: str) -> bool:
        self.strategies = Strategy.load(filename)
        return True

    def save_strategy_file(self, filename: str) -> bool:
        self.strategies.save(filename)
        return True

    @classmethod
    def from_args(cls, argv: Sequence[str]) -> "FFConfig":
        """Parse the reference's flag set (cnn.cc:539-582): -e/--epochs,
        -b/--batch-size, --lr, --wd, -p/--print-freq, -d/--dataset,
        -s/--strategy, plus TPU-native extras (--dtype, --iters, --seed,
        --profiling, -obs-dir/-run-id for the run-telemetry JSONL)."""
        from flexflow_tpu.utils.flags import flag_stream

        cfg = cls()
        for a, val in flag_stream(argv):
            if a in ("-e", "--epochs"):
                cfg.epochs = int(val())
            elif a in ("-b", "--batch-size"):
                cfg.batch_size = int(val())
            elif a in ("--lr", "--learning-rate"):
                cfg.learning_rate = float(val())
            elif a in ("--wd", "--weight-decay"):
                cfg.weight_decay = float(val())
            elif a in ("-p", "--print-freq"):
                cfg.print_freq = int(val())
            elif a in ("-d", "--dataset"):
                cfg.dataset_path = val()
                cfg.synthetic_input = False
            elif a in ("-s", "--strategy"):
                cfg.strategy_file = val()
                cfg.load_strategy_file(cfg.strategy_file)
            elif a == "-ll:gpu":   # accepted for drop-in compatibility
                cfg.workers_per_node = int(val())
            elif a == "-ll:cpu":
                cfg.loaders_per_node = int(val())
            elif a in ("-i", "--iters", "--iterations"):
                cfg.num_iterations = int(val())
            elif a == "--dtype":
                cfg.compute_dtype = val()
            elif a in ("-param-dtype", "--param-dtype"):
                cfg.param_dtype = val()
            elif a == "--seed":
                cfg.seed = int(val())
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--trace-dir":
                cfg.trace_dir = val()
            elif a in ("-obs-dir", "--obs-dir"):
                cfg.obs_dir = val()
            elif a in ("-run-id", "--run-id"):
                cfg.run_id = val()
            elif a == "--obs-max-bytes":
                cfg.obs_max_bytes = int(val())
            elif a in ("-op-time-every", "--op-time-every"):
                cfg.op_time_every = int(val())
            elif a in ("-metrics-path", "--metrics-path"):
                cfg.metrics_path = val()
            elif a in ("-chains", "--chains"):
                cfg.search_chains = int(val())
            elif a in ("-delta", "--delta"):
                cfg.search_delta = val()
            elif a in ("-regrid-planner", "--regrid-planner"):
                cfg.regrid_planner = val()
            elif a in ("-placed-overlap", "--placed-overlap"):
                cfg.placed_overlap = val()
            elif a in ("-prefetch-depth", "--prefetch-depth"):
                cfg.prefetch_depth = int(val())
            elif a in ("-on-divergence", "--on-divergence"):
                cfg.on_divergence = _checked_policy(val())
            elif a in ("-max-rollbacks", "--max-rollbacks"):
                cfg.max_rollbacks = int(val())
            elif a in ("-fault-spec", "--fault-spec"):
                cfg.fault_spec = _checked_fault_spec(val())
            elif a == "--data-retry-attempts":
                cfg.data_retry_attempts = int(val())
            elif a == "--data-skip-budget":
                cfg.data_skip_budget = int(val())
            elif a == "--elastic":
                cfg.elastic = True
            elif a == "--min-devices":
                cfg.min_devices = int(val())
            elif a == "--research-budget-s":
                cfg.research_budget_s = float(val())
            elif a == "--elastic-search-iters":
                cfg.elastic_search_iters = int(val())
            elif a == "--decompose":
                cfg.decompose = True
            elif a == "--block-budget-s":
                cfg.block_budget_s = float(val())
            elif a == "--boundary-refine-iters":
                cfg.boundary_refine_iters = int(val())
            elif a == "--max-regrows":
                cfg.max_regrows = int(val())
            elif a == "--regrow-probes":
                cfg.regrow_probes = int(val())
            elif a == "--drain-budget-s":
                cfg.drain_budget_s = float(val())
            elif a == "--hang-factor":
                cfg.hang_factor = float(val())
            elif a == "--hang-min-s":
                cfg.hang_min_s = float(val())
            elif a == "--transient-reset-steps":
                cfg.transient_reset_steps = int(val())
            elif a == "--ckpt-async":
                cfg.ckpt_async = True
            elif a == "--max-batch":
                cfg.max_batch = int(val())
            elif a == "--serve-queue-hi":
                cfg.serve_queue_hi = int(val())
            elif a == "--serve-idle-boundaries":
                cfg.serve_idle_boundaries = int(val())
            elif a == "--serve-prefill-devices":
                cfg.serve_prefill_devices = int(val())
            elif a == "--serve-prefill-replicas":
                cfg.serve_prefill_replicas = int(val())
            elif a == "--serve-decode-replicas":
                cfg.serve_decode_replicas = int(val())
            elif a == "--fleet-quantum":
                cfg.fleet_quantum = int(val())
            elif a == "--fleet-search-budget-s":
                cfg.fleet_search_budget_s = float(val())
            elif a == "--allow-degraded":
                cfg.allow_degraded = True
            elif a in ("-pallas", "--pallas"):
                cfg.pallas = _checked_pallas(val())
            elif a == "--ckpt-dir":
                cfg.ckpt_dir = val()
            elif a == "--ckpt-freq":
                cfg.ckpt_freq = int(val())
            elif a == "--height":
                cfg.input_height = int(val())
            elif a == "--width":
                cfg.input_width = int(val())
            elif a == "--classes":
                cfg.num_classes = int(val())
            elif a == "--params-ones":
                cfg.params_init = "ones"
            elif a == "--print-intermediates":
                cfg.print_intermediates = True
            elif a == "--dry-compile":
                cfg.dry_compile = True
            # unknown flags are ignored, like the reference parser
        return cfg
