"""Always-on live metrics export: a Prometheus textfile plus a JSON
snapshot, atomically rewritten at the training loop's existing host-sync
boundaries.

The obs JSONL is an append-only event stream — perfect for post-hoc
analysis, wrong for "is the 30-hour run still healthy?": answering that
from JSONL means tailing and parsing an unbounded file.  This module
publishes the handful of gauges an operator actually watches —
throughput, MFU, HBM peak/live bytes, rollback/fault counters, prefetch
stall — as two small files any scraper understands:

  * ``<metrics_path>`` — Prometheus *textfile collector* format
    (``# HELP`` / ``# TYPE`` / ``name value`` lines; point a node
    exporter's ``--collector.textfile.directory`` at the parent dir, or
    read it with :func:`read_textfile`);
  * ``<metrics_path>.json`` — the same gauges as one JSON object, for
    tooling that wants types without a Prometheus parser.

Contracts:

  * **atomic rewrite** — each write goes to a tempfile in the target
    directory and ``os.replace``s into place, so a scraper never reads a
    torn file;
  * **finite values only** — a gauge whose value is None/NaN/inf is
    dropped from the files (a poisoned loss must not corrupt the
    scrape); counters are monotone within one exporter's lifetime;
  * **host-boundary cadence** — ``fit()`` updates at print/checkpoint
    boundaries and once post-loop, never from the device hot path
    (``FFConfig.metrics_path`` enables it, independent of ``obs_dir``).

Every written snapshot is also mirrored as a ``metrics`` obs record when
the run has a live obs stream, so the JSONL and the scrape never
disagree.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
from typing import Dict, Optional

PREFIX = "ff_"

# gauge name -> HELP text; written in this order.  Anything update()d
# outside this table is still exported (HELP omitted).
_HELP = {
    "throughput_items_per_sec": "training throughput (items/s, machine)",
    "images_per_sec": "training throughput alias (images/s, machine)",
    "mfu": "achieved model FLOPs utilization (0..1)",
    "mfu_ceiling": "roofline MFU ceiling of the compiled step (0..1)",
    "step_wall_seconds": "recent mean wall seconds per step",
    "loss": "most recent training loss",
    "steps_total": "training steps completed this run",
    "hbm_peak_bytes": "peak device memory (runtime stats, else compiled "
                      "memory analysis estimate)",
    "hbm_live_bytes": "device bytes currently in use (runtime stats)",
    "prefetch_stall_seconds_total": "input stall the prefetch overlap "
                                    "could not hide",
    "rollbacks_total": "health-guard rollbacks this run",
    "faults_total": "fault records this run (injected, detected, or "
                    "refused-checkpoint)",
    "elastic_events": "elastic resizes (surviving-mesh recoveries and "
                      "re-expansions) this run; also exported per "
                      "direction as ff_elastic_events{direction=...}",
    "ckpt_async_inflight": "async checkpoint writes currently in flight "
                           "(0 or 1)",
    "drain_pending": "1 while a SIGTERM/SIGINT graceful drain is "
                     "committing its final checkpoint, else 0",
    "qps": "serving throughput (completed requests per virtual second)",
    "queue_depth": "serving requests arrived but not yet admitted "
                   "to a decode slot",
    "latency_p50_s": "serving request latency p50 (virtual seconds, "
                     "arrival to completion)",
    "latency_p99_s": "serving request latency p99 (virtual seconds)",
    "ttft_p50_s": "serving time-to-first-token p50 (virtual seconds, "
                  "arrival to first decoded token)",
    "ttft_p99_s": "serving time-to-first-token p99 (virtual seconds)",
    "tpot_p50_s": "serving time-per-output-token p50 (virtual seconds "
                  "per decode token after the first)",
    "requests_total": "serving requests completed this run",
    "serve_pool_queue_depth": "disaggregated serving queue depth per "
                              "pool, exported as ff_serve_pool_"
                              "queue_depth{pool=\"prefill\"|\"decode\"}",
    "serve_pool_active_slots": "occupied decode slots per pool, "
                               "exported as ff_serve_pool_active_slots"
                               "{pool=...}",
    "serve_pool_step_time_s": "virtual step time per pool (the "
                              "per-phase searched strategy's step), "
                              "exported as ff_serve_pool_step_time_s"
                              "{pool=...}",
    "serve_pool_requests_total": "requests completed per pool, "
                                 "exported as ff_serve_pool_requests_"
                                 "total{pool=...}",
    "serve_retries_total": "serving retries this run (handoff "
                           "retransmits + KV rebuilds under the "
                           "router's RetryPolicy)",
    "serve_shed_total": "serving arrivals shed by the SLO-burn "
                        "admission gate this run (explicit "
                        "serve_shed records, never silent drops)",
    "replicas_live": "decode replicas currently live (crashed "
                     "replicas leave until their restart_s revival)",
    "slo_burn_rate": "SLO error-budget burn rate over the full stream "
                     "(1.0 = burning exactly the budget)",
    "slo_max_window_burn_rate": "worst rolling-window SLO burn rate",
    "slo_error_rate": "fraction of requests violating the SLO latency "
                      "target",
    "slo_goodput_qps": "SLO-compliant completed requests per virtual "
                       "second",
    "slo_compliant": "1 if the achieved latency percentile meets the "
                     "SLO target, else 0",
    "fleet_jobs": "fleet jobs by lifecycle state, exported as "
                  "ff_fleet_jobs{state=...}; the plain series is the "
                  "total job count",
    "fleet_job_devices": "devices currently assigned to each fleet "
                         "job, exported as ff_fleet_job_devices"
                         "{job=...}; the plain series is the pool's "
                         "assigned total",
    "fleet_rebalances_total": "fleet packing rebalances this run",
    "fleet_util": "pool utilization last fleet round (busy device-steps "
                  "/ pool capacity x round span, 0..1)",
}
_COUNTER_EXTRA = {"fleet_rebalances_total"}
_COUNTERS = {"steps_total", "rollbacks_total", "faults_total",
             "prefetch_stall_seconds_total", "elastic_events",
             "requests_total", "serve_retries_total",
             "serve_shed_total"} | _COUNTER_EXTRA

# Fixed log-spaced latency buckets: 1 ms .. 100 s in quarter-decade
# steps (21 finite upper bounds + the implicit +Inf).  Fixed — never
# derived from observed data — so scrapes from different replicas
# aggregate bucket-for-bucket.
LATENCY_BUCKETS = tuple(round(0.001 * 10 ** (i / 4), 10)
                        for i in range(21))

_HIST_HELP = {
    "request_latency_s": "serving request latency (virtual seconds, "
                         "arrival to completion)",
    "request_ttft_s": "serving time-to-first-token (virtual seconds)",
    "fleet_job_wait_s": "fleet job queue wait (virtual seconds, submit "
                        "to placement start)",
}


def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


class MetricsExporter:
    """Holds the current gauge values and rewrites the export files.

    ``update(**gauges)`` merges new values (None/non-finite dropped at
    write time), ``write()`` publishes both files atomically.  The
    exporter also carries a small ``meta`` dict (model/run id) rendered
    as an ``ff_run_info`` label line, and a scratch ``cache`` dict fit()
    uses to memoize compiled-cost lookups across boundaries."""

    def __init__(self, path: str, meta: Optional[Dict] = None):
        self.path = path
        self.json_path = path + ".json"
        self.meta = dict(meta or {})
        self.cache: Dict = {}
        self.values: Dict[str, float] = {}
        # labeled series: bare name -> {rendered label string -> value};
        # published right after the same-named plain series (which stays
        # the all-directions total, so unlabeled dashboards keep working)
        self.labeled: Dict[str, Dict[str, float]] = {}
        # histograms: bare name -> {"counts": per-bucket (non-cumulative,
        # +Inf last), "sum": float, "count": int}; buckets are the fixed
        # LATENCY_BUCKETS so replicas aggregate bucket-for-bucket
        self.histograms: Dict[str, Dict] = {}
        self._writes = 0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def update(self, **gauges) -> None:
        for k, v in gauges.items():
            self.values[k] = v

    def update_labeled(self, name: str, labels: Dict[str, str],
                       value) -> None:
        """Set one labeled sample, e.g. ``update_labeled("elastic_events",
        {"direction": "grow"}, 1)`` ->
        ``ff_elastic_events{direction="grow"} 1``."""
        key = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        self.labeled.setdefault(name, {})[key] = value

    def observe(self, name: str, value) -> None:
        """Record one sample into the ``name`` histogram (fixed
        LATENCY_BUCKETS).  Non-finite samples are dropped — same
        poisoned-value contract as the gauges."""
        f = _finite(value)
        if f is None:
            return
        h = self.histograms.setdefault(
            name, {"counts": [0] * (len(LATENCY_BUCKETS) + 1),
                   "sum": 0.0, "count": 0})
        for i, le in enumerate(LATENCY_BUCKETS):
            if f <= le:
                h["counts"][i] += 1
                break
        else:
            h["counts"][-1] += 1  # +Inf bucket
        h["sum"] += f
        h["count"] += 1

    def finite_values(self) -> Dict[str, float]:
        out = {}
        for k, v in self.values.items():
            f = _finite(v)
            if f is not None:
                out[k] = f
        return out

    def render(self) -> str:
        vals = self.finite_values()
        lines = []
        if self.meta:
            labels = ",".join(
                f'{k}="{v}"' for k, v in sorted(self.meta.items()))
            lines.append(f"# HELP {PREFIX}run_info run identity labels")
            lines.append(f"# TYPE {PREFIX}run_info gauge")
            lines.append(f"{PREFIX}run_info{{{labels}}} 1")
        extra = set(vals) | set(self.labeled)
        ordered = [k for k in _HELP if k in extra] \
            + sorted(k for k in extra if k not in _HELP)
        for k in ordered:
            name = PREFIX + k
            if k in _HELP:
                lines.append(f"# HELP {name} {_HELP[k]}")
            lines.append(f"# TYPE {name} "
                         f"{'counter' if k in _COUNTERS else 'gauge'}")
            if k in vals:
                lines.append(f"{name} {vals[k]:.10g}")
            for labels, v in sorted(self.labeled.get(k, {}).items()):
                f = _finite(v)
                if f is not None:
                    lines.append(f"{name}{{{labels}}} {f:.10g}")
        for k in sorted(self.histograms):
            name = PREFIX + k
            if k in _HIST_HELP:
                lines.append(f"# HELP {name} {_HIST_HELP[k]}")
            lines.append(f"# TYPE {name} histogram")
            h = self.histograms[k]
            cum = 0
            for le, n in zip(LATENCY_BUCKETS, h["counts"]):
                cum += n
                lines.append(f'{name}_bucket{{le="{le:.10g}"}} {cum}')
            cum += h["counts"][-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f'{name}_sum {h["sum"]:.10g}')
            lines.append(f'{name}_count {h["count"]}')
        return "\n".join(lines) + "\n"

    def write(self) -> None:
        """Atomic rewrite of the textfile and the JSON snapshot (a
        failed write never tears the published files)."""
        self._writes += 1
        _replace(self.path, self.render())
        snap = {"ts": time.time(), "writes": self._writes,
                "meta": self.meta, "gauges": self.finite_values()}
        if self.histograms:
            snap["histograms"] = self.histograms
        _replace(self.json_path, json.dumps(snap, indent=1) + "\n")


def _replace(path: str, content: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".metrics-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(content)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def from_config(config, meta: Optional[Dict] = None) \
        -> Optional[MetricsExporter]:
    """A live exporter when ``config.metrics_path`` is set, else None.
    Independent of ``obs_dir`` — a run may scrape without JSONL."""
    path = getattr(config, "metrics_path", "") or ""
    if not path:
        return None
    return MetricsExporter(path, meta=meta)


def read_textfile(path: str) -> Dict[str, float]:
    """Parse a Prometheus textfile back into ``{bare_name: value}`` (the
    ``ff_`` prefix stripped, label lines like ``run_info`` skipped) —
    the verification half of the export used by tests and
    ``make budget-smoke``.  Raises ValueError on a malformed sample
    line, which is exactly what "the textfile parses" means."""
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"malformed metrics line: {line!r}")
            name, value = parts
            if "{" in name:
                continue  # labeled series (see read_labeled)
            if not name.startswith(PREFIX):
                raise ValueError(f"unexpected metric name: {name!r}")
            out[name[len(PREFIX):]] = float(value)
    return out


def read_histogram(path: str) -> Dict[str, Dict]:
    """Parse the histogram series of a textfile back into
    ``{bare_name: {"buckets": [(le, cumulative_count), ...],
    "sum": float, "count": int}}`` with ``le`` floats (``inf`` for the
    +Inf bucket) — the verification half of
    :meth:`MetricsExporter.observe`.  Raises ValueError when a
    histogram's buckets are not monotone non-decreasing or its +Inf
    bucket disagrees with ``_count``."""
    out: Dict[str, Dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "_bucket{le=" in line:
                name_part, _, rest = line.partition("_bucket{le=\"")
                le_str, _, val = rest.partition("\"}")
                bare = name_part[len(PREFIX):]
                le = float("inf") if le_str == "+Inf" else float(le_str)
                out.setdefault(bare, {"buckets": [], "sum": 0.0,
                                      "count": 0})
                out[bare]["buckets"].append((le, int(float(val))))
            elif "{" not in line:
                name, _, val = line.partition(" ")
                if name.endswith("_sum") and \
                        name[len(PREFIX):-len("_sum")] in out:
                    out[name[len(PREFIX):-len("_sum")]]["sum"] = \
                        float(val)
                elif name.endswith("_count") and \
                        name[len(PREFIX):-len("_count")] in out:
                    out[name[len(PREFIX):-len("_count")]]["count"] = \
                        int(float(val))
    for bare, h in out.items():
        counts = [n for _le, n in h["buckets"]]
        if any(b > a for a, b in zip(counts[1:], counts)):
            raise ValueError(
                f"histogram {bare!r} buckets not monotone: {counts}")
        if h["buckets"] and not math.isinf(h["buckets"][-1][0]):
            raise ValueError(f"histogram {bare!r} missing +Inf bucket")
        if h["buckets"] and counts[-1] != h["count"]:
            raise ValueError(
                f"histogram {bare!r}: +Inf bucket {counts[-1]} != "
                f"_count {h['count']}")
    return out


def read_labeled(path: str) -> Dict[str, Dict[str, float]]:
    """Parse the LABELED samples of a textfile back into
    ``{bare_name: {label_string: value}}`` (e.g.
    ``{"elastic_events": {'direction="grow"': 1.0}}``), skipping the
    ``run_info`` identity line — the verification half of
    :meth:`MetricsExporter.update_labeled`."""
    out: Dict[str, Dict[str, float]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "{" not in line:
                continue
            head, _, rest = line.partition("{")
            labels, _, value = rest.rpartition("}")
            if not head.startswith(PREFIX):
                raise ValueError(f"unexpected metric name: {head!r}")
            bare = head[len(PREFIX):]
            if bare == "run_info":
                continue
            out.setdefault(bare, {})[labels] = float(value.strip())
    return out
