"""Per-fusion residual account (round 13) — ``report fusions``.

The roofline profile (utils/hlo_profile.roofline_report, committed under
examples/profiles/) ends at a single number: the step runs at
``of_ceiling`` of its floor, leaving ``seconds_per_step -
step_floor_seconds`` of *compute residual* the class split only coarsely
attributes.  This module prices each profiled fusion against the
:class:`~flexflow_tpu.sim.cost_model.TpuChipPerf` roofline and produces
a ranked account of that residual with the same accounting contract as
``obs.budget.build_step_budget``: row allocations are clamped to the
remaining residual, the remainder is an explicit ``unattributed`` bucket,
and rows + unattributed sum to the residual EXACTLY — an account, not an
estimate dump.  Raw (pre-clamp) excesses are kept per row for honesty.

Per-row floors, by fusion class:

* ``vpu`` / ``raw``-with-root — HBM byte floor from the root line's
  output shapes (the same ``dtype[dims]`` line parser as
  utils/hlo_audit.parse_collectives; layout annotations use parens, so
  the bracket regex is safe), with the input volume estimated from the
  root opcode (an ``add`` reads 2x its output, a ``select`` ~2.25x, a
  ``tuple`` root is priced at output volume — a stated lower bound).
* ``mxu`` — byte floors cannot see matrix-unit inefficiency, so the
  floor is ``measured * mxu_eff_during_matmul`` (the profile's own
  flops/(peak * mxu_ms)): what the row would take at 100% MXU.
* ``select_and_scatter`` (raw, no root shapes: unfusable scatter) — the
  measured Pallas maxpool-backward A/B from ops/pallas/maxpool.py (2.9
  ms kernel vs 5.0 ms XLA on the two big inception pools, ratio 0.58)
  prices the floor; the row records the kernel and its predicted win.

Every row carries a machine-applied verdict — ``fusable`` (elementwise
excess XLA could fold into a producer/consumer), ``pallas_worthy``
(unfusable op with a shipped/known kernel route), or ``irreducible``
(at its floor, or MXU-internal utilization no byte rewrite recovers).

jax-free on purpose: ``make fusion-smoke`` runs against the committed
profile in the native-only ``make check`` path.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

# the one dtype-size table shared with the collective auditor
from flexflow_tpu.utils.hlo_audit import _DT

_SHAPE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_OPCODE = re.compile(r"([a-z][a-z0-9_\-]*)\(")

# input volume as a multiple of output volume, by root opcode.  Stated
# estimates: a 2-operand elementwise op reads 2x what it writes; select
# reads two branches + a pred plane (~0.25x at 1 byte vs bf16/f32);
# roots whose operand set the line does not reveal (tuple, reduce,
# convert chains) are priced at output volume — a LOWER bound, so their
# excess is an upper bound and the verdict stays conservative.
_IN_MULT = {"add": 2.0, "subtract": 2.0, "multiply": 2.0, "divide": 2.0,
            "maximum": 2.0, "minimum": 2.0, "select": 2.25,
            "select-n": 2.25, "select_n": 2.25}

# measured Pallas maxpool-backward / XLA select_and_scatter time ratio
# (ops/pallas/maxpool.py: 2.9 ms vs 5.0 ms summed over the two big
# inception pools on v5e) — the floor for unfusable scatter rows
_SS_PALLAS_RATIO = 2.9 / 5.0

# balanced-tree gradient fanout (ops/fanout.py): an n-way branch sum as
# one (n+1)-operand fusion moves (n+1) units vs the add_any chain's
# 3(n-1); at the inception blocks' n=4 that is 5/9 of the traffic
_FANOUT_TRAFFIC_RATIO = 5.0 / 9.0

SCHEMA = "fusion_account_v1"


def _root_bytes(root: str) -> Optional[Dict[str, float]]:
    """Output bytes + estimated input bytes of a profile row's root HLO
    line, or None when the line carries no parseable shapes."""
    op = None
    pos = len(root)
    m = _OPCODE.search(root.split("=", 1)[-1])
    if m:
        op = m.group(1)
        pos = root.index(m.group(0), root.find("=") + 1)
    out = 0
    for sm in _SHAPE.finditer(root[:pos]):
        dt, dims = sm.group(1), sm.group(2)
        if dt not in _DT:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out += n * _DT[dt]
    if out <= 0:
        return None
    mult = _IN_MULT.get(op or "", 1.0)
    return {"out_bytes": float(out), "in_bytes": float(out) * mult,
            "opcode": op or "", "lower_bound": op not in _IN_MULT}


def _price_row(row: dict, mxu_eff: float, hbm_bw: float) -> dict:
    """floor_ms + floor_source (+ kernel/rewrite annotation) for one
    profiled fusion row ({name, ms, class, root})."""
    name, ms = row["name"], float(row["ms"])
    cls, root = row.get("class", ""), row.get("root", "") or ""
    out = {"name": name, "class": cls, "measured_ms": ms}
    if cls == "mxu":
        out["floor_ms"] = ms * mxu_eff
        out["floor_source"] = "mxu_flops"
        out["note"] = (f"at {mxu_eff:.0%} MXU during matmul; excess is "
                       f"matrix-unit utilization, not HBM traffic")
        return out
    if name.startswith("select_and_scatter"):
        out["floor_ms"] = ms * _SS_PALLAS_RATIO
        out["floor_source"] = "pallas_kernel_measured"
        out["kernel"] = "pallas_maxpool_bwd"
        out["predicted_win_ms"] = round(ms * (1 - _SS_PALLAS_RATIO), 3)
        out["note"] = ("unfusable scatter; floor = measured Pallas "
                       "maxpool-backward ratio "
                       f"({_SS_PALLAS_RATIO:.2f}x, ops/pallas/maxpool)")
        return out
    priced = _root_bytes(root)
    if priced is None:
        # no shapes on the root line: price at measured (excess 0) and
        # say so rather than invent a floor
        out["floor_ms"] = ms
        out["floor_source"] = "unpriced"
        out["note"] = "root line carries no parseable shapes"
        return out
    bw_ms = (priced["in_bytes"] + priced["out_bytes"]) / hbm_bw * 1e3
    out["floor_ms"] = min(bw_ms, ms)
    out["floor_source"] = ("root_bytes_lower_bound"
                           if priced["lower_bound"] else "root_bytes")
    out["excess_bytes"] = round(max(0.0, ms - out["floor_ms"])
                                / 1e3 * hbm_bw)
    # only when the root DEFINES the add_any (the fusion IS the
    # accumulation chain), not when it merely reads one as an operand
    if root.lstrip().startswith("%add_any"):
        out["rewrite"] = "grad_fanout"
        out["predicted_win_ms"] = round(
            max(0.0, ms - out["floor_ms"]) * (1 - _FANOUT_TRAFFIC_RATIO),
            3)
        out["note"] = ("branch-gradient add_any chain; grad_fanout tree "
                       f"moves {_FANOUT_TRAFFIC_RATIO:.2f}x the bytes")
    return out


def _verdict(row: dict) -> str:
    tol = max(0.05, 0.05 * row["measured_ms"])
    if row["measured_ms"] - row["floor_ms"] <= tol:
        return "irreducible"
    if row["class"] == "mxu":
        return "irreducible"
    if row["class"] == "raw" or "kernel" in row:
        return "pallas_worthy"
    return "fusable"


def fusion_account(profile: dict, perf=None, top_n: int = 10) -> dict:
    """The ranked residual account for one roofline profile dict
    (examples/profiles/*_roofline.json schema).  Rows are the ``top_n``
    largest pre-clamp excesses; allocation is greedy in that order and
    clamped to the remaining residual (clamped rows listed), and
    ``rows[*].excess_ms + unattributed_ms == residual_ms`` exactly."""
    if perf is None:
        from flexflow_tpu.sim.cost_model import TpuChipPerf

        perf = TpuChipPerf()
    wall_ms = float(profile["seconds_per_step"]) * 1e3
    floor_ms = float(profile["step_floor_seconds"]) * 1e3
    residual_ms = max(0.0, wall_ms - floor_ms)
    mxu_eff = float(profile.get("mxu_eff_during_matmul") or 1.0)
    priced = [_price_row(r, mxu_eff, perf.hbm_bandwidth)
              for r in profile.get("top_ops", [])]
    for p in priced:
        p["excess_ms_raw"] = round(
            max(0.0, p["measured_ms"] - p["floor_ms"]), 3)
        p["floor_ms"] = round(p["floor_ms"], 3)
        p["verdict"] = _verdict(p)
    priced.sort(key=lambda p: p["excess_ms_raw"], reverse=True)
    rows, clamped = priced[:top_n], []
    remaining = residual_ms
    for p in rows:
        alloc = min(p["excess_ms_raw"], remaining)
        if alloc < p["excess_ms_raw"] - 1e-9:
            clamped.append(p["name"])
        p["excess_ms"] = alloc
        p["share_of_residual"] = (alloc / residual_ms
                                  if residual_ms else 0.0)
        remaining -= alloc
    attributed = sum(p["excess_ms"] for p in rows)
    return {"schema": SCHEMA, "model": profile.get("model", ""),
            "bound": profile.get("bound", ""),
            "wall_ms": wall_ms, "floor_ms": floor_ms,
            "residual_ms": residual_ms, "mxu_eff": mxu_eff,
            "rows": rows, "attributed_ms": attributed,
            "unattributed_ms": remaining, "clamped": clamped,
            "top3_frac": (sum(p["excess_ms"] for p in rows[:3])
                          / residual_ms if residual_ms else 0.0)}


def check_account(account: dict, tol_frac: float = 0.01) -> List[str]:
    """The fusion-smoke invariants: rows + unattributed sum to the
    residual within ``tol_frac``, and every row is verdicted (no
    ``unknown``).  Returns problem strings; [] means the account holds."""
    problems = []
    total = (sum(r["excess_ms"] for r in account["rows"])
             + account["unattributed_ms"])
    ref = max(account["residual_ms"], 1e-9)
    if abs(total - account["residual_ms"]) > tol_frac * ref:
        problems.append(
            f"rows+unattributed = {total:.3f} ms != residual "
            f"{account['residual_ms']:.3f} ms")
    for r in account["rows"]:
        if r.get("verdict") not in ("fusable", "pallas_worthy",
                                    "irreducible"):
            problems.append(f"row {r['name']} verdict "
                            f"{r.get('verdict')!r} is not a verdict")
    return problems


def residual_top_frac(profile: dict, k: int = 3) -> float:
    """Share of the compute residual held by the account's top-``k``
    rows (bench.py's ``residual_top_frac`` metric field)."""
    acct = fusion_account(profile)
    ref = acct["residual_ms"]
    return (sum(r["excess_ms"] for r in acct["rows"][:k]) / ref
            if ref else 0.0)


def render_account(account: dict) -> str:
    """Fixed-width text table (``report fusions`` default output)."""
    lines = [
        f"fusion residual account — {account['model'] or '?'} "
        f"({account['bound'] or '?'}-bound): wall {account['wall_ms']:.2f}"
        f" ms, floor {account['floor_ms']:.2f} ms, residual "
        f"{account['residual_ms']:.2f} ms",
        f"{'fusion':<28}{'class':<6}{'meas':>8}{'floor':>8}"
        f"{'excess':>8}{'share':>7}  verdict"]
    for r in account["rows"]:
        extra = ""
        if r.get("kernel"):
            extra = (f"  [{r['kernel']} "
                     f"-{r.get('predicted_win_ms', 0):.2f} ms]")
        elif r.get("rewrite"):
            extra = (f"  [{r['rewrite']} "
                     f"-{r.get('predicted_win_ms', 0):.2f} ms]")
        clamp = "*" if r["name"] in account["clamped"] else " "
        lines.append(
            f"{r['name']:<28}{r['class']:<6}{r['measured_ms']:>8.3f}"
            f"{r['floor_ms']:>8.3f}{r['excess_ms']:>7.3f}{clamp}"
            f"{r['share_of_residual']:>7.1%}  {r['verdict']}{extra}")
    lines.append(
        f"{'unattributed (beyond top rows)':<42}"
        f"{account['unattributed_ms']:>8.3f}"
        f"{account['unattributed_ms'] / account['residual_ms']:>8.1%}"
        if account["residual_ms"] else "unattributed: 0")
    if account["clamped"]:
        lines.append(f"  * clamped to remaining residual: "
                     f"{', '.join(account['clamped'])}")
    return "\n".join(lines)
