"""Declarative serving SLOs and error-budget burn rate.

An SLO here is the standard SRE shape: "the p99 request latency stays
under ``latency_target_s``, and at least ``availability`` of requests
individually meet that target".  The complement of availability is the
**error budget** (availability 0.999 -> 0.1% of requests may miss);
the **burn rate** is how fast a stream is spending that budget:

    burn_rate = error_rate / (1 - availability)

1.0 means the stream is violating at exactly the budgeted rate; 10x
means the monthly budget is gone in three days.  Burn rate is computed
two ways over ``serve_request`` obs streams: once over the whole
stream (:func:`evaluate`) and per rolling window of virtual completion
time (:func:`burn_rate_windows`) so a short burst of violations is not
averaged away by a long quiet tail — the multi-window alerting shape
Prometheus/SRE playbooks use.

All times are the serve engine's *virtual* clock (``done_v`` stamps),
so burn rates are bit-deterministic under a fixed seed — the property
every other serving artifact in this repo leans on.  The module is
pure stdlib: it reads record dicts (from ``obs.read_run`` or an
in-memory list) and never touches jax.

``evaluate`` results flow three ways: an ``slo`` obs record
(:func:`log_record`), ``ff_slo_*`` gauges on a live
:class:`~flexflow_tpu.obs.metrics.MetricsExporter`
(:func:`export_gauges`), and the ``report slo`` CLI.

The serving router's admission gate
(:class:`~flexflow_tpu.serve.router.AdmissionGate`) reuses this
module's burn definition (:func:`_burn`) live at each event-loop
boundary — completions inside the gate's ``window_s`` price the
rolling burn, and while it exceeds the gate's threshold new arrivals
shed through a token bucket (explicit ``serve_shed`` records), so the
same number that drives alerting drives load shedding.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional

__all__ = ["SLOSpec", "burn_rate_windows", "evaluate", "export_gauges",
           "log_record"]


@dataclass(frozen=True)
class SLOSpec:
    """One serving SLO: latency percentile target + availability.

    ``latency_target_s`` is the per-request latency bound (virtual
    seconds, arrival to completion); ``percentile`` is the percentile
    that must meet it for the stream to be *compliant*;
    ``availability`` is the fraction of individual requests that must
    meet it (its complement is the error budget); ``window_s`` is the
    rolling burn-rate window width in virtual seconds."""

    name: str = "default"
    latency_target_s: float = 0.5
    percentile: float = 99.0
    availability: float = 0.999
    window_s: float = 60.0

    def __post_init__(self):
        if not self.latency_target_s > 0:
            raise ValueError("latency_target_s must be > 0")
        if not 0 < self.percentile <= 100:
            raise ValueError("percentile must be in (0, 100]")
        if not 0 < self.availability < 1:
            raise ValueError("availability must be in (0, 1)")
        if not self.window_s > 0:
            raise ValueError("window_s must be > 0")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability

    @classmethod
    def from_dict(cls, d: Dict) -> "SLOSpec":
        known = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in known})

    def to_dict(self) -> Dict:
        return asdict(self)


def _percentile(values: List[float], q: float) -> Optional[float]:
    """np.percentile's default linear interpolation, stdlib-only."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return float(vs[0])
    rank = (q / 100.0) * (len(vs) - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(vs) - 1)
    frac = rank - lo
    return float(vs[lo] + (vs[hi] - vs[lo]) * frac)


def _completed_requests(events: Iterable[Dict], *,
                        kind: str = "serve_request",
                        latency_field: str = "latency_s",
                        time_field: str = "done_v") -> List[Dict]:
    return [e for e in events
            if e.get("kind") == kind
            and e.get(time_field) is not None
            and e.get(latency_field) is not None]


def _violates(rec: Dict, spec: SLOSpec, *,
              latency_field: str = "latency_s") -> bool:
    return float(rec[latency_field]) > spec.latency_target_s


def _burn(bad: int, total: int, budget: float) -> float:
    error_rate = (bad / total) if total else 0.0
    if budget <= 0:
        return math.inf if bad else 0.0
    return error_rate / budget


def burn_rate_windows(events: Iterable[Dict], spec: SLOSpec, *,
                      kind: str = "serve_request",
                      latency_field: str = "latency_s",
                      time_field: str = "done_v") -> List[Dict]:
    """Tile the stream's completion-time (``time_field``) span with
    ``spec.window_s``-wide windows and compute the burn rate in each.
    Empty stream -> ``[]``; a degenerate span (every request completing
    at the same instant) is one window.  Windows with zero completions
    report burn 0.0 — no traffic burns no budget.

    The defaults are the serving shape (``serve_request`` /
    ``latency_s`` / ``done_v``); a wait-time SLO over a fleet stream is
    the SAME math with ``kind="fleet_wait", latency_field="wait_s"``."""
    reqs = _completed_requests(events, kind=kind,
                               latency_field=latency_field,
                               time_field=time_field)
    if not reqs:
        return []
    times = [float(r[time_field]) for r in reqs]
    t0, t_end = min(times), max(times)
    n_win = max(1, int(math.ceil((t_end - t0) / spec.window_s)) or 1)
    if t0 + n_win * spec.window_s <= t_end:  # endpoint lands on edge
        n_win += 1
    windows = []
    for k in range(n_win):
        w0 = t0 + k * spec.window_s
        w1 = w0 + spec.window_s
        members = [r for r in reqs if w0 <= float(r[time_field]) < w1
                   or (k == n_win - 1 and float(r[time_field]) == w1)]
        bad = sum(1 for r in members
                  if _violates(r, spec, latency_field=latency_field))
        total = len(members)
        windows.append({
            "t0": w0, "t1": w1, "total": total, "bad": bad,
            "error_rate": (bad / total) if total else 0.0,
            "burn_rate": _burn(bad, total, spec.error_budget),
        })
    return windows


def evaluate(events: Iterable[Dict], spec: SLOSpec, *,
             kind: str = "serve_request",
             latency_field: str = "latency_s",
             time_field: str = "done_v") -> Dict:
    """Whole-stream SLO verdict for one spec.

    Returns totals, whole-stream and worst-window burn rates, the
    achieved latency at ``spec.percentile``, a ``compliant`` bit
    (achieved percentile within target — the SLO statement itself),
    and ``goodput_qps`` (SLO-meeting completions per virtual second of
    the stream's completion span).  An empty stream is vacuously
    compliant with zero burn.  ``kind`` / ``latency_field`` /
    ``time_field`` retarget the same math at any record family that
    stamps a completion time and a latency-like value — e.g. a
    wait-time SLO over ``fleet_wait`` records (``latency_field=
    "wait_s"``), which is how apps/fleetsim.py scores each pool
    size."""
    events = list(events)
    reqs = _completed_requests(events, kind=kind,
                               latency_field=latency_field,
                               time_field=time_field)
    windows = burn_rate_windows(reqs, spec, kind=kind,
                                latency_field=latency_field,
                                time_field=time_field)
    total = len(reqs)
    bad = sum(1 for r in reqs
              if _violates(r, spec, latency_field=latency_field))
    good = total - bad
    latencies = [float(r[latency_field]) for r in reqs]
    achieved = _percentile(latencies, spec.percentile)
    span = (max(float(r[time_field]) for r in reqs)) if reqs else 0.0
    return {
        "spec": spec.to_dict(),
        "total": total,
        "good": good,
        "violations": bad,
        "error_rate": (bad / total) if total else 0.0,
        "error_budget": spec.error_budget,
        "burn_rate": _burn(bad, total, spec.error_budget),
        "max_window_burn_rate": max(
            (w["burn_rate"] for w in windows), default=0.0),
        "windows": len(windows),
        "achieved_percentile_s": achieved,
        "compliant": bool(achieved is None
                          or achieved <= spec.latency_target_s),
        "goodput_qps": (good / span) if span > 0 else 0.0,
    }


def export_gauges(metrics, result: Dict) -> None:
    """Publish an :func:`evaluate` result as ``ff_slo_*`` gauges on a
    live MetricsExporter (no-op when ``metrics`` is None).  Infinite
    burn rates are dropped by the exporter's finite-only contract."""
    if metrics is None:
        return
    metrics.update(
        slo_burn_rate=result["burn_rate"],
        slo_max_window_burn_rate=result["max_window_burn_rate"],
        slo_error_rate=result["error_rate"],
        slo_goodput_qps=result["goodput_qps"],
        slo_compliant=1.0 if result["compliant"] else 0.0)
    metrics.write()


def log_record(olog, result: Dict) -> None:
    """Mirror an :func:`evaluate` result into the obs stream as one
    ``slo`` record (flat fields; the spec nested under ``spec``)."""
    olog.event("slo", **result)
