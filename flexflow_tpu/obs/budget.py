"""Step-time budget decomposition and the MFU waterfall — the accounting
layer that turns "MFU is low" into a ranked list of levers.

``hlo_profile.roofline_report`` already computes where this compiled
program's MFU *ceiling* sits (the HBM/MXU floor), and ``fit()``'s sampled
op-timing mode measures real sections and per-op shard times — but
nothing accounted a real step into the cost families the FlexFlow
simulator prices per op (compute, communication, data movement,
synchronization).  This module is that accounting:

  * :func:`build_step_budget` — decompose one (sampled) step's wall time
    into named buckets: ``compute`` (isolated per-op shard timings plus
    the optimizer section), ``comm`` (collective/communication time),
    ``input_stall`` (the prefetcher's residual stall, amortized),
    ``host_sync`` (print/guard boundary syncs, amortized),
    ``checkpoint`` (save+verify, amortized) and ``residual`` (what no
    instrument claimed).  Buckets are allocated greedily against the
    wall clock and clamped, so they are non-negative and **provably sum
    to exactly the wall step time** (residual absorbs the remainder;
    raw pre-clamp values are kept alongside for honesty).  ``fit()``
    emits the result as one ``step_budget`` obs record per run, strictly
    post-loop — every input is either an existing measurement or an
    amortized total, zero new per-step host syncs;
  * :func:`mfu_waterfall` — join a run's ``step_budget`` record with its
    ``compile`` record (post-fusion FLOPs/bytes) and the chip roofline:
    achieved MFU at the measured wall, then the MFU recovered by
    removing each bucket in descending-size order, ending at the
    roofline ceiling.  The top row is the next perf PR's biggest lever;
  * :func:`render_waterfall` — the human table behind
    ``python -m flexflow_tpu.apps.report budget``.

Bucket sources are recorded per bucket (``sources``): ``comm`` prefers
the simulator's collective pricing of the loaded strategy (the paper's
per-op cost model, ``StrategySearch.cost_breakdown``) and falls back to
the measured section residual (fwd+bwd section minus the isolated per-op
compute sum); a bucket with no instrument reads 0 with source "none".
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

# allocation priority: earlier buckets claim wall time first; residual
# absorbs whatever remains.  Compute leads — it is the best-instrumented
# bucket — and the externally-amortized costs trail.
BUCKET_ORDER = ("compute", "comm", "input_stall", "host_sync",
                "checkpoint")


def build_step_budget(wall_s: float,
                      compute_s: Optional[float] = None,
                      comm_s: Optional[float] = None,
                      input_stall_s: Optional[float] = None,
                      host_sync_s: Optional[float] = None,
                      checkpoint_s: Optional[float] = None,
                      sources: Optional[Dict[str, str]] = None,
                      n_samples: int = 0) -> Dict:
    """The ``step_budget`` obs record body.  ``wall_s`` is the measured
    step wall time; each bucket argument is that family's raw estimate
    in seconds (None = no instrument, treated as 0 with source "none").

    Invariant (tests/test_budget.py): every bucket is >= 0 and the
    buckets INCLUDING ``residual`` sum to exactly ``wall_s`` — raw
    estimates are clamped to the remaining unallocated wall time in
    :data:`BUCKET_ORDER` priority, so an over-counting instrument (e.g.
    isolated op timings that exceed the fused step) cannot push the sum
    past the clock.  Clamped buckets are listed in ``clamped`` and their
    pre-clamp values kept in ``raw``."""
    wall_s = max(float(wall_s), 0.0)
    raw = {"compute": compute_s, "comm": comm_s,
           "input_stall": input_stall_s, "host_sync": host_sync_s,
           "checkpoint": checkpoint_s}
    buckets: Dict[str, float] = {}
    clamped: List[str] = []
    remaining = wall_s
    for name in BUCKET_ORDER:
        v = max(float(raw[name] or 0.0), 0.0)
        if v > remaining:
            clamped.append(name)
            v = remaining
        buckets[name] = v
        remaining -= v
    buckets["residual"] = remaining
    srcs = dict(sources or {})
    for name in BUCKET_ORDER:
        srcs.setdefault(name, "none" if raw[name] is None else "measured")
    return {
        "step_wall_s": wall_s,
        "buckets": buckets,
        "raw": {k: (None if v is None else float(v))
                for k, v in raw.items()},
        "clamped": clamped,
        "sources": srcs,
        "n_samples": int(n_samples),
    }


def check_budget(rec: Dict, tol: float = 1e-9) -> List[str]:
    """Violations of the budget invariant (empty = sound): buckets
    present, non-negative, and summing to <= step wall time (within
    float tolerance)."""
    errors: List[str] = []
    wall = rec.get("step_wall_s")
    buckets = rec.get("buckets")
    if not isinstance(wall, (int, float)) or wall < 0:
        return ["step_wall_s must be a non-negative number"]
    if not isinstance(buckets, dict):
        return ["buckets must be a dict"]
    total = 0.0
    for name, v in buckets.items():
        if not isinstance(v, (int, float)) or v < -tol:
            errors.append(f"bucket {name!r} must be non-negative, "
                          f"got {v!r}")
            continue
        total += max(float(v), 0.0)
    if total > wall + max(tol, wall * 1e-6):
        errors.append(f"buckets sum to {total} > step wall {wall}")
    return errors


# ---------------------------------------------------------------------------
# the MFU waterfall: budget x roofline ceiling


def _latest(events: Iterable[Dict], kind: str) -> Optional[Dict]:
    found = None
    for e in events:
        if e.get("kind") == kind:
            found = e
    return found


def mfu_waterfall(events: Iterable[Dict], perf=None) -> Optional[Dict]:
    """Join a run's ``step_budget`` record with its ``compile`` record
    (post-fusion FLOPs / bytes) and the chip roofline into the waterfall:

      achieved MFU at the measured wall
        -> MFU after removing bucket 1 (the largest)
        -> ... (each bucket, descending seconds)
        -> roofline ceiling (the HBM/MXU floor of THIS compiled program)

    ``rows`` lists the removable buckets largest-first with the MFU
    reached when that bucket (and every larger one) is removed —
    ``rows[0]`` is the biggest lever.  The ``compute`` bucket is only
    removable down to the roofline floor; its excess is listed as
    ``compute_overhead``.  Returns None when the stream has no
    ``step_budget`` record; MFU fields are None (seconds-only waterfall)
    when the compile record carries no cost analysis."""
    events = list(events)
    budget = _latest(events, "step_budget")
    if budget is None:
        return None
    wall = float(budget.get("step_wall_s") or 0.0)
    buckets = dict(budget.get("buckets") or {})
    compile_rec = _latest(events, "compile") or {}
    flops = float(compile_rec.get("flops") or 0.0)
    bytes_ = float(compile_rec.get("bytes_accessed") or 0.0)
    devices = 1
    for e in events:
        if e.get("kind") == "run_start" and e.get("devices"):
            devices = int(e["devices"])
    if perf is None:
        from flexflow_tpu.sim.cost_model import TpuChipPerf

        perf = TpuChipPerf()
    peak = perf.peak_flops * max(devices, 1)
    hbm = perf.hbm_bandwidth * max(devices, 1)

    floor_s = None
    mfu_ceiling = None
    if flops > 0:
        floor_s = max(flops / peak, bytes_ / hbm)
        mfu_ceiling = flops / floor_s / peak if floor_s > 0 else None

    def mfu_at(seconds: float) -> Optional[float]:
        if flops <= 0 or seconds <= 0:
            return None
        v = flops / seconds / peak
        # the floor is the honest limit; measurement jitter must not
        # report "above ceiling"
        return min(v, mfu_ceiling) if mfu_ceiling else v

    compute = float(buckets.get("compute", 0.0))
    compute_floor = min(compute, floor_s) if floor_s is not None \
        else compute
    removable = {k: float(v) for k, v in buckets.items() if k != "compute"}
    overhead = compute - compute_floor
    if overhead > 0:
        removable["compute_overhead"] = overhead
    rows = []
    remaining = wall
    for name, secs in sorted(removable.items(), key=lambda kv: -kv[1]):
        remaining -= secs
        rows.append({"bucket": name, "seconds": secs,
                     "share_of_step": secs / wall if wall > 0 else 0.0,
                     "mfu_after": mfu_at(remaining)})
    out = {
        "step_wall_s": wall,
        "buckets": buckets,
        "sources": budget.get("sources") or {},
        "n_samples": budget.get("n_samples", 0),
        "devices": devices,
        "flops_per_step": flops or None,
        "bytes_per_step": bytes_ or None,
        "floor_s": floor_s,
        "mfu": mfu_at(wall),
        "mfu_ceiling": mfu_ceiling,
        "rows": rows,
    }
    summary = _latest(events, "summary")
    if summary and summary.get("images_per_sec"):
        out["images_per_sec"] = summary["images_per_sec"]
    return out


def _fmt_s(s: float) -> str:
    return f"{s * 1e3:.3f} ms" if s < 1.0 else f"{s:.3f} s"


def _pct(v: Optional[float]) -> str:
    return f"{100.0 * v:5.1f}%" if v is not None else "    ?"


def render_waterfall(wf: Dict) -> List[str]:
    """The human MFU waterfall table (``report budget``)."""
    lines = [f"== MFU waterfall =="]
    head = (f"  step {_fmt_s(wf['step_wall_s'])}"
            + (f", {wf['devices']} devices" if wf.get("devices") else ""))
    if wf.get("images_per_sec"):
        head += f", {wf['images_per_sec']:.1f} items/s"
    if wf.get("n_samples"):
        head += f" ({wf['n_samples']} sampled steps)"
    lines.append(head)
    if wf.get("mfu") is not None:
        lines.append(f"  achieved MFU {_pct(wf['mfu'])}  "
                     f"(ceiling {_pct(wf['mfu_ceiling'])} at the "
                     f"{_fmt_s(wf['floor_s'])} roofline floor)")
    else:
        lines.append("  (no compiled cost analysis in the stream: "
                     "seconds-only waterfall, MFU columns omitted)")
    lines.append(f"  {'remove bucket':<18s} {'seconds':>12s} "
                 f"{'of step':>8s} {'MFU after':>10s}")
    for r in wf["rows"]:
        lines.append(
            f"  {r['bucket']:<18s} {_fmt_s(r['seconds']):>12s} "
            f"{100.0 * r['share_of_step']:>7.1f}% "
            f"{_pct(r['mfu_after']):>10s}")
    srcs = wf.get("sources") or {}
    noted = {k: v for k, v in sorted(srcs.items()) if v != "measured"}
    if noted:
        lines.append("  sources: " + ", ".join(
            f"{k}={v}" for k, v in noted.items()))
    biggest = wf["rows"][0] if wf.get("rows") else None
    if biggest and biggest["seconds"] > 0:
        lines.append(f"  biggest lever: {biggest['bucket']} "
                     f"({_fmt_s(biggest['seconds'])}/step)")
    return lines
