"""Unified run-telemetry subsystem: one structured, machine-readable event
stream for training, strategy search, and audit/bench.

The reference FlexFlow's only instruments are per-task cudaEvent prints and
Legion ``-lg:prof`` traces (SURVEY §5); this repo already measures more
(OpProfiler, XProf traces, rooflines, the compiled-HLO collective audit)
but each instrument spoke its own dialect — free-form ``fit()`` prints, a
single final dict from ``StrategySearch.search()``, a bench JSON line
fished out of mixed stdout.  This package gives them ONE record schema:

  * every record is one JSON object per line (JSONL), stamped with the
    run id and a host wall-clock timestamp:
    ``{"run": <id>, "ts": <epoch s>, "kind": <str>, ...}``;
  * ``kind`` names the record family.  Core families: ``run_start``,
    ``counter``, ``gauge``, ``timer``, plus the surface records —
    ``compile`` / ``step`` / ``summary`` / ``checkpoint_save`` /
    ``checkpoint_restore`` / ``sim_drift`` (training, model.py::fit),
    ``search_space`` / ``search_chunk`` / ``search_result`` /
    ``search_breakdown`` / ``pipeline_candidate`` / ``pipeline_decision``
    (sim/search.py), ``hlo_audit`` / ``bench`` (audit/bench), the
    execution-performance pair (round 6) — ``regrid_plan`` (the regrid
    planner's coalescing/hop accounting, parallel/regrid.py) and
    ``prefetch`` (device-prefetch stall residual, data/prefetch.py) —
    the MFU-waterfall pair (observability round 3): ``step_budget``
    (one step's wall time decomposed into compute / comm / input_stall /
    host_sync / checkpoint / residual buckets summing to the wall,
    obs/budget.py) and ``metrics`` (a mirror of each live-gauge snapshot
    the Prometheus exporter published, obs/metrics.py) —
    and the fault-tolerance family (robustness round): ``fault`` (an
    injected fault firing, a health-guard divergence detection, or a
    refused non-finite checkpoint), ``rollback`` (guard-driven restore
    of the last verified checkpoint), ``recovery`` (a clean window after
    rollback, or a read succeeding after retries), ``data_fault``
    (retried/skipped data reads, data/hdf5.py + data/imagenet.py),
    ``ckpt_fallback`` (restore cascading past a corrupt step,
    utils/checkpoint.py) and ``thread_leak`` (a worker join that timed
    out at shutdown);
  * :class:`RunLog` is the thread-safe sink; :class:`NullRunLog` (the
    module-level ``NULL``) is the disabled sink whose every method is a
    no-op, so instrumented code pays one predicate/attribute check when
    ``FFConfig.obs_dir`` is unset.  Event files are capped: when the
    current file reaches ``max_bytes`` the stream rolls over to a
    monotonically numbered sibling (``run.jsonl.1``, ``.2``, ...), so a
    long training run with per-op sampling enabled cannot grow one
    unbounded file;
  * :func:`read_events` is the single-file reader, :func:`run_files` /
    :func:`read_run` walk a rotated stream in write order;
    ``apps/report.py`` renders a run back into the summary tables humans
    read today, and ``obs/trace.py`` exports per-op timelines as
    Chrome/Perfetto traces with sim-vs-real drift attribution.

Telemetry is strictly OFF the device hot path: records carry host-side
timestamps only and no instrumentation site may introduce a device sync
(``fit()`` buffers per-step wall times and writes records after the timed
loop).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

SCHEMA_VERSION = 1

# default size cap of one event file before rollover (64 MB); 0 disables
# rotation.  FFConfig.obs_max_bytes overrides per run.
DEFAULT_MAX_BYTES = 64 * 1024 * 1024


def new_run_id() -> str:
    """Sortable, collision-resistant run id: wall time + pid + 2 random
    bytes (two runs in the same second on the same host stay distinct)."""
    return "%s-%x-%s" % (time.strftime("%Y%m%d-%H%M%S"), os.getpid(),
                         os.urandom(2).hex())


class NullRunLog:
    """The disabled sink: every method is a no-op and ``enabled`` is
    False, so hot-path call sites cost one attribute check.  A single
    module-level instance (``NULL``) is shared."""

    enabled = False
    path = None
    run_id = None

    def event(self, kind: str, **fields) -> None:
        pass

    def counter(self, name: str, value: float = 1, **fields) -> None:
        pass

    def gauge(self, name: str, value: float, **fields) -> None:
        pass

    def timer(self, name: str, **fields):
        return contextlib.nullcontext()

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self) -> bool:
        return False


NULL = NullRunLog()


class RunLog:
    """Thread-safe JSONL event sink.

    One instance == one event stream (usually one file per run id; several
    surfaces of the same process — fit, search, bench — may share it, the
    ``surface`` field keeps them separable).  Writes are line-buffered and
    serialized under a lock, so concurrent emitters (e.g. data-loader
    threads) never interleave partial lines."""

    enabled = True

    def __init__(self, path: str, run_id: Optional[str] = None,
                 surface: str = "", meta: Optional[Dict[str, Any]] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        """``path`` is the stream's BASE file; once the current file
        reaches ``max_bytes`` (0 = never) writes continue in
        ``path.<n>`` with n increasing monotonically.  Re-opening an
        already-rotated stream resumes at its newest part."""
        self.path = path
        self.run_id = run_id or new_run_id()
        self.surface = surface
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._max_bytes = max(int(max_bytes or 0), 0)
        self._seq = 0
        while os.path.exists(f"{path}.{self._seq + 1}"):
            self._seq += 1
        self._f = open(self._part_path(), "a")
        self.event("run_start", schema=SCHEMA_VERSION,
                   **(dict(meta) if meta else {}))

    def _part_path(self) -> str:
        return self.path if self._seq == 0 else f"{self.path}.{self._seq}"

    # -- core emitters --------------------------------------------------

    def event(self, kind: str, **fields) -> None:
        rec = {"run": self.run_id, "ts": time.time(), "kind": kind}
        if self.surface:
            rec["surface"] = self.surface
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()
            if self._max_bytes and self._f.tell() >= self._max_bytes:
                # size-based rollover: close the full part, continue in
                # the next numbered sibling (readers walk run_files())
                self._f.close()
                self._seq += 1
                self._f = open(self._part_path(), "a")

    def counter(self, name: str, value: float = 1, **fields) -> None:
        self.event("counter", name=name, value=value, **fields)

    def gauge(self, name: str, value: float, **fields) -> None:
        self.event("gauge", name=name, value=value, **fields)

    @contextlib.contextmanager
    def timer(self, name: str, **fields):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.event("timer", name=name,
                       seconds=time.perf_counter() - t0, **fields)

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _jsonable(o):
    """Last-resort encoder: numpy/jax scalars -> python numbers, tuples of
    them inside payloads -> lists, everything else -> repr (a telemetry
    write must never raise into the instrumented surface)."""
    try:
        return o.item()  # numpy / jax scalar
    except AttributeError:
        pass
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    return repr(o)


def from_config(config, surface: str = "",
                meta: Optional[Dict[str, Any]] = None):
    """The one gate instrumented surfaces call: a live :class:`RunLog`
    when ``config.obs_dir`` is set (file ``<obs_dir>/<run_id>.jsonl``),
    else the shared ``NULL`` sink.  ``config.run_id`` (when set) names the
    run so several processes/surfaces can append to one stream."""
    obs_dir = getattr(config, "obs_dir", "") or ""
    if not obs_dir:
        return NULL
    run_id = getattr(config, "run_id", "") or new_run_id()
    return RunLog(os.path.join(obs_dir, f"{run_id}.jsonl"),
                  run_id=run_id, surface=surface, meta=meta,
                  max_bytes=getattr(config, "obs_max_bytes",
                                    DEFAULT_MAX_BYTES))


def run_files(path: str) -> list:
    """A run stream's files in write order: the base ``path`` plus its
    rotated parts ``path.1``, ``path.2``, ...  (``path`` itself may
    legitimately be missing when a caller points at a rotated part
    directly — only existing files are returned)."""
    out = [path] if os.path.exists(path) else []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        out.append(f"{path}.{i}")
        i += 1
    return out


def read_run(path: str) -> Iterator[Dict[str, Any]]:
    """All records of a possibly-rotated run stream, in write order."""
    for p in run_files(path):
        yield from read_events(p)


def read_events(path: str) -> Iterator[Dict[str, Any]]:
    """Yield the records of a run JSONL in file order.  Malformed lines
    (a crashed writer's torn tail) are skipped, not raised — readers must
    be able to render a partial run."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                yield rec
