"""Per-op timeline tracing: Chrome/Perfetto ``trace_event`` export for
simulated schedules and real training steps, with sim-vs-real drift
attribution — the op-level half of the cost-model recalibration loop.

The ``sim_drift`` gauge (obs PR 1) compares sim vs real at ONE scalar per
run; when the simulator is wrong it cannot say *which op or collective*
it mispredicted.  The native simulator computes the full per-point
schedule and used to discard it — ``ffsim_simulate_trace`` now exports it
(per-op/per-point compute intervals, per-hop transfers with payload
bytes, per-op parameter-sync terms), and ``fit()``'s sampled op-timing
mode produces the measured side (``op_time`` records).  This module turns
both into one artifact family:

  * :func:`sim_trace_events` / :func:`fit_trace_events` — Chrome
    ``trace_event`` lanes (``ph: "X"`` complete events, microsecond
    timestamps, ``process_name``/``thread_name`` metadata) from a
    :meth:`StrategySearch.simulate_trace` dict or from ``op_time`` obs
    records.  Several producers merge into one file (sim lanes next to
    real lanes) loadable in ``ui.perfetto.dev`` / ``chrome://tracing``;
  * :func:`chrome_trace` / :func:`write_trace` / :func:`validate_trace`
    — the JSON container and the schema check the tests enforce
    (required keys, non-negative durations, monotone per-device compute
    intervals);
  * :func:`drift_attribution` — the join: simulated vs measured per-op
    seconds, ranked by absolute drift contribution.  Its output
    (``drift_attribution.json``, written by ``apps/report.py trace``) is
    what ``apps/calibrate.py --from-obs`` consumes to refit per-kind
    anchors and collective constants without a manual probe run — the
    profile-then-attribute loop of Daydream (ATC'20) / Habitat (ATC'21).

``python -m flexflow_tpu.obs.trace --smoke`` builds a toy native graph,
exports its trace and validates it (the ``make trace-smoke`` target).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

_US = 1e6  # trace_event timestamps/durations are microseconds

# fixed pid assignment of the standard lanes; extra producers may pick
# any other pid — pids only have to be distinct within one file
PID_SIM_BEST = 0
PID_SIM_DP = 1
PID_REAL = 2
PID_SERVE = 3
PID_FLEET = 4


def meta_event(pid: int, name: str, tid: Optional[int] = None) -> Dict:
    ev = {"name": "thread_name" if tid is not None else "process_name",
          "ph": "M", "pid": pid, "args": {"name": name}}
    if tid is not None:
        ev["tid"] = tid
    return ev


def sim_trace_events(sim: Dict, pid: int = PID_SIM_BEST,
                     label: str = "sim") -> List[Dict]:
    """Chrome events for one simulated schedule (the dict
    :meth:`StrategySearch.simulate_trace` returns).  Lanes: one thread
    per device for compute intervals, one ``dev N recv`` thread per
    destination device for transfers (concurrent flows may overlap
    there), one ``param sync`` thread for the serialized sync terms."""
    events = [meta_event(pid, label)]
    named = set()

    def lane(tid: int, name: str):
        if tid not in named:
            named.add(tid)
            events.append(meta_event(pid, name, tid))

    for r in sim.get("events", []):
        args = {"op": r.get("op"), "op_kind": r.get("op_kind"),
                "seconds": r["dur"], "cfg": r.get("cfg")}
        if r["kind"] == "compute":
            tid = r["device"]
            lane(tid, f"dev {r['device']}")
            cat = "compute"
        elif r["kind"] == "transfer":
            tid = 1000 + r["dst_device"]
            lane(tid, f"dev {r['dst_device']} recv")
            cat = "transfer"
            args["bytes"] = r.get("bytes", 0.0)
            args["src_device"] = r.get("src_device")
        else:  # sync
            tid = 2000
            lane(tid, "param sync")
            cat = "sync"
        events.append({"name": str(r.get("op")), "cat": cat, "ph": "X",
                       "ts": r["start"] * _US, "dur": r["dur"] * _US,
                       "pid": pid, "tid": tid, "args": args})
    return events


def fit_trace_events(records: Iterable[Dict], pid: int = PID_REAL,
                     label: str = "real") -> List[Dict]:
    """Chrome events for the measured side: ``op_time`` obs records from
    a ``fit()`` run with op timing enabled.  Section samples (forward /
    backward / optimizer, per sampled step) lay out sequentially on one
    ``sections`` thread in record order; isolated per-op shard timings on
    an ``ops (isolated shard)`` thread.  Timestamps are synthetic
    cursors — the lanes show relative durations side by side with the
    simulated schedule, not wall-clock alignment.  Counter lanes
    (:func:`fit_counter_events`) ride along: per-step throughput from
    ``step`` records plus MFU / HBM bytes from ``metrics`` records,
    rendered by Perfetto as value-over-time tracks under the same
    process."""
    records = list(records)
    sections = [r for r in records if r.get("kind") == "op_time"
                and r.get("scope") == "section"]
    per_op = [r for r in records if r.get("kind") == "op_time"
              and r.get("scope") == "op"]
    events = [meta_event(pid, label)]
    if sections:
        events.append(meta_event(pid, "sections", 0))
        t = 0.0
        for r in sections:
            dur = float(r.get("seconds", 0.0))
            events.append({
                "name": str(r.get("section", "?")), "cat": "compute",
                "ph": "X", "ts": t * _US, "dur": dur * _US,
                "pid": pid, "tid": 0,
                "args": {"step": r.get("step"), "seconds": dur}})
            t += dur
    if per_op:
        events.append(meta_event(pid, "ops (isolated shard)", 1))
        t = 0.0
        for r in per_op:
            dur = float(r.get("seconds", 0.0))
            events.append({
                "name": str(r.get("op", "?")), "cat": "compute",
                "ph": "X", "ts": t * _US, "dur": dur * _US,
                "pid": pid, "tid": 1,
                "args": {"op_kind": r.get("op_kind"), "seconds": dur,
                         "measured": r.get("measured")}})
            t += dur
    events.extend(fit_counter_events(records, pid=pid))
    return events


def fit_counter_events(records: Iterable[Dict],
                       pid: int = PID_REAL) -> List[Dict]:
    """Perfetto **counter** lanes (``ph: "C"``) of a fit run's gauges on
    the run's own step-time axis:

      * ``imgs/s`` — per-step throughput from the ``step`` records,
        sampled at each step's cumulative wall time;
      * ``MFU`` and ``HBM bytes`` (live/peak) — from the ``metrics``
        records the exporter mirrors into the obs stream, positioned at
        the cumulative wall time of the step count each snapshot
        reports.

    Counter events carry their series values in ``args`` (Perfetto
    renders one track per arg key).  Empty when the stream has neither
    record kind."""
    records = list(records)
    steps = [r for r in records if r.get("kind") == "step"
             and isinstance(r.get("wall_ms"), (int, float))]
    metrics = [r for r in records if r.get("kind") == "metrics"]
    events: List[Dict] = []
    # cumulative wall-clock cursor per step (seconds), indexed by step
    # ordinal — the shared time axis of every counter lane
    cum: List[float] = [0.0]
    t = 0.0
    for r in steps:
        t += float(r["wall_ms"]) / 1e3
        cum.append(t)

    def at_step(n) -> float:
        try:
            n = int(n)
        except (TypeError, ValueError):
            return cum[-1]
        return cum[min(max(n, 0), len(cum) - 1)]

    for i, r in enumerate(steps):
        v = r.get("images_per_sec")
        if isinstance(v, (int, float)):
            events.append({"name": "imgs/s", "ph": "C", "pid": pid,
                           "tid": 0, "ts": cum[i + 1] * _US,
                           "args": {"imgs/s": float(v)}})
    for r in metrics:
        ts = at_step(r.get("steps_total", None)) * _US
        mfu = r.get("mfu")
        if isinstance(mfu, (int, float)):
            events.append({"name": "MFU", "ph": "C", "pid": pid,
                           "tid": 0, "ts": ts,
                           "args": {"mfu": float(mfu)}})
        hbm = {k: float(r[k]) for k in ("hbm_live_bytes",
                                        "hbm_peak_bytes")
               if isinstance(r.get(k), (int, float))}
        if hbm:
            events.append({"name": "HBM bytes", "ph": "C", "pid": pid,
                           "tid": 0, "ts": ts, "args": hbm})
    return events


def chrome_trace(*event_lists: Iterable[Dict]) -> Dict:
    """The ``trace_event`` JSON object (object-format container, the one
    Perfetto and chrome://tracing both load)."""
    events: List[Dict] = []
    for lst in event_lists:
        events.extend(lst)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(path: str, trace: Dict) -> str:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def validate_trace(trace: Any) -> List[str]:
    """Schema check for a ``trace_event`` object: required keys per
    event, non-negative timestamps/durations, non-overlapping (monotone)
    compute intervals per (pid, tid) lane, and — for counter events
    (``ph: "C"``) — an ``args`` dict of finite numeric series values.
    Returns the list of violations — empty means the trace is loadable
    and internally consistent.  Transfer lanes are exempt from the
    overlap check: concurrent flows into one device legitimately
    overlap."""
    import math

    errors: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
            trace.get("traceEvents"), list):
        return ["trace must be a dict with a traceEvents list"]
    lanes: Dict[tuple, List[tuple]] = {}
    for i, ev in enumerate(trace["traceEvents"]):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        for k in ("name", "ph", "pid"):
            if k not in ev:
                errors.append(f"event {i}: missing required key {k!r}")
        ph = ev.get("ph")
        if ph == "M":
            continue
        if ph != "C" and "tid" not in ev:
            errors.append(f"event {i}: missing required key 'tid'")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: ts must be a non-negative number")
            continue
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not args:
                errors.append(
                    f"event {i}: counter event needs a non-empty args "
                    f"dict of series values")
                continue
            for k, v in args.items():
                if not isinstance(v, (int, float)) \
                        or not math.isfinite(v):
                    errors.append(
                        f"event {i}: counter series {k!r} must be a "
                        f"finite number, got {v!r}")
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(
                    f"event {i}: X event needs non-negative dur")
                continue
            if ev.get("cat") == "compute":
                lanes.setdefault((ev.get("pid"), ev.get("tid")),
                                 []).append((ts, dur, i))
    for (pid, tid), iv in lanes.items():
        iv.sort()
        end = 0.0
        for ts, dur, i in iv:
            if ts < end - 1e-3:  # 1 ns slack in trace microseconds
                errors.append(
                    f"event {i}: compute intervals overlap on lane "
                    f"pid={pid} tid={tid} (start {ts} < prev end {end})")
            end = max(end, ts + dur)
    return errors


# ---------------------------------------------------------------------------
# serving lanes: per-request lifecycle + engine counters


def serve_trace_events(records: Iterable[Dict], pid: int = PID_SERVE,
                       label: str = "serve") -> List[Dict]:
    """Chrome events for one serve-engine run, from its
    ``serve_request`` / ``serve_batch`` obs records (virtual-clock
    timestamps, so the trace is bit-identical under a fixed seed).

    Lanes:

      * one thread per request (``req <rid>``): a ``queue`` span from
        arrival to admission, then a ``decode`` span from admission to
        completion carrying TTFT/TPOT/latency in ``args``.  Request
        cats are NOT ``compute`` — concurrent requests legitimately
        overlap across lanes and within a continuous batch;
      * ROUTED requests (a ``serve_handoff`` record exists for the
        rid, serve/router.py) split the lane into the full lifecycle:
        ``queue`` (arrival -> admit), a ``prefill`` span (admit ->
        first token, the prompt pass), a ``handoff`` flow arrow
        (``ph: "s"``/``"f"``) spanning the priced KV transfer, then
        the ``decode`` span from the handoff landing to completion;
      * admission flow arrows (``ph: "s"``/``"f"``): requests admitted
        at the same virtual instant are one continuous-batching
        admission group — the arrow runs from the group's first
        request lane to each other member;
      * counter lanes from ``serve_batch``: queue depth, active/
        admitted slots, and KV-cache occupancy (tokens + fraction of
        the ``max_batch x max_seq`` rectangle) over virtual time —
        per pool (``... [prefill]``/``... [decode]``) when the batch
        records carry pool labels;
      * resilience instants (``ph: "i"``, cat ``fault`` — never
        ``compute``, so the overlap check ignores them): per-request
        marks on the rid's lane for ``serve_retry`` / ``serve_fault``
        / ``kv_rebuild`` / ``serve_shed`` records (shed rids get a
        lane even though they never produce a ``serve_request``), and
        process-scoped ``replica_down`` marks on a dedicated
        ``replica faults`` lane.

    Timestamps are shifted so the earliest arrival lands at 0 (trace
    viewers and :func:`validate_trace` want non-negative ts)."""
    records = list(records)
    reqs = [r for r in records if r.get("kind") == "serve_request"]
    batches = [r for r in records if r.get("kind") == "serve_batch"]
    handoffs = {r.get("rid"): r for r in records
                if r.get("kind") == "serve_handoff"}
    marks = [r for r in records
             if r.get("kind") in ("serve_retry", "serve_fault",
                                  "kv_rebuild", "serve_shed")]
    downs = [r for r in records if r.get("kind") == "replica_down"]
    events = [meta_event(pid, label)]
    if not reqs and not batches and not marks and not downs:
        return events
    t0 = min([float(r["arrival_v"]) for r in reqs
              if r.get("arrival_v") is not None]
             + [float(b["vnow"]) for b in batches
                if b.get("vnow") is not None]
             + [float(m["vnow"]) for m in marks + downs
                if m.get("vnow") is not None] + [0.0])

    def ts(v: float) -> float:
        return (float(v) - t0) * _US

    tids: Dict[Any, int] = {}
    for r in reqs:
        rid = r.get("rid")
        if rid not in tids:
            tids[rid] = 10 + len(tids)
            events.append(meta_event(pid, f"req {rid}", tids[rid]))
        tid = tids[rid]
        arrival = r.get("arrival_v")
        admit = r.get("admit_v")
        done = r.get("done_v")
        if arrival is not None and admit is not None:
            events.append({
                "name": f"queue {rid}", "cat": "queue", "ph": "X",
                "ts": ts(arrival),
                "dur": max(0.0, (float(admit) - float(arrival)) * _US),
                "pid": pid, "tid": tid,
                "args": {"rid": rid,
                         "queue_wait_s": float(admit) - float(arrival)}})
        decode_args = {"rid": rid, "latency_s": r.get("latency_s"),
                       "ttft_s": r.get("ttft_s"),
                       "tpot_s": r.get("tpot_s"),
                       "prompt_len": r.get("prompt_len"),
                       "new_tokens": r.get("new_tokens")}
        ho = handoffs.get(rid)
        first = r.get("first_token_v")
        land = ho.get("handoff_v") if ho else None
        if ho is not None and admit is not None and done is not None \
                and first is not None and land is not None:
            # routed lifecycle: prefill span -> handoff flow arrow
            # (spanning the priced KV transfer) -> decode span.  Flow
            # ids live above 1_000_000 so they never collide with the
            # admission-group ids (which enumerate from 0).
            events.append({
                "name": f"prefill {rid}", "cat": "prefill", "ph": "X",
                "ts": ts(admit),
                "dur": max(0.0, (float(first) - float(admit)) * _US),
                "pid": pid, "tid": tid,
                "args": {"rid": rid, "prompt_len": r.get("prompt_len"),
                         "from_replica": ho.get("from_replica")}})
            flow_id = 1_000_000 + tid
            ho_args = {"rid": rid, "bytes": ho.get("bytes"),
                       "hops": ho.get("hops"),
                       "predicted_s": ho.get("predicted_s"),
                       "from_replica": ho.get("from_replica"),
                       "to_replica": ho.get("to_replica")}
            events.append({"name": "handoff", "cat": "handoff",
                           "ph": "s", "id": flow_id, "ts": ts(first),
                           "pid": pid, "tid": tid, "args": ho_args})
            events.append({"name": "handoff", "cat": "handoff",
                           "ph": "f", "bp": "e", "id": flow_id,
                           "ts": ts(land), "pid": pid, "tid": tid,
                           "args": ho_args})
            decode_args["to_replica"] = ho.get("to_replica")
            events.append({
                "name": f"decode {rid}", "cat": "decode", "ph": "X",
                "ts": ts(land),
                "dur": max(0.0, (float(done) - float(land)) * _US),
                "pid": pid, "tid": tid, "args": decode_args})
        elif admit is not None and done is not None:
            events.append({
                "name": f"decode {rid}", "cat": "decode", "ph": "X",
                "ts": ts(admit),
                "dur": max(0.0, (float(done) - float(admit)) * _US),
                "pid": pid, "tid": tid, "args": decode_args})
    # resilience marks: per-request fault/retry/rebuild/shed instants
    # on the rid's lane (allocated on demand — a shed request has no
    # serve_request record, but its refusal still deserves a mark)
    for m in marks:
        rid, vnow = m.get("rid"), m.get("vnow")
        if vnow is None:
            continue
        if rid not in tids:
            tids[rid] = 10 + len(tids)
            events.append(meta_event(pid, f"req {rid}", tids[rid]))
        args = {k: m.get(k) for k in
                ("rid", "reason", "attempt", "attempts", "delay_s",
                 "tokens", "to_replica", "burn_rate", "priority")
                if m.get(k) is not None}
        events.append({"name": m["kind"], "cat": "fault", "ph": "i",
                       "s": "t", "ts": ts(vnow), "pid": pid,
                       "tid": tids[rid], "args": args})
    # pool-level replica_down instants on a dedicated faults lane
    if downs:
        events.append(meta_event(pid, "replica faults", 9))
    for d in downs:
        vnow = d.get("vnow")
        if vnow is None:
            continue
        events.append({
            "name": f"replica_down {d.get('pool')}[{d.get('replica')}]",
            "cat": "fault", "ph": "i", "s": "p", "ts": ts(vnow),
            "pid": pid, "tid": 9,
            "args": {k: d.get(k) for k in
                     ("pool", "replica", "in_flight", "queued",
                      "restart_s") if d.get(k) is not None}})
    # admission groups -> flow arrows between member lanes
    groups: Dict[float, List[Dict]] = {}
    for r in reqs:
        if r.get("admit_v") is not None:
            groups.setdefault(float(r["admit_v"]), []).append(r)
    for flow_id, admit in enumerate(sorted(groups)):
        members = groups[admit]
        if len(members) < 2:
            continue  # a single admission needs no arrow
        head, rest = members[0], members[1:]
        events.append({"name": "admit", "cat": "admission", "ph": "s",
                       "id": flow_id, "ts": ts(admit), "pid": pid,
                       "tid": tids[head.get("rid")],
                       "args": {"batch": len(members)}})
        for m in rest:
            events.append({"name": "admit", "cat": "admission",
                           "ph": "f", "bp": "e", "id": flow_id,
                           "ts": ts(admit), "pid": pid,
                           "tid": tids[m.get("rid")],
                           "args": {"batch": len(members)}})
    for b in batches:
        vnow = b.get("vnow")
        if vnow is None:
            continue
        bts = ts(vnow)
        # disaggregated pools get their own counter tracks ("queue
        # depth [prefill]" / "[decode]"); single-pool runs keep the
        # plain names.
        pool = b.get("pool") or ""
        suffix = f" [{pool}]" if pool else ""
        if isinstance(b.get("queue_depth"), (int, float)):
            events.append({"name": f"queue depth{suffix}", "ph": "C",
                           "pid": pid, "tid": 0, "ts": bts,
                           "args": {"queued": float(b["queue_depth"])}})
        slots = {k: float(b[k]) for k in ("active", "admitted")
                 if isinstance(b.get(k), (int, float))}
        if slots:
            events.append({"name": f"slots{suffix}", "ph": "C",
                           "pid": pid, "tid": 0, "ts": bts,
                           "args": slots})
        kv = {k: float(b[k]) for k in ("kv_tokens", "kv_frac")
              if isinstance(b.get(k), (int, float))}
        if kv:
            events.append({"name": f"KV cache{suffix}", "ph": "C",
                           "pid": pid, "tid": 0, "ts": bts,
                           "args": kv})
    return events


# the fleet lifecycle states a trace lane renders (mirrors
# fleet.job.STATES + the historical "evicted" terminal; kept local so
# the obs layer stays importable without the fleet package)
STATES_ORDER = ("pending", "placing", "running", "draining", "resized",
                "done", "failed", "evicted")


def fleet_trace_events(records: Iterable[Dict],
                       pid: int = PID_FLEET,
                       label: str = "fleet") -> List[Dict]:
    """Perfetto lanes for one fleet coordinator run, from its
    ``fleet_job`` / ``fleet_rebalance`` / ``fleet_util`` obs records.

    Lanes:

      * one counter track per job (``job <name> devices``) sampled
        wherever its assignment is visible: ``fleet_job`` records
        carrying a ``devices`` field (admission, resize, completion —
        completion and eviction drop the track to 0) and
        ``fleet_rebalance`` moves (the post-move ``to`` length);
      * one LIFECYCLE thread per job (``job <name>``): an ``X`` span
        per state the job passes through (pending / placing / running
        / draining / resized), named by the state and spanning until
        the next transition; terminal ``done``/``failed`` is a
        zero-duration marker.  Lifecycle cats are not ``compute`` —
        the spans of different jobs legitimately overlap;
      * a ``coordinator`` thread with one zero-duration ``rebalance``
        marker per ``fleet_rebalance`` record, plus flow arrows
        (``ph: "s"``/``"f"``, ids from 2_000_000 — above the serving
        handoff range) from each rebalance to the first subsequent
        ``draining`` transition of every job it moves: the causal
        edge from the packing decision to the resizes it bought;
      * a ``pool util`` counter lane from the per-round ``fleet_util``
        records: average busy / resizing / idle device counts over
        each round span.

    The time axis prefers the records' virtual-clock ``vts`` stamps
    (bit-deterministic under a seed) and falls back to wall ``ts`` for
    pre-clock streams; everything is shifted so the earliest event
    lands at 0."""
    records = list(records)

    def tv(r) -> Optional[float]:
        v = r.get("vts", r.get("ts"))
        return float(v) if isinstance(v, (int, float)) else None

    samples: List[tuple] = []   # (t, job, devices) counter samples
    trail: Dict[str, List[tuple]] = {}   # job -> [(t, state)]
    rebalances: List[tuple] = []         # (t, rebalance_no, [jobs])
    utils: List[tuple] = []              # (t, busy, resizing, idle)
    job_order: List[str] = []
    for r in records:
        kind = r.get("kind")
        t = tv(r)
        if t is None:
            continue
        if kind == "fleet_job":
            job = r.get("job")
            devices = r.get("devices")
            state = r.get("state")
            if job is None:
                continue
            job = str(job)
            if job not in trail:
                trail[job] = []
                job_order.append(job)
            if state in STATES_ORDER:
                trail[job].append((t, state))
            if state in ("done", "failed", "evicted"):
                samples.append((t, job, 0.0))
            elif isinstance(devices, (int, float)):
                samples.append((t, job, float(devices)))
        elif kind == "fleet_rebalance":
            moved = []
            for mv in r.get("moves", []) or []:
                job = mv.get("job")
                to = mv.get("to")
                if job is not None and isinstance(to, list):
                    samples.append((t, str(job), float(len(to))))
                    moved.append(str(job))
            rebalances.append((t, r.get("rebalance"), moved))
        elif kind == "fleet_util":
            span = r.get("span_steps")
            if isinstance(span, (int, float)) and span > 0:
                utils.append((t,
                              float(r.get("busy_steps", 0)) / span,
                              float(r.get("resizing_steps", 0)) / span,
                              float(r.get("idle_steps", 0)) / span))
    events = [meta_event(pid, label)]
    times = ([s[0] for s in samples]
             + [t for ts_ in trail.values() for t, _ in ts_]
             + [t for t, _, _ in rebalances] + [t for t, *_ in utils])
    if not times:
        return events
    t0, t_end = min(times), max(times)

    def ts(t: float) -> float:
        return (t - t0) * _US

    # per-job device-occupancy counters (the original lanes)
    for t, job, devices in sorted(samples):
        events.append({"name": f"job {job} devices", "ph": "C",
                       "pid": pid, "tid": 0, "ts": ts(t),
                       "args": {"devices": devices}})
    # pool-utilization counter lane
    for t, busy, resizing, idle in sorted(utils):
        events.append({"name": "pool util", "ph": "C", "pid": pid,
                       "tid": 0, "ts": ts(t),
                       "args": {"busy": busy, "resizing": resizing,
                                "idle": idle}})
    # per-job lifecycle span lanes
    tids: Dict[str, int] = {}
    for job in job_order:
        tids[job] = 10 + len(tids)
        events.append(meta_event(pid, f"job {job}", tids[job]))
        walk = sorted(trail[job], key=lambda s: s[0])
        for i, (t, state) in enumerate(walk):
            if state in ("done", "failed", "evicted"):
                events.append({"name": state, "cat": "lifecycle",
                               "ph": "X", "ts": ts(t), "dur": 0.0,
                               "pid": pid, "tid": tids[job],
                               "args": {"job": job}})
                continue
            until = walk[i + 1][0] if i + 1 < len(walk) else t_end
            events.append({"name": state, "cat": "lifecycle",
                           "ph": "X", "ts": ts(t),
                           "dur": max(0.0, (until - t) * _US),
                           "pid": pid, "tid": tids[job],
                           "args": {"job": job}})
    # coordinator lane: rebalance markers + causal arrows to the
    # draining transitions each rebalance bought.  Flow ids from
    # 2_000_000 — above the serving handoff range, so merged
    # serve+fleet traces never collide.
    if rebalances:
        events.append(meta_event(pid, "coordinator", 1))
    flow_id = 2_000_000
    for t, number, moved in sorted(rebalances,
                                   key=lambda r: (r[0], str(r[1]))):
        events.append({"name": f"rebalance {number}", "cat": "sched",
                       "ph": "X", "ts": ts(t), "dur": 0.0, "pid": pid,
                       "tid": 1, "args": {"moves": len(moved)}})
        for job in moved:
            drains = [tj for tj, state in trail.get(job, [])
                      if state == "draining" and tj >= t]
            if not drains or job not in tids:
                continue
            args = {"job": job, "rebalance": number}
            events.append({"name": "move", "cat": "sched", "ph": "s",
                           "id": flow_id, "ts": ts(t), "pid": pid,
                           "tid": 1, "args": args})
            events.append({"name": "move", "cat": "sched", "ph": "f",
                           "bp": "e", "id": flow_id,
                           "ts": ts(min(drains)), "pid": pid,
                           "tid": tids[job], "args": args})
            flow_id += 1
    return events


# ---------------------------------------------------------------------------
# drift attribution: the sim-vs-real per-op join


def real_op_seconds(events: Iterable[Dict]) -> Dict[str, Dict]:
    """Measured per-op seconds from ``op_time`` obs records
    (``scope == "op"``): median over samples, op kind carried along.
    Genuinely measured samples outrank analytic stand-ins (records with
    ``measured: false`` — an unrealizable shard that fit() priced via the
    roofline), and the ``measured`` flag is surfaced so consumers like
    ``calibrate --from-obs`` can refuse to fit anchors on a stand-in
    (real/analytic would be exactly 1.0 — circular, not informative)."""
    samples: Dict[str, List[float]] = {}
    fallback: Dict[str, List[float]] = {}
    kinds: Dict[str, str] = {}
    for e in events:
        if e.get("kind") != "op_time" or e.get("scope") != "op":
            continue
        op = str(e.get("op"))
        sink = fallback if e.get("measured") is False else samples
        sink.setdefault(op, []).append(float(e.get("seconds", 0.0)))
        if e.get("op_kind"):
            kinds[op] = e["op_kind"]
    out = {}
    for op in set(samples) | set(fallback):
        vals = sorted(samples.get(op) or fallback.get(op) or [0.0])
        out[op] = {"seconds": vals[len(vals) // 2], "n": len(vals),
                   "op_kind": kinds.get(op),
                   "measured": op in samples}
    return out


def sim_op_seconds(events: Iterable[Dict]) -> Dict[str, Dict]:
    """Simulated per-op seconds from obs records: prefers ``sim_trace``
    records (written by ``apps/search.py -trace``, per-shard scheduled
    times), falls back to ``search_breakdown`` (compute + in-op
    collective per op).  Later records win — the newest search speaks for
    the strategy actually shipped."""
    out: Dict[str, Dict] = {}
    breakdown: Dict[str, Dict] = {}
    for e in events:
        if e.get("kind") == "sim_trace" and isinstance(
                e.get("op_s"), dict):
            for op, s in e["op_s"].items():
                out[str(op)] = {"seconds": float(s), "source": "sim_trace"}
        elif e.get("kind") == "search_breakdown":
            for row in e.get("ops", []):
                breakdown[str(row.get("op"))] = {
                    "seconds": float(row.get("compute_s", 0.0))
                    + float(row.get("collective_s", 0.0)),
                    "op_kind": row.get("kind"),
                    "compute_s": float(row.get("compute_s", 0.0)),
                    "collective_s": float(row.get("collective_s", 0.0)),
                    "source": "search_breakdown"}
    for op, row in breakdown.items():
        if op in out:
            out[op].setdefault("op_kind", row.get("op_kind"))
            out[op]["compute_s"] = row["compute_s"]
            out[op]["collective_s"] = row["collective_s"]
        else:
            out[op] = row
    return out


def drift_attribution(sim_ops: Dict[str, Dict],
                      real_ops: Dict[str, Dict],
                      step: Optional[Dict] = None) -> Dict:
    """Join simulated vs measured per-op seconds and rank ops by absolute
    drift contribution.  ``drift_s = real - sim`` (positive = the
    simulator is optimistic about this op, the round-4 falsification
    direction); ``share`` is each op's fraction of the total absolute
    drift.  Ops present on only one side are listed separately — an op
    the simulator prices but the sampler never measured (or vice versa)
    is a coverage gap, not zero drift."""
    rows = []
    for op in sorted(set(sim_ops) & set(real_ops)):
        sim_s = float(sim_ops[op]["seconds"])
        real_s = float(real_ops[op]["seconds"])
        rows.append({
            "op": op,
            "op_kind": sim_ops[op].get("op_kind")
            or real_ops[op].get("op_kind"),
            "sim_s": sim_s, "real_s": real_s,
            "drift_s": real_s - sim_s,
            "ratio": real_s / sim_s if sim_s > 0 else None,
            "measured": real_ops[op].get("measured", True)})
    total_abs = sum(abs(r["drift_s"]) for r in rows)
    for r in rows:
        r["share"] = abs(r["drift_s"]) / total_abs if total_abs else 0.0
    rows.sort(key=lambda r: -abs(r["drift_s"]))
    out = {
        "ops": rows,
        "totals": {
            "sim_s": sum(r["sim_s"] for r in rows),
            "real_s": sum(r["real_s"] for r in rows),
            "drift_s": sum(r["drift_s"] for r in rows),
            "abs_drift_s": total_abs,
        },
        "sim_only": sorted(set(sim_ops) - set(real_ops)),
        "real_only": sorted(set(real_ops) - set(sim_ops)),
    }
    if step:
        out["step"] = step
    return out


def trace_events_from_file(path: str) -> List[Dict]:
    """Events of an on-disk Chrome trace JSON (a ``*.trace.json`` the
    search wrote), for merging into a combined sim+real file."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and isinstance(obj.get("traceEvents"), list):
        return obj["traceEvents"]
    raise ValueError(f"{path}: not a trace_event JSON object")


# ---------------------------------------------------------------------------
# smoke entry (`make trace-smoke`)


def _smoke() -> int:
    """Toy 2-device, 2-op graph through ffsim_simulate_trace: op0 shards
    rows over both devices, op1 gathers them on device 0, so the trace
    must contain compute intervals on both devices plus one cross-device
    transfer; the exported total must equal ffsim_simulate."""
    from flexflow_tpu.sim.native import NativeSimulator

    ints = [2, 2, 2,
            # op0: no inputs, 1 config, 2 points (rows 0-2 on dev0,
            # rows 2-4 on dev1)
            0, 1, 2,
            0, 0, 2, 0, 1, 0, 1, 0, 1,
            1, 2, 4, 0, 1, 0, 1, 0, 1,
            # op1: consumes op0, 1 config, 1 point on dev0 needing all
            # 4 rows (rows 2-4 must cross from dev1)
            1, 0, 1, 1,
            0, 0, 4, 0, 1, 0, 1, 0, 1, 0, 4, 0, 1, 0, 1, 0, 1]
    dbls = [1.0, 1.0, 0.0,        # intra_bw, cross_bw, latency
            0.0, 0.0,             # param_bytes
            0.25, 0.5,            # compute per config
            1.0, 1.0,             # param_replicas
            0.0, 0.0]             # collective costs
    sim = NativeSimulator(ints, dbls, 2)
    records, total = sim.simulate_trace([0, 0])
    full = sim.simulate([0, 0])
    assert abs(total - full) < 1e-12, (total, full)
    xfers = [r for r in records if r["kind"] == "transfer"]
    assert len(xfers) == 1 and xfers[0]["bytes"] == 8.0, xfers
    wrapped = {"events": [
        {**r, "op": f"op{r['op']}", "op_kind": "Toy"} for r in records],
        "devices": 2}
    trace = chrome_trace(sim_trace_events(wrapped, label="sim:toy"))
    errors = validate_trace(trace)
    assert not errors, errors
    # the file round-trips through json (what Perfetto will parse)
    parsed = json.loads(json.dumps(trace))
    assert not validate_trace(parsed)
    print(f"ffsim trace smoke OK: {len(records)} records, "
          f"total {total:.3f}s, 1 cross-device transfer of 8 bytes")
    return 0


if __name__ == "__main__":
    import sys

    if "--smoke" in sys.argv[1:]:
        raise SystemExit(_smoke())
    print(__doc__.strip())
