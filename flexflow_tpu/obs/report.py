"""Render a run-telemetry JSONL (obs record schema) back into the summary
tables humans read today — the reader side of the obs subsystem.

``python -m flexflow_tpu.apps.report <run.jsonl>`` is the CLI wrapper.
Sections are emitted only for the record kinds actually present, so one
renderer serves fit runs, search runs, bench runs, and mixed streams.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

_SPARK = "▁▂▃▄▅▆▇█"


def _spark(values: List[float], width: int = 40) -> str:
    """Compact ascii curve of ``values`` (downsampled to ``width``)."""
    if not values:
        return ""
    if len(values) > width:
        step = len(values) / width
        values = [values[int(i * step)] for i in range(width)]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(int((v - lo) / (hi - lo) * (len(_SPARK) - 1)),
                   len(_SPARK) - 1)] for v in values)


def _fmt_s(s: float) -> str:
    return f"{s * 1e3:.3f} ms" if s < 1.0 else f"{s:.3f} s"


def _header(events: List[Dict]) -> List[str]:
    runs = sorted({e.get("run") for e in events if e.get("run")})
    surfaces = sorted({e.get("surface") for e in events
                       if e.get("surface")})
    ts = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    lines = [f"run: {', '.join(str(r) for r in runs) or '?'}"]
    if surfaces:
        lines.append(f"surfaces: {', '.join(surfaces)}")
    if ts:
        lines.append(f"records: {len(events)}, span: "
                     f"{max(ts) - min(ts):.1f}s")
    for e in events:
        if e.get("kind") == "run_start":
            extras = {k: v for k, v in e.items()
                      if k not in ("run", "ts", "kind", "surface",
                                   "schema")}
            if extras:
                lines.append("meta: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(extras.items())))
    return lines


def _fit_section(events: List[Dict]) -> List[str]:
    steps = [e for e in events if e.get("kind") == "step"]
    compiles = [e for e in events if e.get("kind") == "compile"]
    summaries = [e for e in events if e.get("kind") == "summary"]
    ckpts = [e for e in events
             if e.get("kind") in ("checkpoint_save", "checkpoint_restore")]
    drift = [e for e in events if e.get("kind") == "sim_drift"]
    no_drift = [e for e in events
                if e.get("kind") == "sim_drift_unavailable"]
    op_times = [e for e in events if e.get("kind") == "op_time"]
    if not (steps or compiles or summaries or op_times or drift
            or no_drift):
        return []
    lines = ["== training =="]
    for c in compiles:
        parts = [f"compile: {c.get('seconds', 0.0):.2f}s"]
        if c.get("flops"):
            parts.append(f"{c['flops']:.3e} FLOPs/step")
        if c.get("bytes_accessed"):
            parts.append(f"{c['bytes_accessed']:.3e} bytes/step")
        lines.append("  " + ", ".join(parts))
    if steps:
        walls = [e["wall_ms"] for e in steps if "wall_ms" in e]
        losses = [e["loss"] for e in steps if e.get("loss") is not None]
        lines.append(
            f"  steps: {len(steps)}"
            + (f", wall ms min/mean/max = {min(walls):.2f}/"
               f"{sum(walls) / len(walls):.2f}/{max(walls):.2f}"
               if walls else ""))
        if losses:
            lines.append(f"  loss: first {losses[0]:.4f} -> "
                         f"final {losses[-1]:.4f}   "
                         f"{_spark([float(l) for l in losses])}")
    for s in summaries:
        lines.append(
            f"  summary: {s.get('iterations', '?')} iters, "
            f"elapsed {s.get('elapsed_s', 0.0):.4f}s, "
            f"tp {s.get('images_per_sec', 0.0):.2f} images/s")
    for c in ckpts:
        lines.append(f"  {c['kind']}: step {c.get('step', '?')} "
                     f"({c.get('seconds', 0.0):.3f}s)")
    if op_times:
        sections = [e for e in op_times if e.get("scope") == "section"]
        per_op = [e for e in op_times if e.get("scope") == "op"]
        if sections:
            by_name: Dict[str, List[float]] = {}
            for e in sections:
                by_name.setdefault(str(e.get("section")), []).append(
                    float(e.get("seconds", 0.0)))
            parts = []
            for name in ("forward", "backward", "optimizer", "step"):
                vals = sorted(by_name.get(name, []))
                if vals:
                    parts.append(
                        f"{name} {_fmt_s(vals[len(vals) // 2])}")
            n_steps = len({e.get("step") for e in sections})
            lines.append(f"  op_time sections ({n_steps} sampled steps, "
                         f"median): " + ", ".join(parts))
        if per_op:
            lines.append(f"  op_time per-op (isolated shard, "
                         f"{len(per_op)} records):")
            rows = sorted(per_op, key=lambda e: -e.get("seconds", 0.0))
            for e in rows[:12]:
                mark = "" if e.get("measured") else "~"
                lines.append(
                    f"    {str(e.get('op', '?')):<18s} "
                    f"{str(e.get('op_kind', '?')):<14s} "
                    f"{mark}{_fmt_s(e.get('seconds', 0.0))}")
    for d in drift:
        lines.append(
            f"  sim_drift: predicted {_fmt_s(d.get('predicted_s', 0.0))} "
            f"vs measured {_fmt_s(d.get('measured_s', 0.0))} "
            f"-> ratio {d.get('value', 0.0):.3f} "
            f"[{d.get('source', '?')}]")
    for u in no_drift:
        # say WHY the gauge is missing — a silently absent sim_drift
        # reads as "no drift", which is exactly wrong
        lines.append("  sim_drift unavailable: "
                     f"{u.get('reason') or u.get('error') or '?'}")
    # execution-performance records (round 6)
    for r in (e for e in events if e.get("kind") == "regrid_plan"):
        lines.append(
            f"  regrid plan: {r.get('edges', 0)} edges "
            f"({r.get('noop_edges', 0)} coalesced no-ops, "
            f"{r.get('shared_edges', 0)} fan-out shared), "
            f"constraints {r.get('constraints_before', 0)} -> "
            f"{r.get('constraints_after', 0)}, predicted transfer "
            f"{_fmt_s(r.get('predicted_transfer_s', 0.0))} "
            f"(greedy {_fmt_s(r.get('greedy_transfer_s', 0.0))})")
    for p in (e for e in events if e.get("kind") == "prefetch"):
        lines.append(
            f"  prefetch: depth {p.get('depth', '?')}, "
            f"{p.get('batches', 0)} batches, input stall "
            f"{_fmt_s(p.get('input_stall_s', 0.0))}")
    # step-budget + live-metrics records (MFU waterfall round): one
    # summary line each; the full waterfall is `report budget`
    for b in (e for e in events if e.get("kind") == "step_budget"):
        bk = b.get("buckets") or {}
        wall = b.get("step_wall_s", 0.0) or 0.0
        parts = [f"{k} {_fmt_s(v)}"
                 for k, v in sorted(bk.items(), key=lambda kv: -kv[1])
                 if v > 0]
        lines.append(
            f"  step budget ({_fmt_s(wall)} wall, "
            f"{b.get('n_samples', 0)} samples): "
            + (", ".join(parts) if parts else "(all zero)")
            + "  [render: report budget]")
    mets = [e for e in events if e.get("kind") == "metrics"]
    if mets:
        m = mets[-1]
        parts = []
        if m.get("images_per_sec") is not None:
            parts.append(f"{m['images_per_sec']:.1f} items/s")
        if m.get("mfu") is not None:
            parts.append(f"mfu {m['mfu']:.4f}")
        if m.get("hbm_peak_bytes"):
            parts.append(f"hbm peak {m['hbm_peak_bytes'] / 1e9:.3f} GB")
        lines.append(f"  metrics export ({len(mets)} writes"
                     + (f", {m['path']}" if m.get("path") else "")
                     + "): " + (", ".join(parts) or "(no finite gauges)"))
    return lines


def _elastic_section(events: List[Dict]) -> List[str]:
    """The elastic-runtime records: device-loss detections/probes,
    resizes in BOTH directions (loss detected -> re-search time ->
    regrid bytes/hops -> steps lost; device return -> regrow),
    step hangs, preemption drains, fallbacks/refusals, rejoins, async
    checkpoint commits."""
    losses = [e for e in events if e.get("kind") == "device_loss"]
    probes = [e for e in events if e.get("kind") == "device_probe"]
    resizes = [e for e in events if e.get("kind") == "elastic_resize"]
    returns = [e for e in events if e.get("kind") == "device_return"]
    hangs = [e for e in events if e.get("kind") == "step_hang"]
    drains = [e for e in events if e.get("kind") == "preempt_drain"]
    fallbacks = [e for e in events if e.get("kind") == "elastic_fallback"]
    refused = [e for e in events if e.get("kind") == "elastic_refused"]
    rejoins = [e for e in events if e.get("kind") == "elastic_rejoin"]
    asyncs = [e for e in events if e.get("kind") == "ckpt_async"]
    if not (losses or resizes or returns or hangs or drains or fallbacks
            or refused or rejoins or asyncs):
        return []
    lines = ["== elastic =="]
    for d in losses:
        what = (f"dead ordinals {d['dead']}" if d.get("dead")
                else f"error {d.get('error', '?')!r}")
        lines.append(f"  device_loss[{d.get('classification', '?')}] at "
                     f"step {d.get('step', '?')}: {what} "
                     f"({d.get('live', '?')} live)")
    for h in hangs:
        lines.append(f"  step_hang at step {h.get('step', '?')}: "
                     f"deadline {_fmt_s(h.get('deadline_s', 0.0))} "
                     f"(estimate {_fmt_s(h.get('estimate_s', 0.0))}, "
                     f"factor {h.get('factor', '?')})")
    for r in returns:
        lines.append(f"  device_return at step {r.get('step', '?')}: "
                     f"ordinals {r.get('returned', '?')} back after "
                     f"{r.get('probes', '?')} probe(s)")
    dead_probes = [p for p in probes if p.get("outcome") == "dead"]
    trans_probes = [p for p in probes if p.get("outcome") == "transient"]
    regrow_probes = [p for p in probes
                     if p.get("outcome") in ("answering", "out")]
    if probes:
        lines.append(f"  probes: {len(dead_probes)} dead, "
                     f"{len(trans_probes)} transient recoveries"
                     + (f", {len(regrow_probes)} regrow"
                        if regrow_probes else ""))
    for f in fallbacks:
        lines.append(f"  fallback to checkpoint at step "
                     f"{f.get('step', '?')}: {f.get('reason', '?')}")
    for r in refused:
        lines.append(f"  REFUSED shrink at step {r.get('step', '?')}: "
                     f"{r.get('live', '?')} live < min-devices "
                     f"{r.get('min_devices', '?')}")
    for r in resizes:
        research = r.get("research") or {}
        regrid = ""
        if r.get("regrid_bytes") is not None:
            regrid = (f", regrid {r['regrid_bytes'] / 1e6:.2f} MB / "
                      f"{r.get('regrid_hops', 0)} hops")
        direction = r.get("direction") or (
            "grow" if r.get("to_devices", 0) > r.get("from_devices", 0)
            else "shrink")
        lines.append(
            f"  elastic_resize[{direction}]: "
            f"{r.get('from_devices', '?')} -> "
            f"{r.get('to_devices', '?')} devices at step "
            f"{r.get('step', '?')} (re-search "
            f"{_fmt_s(r.get('research_s', 0.0))} "
            f"[{research.get('mode', '?')}], migration "
            f"{r.get('migration', '?')}{regrid}, "
            f"{r.get('steps_lost', 0)} step(s) lost)")
    for d in drains:
        at = (f"checkpoint at step {d['ckpt_step']}"
              if d.get("ckpt_step") is not None else "no checkpoint")
        lines.append(
            f"  preempt_drain at step {d.get('step', '?')}: "
            f"{d.get('steps_completed', '?')} step(s) completed, {at} "
            f"({_fmt_s(d.get('seconds', 0.0))} of "
            f"{_fmt_s(d.get('budget_s', 0.0))} budget, mode "
            f"{d.get('mode', '?')})")
    for r in rejoins:
        lines.append(f"  rejoin: step {r.get('step', '?')} on "
                     f"{r.get('devices', '?')} devices "
                     f"(from {r.get('dir', '?')})")
    if asyncs:
        commits = sorted(float(a.get("commit_s", 0.0)) for a in asyncs)
        lines.append(
            f"  async checkpoints: {len(asyncs)} commits, median "
            f"submit->commit {_fmt_s(commits[len(commits) // 2])}")
    return lines


def _fault_section(events: List[Dict]) -> List[str]:
    """The fault-tolerance records (robustness round): injected faults,
    guard detections, rollbacks, recoveries, data retries/skips,
    checkpoint fallbacks, leaked worker threads."""
    faults = [e for e in events if e.get("kind") == "fault"]
    rollbacks = [e for e in events if e.get("kind") == "rollback"]
    recoveries = [e for e in events if e.get("kind") == "recovery"]
    data_faults = [e for e in events if e.get("kind") == "data_fault"]
    fallbacks = [e for e in events if e.get("kind") == "ckpt_fallback"]
    leaks = [e for e in events if e.get("kind") == "thread_leak"]
    if not (faults or rollbacks or recoveries or data_faults or fallbacks
            or leaks):
        return []
    lines = ["== faults / recovery =="]
    for f in faults:
        where = ""
        if f.get("step") is not None:
            where = f" at step {f['step']}"
        elif f.get("occurrence") is not None:
            where = f" (occurrence {f['occurrence']})"
        detail = ""
        if f.get("value") is not None:
            detail = f", loss={f['value']}"
        elif f.get("site"):
            detail = f", site={f['site']}"
        lines.append(f"  fault[{f.get('source', '?')}]: "
                     f"{f.get('fault', '?')}{where}{detail}")
    retries = [d for d in data_faults if d.get("action") == "retry"]
    if retries:
        srcs = sorted({str(d.get("source")) for d in retries})
        lines.append(f"  data retries: {len(retries)} "
                     f"({', '.join(srcs)})")
    for d in data_faults:
        if d.get("action") == "skip":
            lines.append(
                f"  data skip[{d.get('source', '?')}]: "
                f"{d.get('file') or 'batch range'} "
                f"(skip #{d.get('skips', '?')}: {d.get('error', '?')})")
    for c in fallbacks:
        skipped = c.get("skipped") or []
        why = "; ".join(f"step {s.get('step')}: {s.get('reason')}"
                        for s in skipped if isinstance(s, dict))
        lines.append(f"  ckpt_fallback: step {c.get('from_step', '?')} -> "
                     f"{c.get('to_step', '?')}" + (f" ({why})" if why
                                                   else ""))
    for r in rollbacks:
        lines.append(f"  rollback: iteration {r.get('from_step', '?')} -> "
                     f"checkpoint step {r.get('to_step', '?')}")
    for r in recoveries:
        after = r.get("after", "?")
        spot = (f"step {r['step']}" if r.get("step") is not None
                else f"{r.get('failures', '?')} failures")
        lines.append(f"  recovery[{r.get('source', '?')}]: after {after} "
                     f"({spot})")
    for l in leaks:
        lines.append(f"  thread leak: {l.get('source', '?')} (join timed "
                     f"out after {l.get('timeout_s', '?')}s)")
    return lines


def _search_section(events: List[Dict]) -> List[str]:
    space = [e for e in events if e.get("kind") == "search_space"]
    gates = [e for e in events if e.get("kind") == "plan_gate"]
    chunks = [e for e in events if e.get("kind") == "search_chunk"]
    blocks = [e for e in events if e.get("kind") == "search_block"]
    stitches = [e for e in events if e.get("kind") == "search_stitch"]
    results = [e for e in events if e.get("kind") == "search_result"]
    breakdown = [e for e in events if e.get("kind") == "search_breakdown"]
    pipes = [e for e in events if e.get("kind") == "pipeline_decision"]
    if not (space or gates or chunks or blocks or stitches or results):
        return []
    lines = ["== strategy search =="]
    for s in space:
        lines.append(
            f"  space: {s.get('ops', '?')} ops, "
            f"{s.get('candidates', '?')} candidates "
            f"({s.get('axis_options_pruned', 0)} axis options pruned, "
            f"{s.get('mem_rejected', 0)} HBM-rejected)")
    for g in gates:
        by = g.get("by_code") or {}
        lines.append(
            f"  plan gate: {g.get('checked', '?')} candidate grids "
            f"checked, {g.get('rejected', 0)} rejected pre-sim"
            + (f" ({', '.join(f'{k}={v}' for k, v in sorted(by.items()))})"
               if by else ""))
    if chunks:
        curve = [c["best_time_s"] for c in chunks if "best_time_s" in c]
        acc = sum(c.get("accepted", 0) for c in chunks)
        prop = sum(c.get("proposed", 0) for c in chunks)
        pps = [c["proposals_per_sec"] for c in chunks
               if c.get("proposals_per_sec")]
        if curve:
            lines.append(
                f"  best-cost curve ({len(curve)} chunks): "
                f"{_fmt_s(curve[0])} -> {_fmt_s(curve[-1])}   "
                f"{_spark(curve)}")
        lines.append(
            f"  acceptance: {acc}/{prop} "
            f"({100.0 * acc / prop if prop else 0.0:.1f}%)"
            + (f", {sum(pps) / len(pps):,.0f} proposals/s" if pps else ""))
    if blocks:
        searched = [b for b in blocks if not b.get("memo")]
        memoed = [b for b in blocks if b.get("memo")]
        lines.append(
            f"  blocks: {len(blocks)} ({len(searched)} searched, "
            f"{len(memoed)} memo replays)")
        for b in searched[:12]:
            reps = b.get("repeats", 1)
            lines.append(
                f"    {str(b.get('block', '?')):<14s} "
                f"{b.get('ops', '?'):>3} ops"
                + (f" x{reps:<3d}" if reps and reps > 1 else "     ")
                + f" {b.get('accepted', 0)}/{b.get('proposed', 0)} "
                f"accepted -> {_fmt_s(b.get('best_time_s') or 0.0)}")
        if len(searched) > 12:
            lines.append(f"    ... {len(searched) - 12} more searched "
                         f"block(s)")
    for st in stitches:
        lines.append(
            f"  stitch: {st.get('blocks', '?')} blocks "
            f"({st.get('unique_blocks', '?')} unique, "
            f"{st.get('memo_hits', 0)} memo hits) -> "
            f"{_fmt_s(st.get('stitched_time_s', 0.0))}, "
            f"{st.get('boundary_ops', 0)} boundary ops "
            f"(regrid {_fmt_s(st.get('boundary_regrid_s', 0.0))}), "
            f"refine {st.get('refined_proposed', 0)}/"
            f"{st.get('refine_iters', 0)} -> "
            f"{_fmt_s(st.get('best_time_s', 0.0))}"
            + (" [budget hit]" if st.get("budget_hit") else ""))
    for r in results:
        lines.append(
            f"  result: dp {_fmt_s(r.get('dp_time_s', 0.0))}, "
            f"best {_fmt_s(r.get('best_time_s', 0.0))} "
            f"({r.get('speedup_vs_dp', 0.0):.3f}x vs DP)")
        cache = r.get("cost_cache")
        if cache:
            tot = cache.get("hits", 0) + cache.get("misses", 0)
            lines.append(
                f"  cost cache: {cache.get('hits', 0)}/{tot} hits "
                f"({100.0 * cache.get('hits', 0) / tot if tot else 0.0:.1f}%)")
    for b in breakdown:
        ops = sorted(b.get("ops", []),
                     key=lambda o: -(o.get("compute_s", 0.0)
                                     + o.get("collective_s", 0.0)))
        lines.append(f"  winning strategy, per-op cost "
                     f"(top {min(len(ops), 12)} of {len(ops)}):")
        lines.append(f"    {'op':<18s} {'kind':<14s} {'grid':<14s} "
                     f"{'compute':>10s} {'collective':>10s}")
        for o in ops[:12]:
            lines.append(
                f"    {str(o.get('op', '?')):<18s} "
                f"{str(o.get('kind', '?')):<14s} "
                f"{str(tuple(o.get('dims', ()))):<14s} "
                f"{_fmt_s(o.get('compute_s', 0.0)):>10s} "
                f"{_fmt_s(o.get('collective_s', 0.0)):>10s}")
        if b.get("opt_stream_s"):
            lines.append(f"    optimizer param stream: "
                         f"{_fmt_s(b['opt_stream_s'])}")
    for p in pipes:
        lines.append(
            f"  pipeline: {'ACCEPT' if p.get('accepted') else 'REJECT'}"
            + (f" S={p['best'].get('stages')} "
               f"M={p['best'].get('microbatches')} "
               f"tp={p['best'].get('tp')}" if p.get("best") else "")
            + f" (ref {_fmt_s(p.get('reference_time_s', 0.0))})")
    return lines


def _latency_histogram(lat: List[float], buckets: int = 10) -> List[str]:
    """Fixed-width latency histogram lines: one row per bucket with its
    bound, count, and a proportional bar — the ``report serve``
    rendering of the smoke's obs stream."""
    if not lat:
        return []
    lo, hi = min(lat), max(lat)
    span = (hi - lo) or max(hi, 1e-9)
    counts = [0] * buckets
    for v in lat:
        counts[min(int((v - lo) / span * buckets), buckets - 1)] += 1
    peak = max(counts)
    lines = []
    for i, c in enumerate(counts):
        hi_edge = lo + span * (i + 1) / buckets
        bar = "█" * int(round(24 * c / peak)) if peak else ""
        lines.append(f"    <= {_fmt_s(hi_edge):>10s}  {c:>5d}  {bar}")
    return lines


def _serve_section(events: List[Dict]) -> List[str]:
    """The serving-runtime records: per-request latencies (histogram +
    percentiles), batch occupancy, autoscale resizes, the run summary."""
    reqs = [e for e in events if e.get("kind") == "serve_request"]
    batches = [e for e in events if e.get("kind") == "serve_batch"]
    resizes = [e for e in events if e.get("kind") == "serve_resize"]
    summaries = [e for e in events if e.get("kind") == "serve_summary"]
    handoffs = [e for e in events if e.get("kind") == "serve_handoff"]
    refetches = [e for e in events if e.get("kind") == "kv_refetch"]
    routers = [e for e in events if e.get("kind") == "router_summary"]
    retries = [e for e in events if e.get("kind") == "serve_retry"]
    faults = [e for e in events if e.get("kind") == "serve_fault"]
    rebuilds = [e for e in events if e.get("kind") == "kv_rebuild"]
    sheds = [e for e in events if e.get("kind") == "serve_shed"]
    downs = [e for e in events if e.get("kind") == "replica_down"]
    if not (reqs or batches or resizes or summaries or handoffs
            or refetches or routers or retries or faults or rebuilds
            or sheds or downs):
        return []
    lines = ["== serving =="]
    lat = sorted(float(e["latency_s"]) for e in reqs
                 if e.get("latency_s") is not None)
    if lat:
        def pct(q):
            return lat[min(int(q / 100.0 * len(lat)), len(lat) - 1)]
        lines.append(
            f"  requests: {len(reqs)} completed, latency p50 "
            f"{_fmt_s(pct(50))} / p90 {_fmt_s(pct(90))} / p99 "
            f"{_fmt_s(pct(99))} (min {_fmt_s(lat[0])}, max "
            f"{_fmt_s(lat[-1])})")
        ttft = sorted(float(e["ttft_s"]) for e in reqs
                      if e.get("ttft_s") is not None)
        tpot = sorted(float(e["tpot_s"]) for e in reqs
                      if e.get("tpot_s") is not None)
        if ttft:
            def tpct(vals, q):
                return vals[min(int(q / 100.0 * len(vals)),
                                len(vals) - 1)]
            line = (f"  ttft: p50 {_fmt_s(tpct(ttft, 50))} / p99 "
                    f"{_fmt_s(tpct(ttft, 99))}")
            if tpot:
                line += (f", tpot: p50 {_fmt_s(tpct(tpot, 50))} / p99 "
                         f"{_fmt_s(tpct(tpot, 99))}")
            lines.append(line)
        lines.append("  latency histogram (virtual seconds):")
        lines.extend(_latency_histogram(lat))
    if batches:
        occ = [float(b.get("active", 0)) for b in batches]
        admitted = sum(int(b.get("admitted", 0)) for b in batches)
        lines.append(
            f"  batches: {len(batches)} steps, {admitted} admissions, "
            f"occupancy mean {sum(occ) / len(occ):.1f} / max "
            f"{max(occ):.0f}   {_spark(occ)}")
        # disaggregated runs label each serve_batch with its pool —
        # break the stream down per pool (queue depth, slot occupancy,
        # step time), the per-pool view the router's split exists for
        pools = sorted({b.get("pool") for b in batches if b.get("pool")})
        for pool in pools:
            pb = [b for b in batches if b.get("pool") == pool]
            pocc = [float(b.get("active", 0)) for b in pb]
            pq = [float(b.get("queue_depth", 0)) for b in pb]
            pst = [float(b["step_time_s"]) for b in pb
                   if b.get("step_time_s") is not None]
            step_part = f", step {_fmt_s(pst[0])}" if pst else ""
            lines.append(
                f"  pool[{pool}]: {len(pb)} steps, occupancy mean "
                f"{sum(pocc) / len(pocc):.1f} / max {max(pocc):.0f}, "
                f"queue depth mean {sum(pq) / len(pq):.1f} / max "
                f"{max(pq):.0f}{step_part}   {_spark(pocc)}")
    if handoffs:
        hb = sum(float(h.get("bytes", 0.0)) for h in handoffs)
        hs = [float(h.get("predicted_s", 0.0)) for h in handoffs]
        lines.append(
            f"  handoffs: {len(handoffs)} prefill->decode "
            f"({hb / 1e6:.2f} MB KV moved, mean "
            f"{_fmt_s(sum(hs) / len(hs))}/handoff), "
            f"{len(refetches)} kv_refetch(es)")
    elif refetches:
        lines.append(f"  kv_refetches: {len(refetches)}")
    for d in downs:
        lines.append(
            f"  replica_down[{d.get('pool', '?')}"
            f"[{d.get('replica', '?')}]] at v="
            f"{_fmt_s(d.get('vnow') or 0.0)}: "
            f"{d.get('in_flight', 0)} in-flight re-prefill, "
            f"{d.get('queued', 0)} queued retransmit, restart "
            f"{_fmt_s(d.get('restart_s') or 0.0)}")
    if retries or rebuilds or faults:
        by_reason: Dict[str, int] = {}
        for r in retries:
            reason = str(r.get("reason", "?"))
            by_reason[reason] = by_reason.get(reason, 0) + 1
        reason_part = ", ".join(f"{k} x{v}"
                                for k, v in sorted(by_reason.items()))
        lines.append(
            f"  resilience: {len(retries)} serve_retry "
            f"({reason_part or 'none'}), {len(rebuilds)} kv_rebuild "
            f"(re-prefilled sessions), {len(faults)} serve_fault "
            f"(retry budget exhausted)")
    if sheds:
        burns = [float(s.get("burn_rate", 0.0)) for s in sheds]
        lines.append(
            f"  shed: {len(sheds)} arrival(s) refused by the SLO-burn "
            f"admission gate (burn {min(burns):.2f}x..{max(burns):.2f}x"
            f" over threshold) — explicit serve_shed, not drops")
    for r in routers:
        pools = r.get("pools") or {}
        pool_part = ", ".join(
            f"{k}: {v.get('replicas', '?')}x{v.get('devices', 0) // max(v.get('replicas', 1), 1)}dev"
            for k, v in sorted(pools.items()))
        resil_part = ""
        if any(r.get(k) for k in ("retries", "kv_rebuilds",
                                  "replica_down", "shed", "failed")):
            resil_part = (
                f", {r.get('replica_down', 0)} replica(s) down, "
                f"{r.get('retries', 0)} retry(ies), "
                f"{r.get('kv_rebuilds', 0)} rebuild(s), "
                f"{r.get('shed', 0)} shed, "
                f"{r.get('failed', 0)} failed")
        lines.append(
            f"  router: {r.get('completed', 0)}/{r.get('requests', 0)} "
            f"served across {pool_part or '?'}, "
            f"{r.get('handoffs', 0)} handoff(s), "
            f"{r.get('affinity_hits', 0)} affinity hit(s), "
            f"{r.get('kv_refetches', 0)} refetch(es)" + resil_part
            + (", drained" if r.get("drained") else ""))
    for r in resizes:
        research = r.get("research") or {}
        lines.append(
            f"  serve_resize[{r.get('direction', '?')}]: "
            f"{r.get('from_devices', '?')} -> {r.get('to_devices', '?')} "
            f"devices at step {r.get('step', '?')} (queue depth "
            f"{r.get('queue_depth', '?')}, idle streak "
            f"{r.get('idle_streak', '?')}, re-search "
            f"{_fmt_s(r.get('research_s', 0.0))} "
            f"[{research.get('mode', '?')}])")
    for s in summaries:
        ttft_part = ""
        if s.get("ttft_p50_s") is not None:
            ttft_part = (f", ttft p50 {_fmt_s(s.get('ttft_p50_s', 0.0))}"
                         f", tpot p50 {_fmt_s(s.get('tpot_p50_s') or 0.0)}")
        lines.append(
            f"  summary: {s.get('completed', 0)}/{s.get('requests', 0)} "
            f"served ({s.get('unserved', 0)} unserved, "
            f"{s.get('dropped', 0)} dropped), qps "
            f"{s.get('qps', 0.0):.1f}, p50 {_fmt_s(s.get('p50_s', 0.0))},"
            f" p99 {_fmt_s(s.get('p99_s', 0.0))}{ttft_part}, "
            f"{s.get('resizes', 0)} resize(s), "
            f"{s.get('devices', '?')} devices"
            + (", drained" if s.get("drained") else ""))
    return lines


def _slo_section(events: List[Dict]) -> List[str]:
    """The SLO / load-harness records: per-spec burn-rate verdicts
    (``slo``) and sustained-load sweep points (``loadtest``)."""
    slos = [e for e in events if e.get("kind") == "slo"]
    points = [e for e in events if e.get("kind") == "loadtest"]
    if not (slos or points):
        return []
    lines = ["== slo / loadtest =="]
    for s in slos:
        spec = s.get("spec") or {}
        ach = s.get("achieved_percentile_s")
        lines.append(
            f"  slo[{spec.get('name', '?')}]: p{spec.get('percentile')} "
            f"<= {_fmt_s(spec.get('latency_target_s') or 0.0)} @ "
            f"{spec.get('availability')} -> "
            f"{'COMPLIANT' if s.get('compliant') else 'VIOLATED'} "
            f"(achieved {_fmt_s(ach) if ach is not None else '?'}, "
            f"burn {s.get('burn_rate', 0.0):.2f}x, worst window "
            f"{s.get('max_window_burn_rate', 0.0):.2f}x over "
            f"{s.get('windows', 0)} window(s), goodput "
            f"{s.get('goodput_qps', 0.0):.1f} qps)")
    for p in points:
        lines.append(
            f"  loadtest[{p.get('pattern', '?')}] {p.get('devices', '?')}"
            f" device(s): {p.get('completed', '?')}/"
            f"{p.get('requests', '?')} served, qps "
            f"{p.get('qps', 0.0):.1f} (offered "
            f"{p.get('offered_qps', 0.0):.1f}), p50 "
            f"{_fmt_s(p.get('p50_s') or 0.0)}, p99 "
            f"{_fmt_s(p.get('p99_s') or 0.0)}, ttft p50 "
            f"{_fmt_s(p.get('ttft_p50_s') or 0.0)}, goodput "
            f"{p.get('goodput_qps', 0.0):.1f} qps")
    return lines


def _audit_bench_section(events: List[Dict]) -> List[str]:
    audits = [e for e in events if e.get("kind") == "hlo_audit"]
    benches = [e for e in events if e.get("kind") == "bench"]
    if not (audits or benches):
        return []
    lines = ["== audit / bench =="]
    for a in audits:
        lines.append(
            f"  hlo_audit[{a.get('plan', '?')}]: "
            f"searched {a.get('searched_cross_mb', '?')} MB cross-tier "
            f"vs DP {a.get('dp_cross_mb', '?')} MB -> "
            f"{'CONSISTENT' if a.get('consistent') else 'CONTRADICTED'}")
    for b in benches:
        extras = ""
        if b.get("mfu") is not None:
            extras += f", mfu {b['mfu']}"
        if b.get("mfu_ceiling") is not None:
            extras += f" (ceiling {b['mfu_ceiling']})"
        if b.get("hbm_peak_gb") is not None:
            extras += f", hbm {b['hbm_peak_gb']} GB"
        shares = ", ".join(f"{k[:-5]} {100.0 * b[k]:.1f}%"
                           for k in ("comm_frac", "stall_frac")
                           if isinstance(b.get(k), (int, float)))
        if shares:
            extras += f", shares: {shares}"
        lines.append(
            f"  bench: {b.get('metric', '?')} = {b.get('value', '?')} "
            f"{b.get('unit', '')} (vs_baseline {b.get('vs_baseline', '?')}"
            + extras + ")")
    return lines


def _lint_section(events: List[Dict]) -> List[str]:
    lints = [e for e in events if e.get("kind") == "lint"]
    if not lints:
        return []
    lines = ["== lint =="]
    for rec in lints:
        lines.append(
            f"  verifier[{rec.get('model', '?')}]: "
            f"{rec.get('error', 0)} error(s), "
            f"{rec.get('warning', 0)} warning(s), "
            f"{rec.get('exempted', 0)} exempted")
        for f in rec.get("findings", []) or []:
            lines.append(f"    {f.get('severity')} "
                         f"[{f.get('pass_name')}:{f.get('code')}] "
                         f"{f.get('message')}")
        pred = rec.get("predicted")
        if pred:
            lines.append(
                f"    predicted: searched {pred.get('searched_pred_s')} s"
                f" vs dp {pred.get('dp_pred_s')} s "
                f"({pred.get('mode')}) -> "
                f"{'CONSISTENT' if pred.get('consistent') else 'CONTRADICTED'}")
    return lines


def _trace_section(events: List[Dict]) -> List[str]:
    traces = [e for e in events if e.get("kind") == "sim_trace"]
    if not traces:
        return []
    lines = ["== traces =="]
    for t in traces:
        lines.append(
            f"  sim trace: {t.get('path', '?')} "
            f"(best {_fmt_s(t.get('total_s', 0.0))} vs dp "
            f"{_fmt_s(t.get('dp_total_s', 0.0))}; open in "
            f"ui.perfetto.dev)")
    return lines


def _fleet_section(events: List[Dict]) -> List[str]:
    """The coordinator's view: per-job lifecycle trails, wait
    decompositions (``fleet_wait``), each arbiter packing, each
    executed rebalance, the device-second utilization account
    (``fleet_util``), fleet-simulation sweep points (``fleetsim``),
    and the final fleet summary.  Renders merged multi-job streams
    (coordinator + per-job subdirs) as readily as the coordinator's
    stream alone."""
    jobs = [e for e in events if e.get("kind") == "fleet_job"]
    placements = [e for e in events
                  if e.get("kind") == "fleet_placement"]
    rebalances = [e for e in events
                  if e.get("kind") == "fleet_rebalance"]
    summaries = [e for e in events if e.get("kind") == "fleet_summary"]
    waits = [e for e in events if e.get("kind") == "fleet_wait"]
    utils = [e for e in events if e.get("kind") == "fleet_util"]
    sims = [e for e in events if e.get("kind") == "fleetsim"]
    if not (jobs or placements or rebalances or summaries or waits
            or utils or sims):
        return []
    lines = ["== fleet =="]
    trail: Dict[str, List[str]] = {}
    workload: Dict[str, str] = {}
    for e in jobs:
        jid = str(e.get("job"))
        if e.get("workload"):
            workload[jid] = str(e["workload"])
        states = trail.setdefault(jid, [])
        st = str(e.get("state"))
        if not states or states[-1] != st:
            states.append(st)
    for jid in sorted(trail):
        wl = f" ({workload[jid]})" if jid in workload else ""
        lines.append(f"  job {jid}{wl}: " + " -> ".join(trail[jid]))
    for p in placements:
        lines.append(f"  placement #{p.get('pack', '?')}: "
                     f"sizes {p.get('sizes')} (demands "
                     f"{p.get('demands')}, pool {p.get('pool')})")
    for r in rebalances:
        moves = ", ".join(
            f"{m.get('job')} {len(m.get('from') or [])}->"
            f"{len(m.get('to') or [])}" for m in r.get("moves") or [])
        lines.append(f"  rebalance #{r.get('rebalance', '?')}: {moves}")
    for w in waits:
        lines.append(
            f"  wait {w.get('job', '?')}: "
            f"wait {_fmt_s(w.get('wait_s') or 0.0)} + place "
            f"{_fmt_s(w.get('placement_s') or 0.0)} + run "
            f"{_fmt_s(w.get('run_s') or 0.0)} + drain "
            f"{_fmt_s(w.get('drain_s') or 0.0)} + resize "
            f"{_fmt_s(w.get('resize_s') or 0.0)} = "
            f"{_fmt_s(w.get('total_s') or 0.0)} ({w.get('state', '?')})")
    if utils:
        busy = sum(int(u.get("busy_steps") or 0) for u in utils)
        idle = sum(int(u.get("idle_steps") or 0) for u in utils)
        rsz = sum(int(u.get("resizing_steps") or 0) for u in utils)
        cap = busy + idle + rsz
        lines.append(
            f"  util: {len(utils)} round(s), {busy} busy + {idle} idle "
            f"+ {rsz} resizing device-step(s)"
            + (f" -> {100.0 * busy / cap:.1f}% busy" if cap else ""))
    for p in sims:
        slo = p.get("slo_compliant")
        lines.append(
            f"  fleetsim[pool {p.get('pool', '?')}]: "
            f"{p.get('jobs_done', '?')}/{p.get('jobs', '?')} job(s) "
            f"done, util {100.0 * (p.get('util') or 0.0):.1f}%, wait "
            f"p50 {_fmt_s(p.get('wait_p50_s') or 0.0)} p99 "
            f"{_fmt_s(p.get('wait_p99_s') or 0.0)}, "
            f"{p.get('rebalances', 0)} rebalance(s), churn "
            f"{p.get('churn_devices', 0)} device(s), wait-slo "
            + ("?" if slo is None
               else ("COMPLIANT" if slo else "VIOLATED")))
    if summaries:
        s = summaries[-1]
        lines.append(
            f"  summary: {len(s.get('jobs') or [])} job(s) "
            f"{s.get('by_state')}, {s.get('rebalances', 0)} "
            f"rebalance(s), {s.get('packs', 0)} packing(s), "
            f"{s.get('native_prices', 0)} native + "
            f"{s.get('proxy_prices', 0)} proxy price(s), pool "
            f"{s.get('pool_devices')}")
    return lines


def _misc_section(events: List[Dict]) -> List[str]:
    known = {"run_start", "compile", "step", "summary", "checkpoint_save",
             "checkpoint_restore", "sim_drift", "sim_drift_unavailable",
             "op_time", "sim_trace", "search_space", "plan_gate",
             "search_chunk", "search_result", "search_breakdown",
             "pipeline_candidate", "pipeline_decision", "hlo_audit",
             "bench", "regrid_plan", "prefetch",
             "step_budget", "metrics",
             "fault", "rollback", "recovery", "data_fault",
             "ckpt_fallback", "thread_leak",
             "device_loss", "device_probe", "elastic_resize",
             "elastic_fallback", "elastic_refused", "elastic_rejoin",
             "device_return", "step_hang", "preempt_drain",
             "ckpt_async", "lint",
             "serve_request", "serve_batch", "serve_resize",
             "serve_summary", "serve_handoff", "kv_refetch",
             "router_summary", "serve_fault", "serve_retry",
             "kv_rebuild", "serve_shed", "replica_down",
             "fleet_job", "fleet_placement", "fleet_rebalance",
             "fleet_summary", "fleet_wait", "fleet_util", "fleetsim"}
    lines = []
    for e in events:
        kind = e.get("kind")
        if kind in known:
            continue
        if kind == "counter":
            lines.append(f"  counter {e.get('name')}: {e.get('value')}")
        elif kind == "gauge":
            lines.append(f"  gauge {e.get('name')}: {e.get('value')}")
        elif kind == "timer":
            lines.append(f"  timer {e.get('name')}: "
                         f"{_fmt_s(e.get('seconds', 0.0))}")
        else:
            body = {k: v for k, v in e.items()
                    if k not in ("run", "ts", "surface")}
            lines.append(f"  {body}")
    return (["== other records =="] + lines) if lines else []


def render(events: Iterable[Dict]) -> str:
    """One human-readable report of a run's event stream."""
    events = list(events)
    if not events:
        return "(empty run log)"
    sections = [_header(events), _fit_section(events),
                _fault_section(events), _elastic_section(events),
                _serve_section(events), _slo_section(events),
                _fleet_section(events),
                _search_section(events),
                _audit_bench_section(events), _lint_section(events),
                _trace_section(events), _misc_section(events)]
    return "\n".join("\n".join(s) for s in sections if s)


def render_file(path: str) -> str:
    from flexflow_tpu.obs import read_events

    return render(read_events(path))


def _median(values: List[float]) -> float:
    values = sorted(values)
    return values[len(values) // 2] if values else 0.0


def summarize(events: Iterable[Dict]) -> Dict:
    """The machine-readable counterpart of :func:`render` (the report
    CLI's ``--json`` output): one JSON-serializable object per stream so
    CI and bench tooling consume fields instead of scraping prose.  Only
    sections whose record kinds are present appear."""
    events = list(events)
    kinds: Dict[str, int] = {}
    for e in events:
        kinds[str(e.get("kind"))] = kinds.get(str(e.get("kind")), 0) + 1
    out: Dict = {
        "runs": sorted({str(e["run"]) for e in events if e.get("run")}),
        "surfaces": sorted({e["surface"] for e in events
                            if e.get("surface")}),
        "records": len(events),
        "kinds": kinds,
    }
    meta = {}
    for e in events:
        if e.get("kind") == "run_start":
            meta.update({k: v for k, v in e.items()
                         if k not in ("run", "ts", "kind", "surface",
                                      "schema")})
    if meta:
        out["meta"] = meta
    steps = [e for e in events if e.get("kind") == "step"]
    summaries = [e for e in events if e.get("kind") == "summary"]
    compiles = [e for e in events if e.get("kind") == "compile"]
    if steps or summaries or compiles:
        walls = [e["wall_ms"] for e in steps if "wall_ms" in e]
        losses = [e["loss"] for e in steps if e.get("loss") is not None]
        tr: Dict = {"steps": len(steps)}
        if compiles:
            tr["compile_s"] = compiles[0].get("seconds", 0.0)
            if compiles[0].get("flops"):
                tr["flops_per_step"] = compiles[0]["flops"]
        if walls:
            tr["wall_ms"] = {"min": min(walls),
                             "mean": sum(walls) / len(walls),
                             "max": max(walls)}
        if losses:
            tr["loss"] = {"first": float(losses[0]),
                          "final": float(losses[-1])}
        if summaries:
            s = summaries[-1]
            tr["elapsed_s"] = s.get("elapsed_s", 0.0)
            tr["images_per_sec"] = s.get("images_per_sec", 0.0)
        out["training"] = tr
    drift = [e for e in events if e.get("kind") == "sim_drift"]
    if drift:
        d = drift[-1]
        out["sim_drift"] = {"value": d.get("value"),
                            "predicted_s": d.get("predicted_s"),
                            "measured_s": d.get("measured_s"),
                            "source": d.get("source"),
                            "n": len(drift)}
    no_drift = [e for e in events
                if e.get("kind") == "sim_drift_unavailable"]
    if no_drift:
        out["sim_drift_unavailable"] = [
            e.get("reason") or e.get("error") or "?" for e in no_drift]
    op_times = [e for e in events if e.get("kind") == "op_time"]
    if op_times:
        sections = [e for e in op_times if e.get("scope") == "section"]
        per_op = [e for e in op_times if e.get("scope") == "op"]
        ot: Dict = {}
        if sections:
            by_name: Dict[str, List[float]] = {}
            for e in sections:
                by_name.setdefault(str(e.get("section")), []).append(
                    float(e.get("seconds", 0.0)))
            ot["sections_median_s"] = {k: _median(v)
                                       for k, v in by_name.items()}
            ot["sampled_steps"] = len({e.get("step") for e in sections})
        if per_op:
            ot["ops"] = {str(e.get("op")): {
                "seconds": e.get("seconds"),
                "op_kind": e.get("op_kind"),
                "measured": e.get("measured")} for e in per_op}
        out["op_time"] = ot
    space = [e for e in events if e.get("kind") == "search_space"]
    gates = [e for e in events if e.get("kind") == "plan_gate"]
    chunks = [e for e in events if e.get("kind") == "search_chunk"]
    blocks = [e for e in events if e.get("kind") == "search_block"]
    stitches = [e for e in events if e.get("kind") == "search_stitch"]
    results = [e for e in events if e.get("kind") == "search_result"]
    if space or gates or chunks or blocks or stitches or results:
        se: Dict = {}
        if space:
            se["space"] = {k: space[-1].get(k) for k in
                           ("ops", "candidates", "axis_options_pruned",
                            "mem_rejected", "devices", "cost_model")}
        if gates:
            se["plan_gate"] = {k: gates[-1].get(k) for k in
                               ("checked", "rejected", "mem_rejected",
                                "by_code")}
        if chunks:
            curve = [c["best_time_s"] for c in chunks
                     if "best_time_s" in c]
            acc = sum(c.get("accepted", 0) for c in chunks)
            prop = sum(c.get("proposed", 0) for c in chunks)
            se["chunks"] = len(chunks)
            if curve:
                se["best_time_s"] = {"first": curve[0], "last": curve[-1]}
            se["accept_rate"] = acc / prop if prop else 0.0
        if blocks:
            searched = [b for b in blocks if not b.get("memo")]
            se["blocks"] = {
                "total": len(blocks),
                "searched": len(searched),
                "memo_replays": len(blocks) - len(searched),
                "proposed": sum(b.get("proposed", 0) for b in blocks),
                "accepted": sum(b.get("accepted", 0) for b in blocks),
            }
        if stitches:
            st = stitches[-1]
            se["stitch"] = {k: st.get(k) for k in
                            ("blocks", "unique_blocks", "memo_hits",
                             "boundary_ops", "boundary_regrid_s",
                             "refine_iters", "refined_proposed",
                             "stitched_time_s", "best_time_s",
                             "dp_time_s", "budget_hit")}
        if results:
            r = results[-1]
            se["result"] = {k: r.get(k) for k in
                            ("dp_time_s", "best_time_s", "speedup_vs_dp",
                             "iters", "chains", "delta_hit_rate",
                             "proposals_per_sec")}
        out["search"] = se
    audits = [e for e in events if e.get("kind") == "hlo_audit"]
    if audits:
        out["hlo_audit"] = [{k: v for k, v in a.items()
                             if k not in ("run", "ts", "kind", "surface")}
                            for a in audits]
    benches = [e for e in events if e.get("kind") == "bench"]
    if benches:
        out["bench"] = [{k: v for k, v in b.items()
                         if k not in ("run", "ts", "kind", "surface")}
                        for b in benches]
    lints = [e for e in events if e.get("kind") == "lint"]
    if lints:
        rec = lints[-1]
        out["lint"] = {k: rec.get(k) for k in
                       ("model", "strategy", "error", "warning", "info",
                        "exempted", "findings", "predicted", "donation")
                       if rec.get(k) is not None}
    traces = [e for e in events if e.get("kind") == "sim_trace"]
    if traces:
        out["sim_trace"] = [{"path": t.get("path"),
                             "total_s": t.get("total_s"),
                             "dp_total_s": t.get("dp_total_s")}
                            for t in traces]
    budgets = [e for e in events if e.get("kind") == "step_budget"]
    if budgets:
        b = budgets[-1]
        out["step_budget"] = {
            "step_wall_s": b.get("step_wall_s"),
            "buckets": b.get("buckets"),
            "sources": b.get("sources"),
            "clamped": b.get("clamped"),
            "n_samples": b.get("n_samples"),
        }
    mets = [e for e in events if e.get("kind") == "metrics"]
    if mets:
        m = mets[-1]
        out["metrics"] = {
            "writes": len(mets),
            "path": m.get("path"),
            "gauges": {k: v for k, v in m.items()
                       if k not in ("run", "ts", "kind", "surface",
                                    "path")
                       and isinstance(v, (int, float))},
        }
    elastic_kinds = ("device_loss", "device_probe", "elastic_resize",
                     "elastic_fallback", "elastic_refused",
                     "elastic_rejoin", "device_return", "step_hang",
                     "preempt_drain", "ckpt_async")
    if any(kinds.get(k) for k in elastic_kinds):
        el: Dict = {"counts": {k: kinds[k] for k in elastic_kinds
                               if kinds.get(k)}}
        resizes = [e for e in events if e.get("kind") == "elastic_resize"]
        if resizes:
            el["resizes"] = [
                {"step": r.get("step"),
                 "direction": r.get("direction") or (
                     "grow" if (r.get("to_devices") or 0)
                     > (r.get("from_devices") or 0) else "shrink"),
                 "from_devices": r.get("from_devices"),
                 "to_devices": r.get("to_devices"),
                 "research_s": r.get("research_s"),
                 "research_mode": (r.get("research") or {}).get("mode"),
                 "migration": r.get("migration"),
                 "regrid_bytes": r.get("regrid_bytes"),
                 "regrid_hops": r.get("regrid_hops"),
                 "steps_lost": r.get("steps_lost")} for r in resizes]
        dl = [e for e in events if e.get("kind") == "device_loss"]
        if dl:
            el["device_losses"] = [
                {"step": d.get("step"),
                 "classification": d.get("classification"),
                 "dead": d.get("dead")} for d in dl]
        hangs = [e for e in events if e.get("kind") == "step_hang"]
        if hangs:
            el["step_hangs"] = [
                {"step": h.get("step"),
                 "deadline_s": h.get("deadline_s"),
                 "estimate_s": h.get("estimate_s")} for h in hangs]
        rets = [e for e in events if e.get("kind") == "device_return"]
        if rets:
            el["device_returns"] = [
                {"step": r.get("step"),
                 "returned": r.get("returned"),
                 "probes": r.get("probes")} for r in rets]
        drains = [e for e in events if e.get("kind") == "preempt_drain"]
        if drains:
            d = drains[-1]
            el["preempt_drain"] = {
                "step": d.get("step"),
                "ckpt_step": d.get("ckpt_step"),
                "signal": d.get("signal"),
                "seconds": d.get("seconds"),
                "budget_s": d.get("budget_s"),
                "mode": d.get("mode")}
        asyncs = [e for e in events if e.get("kind") == "ckpt_async"]
        if asyncs:
            commits = sorted(float(a.get("commit_s", 0.0))
                             for a in asyncs)
            el["ckpt_async"] = {
                "commits": len(asyncs),
                "median_commit_s": commits[len(commits) // 2],
                "faults": max(int(a.get("faults", 0)) for a in asyncs),
            }
        out["elastic"] = el
    serve_kinds = ("serve_request", "serve_batch", "serve_resize",
                   "serve_summary", "serve_handoff", "kv_refetch",
                   "router_summary", "serve_fault", "serve_retry",
                   "kv_rebuild", "serve_shed", "replica_down")
    if any(kinds.get(k) for k in serve_kinds):
        sv: Dict = {"counts": {k: kinds[k] for k in serve_kinds
                               if kinds.get(k)}}
        lat = sorted(float(e["latency_s"]) for e in events
                     if e.get("kind") == "serve_request"
                     and e.get("latency_s") is not None)
        if lat:
            sv["latency_s"] = {
                "p50": lat[min(len(lat) // 2, len(lat) - 1)],
                "p99": lat[min(int(0.99 * len(lat)), len(lat) - 1)],
                "min": lat[0], "max": lat[-1], "n": len(lat)}
        for key, field in (("ttft_s", "ttft_s"), ("tpot_s", "tpot_s")):
            vals = sorted(float(e[field]) for e in events
                          if e.get("kind") == "serve_request"
                          and e.get(field) is not None)
            if vals:
                sv[key] = {
                    "p50": vals[min(len(vals) // 2, len(vals) - 1)],
                    "p99": vals[min(int(0.99 * len(vals)),
                                    len(vals) - 1)],
                    "n": len(vals)}
        srs = [e for e in events if e.get("kind") == "serve_resize"]
        if srs:
            sv["resizes"] = [
                {"direction": r.get("direction"),
                 "from_devices": r.get("from_devices"),
                 "to_devices": r.get("to_devices"),
                 "step": r.get("step"),
                 "research_s": r.get("research_s"),
                 "research_mode": (r.get("research") or {}).get("mode")}
                for r in srs]
        sums = [e for e in events if e.get("kind") == "serve_summary"]
        if sums:
            s = sums[-1]
            sv["summary"] = {k: s.get(k) for k in
                             ("requests", "completed", "unserved",
                              "dropped", "qps", "p50_s", "p99_s",
                              "ttft_p50_s", "ttft_p99_s", "tpot_p50_s",
                              "tpot_p99_s", "steps",
                              "resizes", "virtual_s", "drained",
                              "devices")}
        hoffs = [e for e in events if e.get("kind") == "serve_handoff"]
        if hoffs:
            sv["handoffs"] = {
                "n": len(hoffs),
                "bytes": sum(float(h.get("bytes", 0.0)) for h in hoffs),
                "kv_refetches": kinds.get("kv_refetch", 0)}
        routers = [e for e in events
                   if e.get("kind") == "router_summary"]
        if routers:
            r = routers[-1]
            sv["router"] = {k: r.get(k) for k in
                            ("requests", "completed", "unserved",
                             "qps", "p50_s", "p99_s", "ttft_p50_s",
                             "ttft_p99_s", "tpot_p50_s", "steps",
                             "devices", "pools", "handoffs",
                             "affinity_hits", "kv_refetches",
                             "drained", "shed", "failed", "retries",
                             "kv_rebuilds", "replica_down",
                             "replicas_live", "recovery")}
        if any(kinds.get(k) for k in ("serve_retry", "serve_fault",
                                      "kv_rebuild", "serve_shed",
                                      "replica_down")):
            sv["resilience"] = {
                "retries": kinds.get("serve_retry", 0),
                "faults": kinds.get("serve_fault", 0),
                "kv_rebuilds": kinds.get("kv_rebuild", 0),
                "sheds": kinds.get("serve_shed", 0),
                "replica_downs": kinds.get("replica_down", 0)}
        out["serve"] = sv
    slos = [e for e in events if e.get("kind") == "slo"]
    if slos:
        out["slo"] = [{k: s.get(k) for k in
                       ("spec", "total", "good", "violations",
                        "error_rate", "error_budget", "burn_rate",
                        "max_window_burn_rate", "windows",
                        "achieved_percentile_s", "compliant",
                        "goodput_qps")} for s in slos]
    points = [e for e in events if e.get("kind") == "loadtest"]
    if points:
        out["loadtest"] = [{k: v for k, v in p.items()
                            if k not in ("run", "ts", "kind", "surface")}
                           for p in points]
    points = [e for e in events if e.get("kind") == "fleetsim"]
    if points:
        out["fleetsim"] = [{k: v for k, v in p.items()
                            if k not in ("run", "ts", "kind", "surface")}
                           for p in points]
    fleet_kinds = ("fleet_job", "fleet_placement", "fleet_rebalance",
                   "fleet_summary", "fleet_wait", "fleet_util")
    if any(kinds.get(k) for k in fleet_kinds):
        fl: Dict = {"counts": {k: kinds[k] for k in fleet_kinds
                               if kinds.get(k)},
                    "rebalances": kinds.get("fleet_rebalance", 0)}
        trail: Dict[str, List[str]] = {}
        for e in events:
            if e.get("kind") != "fleet_job":
                continue
            states = trail.setdefault(str(e.get("job")), [])
            st = str(e.get("state"))
            if not states or states[-1] != st:
                states.append(st)
        if trail:
            fl["jobs"] = trail
        packs = [e for e in events
                 if e.get("kind") == "fleet_placement"]
        if packs:
            fl["packs"] = [{"pack": p.get("pack"),
                            "sizes": p.get("sizes"),
                            "demands": p.get("demands")} for p in packs]
        moves = [e for e in events if e.get("kind") == "fleet_rebalance"]
        if moves:
            fl["moves"] = [
                [{"job": m.get("job"),
                  "from_devices": len(m.get("from") or []),
                  "to_devices": len(m.get("to") or [])}
                 for m in r.get("moves") or []] for r in moves]
        waits = [e for e in events if e.get("kind") == "fleet_wait"]
        if waits:
            fl["waits"] = [{k: w.get(k) for k in
                            ("job", "workload", "state", "wait_s",
                             "placement_s", "run_s", "drain_s",
                             "resize_s", "total_s", "submit_v",
                             "done_v")} for w in waits]
        utils = [e for e in events if e.get("kind") == "fleet_util"]
        if utils:
            busy = sum(int(u.get("busy_steps") or 0) for u in utils)
            idle = sum(int(u.get("idle_steps") or 0) for u in utils)
            rsz = sum(int(u.get("resizing_steps") or 0) for u in utils)
            cap = busy + idle + rsz
            fl["util"] = {"rounds": len(utils), "busy_steps": busy,
                          "idle_steps": idle, "resizing_steps": rsz,
                          "busy_frac": (busy / cap) if cap else 0.0}
        fsums = [e for e in events if e.get("kind") == "fleet_summary"]
        if fsums:
            s = fsums[-1]
            fl["summary"] = {k: s.get(k) for k in
                             ("pool_devices", "by_state", "rebalances",
                              "packs", "native_prices", "proxy_prices",
                              "wall_s", "virtual_s")}
        out["fleet"] = fl
    fault_kinds = ("fault", "rollback", "recovery", "data_fault",
                   "ckpt_fallback", "thread_leak")
    if any(kinds.get(k) for k in fault_kinds):
        fa: Dict = {"counts": {k: kinds[k] for k in fault_kinds
                               if kinds.get(k)}}
        rollbacks = [e for e in events if e.get("kind") == "rollback"]
        if rollbacks:
            fa["rollbacks"] = [{"from_step": r.get("from_step"),
                                "to_step": r.get("to_step")}
                               for r in rollbacks]
        fallbacks = [e for e in events if e.get("kind") == "ckpt_fallback"]
        if fallbacks:
            fa["ckpt_fallbacks"] = [{"from_step": c.get("from_step"),
                                     "to_step": c.get("to_step")}
                                    for c in fallbacks]
        skips = [e for e in events if e.get("kind") == "data_fault"
                 and e.get("action") == "skip"]
        if skips:
            fa["data_skips"] = len(skips)
        out["faults"] = fa
    return out
