"""Render a run-telemetry JSONL (obs record schema) back into the summary
tables humans read today — the reader side of the obs subsystem.

``python -m flexflow_tpu.apps.report <run.jsonl>`` is the CLI wrapper.
Sections are emitted only for the record kinds actually present, so one
renderer serves fit runs, search runs, bench runs, and mixed streams.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

_SPARK = "▁▂▃▄▅▆▇█"


def _spark(values: List[float], width: int = 40) -> str:
    """Compact ascii curve of ``values`` (downsampled to ``width``)."""
    if not values:
        return ""
    if len(values) > width:
        step = len(values) / width
        values = [values[int(i * step)] for i in range(width)]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(int((v - lo) / (hi - lo) * (len(_SPARK) - 1)),
                   len(_SPARK) - 1)] for v in values)


def _fmt_s(s: float) -> str:
    return f"{s * 1e3:.3f} ms" if s < 1.0 else f"{s:.3f} s"


def _header(events: List[Dict]) -> List[str]:
    runs = sorted({e.get("run") for e in events if e.get("run")})
    surfaces = sorted({e.get("surface") for e in events
                       if e.get("surface")})
    ts = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    lines = [f"run: {', '.join(str(r) for r in runs) or '?'}"]
    if surfaces:
        lines.append(f"surfaces: {', '.join(surfaces)}")
    if ts:
        lines.append(f"records: {len(events)}, span: "
                     f"{max(ts) - min(ts):.1f}s")
    for e in events:
        if e.get("kind") == "run_start":
            extras = {k: v for k, v in e.items()
                      if k not in ("run", "ts", "kind", "surface",
                                   "schema")}
            if extras:
                lines.append("meta: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(extras.items())))
    return lines


def _fit_section(events: List[Dict]) -> List[str]:
    steps = [e for e in events if e.get("kind") == "step"]
    compiles = [e for e in events if e.get("kind") == "compile"]
    summaries = [e for e in events if e.get("kind") == "summary"]
    ckpts = [e for e in events
             if e.get("kind") in ("checkpoint_save", "checkpoint_restore")]
    drift = [e for e in events if e.get("kind") == "sim_drift"]
    if not (steps or compiles or summaries):
        return []
    lines = ["== training =="]
    for c in compiles:
        parts = [f"compile: {c.get('seconds', 0.0):.2f}s"]
        if c.get("flops"):
            parts.append(f"{c['flops']:.3e} FLOPs/step")
        if c.get("bytes_accessed"):
            parts.append(f"{c['bytes_accessed']:.3e} bytes/step")
        lines.append("  " + ", ".join(parts))
    if steps:
        walls = [e["wall_ms"] for e in steps if "wall_ms" in e]
        losses = [e["loss"] for e in steps if e.get("loss") is not None]
        lines.append(
            f"  steps: {len(steps)}"
            + (f", wall ms min/mean/max = {min(walls):.2f}/"
               f"{sum(walls) / len(walls):.2f}/{max(walls):.2f}"
               if walls else ""))
        if losses:
            lines.append(f"  loss: first {losses[0]:.4f} -> "
                         f"final {losses[-1]:.4f}   "
                         f"{_spark([float(l) for l in losses])}")
    for s in summaries:
        lines.append(
            f"  summary: {s.get('iterations', '?')} iters, "
            f"elapsed {s.get('elapsed_s', 0.0):.4f}s, "
            f"tp {s.get('images_per_sec', 0.0):.2f} images/s")
    for c in ckpts:
        lines.append(f"  {c['kind']}: step {c.get('step', '?')} "
                     f"({c.get('seconds', 0.0):.3f}s)")
    for d in drift:
        lines.append(
            f"  sim_drift: predicted {_fmt_s(d.get('predicted_s', 0.0))} "
            f"vs measured {_fmt_s(d.get('measured_s', 0.0))} "
            f"-> ratio {d.get('value', 0.0):.3f} "
            f"[{d.get('source', '?')}]")
    return lines


def _search_section(events: List[Dict]) -> List[str]:
    space = [e for e in events if e.get("kind") == "search_space"]
    chunks = [e for e in events if e.get("kind") == "search_chunk"]
    results = [e for e in events if e.get("kind") == "search_result"]
    breakdown = [e for e in events if e.get("kind") == "search_breakdown"]
    pipes = [e for e in events if e.get("kind") == "pipeline_decision"]
    if not (space or chunks or results):
        return []
    lines = ["== strategy search =="]
    for s in space:
        lines.append(
            f"  space: {s.get('ops', '?')} ops, "
            f"{s.get('candidates', '?')} candidates "
            f"({s.get('axis_options_pruned', 0)} axis options pruned, "
            f"{s.get('mem_rejected', 0)} HBM-rejected)")
    if chunks:
        curve = [c["best_time_s"] for c in chunks if "best_time_s" in c]
        acc = sum(c.get("accepted", 0) for c in chunks)
        prop = sum(c.get("proposed", 0) for c in chunks)
        pps = [c["proposals_per_sec"] for c in chunks
               if c.get("proposals_per_sec")]
        if curve:
            lines.append(
                f"  best-cost curve ({len(curve)} chunks): "
                f"{_fmt_s(curve[0])} -> {_fmt_s(curve[-1])}   "
                f"{_spark(curve)}")
        lines.append(
            f"  acceptance: {acc}/{prop} "
            f"({100.0 * acc / prop if prop else 0.0:.1f}%)"
            + (f", {sum(pps) / len(pps):,.0f} proposals/s" if pps else ""))
    for r in results:
        lines.append(
            f"  result: dp {_fmt_s(r.get('dp_time_s', 0.0))}, "
            f"best {_fmt_s(r.get('best_time_s', 0.0))} "
            f"({r.get('speedup_vs_dp', 0.0):.3f}x vs DP)")
        cache = r.get("cost_cache")
        if cache:
            tot = cache.get("hits", 0) + cache.get("misses", 0)
            lines.append(
                f"  cost cache: {cache.get('hits', 0)}/{tot} hits "
                f"({100.0 * cache.get('hits', 0) / tot if tot else 0.0:.1f}%)")
    for b in breakdown:
        ops = sorted(b.get("ops", []),
                     key=lambda o: -(o.get("compute_s", 0.0)
                                     + o.get("collective_s", 0.0)))
        lines.append(f"  winning strategy, per-op cost "
                     f"(top {min(len(ops), 12)} of {len(ops)}):")
        lines.append(f"    {'op':<18s} {'kind':<14s} {'grid':<14s} "
                     f"{'compute':>10s} {'collective':>10s}")
        for o in ops[:12]:
            lines.append(
                f"    {str(o.get('op', '?')):<18s} "
                f"{str(o.get('kind', '?')):<14s} "
                f"{str(tuple(o.get('dims', ()))):<14s} "
                f"{_fmt_s(o.get('compute_s', 0.0)):>10s} "
                f"{_fmt_s(o.get('collective_s', 0.0)):>10s}")
        if b.get("opt_stream_s"):
            lines.append(f"    optimizer param stream: "
                         f"{_fmt_s(b['opt_stream_s'])}")
    for p in pipes:
        lines.append(
            f"  pipeline: {'ACCEPT' if p.get('accepted') else 'REJECT'}"
            + (f" S={p['best'].get('stages')} "
               f"M={p['best'].get('microbatches')} "
               f"tp={p['best'].get('tp')}" if p.get("best") else "")
            + f" (ref {_fmt_s(p.get('reference_time_s', 0.0))})")
    return lines


def _audit_bench_section(events: List[Dict]) -> List[str]:
    audits = [e for e in events if e.get("kind") == "hlo_audit"]
    benches = [e for e in events if e.get("kind") == "bench"]
    if not (audits or benches):
        return []
    lines = ["== audit / bench =="]
    for a in audits:
        lines.append(
            f"  hlo_audit[{a.get('plan', '?')}]: "
            f"searched {a.get('searched_cross_mb', '?')} MB cross-tier "
            f"vs DP {a.get('dp_cross_mb', '?')} MB -> "
            f"{'CONSISTENT' if a.get('consistent') else 'CONTRADICTED'}")
    for b in benches:
        lines.append(
            f"  bench: {b.get('metric', '?')} = {b.get('value', '?')} "
            f"{b.get('unit', '')} (vs_baseline {b.get('vs_baseline', '?')}"
            + (f", mfu {b['mfu']}" if b.get("mfu") is not None else "")
            + ")")
    return lines


def _misc_section(events: List[Dict]) -> List[str]:
    known = {"run_start", "compile", "step", "summary", "checkpoint_save",
             "checkpoint_restore", "sim_drift", "search_space",
             "search_chunk", "search_result", "search_breakdown",
             "pipeline_candidate", "pipeline_decision", "hlo_audit",
             "bench"}
    lines = []
    for e in events:
        kind = e.get("kind")
        if kind in known:
            continue
        if kind == "counter":
            lines.append(f"  counter {e.get('name')}: {e.get('value')}")
        elif kind == "gauge":
            lines.append(f"  gauge {e.get('name')}: {e.get('value')}")
        elif kind == "timer":
            lines.append(f"  timer {e.get('name')}: "
                         f"{_fmt_s(e.get('seconds', 0.0))}")
        else:
            body = {k: v for k, v in e.items()
                    if k not in ("run", "ts", "surface")}
            lines.append(f"  {body}")
    return (["== other records =="] + lines) if lines else []


def render(events: Iterable[Dict]) -> str:
    """One human-readable report of a run's event stream."""
    events = list(events)
    if not events:
        return "(empty run log)"
    sections = [_header(events), _fit_section(events),
                _search_section(events), _audit_bench_section(events),
                _misc_section(events)]
    return "\n".join("\n".join(s) for s in sections if s)


def render_file(path: str) -> str:
    from flexflow_tpu.obs import read_events

    return render(read_events(path))
