"""Multi-host entry point: the GASNet/Legion-transport analog.

The reference scales across nodes by building Legion with GASNet
(USE_GASNET=1, nmt/Makefile:24; `-d` flag README.md:38-41) and launching
one rank per node; Legion/Realm then move region data over the wire.  The
TPU-native equivalent is `jax.distributed` + GSPMD: every host runs THE
SAME program, `initialize()` connects them, and `jax.devices()` then spans
every chip in the slice/pod — after which the entire framework works
unchanged (a MachineModel over the global device list; XLA emits ICI
collectives within a slice and DCN collectives across slices from exactly
the same sharding annotations).

    # on every host (e.g. via gcloud alpha compute tpus tpu-vm ssh --worker=all)
    from flexflow_tpu import distributed
    machine = distributed.initialize()          # TPU pods: auto-detected
    ff = build_inception_v3(cfg, machine)       # unchanged from 1 chip

There is no per-op communication code anywhere to port — SURVEY.md §2.7:
communication is derived, not written.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from flexflow_tpu.machine import MachineModel, Topology

# did THIS process bring up a jax.distributed client?  release()/rejoin
# consult it so single-process runs never touch the coordinator.
# _RELEASE_LOCK makes release() idempotent AND re-entrant: fit()'s drain
# path and its error path can both reach it (possibly from a signal
# handler interrupting the other caller), and exactly one of them may
# run the actual shutdown.
import threading as _threading

_STATE = {"initialized": False}
_RELEASE_LOCK = _threading.RLock()


def is_initialized() -> bool:
    """True when this process initialized (and still holds) the
    jax.distributed client."""
    return _STATE["initialized"]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               topology: Optional[Topology] = None,
               coordinator_timeout_s: Optional[float] = None,
               connect_attempts: int = 1) -> MachineModel:
    """Connect this process to the cluster and return the global machine.

    On Cloud TPU all arguments are auto-detected from the metadata server;
    elsewhere pass coordinator_address ("host:port" of process 0),
    num_processes, and process_id.  Single-process (the common dev case)
    skips `jax.distributed` entirely and is a no-op wrapper around
    ``MachineModel()``.

    The returned MachineModel spans every device of every process, with a
    two-tier Topology (ICI inside a slice = this host's local device
    count per group by default; DCN across) feeding the strategy-search
    cost model.

    Coordinator-timeout handling (elastic round): the explicit path
    passes ``coordinator_timeout_s`` through to jax.distributed's
    ``initialization_timeout`` (where the installed jax supports it) and
    retries a timed-out connection up to ``connect_attempts`` times with
    bounded deterministic backoff (utils/retry.py) — a respawned host
    arriving before its coordinator is a normal event under ``--elastic``
    restarts, not an error."""
    import os

    import jax

    explicit = (coordinator_address is not None
                or (num_processes or 0) > 1 or process_id is not None)
    # env markers Cloud TPU sets on multi-host slices — the zero-arg
    # auto-detect path only fires there, so single-process dev boxes
    # (CPU tests, tunneled single chips) never touch jax.distributed
    auto = any(m in os.environ for m in (
        "TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
        "MEGASCALE_COORDINATOR_ADDRESS", "TPU_PROCESS_ADDRESSES"))
    if explicit:
        def _connect():
            kwargs = dict(coordinator_address=coordinator_address,
                          num_processes=num_processes,
                          process_id=process_id,
                          local_device_ids=local_device_ids)
            if coordinator_timeout_s is not None:
                kwargs["initialization_timeout"] = \
                    int(coordinator_timeout_s)
            try:
                jax.distributed.initialize(**kwargs)
            except TypeError:
                # older jax without initialization_timeout
                kwargs.pop("initialization_timeout", None)
                jax.distributed.initialize(**kwargs)
            _STATE["initialized"] = True

        def _connect_once():
            try:
                _connect()
            except RuntimeError as e:
                # second initialize() in the same process: keep the
                # existing client (jax.distributed is one-shot; use
                # shutdown() before reconfiguring).  A TIMEOUT is
                # retryable; anything else (bad coordinator, mismatched
                # process count) must surface, not silently degrade to a
                # single-host world.
                msg = str(e).lower()
                if "already initialized" in msg:
                    _STATE["initialized"] = True
                    return
                if "timeout" in msg or "timed out" in msg \
                        or "deadline" in msg:
                    raise TimeoutError(str(e)) from e
                raise

        if max(int(connect_attempts), 1) > 1:
            from flexflow_tpu.utils.retry import (RetryPolicy,
                                                  call_with_retry)

            call_with_retry(
                _connect_once,
                policy=RetryPolicy(attempts=max(int(connect_attempts), 1),
                                   base_delay=1.0, max_delay=10.0),
                retry_on=(TimeoutError,))
        else:
            _connect_once()
    elif auto:
        try:
            jax.distributed.initialize()  # args metadata-auto-detected
            _STATE["initialized"] = True
        except (RuntimeError, ValueError):
            # backend already initialized (dev sessions that imported jax
            # first) or metadata incomplete (RuntimeError / ValueError
            # 'coordinator_address should be defined'): stay
            # single-process — the env markers alone are not proof of a
            # usable cluster
            pass
    multiprocess = jax.process_count() > 1
    devices = jax.devices()
    if topology is None and multiprocess:
        # ICI inside each host's slice, DCN across — feed the two-tier
        # cost model accordingly (single-process keeps MachineModel's
        # own all-ICI default)
        topology = Topology(
            devices_per_ici_group=max(len(jax.local_devices()), 1))
    return MachineModel(devices=devices, topology=topology)


def shutdown() -> None:
    """Tear down the jax.distributed client (idempotent)."""
    import jax

    with _RELEASE_LOCK:
        _STATE["initialized"] = False
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


def release() -> bool:
    """Coordinator cleanup: tear down the client IF this process brought
    one up, no-op otherwise.  ``fit()`` calls this on every error exit
    AND at the end of a graceful drain, so a departing host releases the
    coordinator (and its barrier slot) promptly instead of holding the
    other hosts until their timeout.  Idempotent and re-entrant — both
    paths may call it, in any order, and only the first performs the
    shutdown.  Returns True when this call did the teardown."""
    with _RELEASE_LOCK:
        if not _STATE["initialized"]:
            return False
        _STATE["initialized"] = False
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    return True


def elastic_rejoin(ckpt_dir: str,
                   coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   model=None,
                   topology: Optional[Topology] = None,
                   coordinator_timeout_s: float = 60.0,
                   connect_attempts: int = 5,
                   olog=None, log=print) -> Tuple[MachineModel, int,
                                                  Optional[dict],
                                                  Optional[dict],
                                                  Optional[dict]]:
    """The ``--elastic`` restart protocol for a RESPAWNED host.

    A host that crashed (or was preempted) and came back cannot splice
    into the surviving mesh mid-step — collectives are compiled against a
    fixed device set.  Instead it: (1) tears down any stale client and
    re-initializes against the coordinator, retrying connection timeouts
    with bounded backoff (every surviving host must reach the SAME
    restart barrier, which the orchestrator triggers by restarting them
    with identical flags); (2) loads the newest VERIFIED checkpoint from
    ``ckpt_dir`` (the async writer keeps one recent — a respawn costs at
    most one checkpoint interval); (3) returns the fresh global machine
    plus the restored ``(step, params, state, opt_state)`` so the driver
    rebuilds its model on the rejoined mesh and resumes.

    With ``model`` given, restored leaves land on the model's shardings
    (same contract as ``restore_checkpoint``).  ``model`` may also be a
    FACTORY ``machine -> model``: a respawned process cannot build its
    model before rejoining (the global machine does not exist until
    ``initialize`` returns, and jax forbids re-initializing after the
    backend is live), so the factory is called with the rejoined
    machine and the restore places onto the freshly built model.  When
    no checkpoint exists yet, returns step 0 with None trees (a restart
    before the first save simply begins again)."""
    from flexflow_tpu.utils import checkpoint as ckpt

    shutdown()
    machine = initialize(coordinator_address=coordinator_address,
                         num_processes=num_processes,
                         process_id=process_id, topology=topology,
                         coordinator_timeout_s=coordinator_timeout_s,
                         connect_attempts=connect_attempts)
    if model is not None and callable(model) \
            and not hasattr(model, "layers"):
        model = model(machine)
    step, params, state, opt_state = 0, None, None, None
    if ckpt_dir and ckpt.latest_step(ckpt_dir) is not None:
        step, params, state, opt_state = ckpt.restore_checkpoint(
            ckpt_dir, model, olog=olog)
        log(f"elastic rejoin: restored verified checkpoint step {step} "
            f"from {ckpt_dir!r} on a "
            f"{machine.num_devices}-device mesh")
    else:
        log(f"elastic rejoin: no checkpoint under {ckpt_dir!r}; "
            f"rejoining from step 0")
    if olog is not None and getattr(olog, "enabled", False):
        olog.event("elastic_rejoin", step=step, dir=ckpt_dir,
                   devices=machine.num_devices)
    return machine, step, params, state, opt_state
