"""Multi-host entry point: the GASNet/Legion-transport analog.

The reference scales across nodes by building Legion with GASNet
(USE_GASNET=1, nmt/Makefile:24; `-d` flag README.md:38-41) and launching
one rank per node; Legion/Realm then move region data over the wire.  The
TPU-native equivalent is `jax.distributed` + GSPMD: every host runs THE
SAME program, `initialize()` connects them, and `jax.devices()` then spans
every chip in the slice/pod — after which the entire framework works
unchanged (a MachineModel over the global device list; XLA emits ICI
collectives within a slice and DCN collectives across slices from exactly
the same sharding annotations).

    # on every host (e.g. via gcloud alpha compute tpus tpu-vm ssh --worker=all)
    from flexflow_tpu import distributed
    machine = distributed.initialize()          # TPU pods: auto-detected
    ff = build_inception_v3(cfg, machine)       # unchanged from 1 chip

There is no per-op communication code anywhere to port — SURVEY.md §2.7:
communication is derived, not written.
"""

from __future__ import annotations

from typing import Optional, Sequence

from flexflow_tpu.machine import MachineModel, Topology


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               topology: Optional[Topology] = None) -> MachineModel:
    """Connect this process to the cluster and return the global machine.

    On Cloud TPU all arguments are auto-detected from the metadata server;
    elsewhere pass coordinator_address ("host:port" of process 0),
    num_processes, and process_id.  Single-process (the common dev case)
    skips `jax.distributed` entirely and is a no-op wrapper around
    ``MachineModel()``.

    The returned MachineModel spans every device of every process, with a
    two-tier Topology (ICI inside a slice = this host's local device
    count per group by default; DCN across) feeding the strategy-search
    cost model."""
    import os

    import jax

    explicit = (coordinator_address is not None
                or (num_processes or 0) > 1 or process_id is not None)
    # env markers Cloud TPU sets on multi-host slices — the zero-arg
    # auto-detect path only fires there, so single-process dev boxes
    # (CPU tests, tunneled single chips) never touch jax.distributed
    auto = any(m in os.environ for m in (
        "TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
        "MEGASCALE_COORDINATOR_ADDRESS", "TPU_PROCESS_ADDRESSES"))
    if explicit:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids)
        except RuntimeError as e:
            # second initialize() in the same process: keep the existing
            # client (jax.distributed is one-shot; use shutdown() before
            # reconfiguring).  Anything else (bad coordinator, mismatched
            # process count) must surface, not silently degrade to a
            # single-host world.
            if "already initialized" not in str(e).lower():
                raise
    elif auto:
        try:
            jax.distributed.initialize()  # args metadata-auto-detected
        except (RuntimeError, ValueError):
            # backend already initialized (dev sessions that imported jax
            # first) or metadata incomplete (RuntimeError / ValueError
            # 'coordinator_address should be defined'): stay
            # single-process — the env markers alone are not proof of a
            # usable cluster
            pass
    multiprocess = jax.process_count() > 1
    devices = jax.devices()
    if topology is None and multiprocess:
        # ICI inside each host's slice, DCN across — feed the two-tier
        # cost model accordingly (single-process keeps MachineModel's
        # own all-ICI default)
        topology = Topology(
            devices_per_ici_group=max(len(jax.local_devices()), 1))
    return MachineModel(devices=devices, topology=topology)


def shutdown() -> None:
    """Tear down the jax.distributed client (idempotent)."""
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass
