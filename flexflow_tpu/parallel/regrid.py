"""Whole-graph regrid planner: producer->consumer resharding resolved ONCE.

Before this module, FFModel._apply re-derived every producer->consumer
reshard edge-by-edge on EVERY trace (``machine.global_entries`` +
``machine.regrid_steps`` per input, per op) and each consumer of a fanned-
out producer traced its own identical constraint chain.  GSPMD's
observation (Xu et al., 2021) is that resharding *placement* — not just op
partitioning — decides whether a mixed strategy wins; FlexFlow leans on
Legion to make these transfers implicit and cheap (conv_2d.cu:171-208).
The planner is the executor-side analog of the simulator's memoized
transfer plans (PR 2): walk the op graph once at plan time and produce a
per-edge :class:`EdgePlan`, so ``_apply`` becomes a thin consumer.

What planning buys over the per-trace path:

  * **resolved once** — source/target global-mesh entries and the hop
    decomposition are computed at plan time, never inside the traced step;
  * **coalescing** — edges between consecutive ops sharing a layout are
    recognized as no-ops at plan time and carry zero constraints (the
    per-edge path pays the resolution every trace to discover the same
    thing), and identity hops are dropped;
  * **fan-out sharing** — when one producer feeds several consumers that
    want the same layout, the constraint chain is traced ONCE and the
    resharded value reused (the per-edge path emits one chain per
    consumer and hopes XLA CSEs them);
  * **cost-aware hop selection** — among alternative single-axis hop
    decompositions of one edge, a uniform-cost search picks the sequence
    the machine :class:`~flexflow_tpu.machine.Topology` prices cheapest
    (the same ICI/DCN link numbers the native simulator's memoized
    transfer plans use, keeping sim and executor aligned).  The greedy
    ``MachineModel.regrid_steps`` order gathers dropped axes FIRST, which
    prices every later all-to-all at the grown per-shard size; moving
    while still fully sharded and gathering last is often strictly
    cheaper.

Every value move here is data movement only (all-gather / all-to-all /
slice) — planned execution is loss-bit-identical to the per-trace path by
construction (tests/test_regrid_planner.py).
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.machine import MachineModel, Topology
from flexflow_tpu.sim.collectives import _allreduce, _alltoall

# cost charged to a pure split hop (a slice: no wire traffic) — small and
# nonzero so the search prefers fewer hops among traffic-free plans
_SPLIT_EPS = 1.0e-7

# uniform-cost-search state cap; beyond it fall back to the greedy
# decomposition (machine sizes this repo targets stay far below the cap)
_MAX_STATES = 20000


# ---------------------------------------------------------------------------
# hop pricing on the global factored mesh


class _MeshCosts:
    """Link-cost oracle for hops on one machine's global factored mesh.

    Caches, per global-mesh axis subset, the device tuple of the axis
    group containing device 0 (translates share the tier pattern when the
    ICI group size divides the machine — the layout MachineModel builds),
    and prices gather/all-to-all hops with the SAME
    :mod:`flexflow_tpu.sim.collectives` ring formulas the simulator uses.
    """

    def __init__(self, machine: MachineModel):
        self.topo: Topology = machine.topology
        fac = machine.global_factors()
        self.sizes = {name: s for name, s in fac}
        strides: Dict[str, int] = {}
        stride = 1
        for name, s in reversed(fac):
            strides[name] = stride
            stride *= s
        self.strides = strides
        self._groups: Dict[Tuple[str, ...], Tuple[int, ...]] = {}

    def group(self, axes: Tuple[str, ...]) -> Tuple[int, ...]:
        """Device ordinals of the axis-``axes`` collective group holding
        device 0 (the representative group pricing the hop)."""
        key = tuple(sorted(axes))
        devs = self._groups.get(key)
        if devs is None:
            devs = (0,)
            for a in key:
                stride, size = self.strides[a], self.sizes[a]
                devs = tuple(d + i * stride for d in devs
                             for i in range(size))
            devs = tuple(sorted(devs))
            self._groups[key] = devs
        return devs

    def nshards(self, state: Tuple[Tuple[str, ...], ...]) -> int:
        n = 1
        for t in state:
            for a in t:
                n *= self.sizes[a]
        return n

    def alltoall(self, per_shard_bytes: float, axis: str) -> float:
        return _alltoall(per_shard_bytes, self.group((axis,)), self.topo)

    def allgather(self, per_shard_bytes_after: float,
                  axes: Tuple[str, ...]) -> float:
        # an all-gather is half an all-reduce of the gathered volume (the
        # dispatch_overhead_cost convention in sim/collectives.py)
        return 0.5 * _allreduce(per_shard_bytes_after, self.group(axes),
                                self.topo)


def _hop_traffic(costs: _MeshCosts, total_bytes: float,
                 prev, nxt) -> Tuple[float, float]:
    """(seconds, wire_bytes) of the single hop ``prev -> nxt``; both are
    entries tuples (per-tensor-dim tuples of global mesh axes)."""
    prev_axes = [a for t in prev for a in t]
    nxt_axes = [a for t in nxt for a in t]
    removed = tuple(a for a in prev_axes if a not in nxt_axes)
    added = [a for a in nxt_axes if a not in prev_axes]
    per_prev = total_bytes / max(costs.nshards(prev), 1)
    per_nxt = total_bytes / max(costs.nshards(nxt), 1)
    if removed and not added:
        # gather: each shard ends holding the grown block
        p = len(costs.group(removed))
        return (costs.allgather(per_nxt, removed),
                (p - 1) / max(p, 1) * total_bytes)
    if not removed and not added:
        # a move within/between tensor dims: one all-to-all over the moved
        # axis (exactly one axis changes location per hop)
        moved = None
        for a in prev_axes:
            loc_prev = next((j, t.index(a)) for j, t in enumerate(prev)
                            if a in t)
            loc_nxt = next((j, t.index(a)) for j, t in enumerate(nxt)
                           if a in t)
            if loc_prev != loc_nxt:
                moved = a
                break
        if moved is None:
            return 0.0, 0.0
        s = costs.sizes[moved]
        return (costs.alltoall(per_prev, moved),
                (s - 1) / s * total_bytes)
    if added and not removed:
        return _SPLIT_EPS, 0.0  # pure split: a local slice
    # mixed (should not be produced by the planner's move set): price as
    # gather + split, conservatively
    p = len(costs.group(removed))
    return (costs.allgather(per_nxt, removed),
            (p - 1) / max(p, 1) * total_bytes)


def price_chain(machine: MachineModel, src, chain: List,
                shape: Tuple[int, ...], itemsize: int = 4,
                costs: Optional[_MeshCosts] = None) -> Tuple[float, float]:
    """(seconds, wire_bytes) of walking ``src`` through ``chain`` (a list
    of entries tuples ending at the destination)."""
    costs = costs or _MeshCosts(machine)
    total = float(math.prod(shape)) * itemsize
    secs = moved = 0.0
    cur = src
    for step in chain:
        s, b = _hop_traffic(costs, total, cur, step)
        secs += s
        moved += b
        cur = step
    return secs, moved


# ---------------------------------------------------------------------------
# cost-aware hop selection


def _correct_prefix_len(cur_j, dst_j) -> int:
    n = 0
    for a, b in zip(cur_j, dst_j):
        if a != b:
            break
        n += 1
    return n


def plan_hops(machine: MachineModel, src, dst,
              shape: Tuple[int, ...], itemsize: int = 4,
              costs: Optional[_MeshCosts] = None):
    """Min-cost single-axis hop decomposition of the regrid ``src -> dst``
    (both entries tuples of equal rank): a uniform-cost search over states
    whose moves are the same vocabulary ``MachineModel.regrid_steps``
    emits — merged or single all-gathers (axis drops), all-to-alls (axis
    moves onto a ready destination prefix) and slices (axis splits) —
    priced with the machine topology's link costs.  Returns
    ``(chain, seconds, wire_bytes)`` where ``chain`` is the list of
    intermediate entries tuples INCLUDING ``dst`` as its last element
    (empty when ``src == dst``), or the greedy decomposition when the
    search exceeds its state budget.  Unlike the greedy, the search always
    reaches ``dst`` (a misplaced axis can be gathered and re-split), so
    it never returns None."""
    if len(src) != len(dst):
        raise ValueError(f"rank mismatch: {src} vs {dst}")
    if src == dst:
        return [], 0.0, 0.0
    costs = costs or _MeshCosts(machine)
    total = float(math.prod(shape)) * itemsize
    dst_axes = {a for t in dst for a in t}
    src_t = tuple(tuple(t) for t in src)
    dst_t = tuple(tuple(t) for t in dst)

    def neighbors(state):
        cur = [list(t) for t in state]
        loc = {a: j for j, t in enumerate(cur) for a in t}
        out = []
        # merged gather of every axis absent from dst (the greedy's first
        # hop) plus single gathers of misplaced axes
        foreign = [a for t in cur for a in t if a not in dst_axes]
        if foreign:
            out.append(tuple(tuple(a for a in t if a in dst_axes)
                             for t in cur))
        for j, t in enumerate(cur):
            keep = _correct_prefix_len(t, dst_t[j])
            for i, a in enumerate(t):
                if i >= keep and (a in dst_axes or len(foreign) > 1):
                    nxt = [list(x) for x in cur]
                    nxt[j].remove(a)
                    out.append(tuple(tuple(x) for x in nxt))
        # moves / splits building each destination prefix
        for j, t in enumerate(cur):
            p = len(t)
            if p < len(dst_t[j]) and tuple(t) == dst_t[j][:p]:
                a = dst_t[j][p]
                nxt = [list(x) for x in cur]
                if a in loc:
                    nxt[loc[a]].remove(a)
                nxt[j].append(a)
                out.append(tuple(tuple(x) for x in nxt))
        return out

    frontier = [(0.0, 0, src_t, None)]
    best: Dict = {}
    parents: Dict = {}
    order = 0
    explored = 0
    while frontier:
        cost, _, state, parent = heapq.heappop(frontier)
        if state in best and best[state] <= cost:
            continue
        best[state] = cost
        parents[state] = parent
        if state == dst_t:
            chain = []
            cur = state
            while cur is not None and cur != src_t:
                chain.append(cur)
                cur = parents[cur]
            chain.reverse()
            _, moved = price_chain(machine, src_t, chain, shape,
                                   itemsize, costs)
            return chain, cost, moved
        explored += 1
        if explored > _MAX_STATES:
            break
        for nxt in neighbors(state):
            if nxt == state:
                continue
            s, _ = _hop_traffic(costs, total, state, nxt)
            order += 1
            heapq.heappush(frontier, (cost + s, order, nxt, state))
    # state budget exceeded: fall back to the greedy decomposition (or
    # full replicate-and-slice when even that cannot reach dst)
    steps = machine.regrid_steps(src_t, dst_t)
    if steps is None:
        repl = tuple(() for _ in src_t)
        chain = [repl, dst_t]
    else:
        chain = list(steps) + [dst_t]
    secs, moved = price_chain(machine, src_t, chain, shape, itemsize, costs)
    return chain, secs, moved


# ---------------------------------------------------------------------------
# the plan


@dataclasses.dataclass
class EdgePlan:
    """One consumer input's resharding, resolved at plan time.

    ``shardings`` is the full constraint chain to apply in order (hops
    then destination; empty = coalesced no-op edge).  ``share_key`` is set
    when several edges of the plan reshard the same produced value to the
    same destination — the first consumer traces the chain, the rest
    reuse the traced value.

    Accounting separates the plan's two wins: ``naive_constraints``
    counts what per-edge blind resolution would emit for THIS edge (its
    chosen chain, one destination constraint even for a no-op edge),
    against which the summary's after-coalescing counts are compared;
    ``greedy_s``/``greedy_bytes`` price the greedy
    ``MachineModel.regrid_steps`` decomposition against the cost-chosen
    ``predicted_s``/``predicted_bytes``."""

    shardings: List
    share_key: Optional[Tuple] = None
    # coalescing accounting (obs record + tests)
    naive_constraints: int = 0
    constraints: int = 0
    # hop-selection accounting: chosen chain vs the greedy decomposition
    predicted_s: float = 0.0
    predicted_bytes: float = 0.0
    greedy_s: float = 0.0
    greedy_bytes: float = 0.0


class RegridPlan:
    """Per-edge reshard plans for one (model, schedule, fusion) — built
    once by :func:`build_regrid_plan`, consumed by ``FFModel._apply``."""

    def __init__(self, machine: MachineModel):
        self.machine = machine
        self.edges: Dict[Tuple[str, int], EdgePlan] = {}
        self._shared_first: set = set()

    # -- construction ----------------------------------------------------

    def add_edge(self, op_name: str, input_idx: int, src, dst,
                 shape, itemsize: int = 4,
                 replicate_unknown: bool = False,
                 costs: Optional[_MeshCosts] = None,
                 tid: Optional[int] = None) -> None:
        """Plan the edge ``src -> dst`` for ``op``'s ``input_idx``-th
        input.  ``src is None`` means the producer's layout is unknown
        (a non-decomposing placement-group exit): with
        ``replicate_unknown`` the plan states the replicate waypoint the
        legacy path used, otherwise the edge is skipped (the group-input
        convention)."""
        m = self.machine
        key = (op_name, input_idx)
        if dst is None:
            return
        if src is None:
            if not replicate_unknown:
                return
            self.edges[key] = EdgePlan(
                shardings=[m.replicated(), m.entries_sharding(dst)],
                naive_constraints=2, constraints=2)
            return
        if dst == src:
            # coalesced: consecutive ops sharing this layout need no
            # constraint at all — the naive per-edge path would still
            # constrain the input to its wanted layout (1 constraint)
            self.edges[key] = EdgePlan(shardings=[], naive_constraints=1,
                                       constraints=0)
            return
        costs = costs or _MeshCosts(m)
        greedy_steps = m.regrid_steps(src, dst)
        if greedy_steps is None:
            greedy_chain = [tuple(() for _ in src),
                            tuple(tuple(t) for t in dst)]
        else:
            greedy_chain = list(greedy_steps) + [tuple(tuple(t)
                                                       for t in dst)]
        greedy_s, greedy_b = price_chain(m, src, greedy_chain, shape,
                                         itemsize, costs)
        chain, secs, moved = plan_hops(m, src, dst, shape, itemsize, costs)
        # the share key names the PRODUCED VALUE and the destination: only
        # consumers of the same tensor wanting the same layout reuse one
        # traced chain (summary() counts sharing with the same key)
        share_key = (tid, tuple(tuple(t) for t in src),
                     tuple(tuple(t) for t in dst))
        self.edges[key] = EdgePlan(
            shardings=[m.entries_sharding(s) for s in chain],
            share_key=share_key,
            naive_constraints=len(chain), constraints=len(chain),
            predicted_s=secs, predicted_bytes=moved,
            greedy_s=greedy_s, greedy_bytes=greedy_b)

    # -- consumption (inside the traced step) ----------------------------

    def apply(self, op_name: str, input_idx: int, x, cache: Dict):
        """Apply the planned constraint chain for one edge to value ``x``.
        ``cache`` is the per-trace fan-out dict: consumers sharing a
        (produced value, destination) reuse the first traced reshard."""
        ep = self.edges.get((op_name, input_idx))
        if ep is None or not ep.shardings:
            return x
        from jax import lax

        ck = ep.share_key
        if ck is not None and ck in cache:
            return cache[ck]
        for sh in ep.shardings:
            x = lax.with_sharding_constraint(x, sh)
        if ck is not None:
            cache[ck] = x
        return x

    # -- accounting ------------------------------------------------------

    def summary(self) -> Dict:
        """The ``regrid_plan`` obs record body.

        Coalescing axis (same chains on both sides, so the delta is pure
        coalescing): ``constraints_before``/``hops_before`` = every edge
        resolved and constrained independently; ``..._after`` = no-op
        edges elided and fan-out duplicates traced once.  Hop-selection
        axis: ``predicted_transfer_s``/``predicted_bytes`` price the
        cost-chosen chains, ``greedy_transfer_s``/``greedy_bytes`` the
        greedy ``regrid_steps`` decompositions of the same edges."""
        seen_shared: set = set()
        edges = noop = shared = 0
        c_before = c_after = h_before = h_after = 0
        s_after = b_after = 0.0
        s_greedy = b_greedy = 0.0
        for ep in self.edges.values():
            edges += 1
            c_before += ep.naive_constraints
            h_before += len(ep.shardings)
            s_greedy += ep.greedy_s
            b_greedy += ep.greedy_bytes
            if not ep.shardings:
                noop += 1
                continue
            if ep.share_key is not None and ep.share_key in seen_shared:
                shared += 1
                continue
            if ep.share_key is not None:
                seen_shared.add(ep.share_key)
            c_after += ep.constraints
            h_after += len(ep.shardings)
            s_after += ep.predicted_s
            b_after += ep.predicted_bytes
        return {
            "edges": edges, "noop_edges": noop, "shared_edges": shared,
            "constraints_before": c_before, "constraints_after": c_after,
            "hops_before": h_before, "hops_after": h_after,
            "predicted_transfer_s": s_after,
            "greedy_transfer_s": s_greedy,
            "predicted_bytes": b_after,
            "greedy_bytes": b_greedy,
        }


def build_regrid_plan(model, fusion: Dict, schedule) -> RegridPlan:
    """Walk ``schedule`` exactly as ``FFModel._apply`` will, mirroring its
    produced-layout bookkeeping, and plan every reshard edge once.  The
    result is deterministic for a (model, schedule, fusion) triple —
    ``_apply`` then consumes plans by (op name, input index)."""
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.placement import PlacementGroup
    from flexflow_tpu.strategy import ParallelConfig

    machine = model.machine
    plan = RegridPlan(machine)
    costs = _MeshCosts(machine)
    specs: Dict[int, Tuple] = {}
    dp = ParallelConfig.data_parallel(1, machine.num_devices)
    for t in model._inputs:
        specs[t.tid] = machine.global_entries(dp, ("n",), P("n"),
                                              rank=t.ndim)

    from flexflow_tpu.sim.cost_model import dtype_bytes

    def itemsize(t):
        return dtype_bytes(t.dtype)

    for entry in schedule:
        if isinstance(entry, PlacementGroup):
            for m in entry.members:
                if entry.device_rows is not None:
                    targets = [tuple(() for _ in range(t.ndim))
                               for t in m.inputs]
                else:
                    ins = m.input_specs()
                    if ins is None:
                        targets = [None] * len(m.inputs)
                    else:
                        targets = [machine.global_entries(
                            m.pc, m.AXIS_NAMES, spec, rank=t.ndim)
                            if spec is not None else None
                            for spec, t in zip(ins, m.inputs)]
                for i, (t, dst) in enumerate(zip(m.inputs, targets)):
                    src = specs.get(t.tid)
                    if dst is None or src is None:
                        continue  # group inputs skip unknown sources
                    plan.add_edge(m.name, i, src, dst, t.shape,
                                  itemsize(t), costs=costs, tid=t.tid)
                for t, spec in zip(m.all_outputs(), m.output_specs()):
                    if spec is not None:
                        specs[t.tid] = machine.global_entries(
                            m.pc, m.AXIS_NAMES, spec, rank=t.ndim)
            continue
        op = model.layers[entry]
        if entry in fusion:
            # fused LM head: the folded projection never runs and the
            # fused loss output records no layout (the legacy behavior)
            continue
        want = op.regrid_input_specs()
        if want is not None:
            for i, (t, spec) in enumerate(zip(op.inputs, want)):
                if spec is None:
                    continue
                dst = machine.global_entries(op.pc, op.AXIS_NAMES, spec,
                                             rank=t.ndim)
                src = specs.get(t.tid)
                if dst is None:
                    continue
                plan.add_edge(op.name, i, src, dst, t.shape, itemsize(t),
                              replicate_unknown=True, costs=costs,
                              tid=t.tid)
        for t, spec in zip(op.all_outputs(), op.output_specs()):
            if spec is not None:
                specs[t.tid] = machine.global_entries(
                    op.pc, op.AXIS_NAMES, spec, rank=t.ndim)
    return plan


# ---------------------------------------------------------------------------
# live-state migration accounting (elastic resize)


def plan_state_migration(old_model, new_model, params: Dict,
                         state: Optional[Dict] = None,
                         opt_state: Optional[Dict] = None) -> Dict:
    """Accounting plan for moving live train state between two MACHINES
    (the elastic runtime's 8->6 shrink, utils/elastic.py) — the
    cross-machine sibling of :class:`RegridPlan`.

    A resize cannot be expressed as in-mesh hops: no mesh spans the old
    and new device sets at once, so every leaf is gathered off its source
    layout (one hop, priced as the all-gather of its replicated form on
    the OLD machine's links) and re-placed sharded on the new layout (one
    hop, the sharded put's per-device slice traffic on the NEW machine —
    a leaf landing replicated pays the full broadcast instead).  Leaves
    whose source layout is already fully replicated skip the gather: a
    surviving device holds the whole value.

    Returns per-key rows plus the totals the ``elastic_resize`` obs
    record carries (``bytes``, ``hops``, ``predicted_s``).  Pure
    accounting — the actual movement is ``np.asarray`` + the new model's
    placement (``FFModel.place_state``), and this plan never touches
    device data."""
    import numpy as np

    from flexflow_tpu.sim.cost_model import dtype_bytes

    old_n = old_model.machine.num_devices
    new_n = new_model.machine.num_devices
    new_topo = new_model.machine.topology
    old_topo = old_model.machine.topology

    def shard_count(model, key):
        for op in model.layers:
            if op.param_key == key or op.name == key:
                return max(op.pc.num_parts, 1)
        return 1

    rows = []
    total_bytes = 0.0
    total_hops = 0
    total_s = 0.0
    trees = [("params", params)]
    if state:
        trees.append(("state", state))
    if opt_state:
        trees.append(("opt", opt_state))
    for tree_name, tree in trees:
        for key, sub in (tree or {}).items():
            kb = 0.0
            for leaf in (sub or {}).values():
                a = np.asarray(leaf) if not hasattr(leaf, "nbytes") else leaf
                kb += float(getattr(a, "size", 0)
                            * dtype_bytes(str(getattr(a, "dtype",
                                                      "float32"))))
            src_parts = shard_count(old_model, key)
            dst_parts = shard_count(new_model, key)
            hops = 0
            secs = 0.0
            if src_parts > 1:
                # gather the sharded source onto one surviving host copy:
                # half an all-reduce of the full value over the old links
                hops += 1
                secs += 0.5 * _allreduce(kb, tuple(range(old_n)), old_topo)
            if dst_parts > 1:
                # sharded re-place: each new device receives its slice
                hops += 1
                secs += kb / dst_parts / new_topo.ici_bandwidth \
                    + new_topo.ici_latency
            else:
                # replicated landing: full broadcast to every survivor
                hops += 1
                secs += 0.5 * _allreduce(kb, tuple(range(new_n)), new_topo)
            rows.append({"tree": tree_name, "key": key, "bytes": kb,
                         "src_parts": src_parts, "dst_parts": dst_parts,
                         "hops": hops, "predicted_s": secs})
            total_bytes += kb
            total_hops += hops
            total_s += secs
    return {"keys": len(rows), "bytes": total_bytes, "hops": total_hops,
            "predicted_s": total_s,
            "from_devices": old_n, "to_devices": new_n, "rows": rows}
