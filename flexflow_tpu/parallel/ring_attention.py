"""Ring attention: context parallelism over the sequence axis.

Each device holds a sequence shard of Q, K, V.  K/V blocks rotate around the
ring via ``ppermute`` while every device accumulates its queries' attention
over the passing blocks with numerically-stable streaming softmax
(flash-attention-style running max / denominator).  Communication rides
neighbor links (ICI-friendly); memory per chip is O(S/P).  Backward is jax
autodiff through the scan + ppermute (the transpose of a ring is the
reverse ring).

This is new capability relative to the reference (no attention ops exist
there); it fills the CP/ring-attention row of SURVEY.md §2.6 and is the
long-context path required of the framework.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _stream_block(q, k, v, m, l, acc, mask):
    """One streaming-softmax accumulation step.

    q: (B, H, Sq, d), k/v: (B, H, Sk, d); m/l: (B, H, Sq); acc like q.
    mask: (Sq, Sk) additive (-inf where disallowed) or None.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = s + mask
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> nan
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(jnp.isinf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    corr = jnp.where(jnp.isfinite(m), corr, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _causal_mask(sq: int, sk: int, q_off, k_off):
    qpos = q_off + jnp.arange(sq)[:, None]
    kpos = k_off + jnp.arange(sk)[None, :]
    return jnp.where(qpos >= kpos, 0.0, -jnp.inf)


def blockwise_attention(q, k, v, causal: bool = False,
                        block_size: Optional[int] = None,
                        q_offset: int = 0, k_offset: int = 0):
    """Single-device streaming attention over K/V blocks (O(S_block^2)
    memory).  q,k,v: (B, H, S, d) -> (B, H, Sq, d), float32 out."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bs = block_size or sk
    m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    qf = q.astype(jnp.float32)
    for start in range(0, sk, bs):
        kb = k[:, :, start:start + bs].astype(jnp.float32)
        vb = v[:, :, start:start + bs]
        mask = _causal_mask(sq, kb.shape[2], q_offset,
                            k_offset + start) if causal else None
        m, l, acc = _stream_block(qf, kb, vb, m, l, acc, mask)
    l = jnp.maximum(l, 1e-30)
    return acc / l[..., None]


def unchecked_shard_map(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions (the
    kw was renamed check_rep -> check_vma around jax 0.8)."""
    import inspect

    try:
        from jax import shard_map  # jax >= 0.8
        _check_kw = ("check_vma"
                     if "check_vma" in inspect.signature(shard_map).parameters
                     else "check_rep")
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
        _check_kw = "check_rep"
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{_check_kw: False})


def ring_attention(q, k, v, mesh, seq_axis: str, causal: bool = False):
    """Ring attention under shard_map.

    q,k,v: GLOBAL (B, H, S, d) arrays; ``mesh`` must contain ``seq_axis``
    (sequence shards) — other mesh axes may shard batch/heads and are passed
    through untouched.  Returns global (B, H, S, d) float32.
    """
    from jax.sharding import PartitionSpec as P

    axes = dict(mesh.shape)
    p = axes[seq_axis]
    if p == 1:
        return blockwise_attention(q, k, v, causal)

    # batch/head sharding: use 'n' / 'h' axes when present in the mesh
    n_ax = "n" if "n" in axes and axes["n"] > 1 else None
    h_ax = "h" if "h" in axes and axes["h"] > 1 else None
    spec = P(n_ax, h_ax, seq_axis, None)

    def local(ql, kl, vl):
        s_local = ql.shape[2]
        idx = lax.axis_index(seq_axis)
        b, h, sq, d = ql.shape
        m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
        l = jnp.zeros((b, h, sq), jnp.float32)
        acc = jnp.zeros((b, h, sq, d), jnp.float32)
        qf = ql.astype(jnp.float32)
        q_off = idx * s_local
        perm = [(i, (i + 1) % p) for i in range(p)]

        def step(carry, t):
            kb, vb, m, l, acc = carry
            src = (idx - t) % p  # whose block we currently hold
            k_off = src * s_local
            mask = _causal_mask(sq, s_local, q_off, k_off) if causal else None
            m, l, acc = _stream_block(qf, kb.astype(jnp.float32), vb,
                                      m, l, acc, mask)
            kb = lax.ppermute(kb, seq_axis, perm)
            vb = lax.ppermute(vb, seq_axis, perm)
            return (kb, vb, m, l, acc), 0.0

        (kb, vb, m, l, acc), _ = lax.scan(step, (kl, vl, m, l, acc),
                                          jnp.arange(p))
        l = jnp.maximum(l, 1e-30)
        return acc / l[..., None]

    return unchecked_shard_map(local, mesh, (spec, spec, spec), spec)(q, k, v)
