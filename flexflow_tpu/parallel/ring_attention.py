"""Ring attention: context parallelism over the sequence axis.

Each device holds a sequence shard of Q, K, V.  K/V blocks rotate around the
ring via ``ppermute`` while every device accumulates its queries' attention
over the passing blocks with numerically-stable streaming softmax
(flash-attention-style running max / denominator).  Communication rides
neighbor links (ICI-friendly); memory per chip is O(S/P).  Backward is jax
autodiff through the scan + ppermute (the transpose of a ring is the
reverse ring).

This is new capability relative to the reference (no attention ops exist
there); it fills the CP/ring-attention row of SURVEY.md §2.6 and is the
long-context path required of the framework.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import lax


def _stream_block(q, k, v, m, l, acc, mask):
    """One streaming-softmax accumulation step.

    q: (B, H, Sq, d), k/v: (B, H, Sk, d); m/l: (B, H, Sq); acc like q.
    mask: (Sq, Sk) additive (-inf where disallowed) or None.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = s + mask
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> nan
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    if mask is not None:
        p = jnp.where(jnp.isinf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    corr = jnp.where(jnp.isfinite(m), corr, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _causal_mask(sq: int, sk: int, q_off, k_off):
    qpos = q_off + jnp.arange(sq)[:, None]
    kpos = k_off + jnp.arange(sk)[None, :]
    return jnp.where(qpos >= kpos, 0.0, -jnp.inf)


def blockwise_attention(q, k, v, causal: bool = False,
                        block_size: Optional[int] = None,
                        q_offset: int = 0, k_offset: int = 0):
    """Single-device streaming attention over K/V blocks (O(S_block^2)
    memory).  q,k,v: (B, H, S, d) -> (B, H, Sq, d), float32 out."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bs = block_size or sk
    m = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    qf = q.astype(jnp.float32)
    for start in range(0, sk, bs):
        kb = k[:, :, start:start + bs].astype(jnp.float32)
        vb = v[:, :, start:start + bs]
        mask = _causal_mask(sq, kb.shape[2], q_offset,
                            k_offset + start) if causal else None
        m, l, acc = _stream_block(qf, kb, vb, m, l, acc, mask)
    l = jnp.maximum(l, 1e-30)
    return acc / l[..., None]


def unchecked_shard_map(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions (the
    kw was renamed check_rep -> check_vma around jax 0.8)."""
    import inspect

    try:
        from jax import shard_map  # jax >= 0.8
        _check_kw = ("check_vma"
                     if "check_vma" in inspect.signature(shard_map).parameters
                     else "check_rep")
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
        _check_kw = "check_rep"
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{_check_kw: False})


def ring_attention(q, k, v, mesh, seq_axis: str, causal: bool = False):
    """Ring attention under shard_map.

    q,k,v: GLOBAL (B, H, S, d) arrays; ``mesh`` must contain ``seq_axis``
    (sequence shards) — other mesh axes may shard batch/heads and are passed
    through untouched.  Returns global (B, H, S, d) float32.
    """
    from jax.sharding import PartitionSpec as P

    axes = dict(mesh.shape)
    p = axes[seq_axis]
    if p == 1:
        return blockwise_attention(q, k, v, causal)

    # batch/head sharding: use 'n' / 'h' axes when present in the mesh
    n_ax = "n" if "n" in axes and axes["n"] > 1 else None
    h_ax = "h" if "h" in axes and axes["h"] > 1 else None
    spec = P(n_ax, h_ax, seq_axis, None)

    from flexflow_tpu.ops.pallas import flash_enabled

    use_flash = flash_enabled()

    def ring_kv(kl, vl, state, attend_step):
        """The ring protocol, shared by both bodies: K/V chunks rotate to
        the next neighbor each step; ``attend_step(t, kb, vb, state)``
        folds the resident chunk into the running state."""
        perm = [(i, (i + 1) % p) for i in range(p)]

        def step(carry, t):
            kb, vb, state = carry
            state = attend_step(t, kb, vb, state)
            kb = lax.ppermute(kb, seq_axis, perm)
            vb = lax.ppermute(vb, seq_axis, perm)
            return (kb, vb, state), 0.0

        (_, _, state), _ = lax.scan(step, (kl, vl, state), jnp.arange(p))
        return state

    def local_flash(ql, kl, vl):
        """Ring step body on the Pallas kernel: each step attends Q against
        the resident K/V chunk via flash_attention_partial and merges by
        log-sum-exp weight.  Causal masking never needs chunk offsets: a
        step is either fully visible (source chunk strictly behind this
        device's queries -> plain attention), diagonal (same chunk ->
        plain causal), or fully hidden (skip) — so the kernels stay
        offset-free and static."""
        from flexflow_tpu.ops.pallas.flash_attention import (
            combine_partials, flash_attention_partial)

        idx = lax.axis_index(seq_axis)
        b, h, sq, d = ql.shape

        def attend(t, kb, vb, state):
            o, lse = state
            src = (idx - t) % p  # whose chunk we currently hold
            if causal:
                def full_fn(args):
                    return flash_attention_partial(*args, causal=False)

                def diag_fn(args):
                    return flash_attention_partial(*args, causal=True)

                def masked_fn(args):
                    return (jnp.zeros((b, h, sq, d), jnp.float32),
                            jnp.full((b, h, sq), -jnp.inf, jnp.float32))

                branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
                o_t, lse_t = lax.switch(branch, [full_fn, diag_fn, masked_fn],
                                        (ql, kb, vb))
            else:
                o_t, lse_t = flash_attention_partial(ql, kb, vb, causal=False)
            return combine_partials(o, lse, o_t, lse_t)

        o, _ = ring_kv(kl, vl,
                       (jnp.zeros((b, h, sq, d), jnp.float32),
                        jnp.full((b, h, sq), -jnp.inf, jnp.float32)),
                       attend)
        return o

    def local(ql, kl, vl):
        s_local = ql.shape[2]
        idx = lax.axis_index(seq_axis)
        b, h, sq, d = ql.shape
        qf = ql.astype(jnp.float32)
        q_off = idx * s_local

        def attend(t, kb, vb, state):
            m, l, acc = state
            src = (idx - t) % p  # whose block we currently hold
            k_off = src * s_local
            mask = _causal_mask(sq, s_local, q_off, k_off) if causal else None
            return _stream_block(qf, kb.astype(jnp.float32), vb,
                                 m, l, acc, mask)

        m, l, acc = ring_kv(kl, vl,
                            (jnp.full((b, h, sq), -jnp.inf, jnp.float32),
                             jnp.zeros((b, h, sq), jnp.float32),
                             jnp.zeros((b, h, sq, d), jnp.float32)),
                            attend)
        l = jnp.maximum(l, 1e-30)
        return acc / l[..., None]

    body = local_flash if use_flash else local
    return unchecked_shard_map(body, mesh, (spec, spec, spec), spec)(q, k, v)
