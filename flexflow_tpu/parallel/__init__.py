"""Distributed execution primitives beyond plain GSPMD: explicit ring
collectives for sequence/context parallelism (capability extension over the
reference, which has no attention at all — SURVEY.md §2.6 CP row)."""

from flexflow_tpu.parallel.pipeline import (microbatch, spmd_pipeline,
                                            transformer_block_fn)
from flexflow_tpu.parallel.ring_attention import (blockwise_attention,
                                                  ring_attention)

__all__ = ["blockwise_attention", "microbatch", "ring_attention",
           "spmd_pipeline", "transformer_block_fn"]
