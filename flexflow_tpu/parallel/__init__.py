"""Distributed execution primitives beyond plain GSPMD: explicit ring
collectives for sequence/context parallelism (capability extension over the
reference, which has no attention at all — SURVEY.md §2.6 CP row)."""

from flexflow_tpu.parallel.ring_attention import (blockwise_attention,
                                                  ring_attention)

__all__ = ["blockwise_attention", "ring_attention"]
