"""Explicit operator placement: executing ops on the device subsets their
strategy names.

The reference pins each NMT op instance to a specific GPU via mapper tags
(nmt/rnn_mapper.cc:28-41, 131-135); ops pinned to disjoint GPU sets then
execute concurrently under Legion's async task graph — that is where its
operator parallelism and wavefront pipelining over chunk ops come from
(nmt/rnn.cu:298-326).  Under XLA a jitted program is ONE SPMD computation
over ONE device assignment, so subset placement cannot be a mapper decision
made outside the program; it has to be compiled INTO it.  The mechanism
here:

  * the machine is viewed as a mesh ``("_pg", *op_grid_axes)``: a leading
    *placement-group* axis of size ``num_devices / subset_size`` over the
    op's own partition grid;
  * ops placed on disjoint subsets (and mutually independent in the DAG)
    are merged into one PLACEMENT GROUP, executed by a single
    ``shard_map`` whose body switches on ``lax.axis_index("_pg")`` — each
    device-group runs exactly its own op's branch (MPMD expressed inside
    SPMD), device-groups owning no op contribute zeros that are never
    consumed;
  * each member's parameters are stacked along the group axis and sharded
    over it, so weights physically live only on the subset that computes
    with them;
  * the member's own grid (e.g. Linear's (c, n)) partitions work *within*
    its subset via the inner mesh axes, with shard_map's transpose
    inserting the cross-shard reductions (the reference's BWD2/updateGAS).

Supported placements: each op's ``devices`` must be one aligned contiguous
block ``[g*P, (g+1)*P)`` of the machine (P = the op's grid size), or — the
stride family, round 3 — one constant-stride set ``{b + j*(N/P)}`` such as
``(0,2,4,6)``, executed on exactly the named devices via a strided
placement mesh.  Whole-machine device *permutations* are honored one level
up: FFModel rebuilds its machine view on the permuted order
(model.py _permuted_machine_view).  Ops are
groupable when they declare their input partitioning (``Op.input_specs``)
and either share shapes/hyperparameters (``Op.placement_signature`` — the
homogeneous fast path, params stacked with their inner sharding kept) or
are merely *grid-compatible* (same grid dims/axes, block-replicated
params, agreeing output positions — the HETEROGENEOUS path, round-3:
different op kinds run as different branches of one switch, params
flattened to a padded f32 vector stacked over the group axis, outputs
padded to a per-position union aval).  That restores the reference's
Legion-style concurrency between *different* ops on disjoint device sets
(embeds on one block while LSTMs run on another, nmt/rnn.cu:298-326,
nmt/rnn_mapper.cc:28-41).  Anything else degrades to the replicated
normalization in ``MachineModel.sharding`` with a warning.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ops.base import Op


@dataclasses.dataclass
class PlacementGroup:
    """A set of independent ops executing concurrently on disjoint device
    subsets: contiguous blocks, constant-stride sets (``strided``), or —
    the general "set" family (round 4) — arbitrary duplicate-free device
    lists honored in their NAMED order via ``device_rows``."""

    members: List[Op]
    indices: List[int]        # layer indices of members
    slots: List[int]          # device-block index per member
    subset_size: int          # devices per member (= pc.num_parts)
    n_groups: int             # machine blocks of that size
    strided: bool = False     # stride family: slot b owns {b + j*(N/P)}
    #: set family: row g of the placement mesh is exactly device_rows[g]
    #: (member order; the machine pads remaining devices as zero rows)
    device_rows: Optional[List[Tuple[int, ...]]] = None


def placement_slot(op: Op, num_devices: int):
    """("block", g) when ``op``'s ParallelConfig names the contiguous
    device block ``[g*P, (g+1)*P)``; ("stride", b) when it names the
    constant-stride set ``{b + j*(N/P)}`` (VERDICT r2 #3b, e.g.
    ``devices=(0,2,4,6)``); ("set", devices) — round 4, closing
    SURVEY §2.4 — for ANY other duplicate-free list, honored in its
    NAMED order on a mesh whose rows are the listed devices (the
    reference's RnnMapper pins a task to any named GPU,
    nmt/rnn_mapper.cc:131-135).  None when the op cannot run placed
    (no placed support for this grid, duplicates, or a grid that does
    not divide the machine) — those normalize with a warning."""
    pc = op.pc
    p = pc.num_parts
    if num_devices <= 1 or p > num_devices or num_devices % p:
        return None
    if op.placement_signature() is None or op.input_specs() is None:
        return None
    if op.init_state() and op.state_specs() is None:
        return None  # stateful op without placed-state support
    if len(set(pc.devices)) != p:
        return None
    if p == num_devices:
        # full-machine lists: canonical order is the normal (free) path;
        # a single foreign permutation is absorbed by the machine-view
        # rebuild (model._permuted_machine_view) before ops are built, so
        # reaching here non-canonical means CONFLICTING permutations —
        # honor each via per-device dispatch (resharding at entry/exit)
        if pc.devices == tuple(range(num_devices)):
            return None
        return ("set", tuple(pc.devices)) if _set_eligible(op) else None
    # block/stride detection is order-insensitive: a strict-subset grid is
    # placement-symmetric (which grid point lands on which member device
    # permutes shard routing only), so the device SET decides the family —
    # e.g. a permuted-machine remap listing a block in reversed order
    # stays a plain block
    devs = tuple(sorted(pc.devices))
    d0 = devs[0]
    g, rem = divmod(d0, p)
    if rem == 0 and devs == tuple(range(g * p, (g + 1) * p)):
        return ("block", g)
    s = num_devices // p
    if d0 < s and devs == tuple(d0 + j * s for j in range(p)):
        return ("stride", d0)
    return ("set", tuple(pc.devices)) if _set_eligible(op) else None


def _set_eligible(op: Op) -> bool:
    """Can ``op`` run under set-family per-device dispatch?  The runner
    slices every operand per grid point and calls plain ``forward``, so
    the op must be point-local: no collective prelude or grid-aware
    sharded_forward for its grid (``placed_local``), no state, and every
    spec entry a single axis name or None (the slicer's vocabulary)."""
    if not op.placed_local() or op.init_state():
        return False

    def ok(spec):
        return spec is not None and all(
            e is None or isinstance(e, str) for e in tuple(spec))

    outs = op.output_specs()
    if outs is None or not all(ok(s) for s in outs):
        return False
    if not all(ok(s) for s in op.input_specs()):
        return False
    return all(ok(s) for s in op.param_specs().values())


def _signature(op: Op) -> tuple:
    return (type(op).__name__, op.pc.dims,
            tuple((t.shape, t.dtype) for t in op.inputs),
            tuple((t.shape, t.dtype) for t in op.all_outputs()),
            op.placement_signature())


def _params_block_replicated(op: Op) -> bool:
    """True when ``op``'s params are replicated *within* its placement
    block under its grid (every spec axis has grid size 1) — the
    heterogeneous path carries params as one flat vector per block and
    cannot preserve inner param sharding."""
    specs = op.param_specs()
    if not specs:
        return True
    sizes = dict(zip(op.AXIS_NAMES, op.pc.dims))
    for spec in specs.values():
        for entry in spec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if sizes.get(a, 1) != 1:
                    return False
    return True


def _out_positions(op: Op):
    """Per output position: (spec entries, rank, sharded-dim extents,
    dtype) — the compatibility record heterogeneous grouping checks so
    every member's position-k output can share one switch aval and one
    out_spec."""
    sizes = dict(zip(op.AXIS_NAMES, op.pc.dims))
    out = []
    for t, spec in zip(op.all_outputs(), op.output_specs()):
        entries = tuple(spec) if spec is not None else None
        sharded = []
        if entries is not None:
            for d, e in enumerate(entries):
                if e is None:
                    continue
                names = e if isinstance(e, tuple) else (e,)
                if any(sizes.get(a, 1) > 1 for a in names):
                    sharded.append((d, t.shape[d]))
        out.append((entries, t.ndim, tuple(sharded), t.dtype))
    return tuple(out)


def _hetero_eligible(op: Op) -> bool:
    """Can ``op`` join a heterogeneous (mixed-kind) placement group?"""
    if op.init_state():
        return False  # state threading is homogeneous-path only
    if not _params_block_replicated(op):
        return False
    if op.output_specs() is None or any(s is None
                                        for s in op.output_specs()):
        return False
    return all(t.dtype != "int32" for t in op.all_outputs())


def _hetero_compatible(a, b) -> bool:
    """Output-position compatibility of two _out_positions records: shared
    positions must agree on spec, rank and sharded-dim extents (unsharded
    dims are zero-padded to the union; sharded dims cannot be)."""
    for pa, pb in zip(a, b):
        if pa[:3] != pb[:3]:
            return False
    return True


def plan_schedule(layers: Sequence[Op], num_devices: int,
                  exclude: frozenset = frozenset()):
    """Dataflow schedule for ``layers``: a list whose entries are either a
    layer index (execute that op normally) or a :class:`PlacementGroup`
    (execute its members jointly, placed).  ``exclude`` holds layer
    indices that must stay un-placed (e.g. ops claimed by the fused-LM-head
    plan).  Placed ops out of original order are legal because scheduling
    is by dependencies, like the reference's Legion task graph — grouping
    independent ops can never create a cycle (a path between group members
    would make one an ancestor of the other, which grouping forbids)."""
    n = len(layers)
    prod_idx: Dict[int, int] = {}
    for i, op in enumerate(layers):
        for t in op.all_outputs():
            prod_idx[t.tid] = i
    deps: List[List[int]] = []
    anc: List[set] = []
    for i, op in enumerate(layers):
        d = sorted({prod_idx[t.tid] for t in op.inputs
                    if t.tid in prod_idx})
        deps.append(d)
        a = set()
        for p in d:
            a |= anc[p]
            a.add(p)
        anc.append(a)

    # ---- grouping ----
    # Same-signature joins first (the homogeneous fast path keeps inner
    # param sharding); a leftover op may then join a *grid-compatible*
    # group heterogeneously — mixed op kinds as different switch branches
    # (Legion concurrency between different ops, nmt/rnn.cu:298-326).
    groups: List[dict] = []
    open_by_sig: Dict[tuple, List[dict]] = {}
    open_by_grid: Dict[tuple, List[dict]] = {}
    group_of: Dict[int, int] = {}

    def conflicts(fam, g, slots):
        """Can slot ``g`` not coexist with ``slots``?  Block/stride slots
        collide on equality; set-family slots are device tuples and
        collide on any overlap."""
        if fam == "set":
            gs = set(g)
            return any(gs & set(s) for s in slots)
        return g in slots

    def join(grp, i, g, elig, pos):
        grp["indices"].append(i)
        grp["slots"].append(g)
        grp["hetero_ok"] = grp["hetero_ok"] and elig
        if pos is not None and grp["pos"] is not None \
                and len(pos) > len(grp["pos"]):
            grp["pos"] = pos
        group_of[i] = grp["id"]

    for i, op in enumerate(layers):
        if i in exclude:
            continue
        slot = placement_slot(op, num_devices)
        if slot is None:
            continue
        fam, g = slot
        sig = _signature(op)
        # set-family groups are homogeneous-only: their per-device switch
        # slices operands by ONE shared spec set
        elig = fam != "set" and _hetero_eligible(op)
        pos = _out_positions(op) if elig else None
        placed = False
        for grp in open_by_sig.get(sig, []):
            if grp["family"] != fam or conflicts(fam, g, grp["slots"]):
                continue
            if any(m in anc[i] for m in grp["indices"]):
                continue  # dependency path member -> op
            join(grp, i, g, elig, pos)
            placed = True
            break
        if not placed and elig:
            for grp in open_by_grid.get(
                    (op.pc.dims, op.AXIS_NAMES, fam), []):
                if not grp["hetero_ok"] or conflicts(fam, g, grp["slots"]):
                    continue
                if any(m in anc[i] for m in grp["indices"]):
                    continue
                if not _hetero_compatible(grp["pos"], pos):
                    continue
                join(grp, i, g, elig, pos)
                placed = True
                break
        if not placed:
            grp = {"id": len(groups), "indices": [i], "slots": [g],
                   "subset": op.pc.num_parts, "hetero_ok": elig,
                   "pos": pos, "family": fam}
            groups.append(grp)
            open_by_sig.setdefault(sig, []).append(grp)
            if elig:
                open_by_grid.setdefault(
                    (op.pc.dims, op.AXIS_NAMES, fam), []).append(grp)
            group_of[i] = grp["id"]

    # ---- merge into schedule nodes + topological order ----
    # Merging keeps each group acyclic (a path between members would make
    # one an ancestor of the other), but cycles can still arise BETWEEN two
    # multi-member group nodes (A->B and C->D with {A,D} and {B,C} merged).
    # When the topological sort detects one, split the last-added member
    # out of an involved multi-member group and retry — each split strictly
    # shrinks a group, so this terminates.
    while True:
        node_members: List[List[int]] = []
        node_of_layer: Dict[int, int] = {}
        node_group: List[Optional[int]] = []
        for i in range(n):
            if i in node_of_layer:
                continue
            if i in group_of:
                members = groups[group_of[i]]["indices"]
                nid = len(node_members)
                node_members.append(members)
                node_group.append(group_of[i])
                for j in members:
                    node_of_layer[j] = nid
            else:
                nid = len(node_members)
                node_members.append([i])
                node_group.append(None)
                node_of_layer[i] = nid

        nn = len(node_members)
        ndeps: List[set] = [set() for _ in range(nn)]
        nsucc: List[set] = [set() for _ in range(nn)]
        for nid, members in enumerate(node_members):
            for i in members:
                for p in deps[i]:
                    pn = node_of_layer[p]
                    if pn != nid:
                        ndeps[nid].add(pn)
                        nsucc[pn].add(nid)
        indeg = [len(d) for d in ndeps]
        heap = [(min(node_members[nid]), nid) for nid in range(nn)
                if indeg[nid] == 0]
        heapq.heapify(heap)
        schedule = []
        done = [False] * nn
        while heap:
            _, nid = heapq.heappop(heap)
            done[nid] = True
            gid = node_group[nid]
            if gid is None:
                schedule.append(node_members[nid][0])
            else:
                grp = groups[gid]
                is_set = grp["family"] == "set"
                schedule.append(PlacementGroup(
                    members=[layers[i] for i in grp["indices"]],
                    indices=list(grp["indices"]),
                    # set family: members occupy mesh rows 0..m-1 in join
                    # order; the remaining rows hold the unlisted devices
                    slots=(list(range(len(grp["indices"]))) if is_set
                           else list(grp["slots"])),
                    subset_size=grp["subset"],
                    n_groups=num_devices // grp["subset"],
                    strided=grp["family"] == "stride",
                    device_rows=(list(grp["slots"]) if is_set else None)))
            for s in nsucc[nid]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    heapq.heappush(heap, (min(node_members[s]), s))
        if len(schedule) == nn:
            return schedule
        split = None
        for nid in range(nn):
            if not done[nid] and node_group[nid] is not None \
                    and len(node_members[nid]) > 1:
                split = node_group[nid]
                break
        assert split is not None, "cycle without a splittable group"
        last = groups[split]["indices"].pop()
        groups[split]["slots"].pop()
        fam_last, slot_last = placement_slot(layers[last], num_devices)
        grp = {"id": len(groups), "indices": [last],
               "slots": [slot_last],
               "subset": layers[last].pc.num_parts,
               "hetero_ok": False, "pos": None, "family": fam_last}
        groups.append(grp)
        group_of[last] = grp["id"]


def run_group(machine, group: PlacementGroup,
              params_by_member: List[Dict],
              inputs_by_member: List[List], train: bool,
              states_by_member: Optional[List[Dict]] = None):
    """Execute a placement group jointly.  Returns
    ``(outs_by_member, new_states_by_member)``: per member, the tuple of
    its output arrays (each sliced from the group-stacked result, so it
    physically lives on that member's device block) and its new state
    dict ({} for stateless members)."""
    if states_by_member is None:
        states_by_member = [{} for _ in group.members]
    if group.device_rows is not None:
        assert all(not s for s in states_by_member), \
            "set-family groups are stateless (placement_slot gates this)"
        return _run_group_set(machine, group, params_by_member,
                              inputs_by_member, train)
    if len({_signature(op) for op in group.members}) > 1:
        return _run_group_hetero(machine, group, params_by_member,
                                 inputs_by_member, train)
    return _run_group_homogeneous(machine, group, params_by_member,
                                  inputs_by_member, train,
                                  states_by_member)


def set_group_assignment(group: PlacementGroup,
                         axis_names: Tuple[str, ...]):
    """{device: (member, grid-linear, per-axis index dict)} of a
    set-family group — the contract the per-device dispatch executes:
    member m's grid point j (dim 0 fastest) runs on
    ``device_rows[m][j]``, the reference's RnnMapper semantics
    (nmt/rnn_mapper.cc:131-135)."""
    out = {}
    dims = group.members[0].pc.dims
    for m, row in enumerate(group.device_rows):
        for j, dev in enumerate(row):
            rem, idx = j, {}
            for a, d in zip(axis_names, dims):
                idx[a] = rem % d
                rem //= d
            out[dev] = (m, j, idx)
    return out


def _point_slice(arr, spec, sizes, idx):
    """Static slice of one grid point's block of ``arr`` per its
    PartitionSpec (single-axis-or-None entries — _set_eligible's bar)."""
    entries = tuple(spec) + (None,) * (arr.ndim - len(tuple(spec)))
    sl = []
    for d, e in enumerate(entries):
        parts = sizes.get(e, 1) if e is not None else 1
        if parts == 1:
            sl.append(slice(None))
        else:
            n = arr.shape[d] // parts
            sl.append(slice(idx[e] * n, (idx[e] + 1) * n))
    return arr[tuple(sl)]


def _assemble(shards, spec, sizes, axis_names, dims):
    """Inverse of _point_slice over the whole grid: stitch the per-point
    shards (grid-linear order, dim 0 fastest) back into the global
    tensor.  A grid axis absent from the spec replicates the output —
    keep the first copy."""
    import jax.numpy as jnp

    entries = tuple(spec)
    dim_of = {e: d for d, e in enumerate(entries) if e is not None}
    lists = list(shards)
    for a, p in zip(axis_names, dims):
        if p == 1:
            continue
        d = dim_of.get(a)
        nxt = []
        for g in range(len(lists) // p):
            chunk = lists[g * p:(g + 1) * p]
            nxt.append(jnp.concatenate(chunk, axis=d)
                       if d is not None else chunk[0])
        lists = nxt
    assert len(lists) == 1
    return lists[0]


def _run_group_set(machine, group: PlacementGroup,
                   params_by_member: List[Dict],
                   inputs_by_member: List[List], train: bool):
    """Arbitrary-device-list members (round 4, closing SURVEY §2.4): an
    irregular list like ``(0,3,5,6)`` cannot be a mesh reordering (XLA
    admits ONE device assignment per computation; block/stride placement
    meshes work only because they reshape the canonical order), so the
    group runs on the canonical flat ``(_dev,)`` mesh and every device
    switches on its own id to the (member, grid point) the strategy
    assigned it — the reference's tag-based per-task pinning
    (nmt/rnn_mapper.cc:28-41) compiled into one SPMD computation.

    The price, paid at group entry/exit rather than silently dropping the
    placement (the pre-round-4 normalization): operands are replicated to
    all devices (each branch statically slices its point's block), and
    outputs return through a per-device stacked array."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    axes = op0.AXIS_NAMES
    dims = op0.pc.dims
    sizes = dict(zip(axes, dims))
    mesh = machine.flat_mesh()
    N = machine.num_devices
    assign = set_group_assignment(group, axes)
    in_specs_per_op = op0.input_specs()
    out_specs_per_op = op0.output_specs()
    pspecs = op0.param_specs()
    k_in = len(in_specs_per_op)

    have_params = bool(params_by_member and params_by_member[0])
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *params_by_member) \
        if have_params else {}
    flat_inputs = [x for xs in inputs_by_member for x in xs]

    def body(sp, *flat):
        dev = lax.axis_index("_dev")
        xs_by_member = [list(flat[m * k_in:(m + 1) * k_in])
                        for m in range(len(ops))]

        def branch_for(m, idx):
            def br(_):
                # params: member m's leaves, each sliced to the point
                lp = {k: _point_slice(v[m], pspecs[k], sizes, idx)
                      for k, v in sp.items()} if have_params else {}
                xs = [_point_slice(x, s, sizes, idx)
                      for x, s in zip(xs_by_member[m], in_specs_per_op)]
                res, _ = ops[m].forward(lp, {}, xs, train)
                outs = res if isinstance(res, tuple) else (res,)
                return tuple(jnp.expand_dims(o, 0) for o in outs)
            return br

        owned = {d: branch_for(m, idx) for d, (m, _, idx) in assign.items()}
        shapes = jax.eval_shape(next(iter(owned.values())), 0)

        def zero_branch(_):
            return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

        branches = [owned.get(d, zero_branch) for d in range(N)]
        return lax.switch(dev, branches, 0)

    n_out = len(out_specs_per_op)
    res = unchecked_shard_map(
        body, mesh,
        (jax.tree.map(lambda _: P(), stacked),) + (P(),) * len(flat_inputs),
        tuple(P("_dev") for _ in range(n_out)))(stacked, *flat_inputs)

    out = []
    for m, row in enumerate(group.device_rows):
        vals = []
        for r, spec in zip(res, out_specs_per_op):
            shards = [r[d] for d in row]  # grid-linear order by contract
            v = _assemble(shards, spec, sizes, axes, dims)
            v = lax.with_sharding_constraint(
                v, machine.sharding(ops[m].pc, axes, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out, [{} for _ in ops]


def _run_group_homogeneous(machine, group: PlacementGroup,
                           params_by_member: List[Dict],
                           inputs_by_member: List[List], train: bool,
                           states_by_member: List[Dict]):
    """Same-signature members: params (and state, round 3 — lifting the
    BatchNorm exclusion) stacked leaf-wise over the group axis with their
    inner sharding preserved; every branch shares one output aval.
    Branches run ``sharded_forward``, so grid-aware ops (spatial-halo
    convs, global-stats BatchNorm) see the live inner mesh axes."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    G = group.n_groups
    axes = op0.AXIS_NAMES
    mesh = machine.placement_mesh(op0.pc.dims, axes,
                                  strided=group.strided)
    slots = group.slots
    k_in = len(op0.input_specs())

    def stack_leaf(*member_leaves):
        by = dict(zip(slots, member_leaves))
        z = jnp.zeros_like(member_leaves[0])
        return jnp.stack([by.get(g, z) for g in range(G)])

    # ---- stack params along the group axis (zeros in unowned blocks) ----
    have_params = bool(params_by_member and params_by_member[0])
    if have_params:
        stacked = jax.tree.map(stack_leaf, *params_by_member)
        pspecs = {k: P("_pg", *spec)
                  for k, spec in op0.param_specs().items()}
    else:
        stacked = {}
        pspecs = {}
    # ---- state threaded the same way (state_specs gates placement) ----
    have_state = bool(states_by_member and states_by_member[0])
    if have_state:
        stacked_state = jax.tree.map(stack_leaf, *states_by_member)
        sspecs = {k: P("_pg", *spec)
                  for k, spec in op0.state_specs().items()}
        state_keys = sorted(states_by_member[0])
    else:
        stacked_state = {}
        sspecs = {}
        state_keys = []

    in_specs = (pspecs, sspecs) + tuple(op0.input_specs()) * len(ops)
    n_out = len(op0.output_specs())
    out_specs = tuple(P("_pg", *spec) for spec in op0.output_specs()) + \
        tuple(P("_pg", *op0.state_specs()[k]) for k in state_keys)
    flat_inputs = [x for xs in inputs_by_member for x in xs]

    def body(sp, st, *flat):
        local_params = jax.tree.map(lambda a: a[0], sp)
        local_state = jax.tree.map(lambda a: a[0], st)
        gidx = lax.axis_index("_pg")
        xs_by_member = [list(flat[m * k_in:(m + 1) * k_in])
                        for m in range(len(ops))]

        # collective preludes (halo exchange, cross-shard statistics) run
        # for every member UNCONDITIONALLY — member inputs are replicated
        # over the group axis, so this is uniform across device blocks;
        # collectives inside the switch branches would be illegal SPMD
        aux_by_member = [ops[m].placed_prelude(xs_by_member[m], train)
                         for m in range(len(ops))]

        def branch_for(m):
            def br(_):
                res, new_st = ops[m].sharded_forward(
                    local_params, local_state, xs_by_member[m], train,
                    aux=aux_by_member[m])
                outs = res if isinstance(res, tuple) else (res,)
                outs = outs + tuple(new_st[k] for k in state_keys)
                return tuple(jnp.expand_dims(o, 0) for o in outs)
            return br

        owned = {g: branch_for(m) for m, g in enumerate(slots)}
        shapes = jax.eval_shape(owned[slots[0]], 0)

        def zero_branch(_):
            return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

        branches = [owned.get(g, zero_branch) for g in range(G)]
        return lax.switch(gidx, branches, 0)

    res = unchecked_shard_map(body, mesh, in_specs, out_specs)(
        stacked, stacked_state, *flat_inputs)
    new_states = []
    for g in slots:
        new_states.append({k: res[n_out + i][g]
                           for i, k in enumerate(state_keys)})
    res = res[:n_out]
    # Constrain each sliced member output to its pc's normalized sharding
    # (grid over the fast global axes, replicated over the rest).  This
    # splits the stacked->consumer regrid into an explicit gather over the
    # group axis plus a free slice; without the waypoint GSPMD relates the
    # stacked layout to the consumer's (e.g. full-DP) layout in one jump
    # and falls back to involuntary full rematerialization in the backward.
    out = []
    for g, m in zip(slots, ops):
        vals = []
        for r, spec in zip(res, op0.output_specs()):
            v = r[g]
            if spec is not None:
                v = lax.with_sharding_constraint(
                    v, machine.sharding(m.pc, m.AXIS_NAMES, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out, new_states


def _run_group_hetero(machine, group: PlacementGroup,
                      params_by_member: List[Dict],
                      inputs_by_member: List[List], train: bool):
    """Mixed-kind members (round-3): each member is its own switch branch.

    lax.switch requires every branch to return identical avals, and the
    members' param trees don't mirror, so:

      * params: each member's tree is flattened, raveled to ONE f32
        vector, zero-padded to the group max and stacked over the group
        axis — sharded ``P("_pg")``, so weights still physically live only
        on the block that computes with them (the branch unflattens its
        slice back to shapes/dtypes).  Grouping admits only members whose
        params are replicated within their block
        (:func:`_params_block_replicated`), so no inner sharding is lost.
      * inputs: per-member ``input_specs`` (counts and ranks may differ) —
        the flat argument list concatenates every member's inputs.
      * outputs: padded to the per-position union aval (grouping
        guaranteed shared positions agree on spec/rank/sharded extents —
        only unsharded dims pad); missing positions are zeros.  The caller
        crops each member's outputs back to its true shapes/dtypes.

    This is the reference's operator parallelism: different Legion tasks
    on disjoint GPU sets executing concurrently (nmt/rnn.cu:298-326),
    compiled into one SPMD computation.
    """
    import math as _math

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    G = group.n_groups
    mesh = machine.placement_mesh(op0.pc.dims, op0.AXIS_NAMES,
                                  strided=group.strided)
    slots = group.slots

    # ---- params: flatten -> f32 ravel -> pad -> stack over _pg ----
    metas = []   # per member: (treedef, [(shape, dtype)])
    vecs = []
    for m, p in zip(ops, params_by_member):
        leaves, treedef = jax.tree.flatten(p)
        for l in leaves:
            # the vector rides through f32: exact for f32/bf16/f16 leaves,
            # lossy for anything else — fail loudly rather than corrupt
            if str(l.dtype) not in ("float32", "bfloat16", "float16"):
                raise TypeError(
                    f"heterogeneous placement of {m.name!r}: param dtype "
                    f"{l.dtype} does not round-trip through the f32 "
                    f"group vector")
        metas.append((treedef,
                      [(l.shape, str(l.dtype)) for l in leaves]))
        vecs.append(
            jnp.concatenate([l.ravel().astype(jnp.float32)
                             for l in leaves])
            if leaves else jnp.zeros((0,), jnp.float32))
    lmax = max((v.shape[0] for v in vecs), default=0)
    by_slot = {g: jnp.pad(v, (0, lmax - v.shape[0]))
               for g, v in zip(slots, vecs)}
    zero_vec = jnp.zeros((lmax,), jnp.float32)
    stacked = jnp.stack([by_slot.get(g, zero_vec) for g in range(G)])

    member_in_specs = [m.input_specs() for m in ops]
    in_specs = (P("_pg", None),) + tuple(s for specs in member_in_specs
                                         for s in specs)
    flat_inputs = [x for xs in inputs_by_member for x in xs]
    # the members' REAL global output avals (declared Tensor dtypes can be
    # stale under compute-dtype propagation): crop/cast targets
    real_avals = []
    for m in range(len(ops)):
        def fwd(m=m):
            res, _ = ops[m].forward(params_by_member[m], {},
                                    inputs_by_member[m], train)
            return res if isinstance(res, tuple) else (res,)
        real_avals.append(jax.eval_shape(fwd))
    offs = [0]
    for specs in member_in_specs:
        offs.append(offs[-1] + len(specs))

    # out_specs from the first member carrying each position
    pos_spec = {}
    for m in ops:
        for k, spec in enumerate(m.output_specs()):
            pos_spec.setdefault(k, spec)
    n_pos = len(pos_spec)

    def body(sp, *flat):
        local_vec = sp[0]
        gidx = lax.axis_index("_pg")
        # collective preludes run for every member unconditionally (same
        # rationale as the homogeneous path: member inputs are replicated
        # over the group axis; collectives inside branches are illegal)
        aux_by_member = [
            ops[m].placed_prelude(list(flat[offs[m]:offs[m + 1]]), train)
            for m in range(len(ops))]

        def raw_branch(m):
            def br(_):
                treedef, leaf_meta = metas[m]
                leaves = []
                off = 0
                for shape, dtype in leaf_meta:
                    size = int(_math.prod(shape))
                    leaves.append(local_vec[off:off + size]
                                  .reshape(shape).astype(dtype))
                    off += size
                p = jax.tree.unflatten(treedef, leaves)
                res, _st = ops[m].sharded_forward(
                    p, {}, list(flat[offs[m]:offs[m + 1]]), train,
                    aux=aux_by_member[m])
                return res if isinstance(res, tuple) else (res,)
            return br

        shapes_by_m = [jax.eval_shape(raw_branch(m), 0)
                       for m in range(len(ops))]
        union = []
        for k in range(n_pos):
            cands = [s[k] for s in shapes_by_m if len(s) > k]
            shape = tuple(max(c.shape[d] for c in cands)
                          for d in range(cands[0].ndim))
            union.append((shape, jnp.result_type(*[c.dtype
                                                   for c in cands])))

        def padded_branch(m):
            def br(_):
                outs = raw_branch(m)(0)
                padded = []
                for k, (shape, dtype) in enumerate(union):
                    if k < len(outs):
                        o = outs[k].astype(dtype)
                        o = jnp.pad(o, [(0, shape[d] - o.shape[d])
                                        for d in range(o.ndim)])
                    else:
                        o = jnp.zeros(shape, dtype)
                    padded.append(jnp.expand_dims(o, 0))
                return tuple(padded)
            return br

        owned = {g: padded_branch(m) for m, g in enumerate(slots)}

        def zero_branch(_):
            return tuple(jnp.zeros((1,) + s, d) for s, d in union)

        return lax.switch(gidx, [owned.get(g, zero_branch)
                                 for g in range(G)], 0)

    out_specs = tuple(P("_pg", *pos_spec[k]) for k in range(n_pos))
    res = unchecked_shard_map(body, mesh, in_specs, out_specs)(
        stacked, *flat_inputs)
    # crop each member's outputs back to its true global shapes/dtypes,
    # with the same anti-remat sharding waypoint as the homogeneous path
    out = []
    for i, (g, m) in enumerate(zip(slots, ops)):
        vals = []
        for k, spec in enumerate(m.output_specs()):
            av = real_avals[i][k]
            v = res[k][g]
            if v.shape != av.shape:
                v = lax.slice(v, (0,) * av.ndim, av.shape)
            v = v.astype(av.dtype)
            if spec is not None:
                v = lax.with_sharding_constraint(
                    v, machine.sharding(m.pc, m.AXIS_NAMES, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out, [{} for _ in ops]  # hetero members are stateless
