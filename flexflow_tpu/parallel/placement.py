"""Explicit operator placement: executing ops on the device subsets their
strategy names.

The reference pins each NMT op instance to a specific GPU via mapper tags
(nmt/rnn_mapper.cc:28-41, 131-135); ops pinned to disjoint GPU sets then
execute concurrently under Legion's async task graph — that is where its
operator parallelism and wavefront pipelining over chunk ops come from
(nmt/rnn.cu:298-326).  Under XLA a jitted program is ONE SPMD computation
over ONE device assignment, so subset placement cannot be a mapper decision
made outside the program; it has to be compiled INTO it.  The mechanism
here:

  * the machine is viewed as a mesh ``("_pg", *op_grid_axes)``: a leading
    *placement-group* axis of size ``num_devices / subset_size`` over the
    op's own partition grid;
  * ops placed on disjoint subsets (and mutually independent in the DAG)
    are merged into one PLACEMENT GROUP, executed by a single
    ``shard_map`` whose body switches on ``lax.axis_index("_pg")`` — each
    device-group runs exactly its own op's branch (MPMD expressed inside
    SPMD), device-groups owning no op contribute zeros that are never
    consumed;
  * each member's parameters are stacked along the group axis and sharded
    over it, so weights physically live only on the subset that computes
    with them;
  * the member's own grid (e.g. Linear's (c, n)) partitions work *within*
    its subset via the inner mesh axes, with shard_map's transpose
    inserting the cross-shard reductions (the reference's BWD2/updateGAS).

Supported placements: each op's ``devices`` must be one aligned contiguous
block ``[g*P, (g+1)*P)`` of the machine (P = the op's grid size).  Ops are
groupable when they share shapes/hyperparameters (``Op.placement_signature``)
and declare their input partitioning (``Op.input_specs``).  Anything else
degrades to the replicated normalization in ``MachineModel.sharding`` with
a warning.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ops.base import Op


@dataclasses.dataclass
class PlacementGroup:
    """A set of independent ops executing concurrently on disjoint aligned
    device blocks."""

    members: List[Op]
    indices: List[int]        # layer indices of members
    slots: List[int]          # device-block index per member
    subset_size: int          # devices per member (= pc.num_parts)
    n_groups: int             # machine blocks of that size


def placement_slot(op: Op, num_devices: int) -> Optional[int]:
    """Block index if ``op``'s ParallelConfig names a placeable aligned
    device block that is a strict subset of the machine, else None."""
    pc = op.pc
    p = pc.num_parts
    if num_devices <= 1 or p >= num_devices or num_devices % p:
        return None
    g, rem = divmod(pc.devices[0], p)
    if rem or pc.devices != tuple(range(g * p, (g + 1) * p)):
        return None
    if op.placement_signature() is None or op.input_specs() is None:
        return None
    if op.init_state():
        return None  # stateful ops (BatchNorm) not supported placed
    return g


def _signature(op: Op) -> tuple:
    return (type(op).__name__, op.pc.dims,
            tuple((t.shape, t.dtype) for t in op.inputs),
            tuple((t.shape, t.dtype) for t in op.all_outputs()),
            op.placement_signature())


def plan_schedule(layers: Sequence[Op], num_devices: int,
                  exclude: frozenset = frozenset()):
    """Dataflow schedule for ``layers``: a list whose entries are either a
    layer index (execute that op normally) or a :class:`PlacementGroup`
    (execute its members jointly, placed).  ``exclude`` holds layer
    indices that must stay un-placed (e.g. ops claimed by the fused-LM-head
    plan).  Placed ops out of original order are legal because scheduling
    is by dependencies, like the reference's Legion task graph — grouping
    independent ops can never create a cycle (a path between group members
    would make one an ancestor of the other, which grouping forbids)."""
    n = len(layers)
    prod_idx: Dict[int, int] = {}
    for i, op in enumerate(layers):
        for t in op.all_outputs():
            prod_idx[t.tid] = i
    deps: List[List[int]] = []
    anc: List[set] = []
    for i, op in enumerate(layers):
        d = sorted({prod_idx[t.tid] for t in op.inputs
                    if t.tid in prod_idx})
        deps.append(d)
        a = set()
        for p in d:
            a |= anc[p]
            a.add(p)
        anc.append(a)

    # ---- grouping ----
    groups: List[dict] = []
    open_by_sig: Dict[tuple, List[dict]] = {}
    group_of: Dict[int, int] = {}
    for i, op in enumerate(layers):
        if i in exclude:
            continue
        g = placement_slot(op, num_devices)
        if g is None:
            continue
        sig = _signature(op)
        for grp in open_by_sig.get(sig, []):
            if g in grp["slots"]:
                continue
            if any(m in anc[i] for m in grp["indices"]):
                continue  # dependency path member -> op
            grp["indices"].append(i)
            grp["slots"].append(g)
            group_of[i] = grp["id"]
            break
        else:
            grp = {"id": len(groups), "indices": [i], "slots": [g],
                   "subset": op.pc.num_parts}
            groups.append(grp)
            open_by_sig.setdefault(sig, []).append(grp)
            group_of[i] = grp["id"]

    # ---- merge into schedule nodes + topological order ----
    # Merging keeps each group acyclic (a path between members would make
    # one an ancestor of the other), but cycles can still arise BETWEEN two
    # multi-member group nodes (A->B and C->D with {A,D} and {B,C} merged).
    # When the topological sort detects one, split the last-added member
    # out of an involved multi-member group and retry — each split strictly
    # shrinks a group, so this terminates.
    while True:
        node_members: List[List[int]] = []
        node_of_layer: Dict[int, int] = {}
        node_group: List[Optional[int]] = []
        for i in range(n):
            if i in node_of_layer:
                continue
            if i in group_of:
                members = groups[group_of[i]]["indices"]
                nid = len(node_members)
                node_members.append(members)
                node_group.append(group_of[i])
                for j in members:
                    node_of_layer[j] = nid
            else:
                nid = len(node_members)
                node_members.append([i])
                node_group.append(None)
                node_of_layer[i] = nid

        nn = len(node_members)
        ndeps: List[set] = [set() for _ in range(nn)]
        nsucc: List[set] = [set() for _ in range(nn)]
        for nid, members in enumerate(node_members):
            for i in members:
                for p in deps[i]:
                    pn = node_of_layer[p]
                    if pn != nid:
                        ndeps[nid].add(pn)
                        nsucc[pn].add(nid)
        indeg = [len(d) for d in ndeps]
        heap = [(min(node_members[nid]), nid) for nid in range(nn)
                if indeg[nid] == 0]
        heapq.heapify(heap)
        schedule = []
        done = [False] * nn
        while heap:
            _, nid = heapq.heappop(heap)
            done[nid] = True
            gid = node_group[nid]
            if gid is None:
                schedule.append(node_members[nid][0])
            else:
                grp = groups[gid]
                schedule.append(PlacementGroup(
                    members=[layers[i] for i in grp["indices"]],
                    indices=list(grp["indices"]),
                    slots=list(grp["slots"]),
                    subset_size=grp["subset"],
                    n_groups=num_devices // grp["subset"]))
            for s in nsucc[nid]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    heapq.heappush(heap, (min(node_members[s]), s))
        if len(schedule) == nn:
            return schedule
        split = None
        for nid in range(nn):
            if not done[nid] and node_group[nid] is not None \
                    and len(node_members[nid]) > 1:
                split = node_group[nid]
                break
        assert split is not None, "cycle without a splittable group"
        last = groups[split]["indices"].pop()
        groups[split]["slots"].pop()
        grp = {"id": len(groups), "indices": [last],
               "slots": [placement_slot(layers[last], num_devices)],
               "subset": layers[last].pc.num_parts}
        groups.append(grp)
        group_of[last] = grp["id"]


def run_group(machine, group: PlacementGroup,
              params_by_member: List[Dict],
              inputs_by_member: List[List], train: bool):
    """Execute a placement group jointly.  Returns, per member, the tuple
    of its output arrays (each sliced from the group-stacked result, so it
    physically lives on that member's device block)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    G = group.n_groups
    axes = op0.AXIS_NAMES
    mesh = machine.placement_mesh(op0.pc.dims, axes)
    slots = group.slots
    k_in = len(op0.input_specs())

    # ---- stack params along the group axis (zeros in unowned blocks) ----
    have_params = bool(params_by_member and params_by_member[0])
    if have_params:
        def stack_leaf(*member_leaves):
            by = dict(zip(slots, member_leaves))
            z = jnp.zeros_like(member_leaves[0])
            return jnp.stack([by.get(g, z) for g in range(G)])

        stacked = jax.tree.map(stack_leaf, *params_by_member)
        pspecs = {k: P("_pg", *spec)
                  for k, spec in op0.param_specs().items()}
    else:
        stacked = {}
        pspecs = {}

    in_specs = (pspecs,) + tuple(op0.input_specs()) * len(ops)
    out_specs = tuple(P("_pg", *spec) for spec in op0.output_specs())
    flat_inputs = [x for xs in inputs_by_member for x in xs]

    def body(sp, *flat):
        local_params = jax.tree.map(lambda a: a[0], sp)
        gidx = lax.axis_index("_pg")
        xs_by_member = [list(flat[m * k_in:(m + 1) * k_in])
                        for m in range(len(ops))]

        def branch_for(m):
            def br(_):
                res, _st = ops[m].forward(local_params, {},
                                          xs_by_member[m], train)
                outs = res if isinstance(res, tuple) else (res,)
                return tuple(jnp.expand_dims(o, 0) for o in outs)
            return br

        owned = {g: branch_for(m) for m, g in enumerate(slots)}
        shapes = jax.eval_shape(owned[slots[0]], 0)

        def zero_branch(_):
            return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

        branches = [owned.get(g, zero_branch) for g in range(G)]
        return lax.switch(gidx, branches, 0)

    res = unchecked_shard_map(body, mesh, in_specs, out_specs)(
        stacked, *flat_inputs)
    # Constrain each sliced member output to its pc's normalized sharding
    # (grid over the fast global axes, replicated over the rest).  This
    # splits the stacked->consumer regrid into an explicit gather over the
    # group axis plus a free slice; without the waypoint GSPMD relates the
    # stacked layout to the consumer's (e.g. full-DP) layout in one jump
    # and falls back to involuntary full rematerialization in the backward.
    out = []
    for g, m in zip(slots, ops):
        vals = []
        for r, spec in zip(res, op0.output_specs()):
            v = r[g]
            if spec is not None:
                v = lax.with_sharding_constraint(
                    v, machine.sharding(m.pc, m.AXIS_NAMES, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out
