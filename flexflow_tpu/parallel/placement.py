"""Explicit operator placement: executing ops on the device subsets their
strategy names.

The reference pins each NMT op instance to a specific GPU via mapper tags
(nmt/rnn_mapper.cc:28-41, 131-135); ops pinned to disjoint GPU sets then
execute concurrently under Legion's async task graph — that is where its
operator parallelism and wavefront pipelining over chunk ops come from
(nmt/rnn.cu:298-326).  Under XLA a jitted program is ONE SPMD computation
over ONE device assignment, so subset placement cannot be a mapper decision
made outside the program; it has to be compiled INTO it.  The mechanism
here:

  * the machine is viewed as a mesh ``("_pg", *op_grid_axes)``: a leading
    *placement-group* axis of size ``num_devices / subset_size`` over the
    op's own partition grid;
  * ops placed on disjoint subsets (and mutually independent in the DAG)
    are merged into one PLACEMENT GROUP, executed by a single
    ``shard_map`` whose body switches on ``lax.axis_index("_pg")`` — each
    device-group runs exactly its own op's branch (MPMD expressed inside
    SPMD), device-groups owning no op contribute zeros that are never
    consumed;
  * each member's parameters are stacked along the group axis and sharded
    over it, so weights physically live only on the subset that computes
    with them;
  * the member's own grid (e.g. Linear's (c, n)) partitions work *within*
    its subset via the inner mesh axes, with shard_map's transpose
    inserting the cross-shard reductions (the reference's BWD2/updateGAS).

Supported placements: an aligned contiguous block ``[g*P, (g+1)*P)``
(P = the op's grid size); a constant-stride set ``{b + j*(N/P)}`` such as
``(0,2,4,6)`` (stride family, round 3); or — round 4, closing SURVEY
§2.4 — ANY other duplicate-free list (``(0,3,5,6)``, misaligned blocks,
conflicting whole-machine permutations), honored in its named order by
set-family per-device dispatch.  A single whole-machine *permutation* is
honored one level up: FFModel rebuilds its machine view on the permuted
order (model.py _permuted_machine_view).  Ops are groupable when they
declare their input partitioning (``Op.input_specs``) and either share
shapes/hyperparameters (``Op.placement_signature`` — the homogeneous
fast path, params stacked with their inner sharding kept) or join the
HETEROGENEOUS path: different op kinds as different branches of one
switch, params (and, round 4, state) flattened to padded f32 vectors
stacked over the group axis.  Round 4 generalizes hetero membership
beyond "same grid, agreeing outputs": the mesh is built on one OWNER
grid, members of any other grid shape (same subset size) join as
point-local guests with their specs rewritten through an axis
translation (a conv(2,2,1,.) hosts an LSTM(4,) guest), and members with
incompatible output avals occupy disjoint switch positions instead of
being refused.  That restores the reference's Legion-style concurrency
between *different* ops on disjoint device sets (embeds on one block
while LSTMs run on another, nmt/rnn.cu:298-326, nmt/rnn_mapper.cc:28-41).
Only duplicate device lists and ops without placed support degrade to
the replicated normalization in ``MachineModel.sharding`` with a warning.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ops.base import Op
from flexflow_tpu.ops.base import point_slice as _point_slice


@dataclasses.dataclass
class PlacementGroup:
    """A set of independent ops executing concurrently on disjoint device
    subsets: contiguous blocks, constant-stride sets (``strided``), or —
    the general "set" family (round 4) — arbitrary duplicate-free device
    lists honored in their NAMED order via ``device_rows``."""

    members: List[Op]
    indices: List[int]        # layer indices of members
    slots: List[int]          # device-block index per member
    subset_size: int          # devices per member (= pc.num_parts)
    n_groups: int             # machine blocks of that size
    strided: bool = False     # stride family: slot b owns {b + j*(N/P)}
    #: set family: row g of the placement mesh is exactly device_rows[g]
    #: (member order; the machine pads remaining devices as zero rows)
    device_rows: Optional[List[Tuple[int, ...]]] = None
    #: hetero owner grid: the mesh is built on these dims/axes; members
    #: with any other grid run as point-local guests with translated
    #: specs (round 4 — None means the first member's grid)
    owner_dims: Optional[Tuple[int, ...]] = None
    owner_axes: Optional[Tuple[str, ...]] = None
    #: placed-op overlap (round 10): per-member LEAF flags — a True
    #: member's params thread through the hetero runner as group-stacked
    #: leaf trees with their inner sharding preserved (the homogeneous
    #: stacking) instead of the block-replicated f32 ravel vector, which
    #: admits inner-sharded-param ops (e.g. channel-split linears) into
    #: one fused dispatch.  None means all-vector (legacy).
    leaf_members: Optional[List[bool]] = None


def placement_slot(op: Op, num_devices: int,
                   pc: Optional["ParallelConfig"] = None):
    """("block", g) when ``op``'s ParallelConfig names the contiguous
    device block ``[g*P, (g+1)*P)``; ("stride", b) when it names the
    constant-stride set ``{b + j*(N/P)}`` (VERDICT r2 #3b, e.g.
    ``devices=(0,2,4,6)``); ("set", devices) — round 4, closing
    SURVEY §2.4 — for ANY other duplicate-free list, honored in its
    NAMED order on a mesh whose rows are the listed devices (the
    reference's RnnMapper pins a task to any named GPU,
    nmt/rnn_mapper.cc:131-135).  None when the op cannot run placed
    (no placed support for this grid, duplicates, or a grid that does
    not divide the machine) — those normalize with a warning.

    ``pc`` overrides the op's own config — the simulator asks whether a
    CANDIDATE grid/device list would lower as a placement group (the
    dispatch-overhead gate, sim/collectives.py) without mutating the
    op."""
    if pc is None:
        pc = op.pc
    p = pc.num_parts
    if num_devices <= 1 or p > num_devices:
        return None
    if op.placement_signature() is None:
        return None
    if len(set(pc.devices)) != p or \
            any(d < 0 or d >= num_devices for d in pc.devices):
        return None  # duplicates / out-of-range ids: normalize + warn
    if p == num_devices and pc.devices == tuple(range(num_devices)):
        # canonical full-machine list: the normal (free) GSPMD path —
        # never a placement group
        return None
    if op.input_specs(pc) is None or \
            (op.init_state() and op.state_specs() is None):
        # block/stride execution impossible (no placed specs for this
        # grid, or stateful without placed-state support) — but
        # set-family point dispatch may still honor the list: an op
        # overriding point_forward slices its own windows from the FULL
        # replicated operands and needs neither (round 5, e.g. a
        # stride-2 spatial conv on ANY duplicate-free device list)
        return ("set", tuple(pc.devices)) if _set_eligible(op, pc) else None
    if num_devices % p:
        # block/stride tilings need P | N; set-family per-device dispatch
        # does not (its flat mesh just leaves more devices on the zero
        # branch), so e.g. a (1,3) grid on (0,3,5) of 8 is still honored
        return ("set", tuple(pc.devices)) if _set_eligible(op, pc) else None
    if p == num_devices:
        # non-canonical full-machine list (the canonical order returned
        # above): a single foreign permutation is absorbed by the
        # machine-view rebuild (model._permuted_machine_view) before ops
        # are built, so reaching here means CONFLICTING permutations —
        # honor each via per-device dispatch (resharding at entry/exit)
        return ("set", tuple(pc.devices)) if _set_eligible(op, pc) else None
    # block/stride detection is order-insensitive: a strict-subset grid is
    # placement-symmetric (which grid point lands on which member device
    # permutes shard routing only), so the device SET decides the family —
    # e.g. a permuted-machine remap listing a block in reversed order
    # stays a plain block
    devs = tuple(sorted(pc.devices))
    d0 = devs[0]
    g, rem = divmod(d0, p)
    if rem == 0 and devs == tuple(range(g * p, (g + 1) * p)):
        return ("block", g)
    s = num_devices // p
    if d0 < s and devs == tuple(d0 + j * s for j in range(p)):
        return ("stride", d0)
    return ("set", tuple(pc.devices)) if _set_eligible(op, pc) else None


def _set_eligible(op: Op, pc: Optional["ParallelConfig"] = None) -> bool:
    """Can ``op`` run under set-family per-device dispatch?  The runner
    computes each grid point from the FULL (replicated) operands via
    ``Op.point_forward``: the op must declare point capability
    (``point_placeable`` — by default the point-local bar; spatial
    conv/pool override it, their halos being static slices of the full
    input), and its OUTPUT specs must be single-axis entries dividing
    evenly (the assembler's vocabulary).  STATEFUL members (round 5)
    need placed-state specs AND a point_forward override that computes
    from the full input (BatchNorm: global statistics, zero
    collectives).  Ops on the default ``point_forward`` additionally
    need sliceable input and param specs (the default slices by spec;
    overriders slice their own windows)."""
    if pc is None:
        pc = op.pc
    if not op.point_placeable():
        return False
    if op.init_state() and (
            op.state_specs() is None
            or type(op).point_forward is Op.point_forward):
        return False
    sizes = dict(zip(op.AXIS_NAMES, pc.dims))

    def ok(spec, shape):
        # single-axis entries only, and every sharded dim must divide
        # evenly (the per-point slicer floor-divides; a ragged dim would
        # silently truncate)
        if spec is None:
            return False
        for d, e in enumerate(tuple(spec)):
            if e is None:
                continue
            if not isinstance(e, str):
                return False
            parts = sizes.get(e, 1)
            if parts > 1 and (d >= len(shape) or shape[d] % parts):
                return False
        return True

    outs = op.output_specs()
    if outs is None or not all(
            ok(s, t.shape) for s, t in zip(outs, op.all_outputs())):
        return False
    params = op.param_specs()
    if params:
        import jax

        shapes = jax.eval_shape(lambda: op.init_params(
            jax.random.PRNGKey(0)))
        if not all(ok(params[k], shapes[k].shape) for k in params):
            return False  # param point-slicing is shared by both paths
    if type(op).point_forward is Op.point_forward:
        if op.input_specs(pc) is None or not all(
                ok(s, t.shape)
                for s, t in zip(op.input_specs(pc), op.inputs)):
            return False
    return True


def _signature(op: Op) -> tuple:
    return (type(op).__name__, op.pc.dims,
            tuple((t.shape, t.dtype) for t in op.inputs),
            tuple((t.shape, t.dtype) for t in op.all_outputs()),
            op.placement_signature())


def _params_block_replicated(op: Op) -> bool:
    """True when ``op``'s params are replicated *within* its placement
    block under its grid (every spec axis has grid size 1) — the
    heterogeneous path carries params as one flat vector per block and
    cannot preserve inner param sharding."""
    specs = op.param_specs()
    if not specs:
        return True
    sizes = dict(zip(op.AXIS_NAMES, op.pc.dims))
    for spec in specs.values():
        for entry in spec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if sizes.get(a, 1) != 1:
                    return False
    return True


def _state_block_replicated(op: Op) -> bool:
    """True when ``op``'s state rides the hetero f32 group vector without
    losing sharding or precision: state_specs exist, every entry is
    replicated within the block, and leaves are f32-family."""
    specs = op.state_specs()
    if specs is None:
        return False
    sizes = dict(zip(op.AXIS_NAMES, op.pc.dims))
    for spec in specs.values():
        for entry in spec:
            if entry is None:
                continue
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if sizes.get(a, 1) != 1:
                    return False
    return all(str(l.dtype) in ("float32", "bfloat16", "float16")
               for l in op.init_state().values())


def _hetero_eligible(op: Op) -> bool:
    """Can ``op`` join a heterogeneous (mixed-kind) placement group?
    Round 4 lifts the round-3 stateless restriction: stateful members
    (e.g. BatchNorm) thread their state through a second group-stacked
    f32 vector, provided it is block-replicated."""
    if op.init_state() and not _state_block_replicated(op):
        return False
    if not _params_block_replicated(op):
        return False
    if op.output_specs() is None or any(s is None
                                        for s in op.output_specs()):
        return False
    return all(t.dtype != "int32" for t in op.all_outputs())


def _overlap_eligible(op: Op) -> bool:
    """Can ``op`` join a heterogeneous group as a LEAF member (placed-op
    overlap, round 10)?  Leaf members' params are carried as
    group-stacked leaf trees with their inner sharding preserved — the
    homogeneous stacking — instead of the block-replicated f32 ravel
    vector, so ``_params_block_replicated`` no longer gates them.  The
    member must be stateless (state still rides the ravel vector), have
    full placed specs, and run NATIVE on the group's owner grid (its
    param specs name its own grid axes — enforced at grouping time)."""
    if op.init_state():
        return False
    if op.param_specs() is None or op.input_specs() is None:
        return False
    if op.output_specs() is None or any(s is None
                                        for s in op.output_specs()):
        return False
    return all(t.dtype != "int32" for t in op.all_outputs())


def _axis_translation(op: Op, owner_dims, owner_axes):
    """Map each of ``op``'s grid axes to owner mesh axes such that the
    two linearizations (dim 0 fastest) coincide: every nontrivial guest
    dim must equal a product of CONSECUTIVE nontrivial owner dims.
    Returns {guest axis: tuple of owner axes, slowest-first (the
    PartitionSpec multi-axis convention)} or None if not expressible.
    Identity grids translate to themselves."""
    o = [(a, d) for a, d in zip(owner_axes, owner_dims) if d > 1]
    i = 0
    mapping = {}
    for ga, gd in zip(op.AXIS_NAMES, op.pc.dims):
        if gd == 1:
            continue
        prod, take = 1, []
        while prod < gd and i < len(o):
            take.append(o[i][0])
            prod *= o[i][1]
            i += 1
        if prod != gd:
            return None
        mapping[ga] = tuple(reversed(take))
    return mapping if i == len(o) else None


def _translate_spec(spec, mapping):
    """Rewrite a single-axis-entry PartitionSpec onto owner mesh axes."""
    from jax.sharding import PartitionSpec as P

    entries = []
    for e in tuple(spec):
        if e is None:
            entries.append(None)
            continue
        if not isinstance(e, str):
            return None  # multi-axis guest entries unsupported
        t = mapping.get(e, ())
        entries.append(None if len(t) == 0 else
                       (t[0] if len(t) == 1 else t))
    return P(*entries)


def _member_view(op: Op, owner_dims, owner_axes):
    """(native, mapping, in_specs, out_specs) of ``op`` on the owner
    mesh, or None when the member cannot run there.  Native members
    (exact same grid dims AND axis names) keep their specs and may be
    grid-aware (their placed hooks see the live owner axes); any other
    grid joins as a point-local GUEST with its specs rewritten through
    the axis translation."""
    native = (op.pc.dims == tuple(owner_dims)
              and op.AXIS_NAMES == tuple(owner_axes))
    if native:
        return True, None, list(op.input_specs()), list(op.output_specs())
    if not op.placed_local() or op.init_state():
        return None
    mapping = _axis_translation(op, owner_dims, owner_axes)
    if mapping is None:
        return None
    ins = [_translate_spec(s, mapping) for s in op.input_specs()]
    outs = [_translate_spec(s, mapping) for s in op.output_specs()]
    if any(s is None for s in ins) or any(s is None for s in outs):
        return None
    return False, mapping, ins, outs


def _out_positions_on(op: Op, out_specs, owner_sizes):
    """Per output position (live spec entries, rank, sharded-dim extents,
    dtype) — computed against owner-mesh specs so members of different
    grids compare in one vocabulary.  Entries naming only size-1 owner
    axes normalize to None, so a native spec like P("n","h","w","c") on
    a batch-only grid matches a guest's translated P("n",None,None,None)."""
    def live(e):
        names = e if isinstance(e, tuple) else (e,)
        return any(owner_sizes.get(a, 1) > 1 for a in names)

    out = []
    for t, spec in zip(op.all_outputs(), out_specs):
        raw = tuple(spec) if spec is not None else None
        entries = None
        sharded = []
        if raw is not None:
            entries = tuple(e if (e is not None and live(e)) else None
                            for e in raw)
            for d, e in enumerate(entries):
                if e is not None:
                    sharded.append((d, t.shape[d]))
        out.append((entries, t.ndim, tuple(sharded), t.dtype))
    return tuple(out)


def _hetero_compatible(a, b) -> bool:
    """Output-position compatibility of two position records: shared
    positions must agree on spec, rank and sharded-dim extents (unsharded
    dims are zero-padded to the union; sharded dims cannot be)."""
    for pa, pb in zip(a, b):
        if pa[:3] != pb[:3]:
            return False
    return True


def plan_schedule(layers: Sequence[Op], num_devices: int,
                  exclude: frozenset = frozenset(),
                  overlap: bool = False):
    """Dataflow schedule for ``layers``: a list whose entries are either a
    layer index (execute that op normally) or a :class:`PlacementGroup`
    (execute its members jointly, placed).  ``exclude`` holds layer
    indices that must stay un-placed (e.g. ops claimed by the fused-LM-head
    plan).  Placed ops out of original order are legal because scheduling
    is by dependencies, like the reference's Legion task graph — grouping
    independent ops can never create a cycle (a path between group members
    would make one an ancestor of the other, which grouping forbids).

    ``overlap`` (round 10, ``FFConfig.placed_overlap``) additionally
    admits ops failing only ``_params_block_replicated`` into mixed
    groups as LEAF members (see :func:`_overlap_eligible`): independent
    same-level placed ops with inner-sharded params — e.g. two
    channel-split linears on disjoint blocks — fuse into ONE grouped
    dispatch instead of serializing as sequential shard_maps.  A group
    holding a leaf member has its owner grid PINNED (leaf param specs
    name the member's own grid axes, so owner switches would orphan
    them); False keeps the legacy grouping exactly."""
    n = len(layers)
    prod_idx: Dict[int, int] = {}
    for i, op in enumerate(layers):
        for t in op.all_outputs():
            prod_idx[t.tid] = i
    deps: List[List[int]] = []
    anc: List[set] = []
    for i, op in enumerate(layers):
        d = sorted({prod_idx[t.tid] for t in op.inputs
                    if t.tid in prod_idx})
        deps.append(d)
        a = set()
        for p in d:
            a |= anc[p]
            a.add(p)
        anc.append(a)

    # ---- grouping ----
    # Same-signature joins first (the homogeneous fast path keeps inner
    # param sharding); a leftover op may then join a *grid-compatible*
    # group heterogeneously — mixed op kinds as different switch branches
    # (Legion concurrency between different ops, nmt/rnn.cu:298-326).
    groups: List[dict] = []
    open_by_sig: Dict[tuple, List[dict]] = {}
    open_by_grid: Dict[tuple, List[dict]] = {}
    group_of: Dict[int, int] = {}

    def conflicts(fam, g, slots):
        """Can slot ``g`` not coexist with ``slots``?  Block/stride slots
        collide on equality; set-family slots are device tuples and
        collide on any overlap."""
        if fam == "set":
            gs = set(g)
            return any(gs & set(s) for s in slots)
        return g in slots

    def join(grp, i, g, elig, leaf=False):
        grp["indices"].append(i)
        grp["slots"].append(g)
        grp["leaf"].append(leaf)
        grp["hetero_ok"] = grp["hetero_ok"] and (elig or leaf)
        grp["pinned"] = grp["pinned"] or leaf
        group_of[i] = grp["id"]

    def group_fits(member_ids, owner_dims, owner_axes):
        """Every member of ``member_ids`` can run on the owner grid
        (native, or as a translated point-local guest).  Output-aval
        compatibility is NOT required: incompatible members occupy
        disjoint output positions of the switch (round 4 — a 4-D spatial
        conv and a 2-D batch linear share one group)."""
        return all(_member_view(layers[j], owner_dims, owner_axes)
                   is not None for j in member_ids)

    for i, op in enumerate(layers):
        if i in exclude:
            continue
        slot = placement_slot(op, num_devices)
        if slot is None:
            continue
        fam, g = slot
        sig = _signature(op)
        # set-family groups are homogeneous-only: their per-device switch
        # slices operands by ONE shared spec set
        elig = fam != "set" and _hetero_eligible(op)
        # placed-op overlap (round 10): a vector-ineligible op may still
        # join mixed groups as a LEAF member when the knob is on
        oelig = (overlap and fam != "set" and not elig
                 and _overlap_eligible(op))
        placed = False
        for grp in open_by_sig.get(sig, []):
            if grp["family"] != fam or conflicts(fam, g, grp["slots"]):
                continue
            if any(m in anc[i] for m in grp["indices"]):
                continue  # dependency path member -> op
            if grp["mixed"] and not group_fits(
                    grp["indices"] + [i],
                    grp["owner_dims"], grp["owner_axes"]):
                # hetero members arrived since and the candidate does not
                # fit the (possibly switched) owner grid
                continue
            if grp["mixed"] and oelig and (
                    tuple(grp["owner_dims"]) != op.pc.dims
                    or tuple(grp["owner_axes"]) != op.AXIS_NAMES):
                continue  # leaf members must run native on the owner
            join(grp, i, g, elig, oelig)
            placed = True
            break
        if not placed and (elig or oelig):
            for grp in open_by_grid.get((op.pc.num_parts, fam), []):
                if not grp["hetero_ok"] or conflicts(fam, g, grp["slots"]):
                    continue
                if any(m in anc[i] for m in grp["indices"]):
                    continue
                if oelig:
                    # leaf candidate: native on the current owner, or the
                    # owner repins to its grid (only while no other leaf
                    # member has pinned it)
                    native = (tuple(grp["owner_dims"]) == op.pc.dims
                              and tuple(grp["owner_axes"])
                              == op.AXIS_NAMES)
                    owner = (grp["owner_dims"], grp["owner_axes"])
                    if not native:
                        if grp["pinned"]:
                            continue
                        owner = (op.pc.dims, op.AXIS_NAMES)
                    if not group_fits(grp["indices"] + [i], *owner):
                        continue
                else:
                    # candidate on the group's current owner grid ...
                    owner = (grp["owner_dims"], grp["owner_axes"])
                    if not group_fits(grp["indices"] + [i], *owner):
                        # ... or the candidate's grid becomes the owner
                        # (it may refine the current one, e.g. a spatial
                        # conv joining batch-grid guests — round 4),
                        # unless a leaf member pinned it
                        if grp["pinned"]:
                            continue
                        owner = (op.pc.dims, op.AXIS_NAMES)
                        if not group_fits(grp["indices"] + [i], *owner):
                            continue
                grp["owner_dims"], grp["owner_axes"] = owner
                join(grp, i, g, elig, oelig)
                grp["mixed"] = True
                placed = True
                break
        if not placed:
            grp = {"id": len(groups), "indices": [i], "slots": [g],
                   "subset": op.pc.num_parts, "hetero_ok": elig or oelig,
                   "family": fam, "mixed": False, "leaf": [oelig],
                   "pinned": oelig,
                   "owner_dims": op.pc.dims, "owner_axes": op.AXIS_NAMES}
            groups.append(grp)
            open_by_sig.setdefault(sig, []).append(grp)
            if elig or oelig:
                open_by_grid.setdefault(
                    (op.pc.num_parts, fam), []).append(grp)
            group_of[i] = grp["id"]

    # ---- merge into schedule nodes + topological order ----
    # Merging keeps each group acyclic (a path between members would make
    # one an ancestor of the other), but cycles can still arise BETWEEN two
    # multi-member group nodes (A->B and C->D with {A,D} and {B,C} merged).
    # When the topological sort detects one, split the last-added member
    # out of an involved multi-member group and retry — each split strictly
    # shrinks a group, so this terminates.
    while True:
        node_members: List[List[int]] = []
        node_of_layer: Dict[int, int] = {}
        node_group: List[Optional[int]] = []
        for i in range(n):
            if i in node_of_layer:
                continue
            if i in group_of:
                members = groups[group_of[i]]["indices"]
                nid = len(node_members)
                node_members.append(members)
                node_group.append(group_of[i])
                for j in members:
                    node_of_layer[j] = nid
            else:
                nid = len(node_members)
                node_members.append([i])
                node_group.append(None)
                node_of_layer[i] = nid

        nn = len(node_members)
        ndeps: List[set] = [set() for _ in range(nn)]
        nsucc: List[set] = [set() for _ in range(nn)]
        for nid, members in enumerate(node_members):
            for i in members:
                for p in deps[i]:
                    pn = node_of_layer[p]
                    if pn != nid:
                        ndeps[nid].add(pn)
                        nsucc[pn].add(nid)
        indeg = [len(d) for d in ndeps]
        heap = [(min(node_members[nid]), nid) for nid in range(nn)
                if indeg[nid] == 0]
        heapq.heapify(heap)
        schedule = []
        done = [False] * nn
        while heap:
            _, nid = heapq.heappop(heap)
            done[nid] = True
            gid = node_group[nid]
            if gid is None:
                schedule.append(node_members[nid][0])
            else:
                grp = groups[gid]
                is_set = grp["family"] == "set"
                schedule.append(PlacementGroup(
                    members=[layers[i] for i in grp["indices"]],
                    indices=list(grp["indices"]),
                    # set family: members occupy mesh rows 0..m-1 in join
                    # order; the remaining rows hold the unlisted devices
                    slots=(list(range(len(grp["indices"]))) if is_set
                           else list(grp["slots"])),
                    subset_size=grp["subset"],
                    n_groups=num_devices // grp["subset"],
                    strided=grp["family"] == "stride",
                    device_rows=(list(grp["slots"]) if is_set else None),
                    owner_dims=grp["owner_dims"],
                    owner_axes=grp["owner_axes"],
                    leaf_members=list(grp["leaf"])))
            for s in nsucc[nid]:
                indeg[s] -= 1
                if indeg[s] == 0:
                    heapq.heappush(heap, (min(node_members[s]), s))
        if len(schedule) == nn:
            return schedule
        split = None
        for nid in range(nn):
            if not done[nid] and node_group[nid] is not None \
                    and len(node_members[nid]) > 1:
                split = node_group[nid]
                break
        assert split is not None, "cycle without a splittable group"
        last = groups[split]["indices"].pop()
        groups[split]["slots"].pop()
        was_leaf = groups[split]["leaf"].pop()
        groups[split]["pinned"] = any(groups[split]["leaf"])
        fam_last, slot_last = placement_slot(layers[last], num_devices)
        grp = {"id": len(groups), "indices": [last],
               "slots": [slot_last],
               "subset": layers[last].pc.num_parts,
               "hetero_ok": False, "family": fam_last,
               "mixed": False, "leaf": [was_leaf], "pinned": was_leaf,
               "owner_dims": layers[last].pc.dims,
               "owner_axes": layers[last].AXIS_NAMES}
        groups.append(grp)
        group_of[last] = grp["id"]


def run_group(machine, group: PlacementGroup,
              params_by_member: List[Dict],
              inputs_by_member: List[List], train: bool,
              states_by_member: Optional[List[Dict]] = None,
              prestacked: Optional[List[bool]] = None,
              state_prestacked: Optional[List[bool]] = None):
    """Execute a placement group jointly.  Returns
    ``(outs_by_member, new_states_by_member)``: per member, the tuple of
    its output arrays (each sliced from the group-stacked result, so it
    physically lives on that member's device block) and its new state
    dict ({} for stateless members).  ``state_prestacked`` members'
    state arrives AND returns in the stacked (G, ...) block-resident
    layout (round 5 — no state byte crosses the group axis)."""
    if states_by_member is None:
        states_by_member = [{} for _ in group.members]
    hetero = len({_signature(op) for op in group.members}) > 1
    if group.device_rows is not None:
        return _run_group_set(machine, group, params_by_member,
                              inputs_by_member, train,
                              prestacked or [False] * len(group.members),
                              states_by_member,
                              state_prestacked
                              or [False] * len(group.members))
    if hetero:
        return _run_group_hetero(
            machine, group, params_by_member, inputs_by_member, train,
            states_by_member,
            prestacked or [False] * len(group.members),
            state_prestacked or [False] * len(group.members))
    return _run_group_homogeneous(
        machine, group, params_by_member, inputs_by_member, train,
        states_by_member,
        prestacked or [False] * len(group.members),
        state_prestacked or [False] * len(group.members))


def grid_index(j: int, dims, axes) -> Dict[str, int]:
    """Grid-linear ``j`` (dim 0 fastest — the Rect order) -> per-axis
    index dict."""
    idx = {}
    for a, d in zip(axes, dims):
        idx[a] = j % d
        j //= d
    return idx


def set_group_assignment(group: PlacementGroup,
                         axis_names: Tuple[str, ...]):
    """{device: (member, grid-linear, per-axis index dict)} of a
    set-family group — the contract the per-device dispatch executes:
    member m's grid point j (dim 0 fastest) runs on
    ``device_rows[m][j]``, the reference's RnnMapper semantics
    (nmt/rnn_mapper.cc:131-135)."""
    out = {}
    dims = group.members[0].pc.dims
    for m, row in enumerate(group.device_rows):
        for j, dev in enumerate(row):
            out[dev] = (m, j, grid_index(j, dims, axis_names))
    return out




def _assemble(shards, spec, sizes, axis_names, dims):
    """Inverse of _point_slice over the whole grid: stitch the per-point
    shards (grid-linear order, dim 0 fastest) back into the global
    tensor.  A grid axis absent from the spec replicates the output —
    keep the first copy."""
    import jax.numpy as jnp

    entries = tuple(spec)
    dim_of = {e: d for d, e in enumerate(entries) if e is not None}
    lists = list(shards)
    for a, p in zip(axis_names, dims):
        if p == 1:
            continue
        d = dim_of.get(a)
        nxt = []
        for g in range(len(lists) // p):
            chunk = lists[g * p:(g + 1) * p]
            nxt.append(jnp.concatenate(chunk, axis=d)
                       if d is not None else chunk[0])
        lists = nxt
    assert len(lists) == 1
    return lists[0]


def _run_group_set(machine, group: PlacementGroup,
                   params_by_member: List[Dict],
                   inputs_by_member: List[List], train: bool,
                   prestacked: Optional[List[bool]] = None,
                   states_by_member: Optional[List[Dict]] = None,
                   state_prestacked: Optional[List[bool]] = None):
    """Arbitrary-device-list members (round 4, closing SURVEY §2.4): an
    irregular list like ``(0,3,5,6)`` cannot be a mesh reordering (XLA
    admits ONE device assignment per computation; block/stride placement
    meshes work only because they reshape the canonical order), so the
    group runs on the canonical flat ``(_dev,)`` mesh and every device
    switches on its own id to the (member, grid point) the strategy
    assigned it — the reference's tag-based per-task pinning
    (nmt/rnn_mapper.cc:28-41) compiled into one SPMD computation.

    The price, paid at group entry/exit rather than silently dropping
    the placement (the pre-round-4 normalization): operands are
    replicated to all devices (each branch computes its point via
    ``Op.point_forward`` from the full inputs — which is also what
    admits spatial/halo and irregular-window members, round 5), and
    outputs return through a per-device stacked array.  PARAMS no
    longer pay that price: block-resident members
    (model._derive_block_params, set family) arrive as per-device point
    rows ``(N, *point_shape)`` sharded over ``_dev`` — each device
    reads row [0] of its local block, so no parameter byte crosses the
    tier at entry, and gradients/optimizer state stay resident the same
    way (the reference keeps weights on their op's GPUs,
    nmt/rnn.cu:159-296)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    axes = op0.AXIS_NAMES
    dims = op0.pc.dims
    sizes = dict(zip(axes, dims))
    mesh = machine.flat_mesh()
    N = machine.num_devices
    assign = set_group_assignment(group, axes)
    out_specs_per_op = op0.output_specs()
    pspecs = op0.param_specs()
    sspecs = op0.state_specs() or {}
    k_in = len(op0.inputs)
    prestacked = prestacked or [False] * len(ops)
    states_by_member = states_by_member or [{} for _ in ops]
    state_prestacked = state_prestacked or [False] * len(ops)
    have_state = bool(states_by_member and states_by_member[0])
    state_keys = sorted(states_by_member[0]) if have_state else []

    flat_inputs = [x for xs in inputs_by_member for x in xs]
    param_in_specs = tuple(
        jax.tree.map(lambda _, pre=pre: P("_dev") if pre else P(), p)
        for p, pre in zip(params_by_member, prestacked))
    state_in_specs = tuple(
        jax.tree.map(lambda _, pre=pre: P("_dev") if pre else P(), st)
        for st, pre in zip(states_by_member, state_prestacked))

    def body(*args):
        sp_by_member = args[:len(ops)]
        st_by_member = args[len(ops):2 * len(ops)]
        flat = args[2 * len(ops):]
        dev = lax.axis_index("_dev")
        xs_by_member = [list(flat[m * k_in:(m + 1) * k_in])
                        for m in range(len(ops))]

        def branch_for(m, idx):
            def br(_):
                sp = sp_by_member[m]
                if prestacked[m]:
                    # per-device point row: [0] of the local (1, ...)
                    # block — already this point's slice, zero traffic
                    lp = jax.tree.map(lambda l: l[0], sp)
                else:
                    lp = {k: _point_slice(v, pspecs[k], sizes, idx)
                          for k, v in sp.items()}
                st = st_by_member[m]
                if state_prestacked[m]:
                    ls = jax.tree.map(lambda l: l[0], st)
                else:
                    ls = {k: _point_slice(v, sspecs[k], sizes, idx)
                          for k, v in st.items()}
                outs, new_st = ops[m].point_forward(
                    lp, ls, xs_by_member[m], idx, sizes, train)
                outs = outs + tuple(new_st[k] for k in state_keys)
                return tuple(jnp.expand_dims(o, 0) for o in outs)
            return br

        owned = {d: branch_for(m, idx) for d, (m, _, idx) in assign.items()}
        shapes = jax.eval_shape(next(iter(owned.values())), 0)

        def zero_branch(_):
            return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

        branches = [owned.get(d, zero_branch) for d in range(N)]
        return lax.switch(dev, branches, 0)

    n_out = len(out_specs_per_op)
    res = unchecked_shard_map(
        body, mesh,
        param_in_specs + state_in_specs + (P(),) * len(flat_inputs),
        tuple(P("_dev") for _ in range(n_out + len(state_keys))))(
            *params_by_member, *states_by_member, *flat_inputs)
    new_states = []
    if state_keys:
        import numpy as _np

        for m, (row, spre) in enumerate(zip(group.device_rows,
                                            state_prestacked)):
            st = {}
            for i, k in enumerate(state_keys):
                r = res[n_out + i]
                if spre:
                    # keep the (N, ...) per-device storage with only
                    # this member's rows live — a static boolean mask,
                    # row-local (slicing would gather across devices)
                    mask = _np.zeros((N,) + (1,) * (r.ndim - 1), bool)
                    mask[list(row)] = True
                    st[k] = jnp.where(jnp.asarray(mask), r,
                                      jnp.zeros_like(r))
                else:
                    st[k] = _assemble([r[d] for d in row], sspecs[k],
                                      sizes, axes, dims)
            new_states.append(st)
    else:
        new_states = [{} for _ in ops]
    res = res[:n_out]

    out = []
    repl = machine.replicated()
    for m, row in enumerate(group.device_rows):
        vals = []
        for r, spec in zip(res, out_specs_per_op):
            shards = [r[d] for d in row]  # grid-linear order by contract
            v = _assemble(shards, spec, sizes, axes, dims)
            # explicit replicated waypoint: the row-gather out of the
            # per-device stacked layout has no efficient GSPMD lowering
            # to an arbitrary grid sharding — without the waypoint the
            # partitioner takes the same replicate-then-slice path
            # anyway, but as an "involuntary full rematerialization"
            # (warned); stating it keeps the program identical and the
            # compile log clean
            v = lax.with_sharding_constraint(v, repl)
            v = lax.with_sharding_constraint(
                v, machine.sharding(ops[m].pc, axes, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out, new_states


def _run_group_homogeneous(machine, group: PlacementGroup,
                           params_by_member: List[Dict],
                           inputs_by_member: List[List], train: bool,
                           states_by_member: List[Dict],
                           prestacked: Optional[List[bool]] = None,
                           state_prestacked: Optional[List[bool]] = None):
    """Same-signature members: params (and state, round 3 — lifting the
    BatchNorm exclusion) stacked leaf-wise over the group axis with their
    inner sharding preserved; every branch shares one output aval.
    Branches run ``sharded_forward``, so grid-aware ops (spatial-halo
    convs, global-stats BatchNorm) see the live inner mesh axes."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    G = group.n_groups
    axes = op0.AXIS_NAMES
    mesh = machine.placement_mesh(op0.pc.dims, axes,
                                  strided=group.strided)
    slots = group.slots
    k_in = len(op0.input_specs())

    prestacked = prestacked or [False] * len(ops)
    state_prestacked = state_prestacked or [False] * len(ops)

    def make_stacker(flags):
        """(G, ...) group-stacked leaf merger.  BLOCK-RESIDENT members
        (model._derive_block_params) arrive already stacked and
        _pg-sharded — their rows merge by a one-hot mask-sum, all
        block-local, so no byte crosses the group axis (on a two-tier
        machine, DCN); legacy unstacked members go through jnp.stack as
        before (GSPMD reshards them to the group layout).  Shared by
        params (``prestacked`` flags) and, round 5, state
        (``state_prestacked``)."""
        def stack(*member_leaves):
            by = {}
            pre = []
            for leaf, g, p in zip(member_leaves, slots, flags):
                if p:
                    io = jax.lax.broadcasted_iota(
                        jnp.int32, (G,) + (1,) * (leaf.ndim - 1), 0)
                    pre.append(jnp.where(io == g, leaf,
                                         jnp.zeros_like(leaf)))
                else:
                    by[g] = leaf
            out = None
            if by:
                z = jnp.zeros_like(next(iter(by.values())))
                out = jnp.stack([by.get(g, z) for g in range(G)])
            for v in pre:
                out = v if out is None else out + v
            return out
        return stack

    # ---- stack params along the group axis (zeros in unowned blocks) ----
    have_params = bool(params_by_member and params_by_member[0])
    if have_params:
        stacked = jax.tree.map(make_stacker(prestacked),
                               *params_by_member)
        pspecs = {k: P("_pg", *spec)
                  for k, spec in op0.param_specs().items()}
    else:
        stacked = {}
        pspecs = {}
    # ---- state threaded the same way (state_specs gates placement) ----
    have_state = bool(states_by_member and states_by_member[0])
    if have_state:
        stacked_state = jax.tree.map(make_stacker(state_prestacked),
                                     *states_by_member)
        sspecs = {k: P("_pg", *spec)
                  for k, spec in op0.state_specs().items()}
        state_keys = sorted(states_by_member[0])
    else:
        stacked_state = {}
        sspecs = {}
        state_keys = []

    in_specs = (pspecs, sspecs) + tuple(op0.input_specs()) * len(ops)
    n_out = len(op0.output_specs())
    out_specs = tuple(P("_pg", *spec) for spec in op0.output_specs()) + \
        tuple(P("_pg", *op0.state_specs()[k]) for k in state_keys)
    flat_inputs = [x for xs in inputs_by_member for x in xs]

    def body(sp, st, *flat):
        local_params = jax.tree.map(lambda a: a[0], sp)
        local_state = jax.tree.map(lambda a: a[0], st)
        gidx = lax.axis_index("_pg")
        xs_by_member = [list(flat[m * k_in:(m + 1) * k_in])
                        for m in range(len(ops))]

        # collective preludes (halo exchange, cross-shard statistics) run
        # for every member UNCONDITIONALLY — member inputs are replicated
        # over the group axis, so this is uniform across device blocks;
        # collectives inside the switch branches would be illegal SPMD
        aux_by_member = [ops[m].placed_prelude(xs_by_member[m], train)
                         for m in range(len(ops))]

        def branch_for(m):
            def br(_):
                res, new_st = ops[m].sharded_forward(
                    local_params, local_state, xs_by_member[m], train,
                    aux=aux_by_member[m])
                outs = res if isinstance(res, tuple) else (res,)
                outs = outs + tuple(new_st[k] for k in state_keys)
                return tuple(jnp.expand_dims(o, 0) for o in outs)
            return br

        owned = {g: branch_for(m) for m, g in enumerate(slots)}
        shapes = jax.eval_shape(owned[slots[0]], 0)

        def zero_branch(_):
            return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

        branches = [owned.get(g, zero_branch) for g in range(G)]
        return lax.switch(gidx, branches, 0)

    res = unchecked_shard_map(body, mesh, in_specs, out_specs)(
        stacked, stacked_state, *flat_inputs)
    new_states = []
    for j, g in enumerate(slots):
        if state_prestacked[j]:
            # block-resident member: return the FULL stacked (G, ...)
            # array with only this member's row live — a one-hot mask is
            # row-local, whereas slicing row g would gather across _pg
            import jax as _jax

            st = {}
            for i, k in enumerate(state_keys):
                r = res[n_out + i]
                io = _jax.lax.broadcasted_iota(
                    jnp.int32, (G,) + (1,) * (r.ndim - 1), 0)
                st[k] = jnp.where(io == g, r, jnp.zeros_like(r))
            new_states.append(st)
        else:
            new_states.append({k: res[n_out + i][g]
                               for i, k in enumerate(state_keys)})
    res = res[:n_out]
    # Constrain each sliced member output to its pc's normalized sharding
    # (grid over the fast global axes, replicated over the rest).  This
    # splits the stacked->consumer regrid into an explicit gather over the
    # group axis plus a free slice; without the waypoint GSPMD relates the
    # stacked layout to the consumer's (e.g. full-DP) layout in one jump
    # and falls back to involuntary full rematerialization in the backward.
    out = []
    for g, m in zip(slots, ops):
        vals = []
        for r, spec in zip(res, op0.output_specs()):
            v = r[g]
            if spec is not None:
                v = lax.with_sharding_constraint(
                    v, machine.sharding(m.pc, m.AXIS_NAMES, spec))
            vals.append(v)
        out.append(tuple(vals))
    return out, new_states


def _run_group_hetero(machine, group: PlacementGroup,
                      params_by_member: List[Dict],
                      inputs_by_member: List[List], train: bool,
                      states_by_member: Optional[List[Dict]] = None,
                      prestacked: Optional[List[bool]] = None,
                      state_prestacked: Optional[List[bool]] = None):
    """Mixed-kind members (round 3; generalized round 4): each member is
    its own switch branch.

    lax.switch requires every branch to return identical avals, and the
    members' param trees don't mirror, so:

      * params: each member's tree is flattened, raveled to ONE f32
        vector, zero-padded to the group max and stacked over the group
        axis — sharded ``P("_pg")``, so weights still physically live only
        on the block that computes with them (the branch unflattens its
        slice back to shapes/dtypes).  Grouping admits only members whose
        params are replicated within their block
        (:func:`_params_block_replicated`), so no inner sharding is lost.
      * state (round 4, lifting the stateless restriction): threaded the
        same way through a SECOND group-stacked f32 vector; the branch
        unflattens, runs, and re-ravels its new state, which returns as
        an extra output position (``_state_block_replicated`` gates
        eligibility, so no inner sharding is lost here either).
      * grids (round 4): the mesh is built on the group's OWNER grid
        (``group.owner_dims/axes``); members with the exact owner grid
        are native and may be grid-aware (their placed hooks see the
        live axes — e.g. a spatial conv's halo ppermutes), while any
        other grid of the same subset size joins as a point-local GUEST
        whose specs are rewritten through :func:`_axis_translation`
        (its single batch axis becomes a tuple of owner axes) — a
        conv(2,2,1,.) and an LSTM(4,) now share one switch.
      * inputs: per-member translated ``input_specs`` (counts and ranks
        may differ) — the flat argument list concatenates every member's
        inputs.
      * outputs: padded to the per-position union aval (grouping
        guaranteed shared positions agree on spec/rank/sharded extents —
        only unsharded dims pad); missing positions are zeros.  The
        caller crops each member's outputs back to its true
        shapes/dtypes.

    This is the reference's operator parallelism: different Legion tasks
    on disjoint GPU sets executing concurrently (nmt/rnn.cu:298-326),
    compiled into one SPMD computation.
    """
    import math as _math

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.ring_attention import unchecked_shard_map

    ops = group.members
    op0 = ops[0]
    G = group.n_groups
    owner_dims = group.owner_dims or op0.pc.dims
    owner_axes = group.owner_axes or op0.AXIS_NAMES
    mesh = machine.placement_mesh(owner_dims, owner_axes,
                                  strided=group.strided)
    slots = group.slots
    if states_by_member is None:
        states_by_member = [{} for _ in ops]
    views = [_member_view(m, owner_dims, owner_axes) for m in ops]
    assert all(v is not None for v in views), \
        "grouping admitted a member the owner grid cannot host"

    def check_f32_family(leaves, what, name):
        # the vector rides through f32: exact for f32/bf16/f16 leaves,
        # lossy for anything else — fail loudly rather than corrupt
        for l in leaves:
            if str(l.dtype) not in ("float32", "bfloat16", "float16"):
                raise TypeError(
                    f"heterogeneous placement of {name!r}: {what} dtype "
                    f"{l.dtype} does not round-trip through the f32 "
                    f"group vector")

    def ravel_tree(tree, what, name):
        leaves, treedef = jax.tree.flatten(tree)
        check_f32_family(leaves, what, name)
        vec = jnp.concatenate([l.ravel().astype(jnp.float32)
                               for l in leaves]) \
            if leaves else jnp.zeros((0,), jnp.float32)
        return vec, (treedef, [(l.shape, str(l.dtype)) for l in leaves])

    # ---- params and state: flatten -> f32 ravel -> pad -> stack ----
    # BLOCK-RESIDENT members (model._derive_block_params) arrive as
    # stacked (G, ...) leaves.  Their group vector is built ROW-WISE —
    # reshape (G, -1) keeping the sharded group dim, concat along the
    # vector dim, pad, one-hot-mask the member's row — every op per-row
    # local, so no parameter byte crosses the group axis (a row SLICE
    # would: GSPMD lowers cross-_pg slicing to gathers, measured as MORE
    # collectives than the legacy restack)
    prestacked = prestacked or [False] * len(ops)
    leaf_flags = list(group.leaf_members or [False] * len(ops))
    metas = []
    legacy = []        # (slot, 1-D vec) for plain members
    pre_rows = []      # (slot, (G, L_m) row-local vectors) for prestacked
    leaf_trees = []    # (G, ...)-stacked leaf trees for LEAF members
    leaf_specs = []    # matching P("_pg", *spec) pytrees
    leaf_pos = {}      # member index -> position in leaf_trees
    for mi, (m, p, g, pre) in enumerate(zip(ops, params_by_member, slots,
                                            prestacked)):
        if leaf_flags[mi]:
            # LEAF member (placed-op overlap, round 10): params keep
            # their leaf structure and inner sharding, group-stacked
            # exactly like the homogeneous path — zeros in unowned rows
            # for legacy arrival, a row-local one-hot mask for
            # block-resident (G, ...) arrival.  Leaf members run native
            # on the owner grid (grouping pinned it), so their param
            # specs name live mesh axes.
            pspecs = m.param_specs()
            tree = {}
            for k, l in p.items():
                if pre:
                    io = jax.lax.broadcasted_iota(
                        jnp.int32, (G,) + (1,) * (l.ndim - 1), 0)
                    tree[k] = jnp.where(io == g, l, jnp.zeros_like(l))
                else:
                    z = jnp.zeros_like(l)
                    tree[k] = jnp.stack([l if gg == g else z
                                         for gg in range(G)])
            leaf_pos[mi] = len(leaf_trees)
            leaf_trees.append(tree)
            leaf_specs.append({k: P("_pg", *pspecs[k]) for k in tree})
            metas.append(None)
        elif pre:
            leaves, treedef = jax.tree.flatten(p)
            check_f32_family(leaves, "param", m.name)
            for l in leaves:
                assert l.shape[0] == G, (
                    f"block-resident leaf of {m.name!r} stacked for "
                    f"{l.shape[0]} groups, mesh has {G} — mis-stacked "
                    f"storage would scramble rows silently")
            rowvec = jnp.concatenate(
                [l.reshape(G, -1).astype(jnp.float32) for l in leaves],
                axis=1) if leaves else jnp.zeros((G, 0), jnp.float32)
            pre_rows.append((g, rowvec))
            metas.append((treedef,
                          [(l.shape[1:], str(l.dtype)) for l in leaves]))
        else:
            v, meta = ravel_tree(p, "param", m.name)
            legacy.append((g, v))
            metas.append(meta)
    lmax = max([r.shape[1] for _, r in pre_rows] +
               [v.shape[0] for _, v in legacy] + [0])
    by_slot = {g: jnp.pad(v, (0, lmax - v.shape[0])) for g, v in legacy}
    zero = jnp.zeros((lmax,), jnp.float32)
    stacked = jnp.stack([by_slot.get(g, zero) for g in range(G)])
    for g, rowvec in pre_rows:
        padded = jnp.pad(rowvec, ((0, 0), (0, lmax - rowvec.shape[1])))
        io = jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
        stacked = stacked + jnp.where(io == g, padded,
                                      jnp.zeros_like(padded))
    # state rides a second group-stacked f32 vector; round 5: BLOCK-
    # RESIDENT state (stacked (G, ...) leaves) builds its rows the same
    # row-wise way as params — reshape (G, -1), concat, one-hot mask —
    # so no state byte crosses the group axis either
    state_prestacked = state_prestacked or [False] * len(ops)
    smetas = []
    s_legacy = []      # (slot, 1-D vec)
    s_pre_rows = []    # (slot, (G, L_m) row-local vectors)
    for m, st, g, spre in zip(ops, states_by_member, slots,
                              state_prestacked):
        if spre:
            leaves, treedef = jax.tree.flatten(st)
            check_f32_family(leaves, "state", m.name)
            for l in leaves:
                assert l.shape[0] == G, (
                    f"block-resident state leaf of {m.name!r} stacked "
                    f"for {l.shape[0]} groups, mesh has {G}")
            rowvec = jnp.concatenate(
                [l.reshape(G, -1).astype(jnp.float32) for l in leaves],
                axis=1) if leaves else jnp.zeros((G, 0), jnp.float32)
            s_pre_rows.append((g, rowvec))
            smetas.append((treedef,
                           [(l.shape[1:], str(l.dtype)) for l in leaves]))
        else:
            v, meta = ravel_tree(st, "state", m.name)
            s_legacy.append((g, v))
            smetas.append(meta)
    smax = max([r.shape[1] for _, r in s_pre_rows] +
               [v.shape[0] for _, v in s_legacy] + [0])
    s_by_slot = {g: jnp.pad(v, (0, smax - v.shape[0]))
                 for g, v in s_legacy}
    s_zero = jnp.zeros((smax,), jnp.float32)
    stacked_state = jnp.stack([s_by_slot.get(g, s_zero)
                               for g in range(G)])
    for g, rowvec in s_pre_rows:
        padded = jnp.pad(rowvec, ((0, 0), (0, smax - rowvec.shape[1])))
        io = jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
        stacked_state = stacked_state + jnp.where(
            io == g, padded, jnp.zeros_like(padded))

    member_in_specs = [v[2] for v in views]
    in_specs = (P("_pg", None), P("_pg", None)) + tuple(leaf_specs) + \
        tuple(s for specs in member_in_specs for s in specs)
    flat_inputs = [x for xs in inputs_by_member for x in xs]
    # the members' REAL global output avals (declared Tensor dtypes can be
    # stale under compute-dtype propagation): crop/cast targets
    real_avals = []
    for m in range(len(ops)):
        def fwd(m=m):
            p = jax.tree.map(lambda l: l[slots[m]], params_by_member[m]) \
                if prestacked[m] else params_by_member[m]
            s = jax.tree.map(lambda l: l[slots[m]], states_by_member[m]) \
                if state_prestacked[m] else states_by_member[m]
            res, _ = ops[m].forward(p, s, inputs_by_member[m], train)
            return res if isinstance(res, tuple) else (res,)
        real_avals.append(jax.eval_shape(fwd))
    offs = [0]
    for specs in member_in_specs:
        offs.append(offs[-1] + len(specs))

    # Output positions: members CLUSTER by output-aval compatibility
    # (same translated specs / rank / sharded extents per position);
    # each cluster owns a disjoint contiguous range of switch positions,
    # so members with unrelated outputs — a 4-D spatial conv beside a
    # 2-D batch linear — still share one switch (round 4; previously a
    # grouping-time gate).  Within a cluster, unsharded dims pad to the
    # union aval as before.
    sizes = dict(zip(owner_axes, owner_dims))
    records = [_out_positions_on(m, v[3], sizes)
               for m, v in zip(ops, views)]
    clusters = []      # {"members": [i..], "record": union, "specs": []}
    cluster_of = []
    for i, rec in enumerate(records):
        for ci, cl in enumerate(clusters):
            if _hetero_compatible(cl["record"], rec):
                cl["members"].append(i)
                if len(rec) > len(cl["record"]):
                    cl["record"] = rec
                for k, spec in enumerate(views[i][3]):
                    if k >= len(cl["specs"]):
                        cl["specs"].append(spec)
                cluster_of.append(ci)
                break
        else:
            clusters.append({"members": [i], "record": rec,
                             "specs": list(views[i][3])})
            cluster_of.append(len(clusters) - 1)
    pos_off = [0]
    for cl in clusters:
        pos_off.append(pos_off[-1] + len(cl["record"]))
    n_pos = pos_off[-1]
    pos_spec = []
    for cl in clusters:
        pos_spec.extend(cl["specs"])
    assert len(pos_spec) == n_pos

    def unravel(vec, meta):
        treedef, leaf_meta = meta
        leaves, off = [], 0
        for shape, dtype in leaf_meta:
            size = int(_math.prod(shape))
            leaves.append(vec[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(treedef, leaves)

    def body(sp, st, *rest):
        leaf_sp = rest[:len(leaf_trees)]
        flat = rest[len(leaf_trees):]
        local_vec = sp[0]
        local_svec = st[0]
        gidx = lax.axis_index("_pg")
        # collective preludes run for every member unconditionally (same
        # rationale as the homogeneous path: member inputs are replicated
        # over the group axis; collectives inside branches are illegal).
        # Guests are point-local by construction, so their preludes are
        # no-ops
        aux_by_member = [
            ops[m].placed_prelude(list(flat[offs[m]:offs[m + 1]]), train)
            for m in range(len(ops))]

        def raw_branch(m):
            def br(_):
                if leaf_flags[m]:
                    # local row of the group-stacked leaf tree (inner
                    # sharding intact) — no ravel round-trip
                    p = jax.tree.map(lambda a: a[0], leaf_sp[leaf_pos[m]])
                else:
                    p = unravel(local_vec, metas[m])
                s = unravel(local_svec, smetas[m])
                res, new_st = ops[m].sharded_forward(
                    p, s, list(flat[offs[m]:offs[m + 1]]), train,
                    aux=aux_by_member[m])
                outs = res if isinstance(res, tuple) else (res,)
                nsv, _ = ravel_tree(new_st, "state", ops[m].name)
                nsv = jnp.pad(nsv, (0, smax - nsv.shape[0]))
                return outs, nsv
            return br

        shapes_by_m = [jax.eval_shape(lambda x, m=m: raw_branch(m)(x)[0],
                                      0) for m in range(len(ops))]
        # per-cluster union avals laid out over the global position range
        union = [None] * n_pos
        for ci, cl in enumerate(clusters):
            for k in range(len(cl["record"])):
                cands = [shapes_by_m[i][k] for i in cl["members"]
                         if len(shapes_by_m[i]) > k]
                shape = tuple(max(c.shape[d] for c in cands)
                              for d in range(cands[0].ndim))
                union[pos_off[ci] + k] = (
                    shape, jnp.result_type(*[c.dtype for c in cands]))

        def padded_branch(m):
            ci = cluster_of[m]

            def br(_):
                outs, nsv = raw_branch(m)(0)
                padded = []
                for k, (shape, dtype) in enumerate(union):
                    j = k - pos_off[ci]
                    if 0 <= j < len(outs):
                        o = outs[j].astype(dtype)
                        o = jnp.pad(o, [(0, shape[d] - o.shape[d])
                                        for d in range(o.ndim)])
                    else:
                        o = jnp.zeros(shape, dtype)
                    padded.append(jnp.expand_dims(o, 0))
                return tuple(padded) + (jnp.expand_dims(nsv, 0),)
            return br

        owned = {g: padded_branch(m) for m, g in enumerate(slots)}

        def zero_branch(_):
            return tuple(jnp.zeros((1,) + s, d) for s, d in union) + \
                (jnp.zeros((1, smax), jnp.float32),)

        return lax.switch(gidx, [owned.get(g, zero_branch)
                                 for g in range(G)], 0)

    out_specs = tuple(P("_pg", *spec) for spec in pos_spec) + \
        (P("_pg", None),)
    res = unchecked_shard_map(body, mesh, in_specs, out_specs)(
        stacked, stacked_state, *leaf_trees, *flat_inputs)
    new_svecs = res[n_pos]
    res = res[:n_pos]
    # crop each member's outputs back to its true global shapes/dtypes,
    # with the same anti-remat sharding waypoint as the homogeneous path
    out = []
    new_states = []
    for i, (g, m) in enumerate(zip(slots, ops)):
        base = pos_off[cluster_of[i]]
        vals = []
        for k, spec in enumerate(m.output_specs()):
            av = real_avals[i][k]
            v = res[base + k][g]
            if v.shape != av.shape:
                v = lax.slice(v, (0,) * av.ndim, av.shape)
            v = v.astype(av.dtype)
            if spec is not None:
                v = lax.with_sharding_constraint(
                    v, machine.sharding(m.pc, m.AXIS_NAMES, spec))
            vals.append(v)
        out.append(tuple(vals))
        if not states_by_member[i]:
            new_states.append({})
        elif state_prestacked[i]:
            # rebuild the stacked (G, ...) storage row-locally: reshape
            # the (G, smax) vector's columns, one-hot-mask the member's
            # row (slicing row g would gather across _pg)
            treedef, leaf_meta = smetas[i]
            leaves, off = [], 0
            for shape, dtype in leaf_meta:
                size = int(_math.prod(shape))
                seg = new_svecs[:, off:off + size] \
                    .reshape((G,) + tuple(shape)).astype(dtype)
                io = jax.lax.broadcasted_iota(
                    jnp.int32, (G,) + (1,) * len(shape), 0)
                leaves.append(jnp.where(io == g, seg,
                                        jnp.zeros_like(seg)))
                off += size
            new_states.append(jax.tree.unflatten(treedef, leaves))
        else:
            new_states.append(unravel(new_svecs[g], smetas[i]))
    return out, new_states
