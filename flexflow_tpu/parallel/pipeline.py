"""Pipeline parallelism: an explicit GPipe-style microbatch scheduler.

The reference has NO pipeline scheduler — its "pipelining" is emergent:
per-(layer, chunk) ops placed on different GPUs execute as a wavefront
under Legion's async task graph (SURVEY.md §2.6 "PP de-facto",
nmt/rnn.cu:298-326).  This module supplies the explicit capability,
TPU-native:

  * stages live on a named mesh axis (``stage``); each stage holds its own
    slice of the stacked stage parameters (sharded over that axis);
  * microbatches stream through the ring: every tick each device applies
    its stage to its current activation, then ``ppermute`` rotates
    activations one stage forward over neighbor ICI links;
  * the schedule is GPipe (fill, steady state, drain): M microbatches over
    S stages take M + S - 1 ticks with an S-1 bubble; backward is jax
    autodiff through the scan + ppermute (the transpose of a shift is the
    reverse shift), which interleaves into the same ring;
  * composes with data parallelism: extra mesh axes (e.g. ``n``) shard the
    microbatch batch dim; replicated-param cotangents are reduced by
    shard_map's transpose machinery.

All collectives are neighbor ppermutes — no all-to-all, no host round
trips; exactly the layout "How to Scale Your Model" prescribes for
pipelining on TPU meshes.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def microbatch(x, num_microbatches: int):
    """(B, ...) -> (M, B//M, ...) leading microbatch axis."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def spmd_pipeline(stage_fn: Callable, stage_params, xs, mesh: Mesh,
                  stage_axis: str = "stage",
                  batch_spec: Optional[P] = None,
                  param_specs=None):
    """Run microbatches through a homogeneous pipeline of S stages.

    stage_fn(params_one_stage, x_mb) -> y_mb; activations must keep the
    same shape through every stage (the classic pipeline contract).

    stage_params: pytree with a leading axis of size S (stage-stacked),
    sharded over ``stage_axis``.  xs: (M, mb, ...) microbatched input.
    batch_spec: PartitionSpec of one microbatch's data dims (after the
    leading M axis), e.g. P("n") to shard the microbatch over a data
    axis; defaults to fully replicated.
    param_specs: optional pytree (matching stage_params) of per-leaf
    PartitionSpecs — round 5: stage params may be TENSOR-PARALLEL within
    each stage's submesh (leaf dims sharded over e.g. a "tp" axis in
    addition to the leading stage axis); stage_fn then runs with those
    axes live and inserts its own psums.  Default: every leaf
    P(stage_axis) (stage-stacked, otherwise replicated).

    Returns (M, mb, ...) outputs, replicated over ``stage_axis``.
    """
    import inspect
    try:
        from jax import shard_map  # jax >= 0.8
        rep_kw = {"check_vma": False} \
            if "check_vma" in inspect.signature(shard_map).parameters \
            else {"check_rep": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
        rep_kw = {"check_rep": False}

    s_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    num_stages = s_sizes[stage_axis]
    num_mb = xs.shape[0]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != num_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != stage mesh "
                f"axis size {num_stages}; each device must hold exactly "
                f"one stage slice")
    data_spec = batch_spec if batch_spec is not None else P()
    xs_spec = P(None, *data_spec)   # leading M axis never sharded
    param_spec = param_specs if param_specs is not None \
        else jax.tree.map(lambda _: P(stage_axis), stage_params)

    def pipelined(params, xs_local):
        local_params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(stage_axis)
        ticks = num_mb + num_stages - 1
        zero = jnp.zeros(xs_local.shape[1:], xs_local.dtype)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(carry, t):
            recv = carry
            x_t = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False)
            inp = jnp.where(idx == 0, x_t, recv)
            y = stage_fn(local_params, inp)
            recv_next = lax.ppermute(y, stage_axis, perm)
            return recv_next, y

        _, ys = lax.scan(tick, zero, jnp.arange(ticks))
        # stage S-1 emits microbatch m at tick m + S - 1
        out_local = lax.slice_in_dim(ys, num_stages - 1,
                                     num_stages - 1 + num_mb, axis=0)
        # broadcast the last stage's outputs to every stage (masked psum)
        out = lax.psum(
            jnp.where(idx == num_stages - 1, out_local,
                      jnp.zeros_like(out_local)),
            stage_axis)
        return out

    return shard_map(
        pipelined, mesh=mesh,
        in_specs=(param_spec, xs_spec),
        out_specs=xs_spec,
        **rep_kw,
    )(stage_params, xs)


def sequential_reference(stage_fn: Callable, stage_params, xs):
    """Non-pipelined ground truth: apply the S stages in order to each
    microbatch (used by tests to pin the pipeline's semantics)."""
    num_stages = jax.tree.leaves(stage_params)[0].shape[0]

    def apply_all(x_mb):
        for s in range(num_stages):
            p_s = jax.tree.map(lambda p: p[s], stage_params)
            x_mb = stage_fn(p_s, x_mb)
        return x_mb

    return jax.vmap(apply_all)(xs)


# ----------------------------------------------------------------------
# pipelined transformer blocks (flagship integration)


def _layer_norm(g, b, x, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def transformer_block_fn(num_heads: int, causal: bool = False,
                         tp_axis: Optional[str] = None):
    """A pre-norm transformer block as a pipeline stage_fn.  Params:
    {"ln1": (2, D), "wqkv": (D, 3, D), "bqkv": (3, D), "wo": (D, D),
     "bo": (D,), "ln2": (2, D), "w1": (D, F), "b1": (F,), "w2": (F, D),
     "b2": (D,)}.

    Round 5 — stage-internal tensor parallelism: with ``tp_axis`` set
    (a live mesh axis inside the pipeline shard_map) the block is
    Megatron-sharded over it: wqkv/bqkv/w1/b1 column-split, wo/w2
    row-split (see :func:`stage_param_specs`), each device computes its
    head/ffn slice from the replicated activation, and the two partial
    products psum over the axis.  With tp_axis=None the same code runs
    the full block (the sequential reference path) — the local head
    count is derived from the actual shard shapes, so one body serves
    both."""

    def block(p, x):
        d = x.shape[-1]
        head_dim = d // num_heads
        h = _layer_norm(p["ln1"][0], p["ln1"][1], x)
        # (B, S, 3, E) where E = D/tp locally: q/k/v each get their own
        # contiguous head subset (the (D, 3, D) layout keeps the three
        # projections separable under a last-dim shard)
        qkv = jnp.einsum("bsd,dte->bste", h, p["wqkv"]) + p["bqkv"]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        def heads(t):  # (B, S, E) -> (B, H_local, S, d_h)
            b_, s_, e_ = t.shape
            return t.reshape(b_, s_, e_ // head_dim, head_dim) \
                    .transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, x.dtype))
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            mask = jnp.tril(jnp.ones(s.shape[-2:], bool))
            s = jnp.where(mask, s, -jnp.inf)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        b_, hl, s_, _ = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b_, s_, hl * head_dim)
        attn = o @ p["wo"]            # (E, D) row-shard -> partial sums
        if tp_axis is not None:
            attn = lax.psum(attn, tp_axis)
        x = x + attn + p["bo"]

        h = _layer_norm(p["ln2"][0], p["ln2"][1], x)
        h = jax.nn.gelu(h @ p["w1"] + p["b1"])
        ffn = h @ p["w2"]             # (F/tp, D) row-shard -> partial
        if tp_axis is not None:
            ffn = lax.psum(ffn, tp_axis)
        return x + ffn + p["b2"]

    return block


def init_block_stack(rng, num_stages: int, d_model: int, d_ff: int):
    """Stage-stacked transformer block params (leading axis = stage).
    wqkv is (D, 3, D) — the three projections on their own dim, so a
    last-dim tensor-parallel shard splits each of q/k/v by heads instead
    of slicing across the q|k|v concatenation."""
    ks = jax.random.split(rng, 4)
    shapes = {
        "ln1": ((2, d_model), None),
        "wqkv": ((d_model, 3, d_model), 0),
        "bqkv": ((3, d_model), None),
        "wo": ((d_model, d_model), 1),
        "bo": ((d_model,), None),
        "ln2": ((2, d_model), None),
        "w1": ((d_model, d_ff), 2),
        "b1": ((d_ff,), None),
        "w2": ((d_ff, d_model), 3),
        "b2": ((d_model,), None),
    }
    params = {}
    for name, (shape, ki) in shapes.items():
        full = (num_stages,) + shape
        if ki is None:
            init = jnp.zeros(full, "float32")
            if name.startswith("ln"):
                init = init.at[:, 0].set(1.0)  # scale=1, bias=0
            params[name] = init
        else:
            fan_in = shape[0]
            params[name] = jax.random.normal(ks[ki], full, "float32") \
                * (1.0 / jnp.sqrt(fan_in))
    return params


def stage_param_specs(stage_axis: str = "stage",
                      tp_axis: Optional[str] = None,
                      sub_dims: int = 0):
    """Per-leaf PartitionSpecs of the block stack: stage-stacked on the
    leading axis, and (round 5) Megatron-sharded over ``tp_axis`` —
    wqkv/bqkv/w1/b1 column-split (head/ffn slices), wo/w2 row-split
    (partials psum in the block).  ``sub_dims`` extra None dims between
    the stage axis and the param dims (PipelinedLM stacks (S, L/S, ...))."""
    s = (stage_axis,) + (None,) * sub_dims
    t = tp_axis
    return {
        "ln1": P(*s), "ln2": P(*s), "bo": P(*s), "b2": P(*s),
        "wqkv": P(*s, None, None, t), "bqkv": P(*s, None, t),
        "wo": P(*s, t, None), "w1": P(*s, None, t),
        "b1": P(*s, t), "w2": P(*s, t, None),
    }


def place_stage_params(params, mesh: Mesh, stage_axis: str = "stage",
                       param_specs=None):
    """Shard the stage-stacked params over the stage axis of ``mesh``
    (and any additional per-leaf axes in ``param_specs``)."""
    if param_specs is None:
        return jax.tree.map(
            lambda p: jax.device_put(
                p, NamedSharding(mesh, P(*((stage_axis,) +
                                           (None,) * (p.ndim - 1))))),
            params)
    return jax.tree.map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        params, param_specs)


# ----------------------------------------------------------------------
# PipelinedLM: a complete causal/encoder LM trained through the GPipe
# ring — the driver-level integration of pipeline parallelism
# (apps/lm --pipeline-stages), composing PP (stage axis) x DP (n axis).


class PipelinedLM:
    """Embed -> L transformer blocks split over S pipeline stages ->
    final-norm -> vocab head + CE.  Blocks run through spmd_pipeline on a
    ('stage', 'n') mesh; embed/head run under plain GSPMD batch sharding.

    Not an FFModel: stage params are stacked on a leading axis (one slice
    per device along 'stage'), which is a different parameter layout than
    the op DAG; the op-DAG path covers per-layer SOAP strategies, this
    class covers explicit microbatch pipelining of a homogeneous stack.
    """

    def __init__(self, machine, num_stages: int, num_microbatches: int,
                 num_layers: int = 12, d_model: int = 768,
                 num_heads: int = 12, d_ff: int = 3072,
                 vocab_size: int = 32768, seq_length: int = 512,
                 batch_size: int = 16, causal: bool = True,
                 learning_rate: float = 1e-3, compute_dtype="float32",
                 tp: int = 1):
        import numpy as np

        if num_layers % num_stages:
            raise ValueError(f"{num_layers} layers not divisible into "
                             f"{num_stages} stages")
        if machine.num_devices % (num_stages * tp):
            raise ValueError(f"{machine.num_devices} devices not divisible "
                             f"into {num_stages} stages x {tp} tp")
        if num_heads % tp or d_ff % tp:
            raise ValueError(f"tp={tp} must divide num_heads ({num_heads}) "
                             f"and d_ff ({d_ff})")
        if batch_size % num_microbatches:
            raise ValueError("batch not divisible by microbatches")
        dp = machine.num_devices // (num_stages * tp)
        if (batch_size // num_microbatches) % dp:
            raise ValueError(
                f"microbatch size {batch_size // num_microbatches} not "
                f"divisible by the data-parallel axis ({dp} devices)")
        self.machine = machine
        self.S, self.M, self.tp = num_stages, num_microbatches, tp
        self.L, self.D, self.H = num_layers, d_model, num_heads
        self.F, self.V = d_ff, vocab_size
        self.seq, self.batch = seq_length, batch_size
        self.causal = causal
        self.lr = learning_rate
        self.dtype = compute_dtype
        dev = np.empty(machine.num_devices, object)
        for i, d in enumerate(machine.devices):
            dev[i] = d
        # tp innermost: a stage's tp group is ICI-contiguous, its psums
        # never cross a stage boundary (round 5 — stage-internal TP from
        # the strategy file's pipeline block)
        self.mesh = Mesh(dev.reshape(num_stages, dp, tp),
                         ("stage", "n", "tp"))
        self.block = transformer_block_fn(
            num_heads, causal, tp_axis="tp" if tp > 1 else None)

    # -- params ---------------------------------------------------------

    def init(self, seed: int = 0):
        k0, k1, k2 = jax.random.split(jax.random.PRNGKey(seed), 3)
        blocks = init_block_stack(k0, self.L, self.D, self.F)
        # (L, ...) -> (S, L/S, ...): one leading slice per stage
        blocks = jax.tree.map(
            lambda p: p.reshape((self.S, self.L // self.S) + p.shape[1:]),
            blocks)
        blocks = place_stage_params(blocks, self.mesh,
                                    param_specs=self._block_specs())
        repl = NamedSharding(self.mesh, P())
        scale = 1.0 / jnp.sqrt(jnp.asarray(self.D, "float32"))
        other = {
            "embed": jax.random.normal(k1, (self.V, self.D), "float32")
            * scale,
            "pos": jax.random.normal(k2, (self.seq, self.D), "float32")
            * scale,
            "ln_f": jnp.stack([jnp.ones((self.D,), "float32"),
                               jnp.zeros((self.D,), "float32")]),
            "head_w": jnp.zeros((self.D, self.V), "float32"),
            "head_b": jnp.zeros((self.V,), "float32"),
        }
        other = {k: jax.device_put(v, repl) for k, v in other.items()}
        return {"blocks": blocks, **other}

    # -- forward/loss ---------------------------------------------------

    def _block_specs(self):
        return stage_param_specs(
            "stage", "tp" if self.tp > 1 else None, sub_dims=1)

    def _stage_fn(self, block=None):
        block = block or self.block
        n_sub, dtype = self.L // self.S, self.dtype

        def stage(p, x):
            p = jax.tree.map(lambda q: q.astype(dtype), p)
            for i in range(n_sub):  # static sub-layer loop within a stage
                x = block(jax.tree.map(lambda q: q[i], p), x)
            return x

        return stage

    def _embed(self, params, tokens):
        # gather before casting (f32 scatter-add in the VJP, no full-
        # vocab low-precision table copy)
        return params["embed"][tokens].astype(self.dtype) \
            + params["pos"].astype(self.dtype)[None]

    def _head_loss(self, params, ys, labels):
        """Final-norm + vocab head + shifted masked CE over the
        (M, mb, seq, D) pipeline outputs — shared by the pipelined and
        sequential-reference paths so their semantics cannot drift."""
        y = ys.reshape(self.batch, self.seq, self.D)
        y = _layer_norm(params["ln_f"][0], params["ln_f"][1],
                        y.astype("float32"))
        logits = y @ params["head_w"] + params["head_b"]
        if self.causal:
            labels = jnp.concatenate(
                [labels[:, 1:],
                 jnp.full((labels.shape[0], 1), -1, labels.dtype)], axis=1)
        valid = labels >= 0
        lp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            lp, jnp.where(valid, labels, 0)[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, nll, 0.0)) \
            / jnp.maximum(valid.sum(), 1)

    def loss_fn(self, params, tokens, labels):
        xs = microbatch(self._embed(params, tokens), self.M)
        ys = spmd_pipeline(self._stage_fn(), params["blocks"], xs,
                           self.mesh, batch_spec=P("n"),
                           param_specs=self._block_specs())
        return self._head_loss(params, ys, labels)

    def loss_reference(self, params, tokens, labels):
        """Same model WITHOUT the pipeline ring (sequential stages, full
        unsharded math — no tp psums) — pins the pipelined semantics in
        tests."""
        xs = microbatch(self._embed(params, tokens), self.M)
        ref_block = transformer_block_fn(self.H, self.causal)
        ys = sequential_reference(self._stage_fn(ref_block),
                                  params["blocks"], xs)
        return self._head_loss(params, ys, labels)

    # -- training -------------------------------------------------------

    def make_train_step(self):
        def step(params, tokens, labels):
            loss, g = jax.value_and_grad(self.loss_fn)(params, tokens,
                                                       labels)
            new = jax.tree.map(lambda p, gr: p - self.lr * gr, params, g)
            return new, loss

        return jax.jit(step, donate_argnums=(0,))
