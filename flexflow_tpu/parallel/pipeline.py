"""Pipeline parallelism: an explicit GPipe-style microbatch scheduler.

The reference has NO pipeline scheduler — its "pipelining" is emergent:
per-(layer, chunk) ops placed on different GPUs execute as a wavefront
under Legion's async task graph (SURVEY.md §2.6 "PP de-facto",
nmt/rnn.cu:298-326).  This module supplies the explicit capability,
TPU-native:

  * stages live on a named mesh axis (``stage``); each stage holds its own
    slice of the stacked stage parameters (sharded over that axis);
  * microbatches stream through the ring: every tick each device applies
    its stage to its current activation, then ``ppermute`` rotates
    activations one stage forward over neighbor ICI links;
  * the schedule is GPipe (fill, steady state, drain): M microbatches over
    S stages take M + S - 1 ticks with an S-1 bubble; backward is jax
    autodiff through the scan + ppermute (the transpose of a shift is the
    reverse shift), which interleaves into the same ring;
  * composes with data parallelism: extra mesh axes (e.g. ``n``) shard the
    microbatch batch dim; replicated-param cotangents are reduced by
    shard_map's transpose machinery.

All collectives are neighbor ppermutes — no all-to-all, no host round
trips; exactly the layout "How to Scale Your Model" prescribes for
pipelining on TPU meshes.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def microbatch(x, num_microbatches: int):
    """(B, ...) -> (M, B//M, ...) leading microbatch axis."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def spmd_pipeline(stage_fn: Callable, stage_params, xs, mesh: Mesh,
                  stage_axis: str = "stage",
                  batch_spec: Optional[P] = None):
    """Run microbatches through a homogeneous pipeline of S stages.

    stage_fn(params_one_stage, x_mb) -> y_mb; activations must keep the
    same shape through every stage (the classic pipeline contract).

    stage_params: pytree with a leading axis of size S (stage-stacked),
    sharded over ``stage_axis``.  xs: (M, mb, ...) microbatched input.
    batch_spec: PartitionSpec of one microbatch's data dims (after the
    leading M axis), e.g. P("n") to shard the microbatch over a data
    axis; defaults to fully replicated.

    Returns (M, mb, ...) outputs, replicated over ``stage_axis``.
    """
    import inspect
    try:
        from jax import shard_map  # jax >= 0.8
        rep_kw = {"check_vma": False} \
            if "check_vma" in inspect.signature(shard_map).parameters \
            else {"check_rep": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
        rep_kw = {"check_rep": False}

    s_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    num_stages = s_sizes[stage_axis]
    num_mb = xs.shape[0]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != num_stages:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != stage mesh "
                f"axis size {num_stages}; each device must hold exactly "
                f"one stage slice")
    data_spec = batch_spec if batch_spec is not None else P()
    xs_spec = P(None, *data_spec)   # leading M axis never sharded
    param_spec = P(stage_axis)      # leading stage-stack axis

    def pipelined(params, xs_local):
        local_params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(stage_axis)
        ticks = num_mb + num_stages - 1
        zero = jnp.zeros(xs_local.shape[1:], xs_local.dtype)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(carry, t):
            recv = carry
            x_t = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False)
            inp = jnp.where(idx == 0, x_t, recv)
            y = stage_fn(local_params, inp)
            recv_next = lax.ppermute(y, stage_axis, perm)
            return recv_next, y

        _, ys = lax.scan(tick, zero, jnp.arange(ticks))
        # stage S-1 emits microbatch m at tick m + S - 1
        out_local = lax.slice_in_dim(ys, num_stages - 1,
                                     num_stages - 1 + num_mb, axis=0)
        # broadcast the last stage's outputs to every stage (masked psum)
        out = lax.psum(
            jnp.where(idx == num_stages - 1, out_local,
                      jnp.zeros_like(out_local)),
            stage_axis)
        return out

    return shard_map(
        pipelined, mesh=mesh,
        in_specs=(param_spec, xs_spec),
        out_specs=xs_spec,
        **rep_kw,
    )(stage_params, xs)


def sequential_reference(stage_fn: Callable, stage_params, xs):
    """Non-pipelined ground truth: apply the S stages in order to each
    microbatch (used by tests to pin the pipeline's semantics)."""
    num_stages = jax.tree.leaves(stage_params)[0].shape[0]

    def apply_all(x_mb):
        for s in range(num_stages):
            p_s = jax.tree.map(lambda p: p[s], stage_params)
            x_mb = stage_fn(p_s, x_mb)
        return x_mb

    return jax.vmap(apply_all)(xs)


# ----------------------------------------------------------------------
# pipelined transformer blocks (flagship integration)


def _layer_norm(g, b, x, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def transformer_block_fn(num_heads: int, causal: bool = False):
    """A pre-norm transformer block as a pipeline stage_fn.  Params:
    {"ln1": (2, D), "wqkv": (D, 3D), "bqkv": (3D,), "wo": (D, D),
     "bo": (D,), "ln2": (2, D), "w1": (D, F), "b1": (F,), "w2": (F, D),
     "b2": (D,)}."""

    def block(p, x):
        d = x.shape[-1]
        h = _layer_norm(p["ln1"][0], p["ln1"][1], x)
        qkv = h @ p["wqkv"] + p["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # (B, S, D) -> (B, H, S, d_h)
            b_, s_, _ = t.shape
            return t.reshape(b_, s_, num_heads, d // num_heads) \
                    .transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scale = 1.0 / jnp.sqrt(jnp.asarray(d // num_heads, x.dtype))
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            mask = jnp.tril(jnp.ones(s.shape[-2:], bool))
            s = jnp.where(mask, s, -jnp.inf)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        o = o.transpose(0, 2, 1, 3).reshape(x.shape)
        x = x + (o @ p["wo"] + p["bo"])

        h = _layer_norm(p["ln2"][0], p["ln2"][1], x)
        h = jax.nn.gelu(h @ p["w1"] + p["b1"])
        return x + (h @ p["w2"] + p["b2"])

    return block


def init_block_stack(rng, num_stages: int, d_model: int, d_ff: int):
    """Stage-stacked transformer block params (leading axis = stage)."""
    ks = jax.random.split(rng, 4)
    shapes = {
        "ln1": ((2, d_model), None),
        "wqkv": ((d_model, 3 * d_model), 0),
        "bqkv": ((3 * d_model,), None),
        "wo": ((d_model, d_model), 1),
        "bo": ((d_model,), None),
        "ln2": ((2, d_model), None),
        "w1": ((d_model, d_ff), 2),
        "b1": ((d_ff,), None),
        "w2": ((d_ff, d_model), 3),
        "b2": ((d_model,), None),
    }
    params = {}
    for name, (shape, ki) in shapes.items():
        full = (num_stages,) + shape
        if ki is None:
            init = jnp.zeros(full, "float32")
            if name.startswith("ln"):
                init = init.at[:, 0].set(1.0)  # scale=1, bias=0
            params[name] = init
        else:
            fan_in = shape[0]
            params[name] = jax.random.normal(ks[ki], full, "float32") \
                * (1.0 / jnp.sqrt(fan_in))
    return params


def place_stage_params(params, mesh: Mesh, stage_axis: str = "stage"):
    """Shard the stage-stacked params over the stage axis of ``mesh``."""
    return jax.tree.map(
        lambda p: jax.device_put(
            p, NamedSharding(mesh, P(*((stage_axis,) +
                                       (None,) * (p.ndim - 1))))),
        params)


# ----------------------------------------------------------------------
# PipelinedLM: a complete causal/encoder LM trained through the GPipe
# ring — the driver-level integration of pipeline parallelism
# (apps/lm --pipeline-stages), composing PP (stage axis) x DP (n axis).


class PipelinedLM:
    """Embed -> L transformer blocks split over S pipeline stages ->
    final-norm -> vocab head + CE.  Blocks run through spmd_pipeline on a
    ('stage', 'n') mesh; embed/head run under plain GSPMD batch sharding.

    Not an FFModel: stage params are stacked on a leading axis (one slice
    per device along 'stage'), which is a different parameter layout than
    the op DAG; the op-DAG path covers per-layer SOAP strategies, this
    class covers explicit microbatch pipelining of a homogeneous stack.
    """

    def __init__(self, machine, num_stages: int, num_microbatches: int,
                 num_layers: int = 12, d_model: int = 768,
                 num_heads: int = 12, d_ff: int = 3072,
                 vocab_size: int = 32768, seq_length: int = 512,
                 batch_size: int = 16, causal: bool = True,
                 learning_rate: float = 1e-3, compute_dtype="float32"):
        import numpy as np

        if num_layers % num_stages:
            raise ValueError(f"{num_layers} layers not divisible into "
                             f"{num_stages} stages")
        if machine.num_devices % num_stages:
            raise ValueError(f"{machine.num_devices} devices not divisible "
                             f"into {num_stages} stages")
        if batch_size % num_microbatches:
            raise ValueError("batch not divisible by microbatches")
        dp = machine.num_devices // num_stages
        if (batch_size // num_microbatches) % dp:
            raise ValueError(
                f"microbatch size {batch_size // num_microbatches} not "
                f"divisible by the data-parallel axis ({dp} devices)")
        self.machine = machine
        self.S, self.M = num_stages, num_microbatches
        self.L, self.D, self.H = num_layers, d_model, num_heads
        self.F, self.V = d_ff, vocab_size
        self.seq, self.batch = seq_length, batch_size
        self.causal = causal
        self.lr = learning_rate
        self.dtype = compute_dtype
        dev = np.empty(machine.num_devices, object)
        for i, d in enumerate(machine.devices):
            dev[i] = d
        self.mesh = Mesh(dev.reshape(num_stages, dp), ("stage", "n"))
        self.block = transformer_block_fn(num_heads, causal)

    # -- params ---------------------------------------------------------

    def init(self, seed: int = 0):
        k0, k1, k2 = jax.random.split(jax.random.PRNGKey(seed), 3)
        blocks = init_block_stack(k0, self.L, self.D, self.F)
        # (L, ...) -> (S, L/S, ...): one leading slice per stage
        blocks = jax.tree.map(
            lambda p: p.reshape((self.S, self.L // self.S) + p.shape[1:]),
            blocks)
        blocks = place_stage_params(blocks, self.mesh)
        repl = NamedSharding(self.mesh, P())
        scale = 1.0 / jnp.sqrt(jnp.asarray(self.D, "float32"))
        other = {
            "embed": jax.random.normal(k1, (self.V, self.D), "float32")
            * scale,
            "pos": jax.random.normal(k2, (self.seq, self.D), "float32")
            * scale,
            "ln_f": jnp.stack([jnp.ones((self.D,), "float32"),
                               jnp.zeros((self.D,), "float32")]),
            "head_w": jnp.zeros((self.D, self.V), "float32"),
            "head_b": jnp.zeros((self.V,), "float32"),
        }
        other = {k: jax.device_put(v, repl) for k, v in other.items()}
        return {"blocks": blocks, **other}

    # -- forward/loss ---------------------------------------------------

    def _stage_fn(self):
        block, n_sub, dtype = self.block, self.L // self.S, self.dtype

        def stage(p, x):
            p = jax.tree.map(lambda q: q.astype(dtype), p)
            for i in range(n_sub):  # static sub-layer loop within a stage
                x = block(jax.tree.map(lambda q: q[i], p), x)
            return x

        return stage

    def _embed(self, params, tokens):
        # gather before casting (f32 scatter-add in the VJP, no full-
        # vocab low-precision table copy)
        return params["embed"][tokens].astype(self.dtype) \
            + params["pos"].astype(self.dtype)[None]

    def _head_loss(self, params, ys, labels):
        """Final-norm + vocab head + shifted masked CE over the
        (M, mb, seq, D) pipeline outputs — shared by the pipelined and
        sequential-reference paths so their semantics cannot drift."""
        y = ys.reshape(self.batch, self.seq, self.D)
        y = _layer_norm(params["ln_f"][0], params["ln_f"][1],
                        y.astype("float32"))
        logits = y @ params["head_w"] + params["head_b"]
        if self.causal:
            labels = jnp.concatenate(
                [labels[:, 1:],
                 jnp.full((labels.shape[0], 1), -1, labels.dtype)], axis=1)
        valid = labels >= 0
        lp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            lp, jnp.where(valid, labels, 0)[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, nll, 0.0)) \
            / jnp.maximum(valid.sum(), 1)

    def loss_fn(self, params, tokens, labels):
        xs = microbatch(self._embed(params, tokens), self.M)
        ys = spmd_pipeline(self._stage_fn(), params["blocks"], xs,
                           self.mesh, batch_spec=P("n"))
        return self._head_loss(params, ys, labels)

    def loss_reference(self, params, tokens, labels):
        """Same model WITHOUT the pipeline ring (sequential stages) —
        pins the pipelined semantics in tests."""
        xs = microbatch(self._embed(params, tokens), self.M)
        ys = sequential_reference(self._stage_fn(), params["blocks"], xs)
        return self._head_loss(params, ys, labels)

    # -- training -------------------------------------------------------

    def make_train_step(self):
        def step(params, tokens, labels):
            loss, g = jax.value_and_grad(self.loss_fn)(params, tokens,
                                                       labels)
            new = jax.tree.map(lambda p, gr: p - self.lr * gr, params, g)
            return new, loss

        return jax.jit(step, donate_argnums=(0,))
