"""Step health guard: windowed finite-loss checks for the training loop.

A single NaN loss used to propagate silently — the run kept training on
diverged state, and the checkpoint pruner would happily overwrite the
last healthy checkpoints with NaN parameters.  The guard closes that
hole WITHOUT touching the device hot path: ``fit()`` accumulates per-step
losses as raw device arrays (as it always has) and hands the guard the
window since the last check only at boundaries that already host-sync —
``print_freq`` prints, checkpoint saves, and the final step.  Zero
per-step host syncs are added, and with finite losses the run is
byte-identical to an unguarded one.

Policies (``FFConfig.on_divergence``):

  * ``halt``     — raise :class:`TrainingDiverged` (the default: fail
                   fast and loud, never train on NaN state);
  * ``warn``     — log + emit the ``fault`` record, keep training;
  * ``rollback`` — tell ``fit()`` to restore the last VERIFIED
                   checkpoint (utils/checkpoint.py cascade) and continue
                   on fresh data; after ``max_rollbacks`` restores the
                   guard raises anyway, so a deterministic NaN cannot
                   loop forever.

All detections flow through obs as first-class ``fault`` records
(source="guard"); the first clean window after a rollback emits the
matching ``recovery`` record.
"""

from __future__ import annotations

import math

POLICIES = ("halt", "warn", "rollback")


class TrainingDiverged(RuntimeError):
    """A non-finite loss under the ``halt`` policy, or divergence that
    survived every allowed rollback."""

    def __init__(self, step: int, value: float, rollbacks: int = 0):
        self.step = step
        self.value = value
        self.rollbacks = rollbacks
        extra = (f" after {rollbacks} rollback(s)" if rollbacks else "")
        super().__init__(
            f"training diverged: non-finite loss {value!r} at iteration "
            f"{step}{extra}")


class StepHealthGuard:
    """One guard per ``fit()`` call.  ``check()`` is invoked only at
    existing sync boundaries with the loss window accumulated since the
    previous check."""

    def __init__(self, policy: str = "halt", max_rollbacks: int = 3,
                 olog=None, log=print):
        if policy not in POLICIES:
            raise ValueError(
                f"on_divergence must be one of {'|'.join(POLICIES)}, "
                f"got {policy!r}")
        from flexflow_tpu import obs

        self.policy = policy
        self.max_rollbacks = max(int(max_rollbacks), 0)
        self.rollbacks = 0
        self.olog = olog if olog is not None else obs.NULL
        self.log = log
        self._await_recovery = False

    def check(self, window, first_step: int):
        """Inspect the loss window (device or host scalars) covering
        steps ``first_step .. first_step+len(window)-1``.  Returns None
        (healthy), ``"warn"`` (diverged, policy says continue) or
        ``"rollback"`` (caller must restore + rewind); raises
        :class:`TrainingDiverged` under ``halt`` or when the rollback
        budget is spent."""
        if not window:
            return None
        import jax

        try:
            vals = [float(v) for v in jax.device_get(list(window))]
        except Exception as e:
            # a dead device can make the window itself unreadable — say
            # so in the obs stream, then let the error propagate so the
            # elastic runtime (utils/elastic.py) can classify/probe it
            self.olog.event("fault", source="guard",
                            fault="window_unreadable",
                            step=first_step + len(window) - 1,
                            error=str(e))
            raise
        bad = next((i for i, v in enumerate(vals)
                    if not math.isfinite(v)), None)
        if bad is None:
            if self._await_recovery:
                self._await_recovery = False
                step = first_step + len(vals) - 1
                self.olog.event("recovery", source="guard",
                                after="rollback", step=step)
                self.log(f"health guard: recovered — window through "
                         f"iteration {step} is finite again")
            return None
        step = first_step + bad
        value = vals[bad]
        self.olog.event("fault", source="guard", fault="loss_divergence",
                        step=step, value=value, policy=self.policy)
        if self.policy == "warn":
            self.log(f"warning: non-finite loss {value!r} at iteration "
                     f"{step} (on_divergence=warn; continuing)")
            return "warn"
        if self.policy == "rollback":
            if self.rollbacks >= self.max_rollbacks:
                self.olog.event("fault", source="guard",
                                fault="rollback_budget_exhausted",
                                step=step, rollbacks=self.rollbacks)
                raise TrainingDiverged(step, value, self.rollbacks)
            self.rollbacks += 1
            self._await_recovery = True
            return "rollback"
        raise TrainingDiverged(step, value)
