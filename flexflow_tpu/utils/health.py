"""Step health guard: windowed finite-loss checks for the training loop.

A single NaN loss used to propagate silently — the run kept training on
diverged state, and the checkpoint pruner would happily overwrite the
last healthy checkpoints with NaN parameters.  The guard closes that
hole WITHOUT touching the device hot path: ``fit()`` accumulates per-step
losses as raw device arrays (as it always has) and hands the guard the
window since the last check only at boundaries that already host-sync —
``print_freq`` prints, checkpoint saves, and the final step.  Zero
per-step host syncs are added, and with finite losses the run is
byte-identical to an unguarded one.

Policies (``FFConfig.on_divergence``):

  * ``halt``     — raise :class:`TrainingDiverged` (the default: fail
                   fast and loud, never train on NaN state);
  * ``warn``     — log + emit the ``fault`` record, keep training;
  * ``rollback`` — tell ``fit()`` to restore the last VERIFIED
                   checkpoint (utils/checkpoint.py cascade) and continue
                   on fresh data; after ``max_rollbacks`` restores the
                   guard raises anyway, so a deterministic NaN cannot
                   loop forever.

All detections flow through obs as first-class ``fault`` records
(source="guard"); the first clean window after a rollback emits the
matching ``recovery`` record.

Round 9 adds :class:`StepWatchdog` for the failure mode the guard cannot
see: a WEDGED collective.  Device loss that raises is classified by
utils/elastic.py, but a hang never raises — the blocking ``device_get``
at a boundary just sits there forever.  The watchdog arms a one-shot
timer around exactly those blocking windows (zero per-step cost; off by
default via ``--hang-factor 0``) with a deadline of ``hang_factor`` × a
robust rolling per-step time estimate (median of recent boundaries,
floored at ``--hang-min-s``).  On expiry it emits a ``step_hang`` fault
record from the timer thread; the MAIN thread — once whatever was wedged
finally returns or the injected stall ends — sees the expiry at
``disarm()`` and routes into the existing probe/classify path
(transient -> keep training, permanent -> ``DeviceLossDetected`` ->
shrink).  The injected ``step_hang@N`` stalls inside an armed window
deterministically (``stall()``) so CI drives the full path.
"""

from __future__ import annotations

import math

POLICIES = ("halt", "warn", "rollback")


class TrainingDiverged(RuntimeError):
    """A non-finite loss under the ``halt`` policy, or divergence that
    survived every allowed rollback."""

    def __init__(self, step: int, value: float, rollbacks: int = 0):
        self.step = step
        self.value = value
        self.rollbacks = rollbacks
        extra = (f" after {rollbacks} rollback(s)" if rollbacks else "")
        super().__init__(
            f"training diverged: non-finite loss {value!r} at iteration "
            f"{step}{extra}")


class StepHealthGuard:
    """One guard per ``fit()`` call.  ``check()`` is invoked only at
    existing sync boundaries with the loss window accumulated since the
    previous check."""

    def __init__(self, policy: str = "halt", max_rollbacks: int = 3,
                 olog=None, log=print):
        if policy not in POLICIES:
            raise ValueError(
                f"on_divergence must be one of {'|'.join(POLICIES)}, "
                f"got {policy!r}")
        from flexflow_tpu import obs

        self.policy = policy
        self.max_rollbacks = max(int(max_rollbacks), 0)
        self.rollbacks = 0
        self.olog = olog if olog is not None else obs.NULL
        self.log = log
        self._await_recovery = False

    def check(self, window, first_step: int):
        """Inspect the loss window (device or host scalars) covering
        steps ``first_step .. first_step+len(window)-1``.  Returns None
        (healthy), ``"warn"`` (diverged, policy says continue) or
        ``"rollback"`` (caller must restore + rewind); raises
        :class:`TrainingDiverged` under ``halt`` or when the rollback
        budget is spent."""
        if not window:
            return None
        import jax

        try:
            vals = [float(v) for v in jax.device_get(list(window))]
        except Exception as e:
            # a dead device can make the window itself unreadable — say
            # so in the obs stream, then let the error propagate so the
            # elastic runtime (utils/elastic.py) can classify/probe it
            self.olog.event("fault", source="guard",
                            fault="window_unreadable",
                            step=first_step + len(window) - 1,
                            error=str(e))
            raise
        bad = next((i for i, v in enumerate(vals)
                    if not math.isfinite(v)), None)
        if bad is None:
            if self._await_recovery:
                self._await_recovery = False
                step = first_step + len(vals) - 1
                self.olog.event("recovery", source="guard",
                                after="rollback", step=step)
                self.log(f"health guard: recovered — window through "
                         f"iteration {step} is finite again")
            return None
        step = first_step + bad
        value = vals[bad]
        self.olog.event("fault", source="guard", fault="loss_divergence",
                        step=step, value=value, policy=self.policy)
        if self.policy == "warn":
            self.log(f"warning: non-finite loss {value!r} at iteration "
                     f"{step} (on_divergence=warn; continuing)")
            return "warn"
        if self.policy == "rollback":
            if self.rollbacks >= self.max_rollbacks:
                self.olog.event("fault", source="guard",
                                fault="rollback_budget_exhausted",
                                step=step, rollbacks=self.rollbacks)
                raise TrainingDiverged(step, value, self.rollbacks)
            self.rollbacks += 1
            self._await_recovery = True
            return "rollback"
        raise TrainingDiverged(step, value)


class StepWatchdog:
    """Hang detector armed around fit()'s blocking host-sync windows.

    One instance per ``fit()`` call.  Lifecycle per boundary::

        wd.observe(wall_s, steps)   # feed the rolling step-time estimate
        wd.arm(step)                # start the one-shot deadline timer
        ... blocking device_get / checkpoint sync ...
        info = wd.disarm()          # cancel (or collect the expiry)
        if info: <probe/classify>   # main thread routes the recovery

    The timer thread only SETS state and emits the ``step_hang`` obs
    record (the obs sink is already thread-safe — the fault injector
    fires from data threads); all recovery decisions stay on the main
    thread.  ``close()`` is idempotent and joins any live timer so the
    thread-leak checks stay clean."""

    def __init__(self, factor: float, min_deadline_s: float = 60.0,
                 window: int = 32, olog=None, log=print):
        from flexflow_tpu import obs

        self.factor = float(factor)
        self.min_deadline_s = float(min_deadline_s)
        self.window = max(int(window), 1)
        self.olog = olog if olog is not None else obs.NULL
        self.log = log
        self.enabled = self.factor > 0
        self._estimates: list = []
        self._timer = None
        self._expired = None
        self._step = None
        self.hangs = 0

    def observe(self, wall_s: float, steps: int = 1) -> None:
        """Feed one inter-boundary wall time covering ``steps`` steps."""
        if steps <= 0 or wall_s <= 0:
            return
        self._estimates.append(float(wall_s) / steps)
        del self._estimates[:-self.window]

    def step_estimate_s(self) -> float:
        """Robust (median) per-step wall estimate; 0 until observed."""
        if not self._estimates:
            return 0.0
        vals = sorted(self._estimates)
        return vals[len(vals) // 2]

    def deadline_s(self) -> float:
        return max(self.factor * self.step_estimate_s(),
                   self.min_deadline_s)

    def _expire(self, step: int, deadline: float) -> None:
        self._expired = {"step": step, "deadline_s": deadline,
                         "estimate_s": self.step_estimate_s()}
        self.hangs += 1
        self.olog.event("step_hang", step=step, deadline_s=deadline,
                        estimate_s=self._expired["estimate_s"],
                        factor=self.factor)
        self.log(f"watchdog: boundary at iteration {step} exceeded its "
                 f"{deadline:.1f}s deadline — probing devices when it "
                 f"returns")

    def arm(self, step: int) -> None:
        """Start the one-shot deadline timer for this boundary."""
        if not self.enabled:
            return
        import threading

        self.disarm()
        self._expired = None
        self._step = int(step)
        deadline = self.deadline_s()
        self._timer = threading.Timer(
            deadline, self._expire, args=(self._step, deadline))
        self._timer.daemon = True
        self._timer.name = f"ff-step-watchdog-{self._step}"
        self._timer.start()

    def disarm(self):
        """Cancel the timer (joining it so no thread outlives the call)
        and return the expiry info dict if the deadline fired, else
        None."""
        t, self._timer = self._timer, None
        if t is not None:
            t.cancel()
            t.join(timeout=5.0)
        info, self._expired = self._expired, None
        return info

    def stall(self, margin_s: float = 0.25, sleep=None) -> None:
        """The injected ``step_hang`` wedge: block inside the armed
        window until just past the deadline, deterministically forcing
        an expiry without any real hardware misbehaving."""
        import time as _time

        (sleep or _time.sleep)(self.deadline_s() + margin_s)

    def close(self) -> None:
        self.disarm()
