"""Measure the cross-process (DCN-tier) link with the 2-process rig
(round 5, VERDICT r4 #6).

The simulator's ICI constants are chip-calibrated (apps/calibrate,
protocol v3), but its DCN side was an assumed 25 GB/s (machine.py
Topology).  This probe measures the EFFECTIVE cross-process all-reduce
bandwidth and latency on the same 2-process rig that executes and audits
the two-tier plans (tests/test_two_tier.py): two workers, each with half
the virtual devices, time a psum over the process axis at two volumes;
the slope gives bandwidth, the intercept latency — the reference's two
bandwidth constants were modeled, not measured
(ref:scripts/simulator.cc:37-38); here the rig's tier constant is a
measurement.

The fitted constants parameterize the simulator's own hierarchical
all-reduce model (sim/collectives._allreduce): for a 2-group reduce of
per-device volume v the cross term is t = v/bw + 2*lat, so the recorded
bw/lat plug back in consistently.  "Effective" means link sharing by the
concurrent per-device pairs is absorbed into the constant — exactly what
the list-scheduling simulator wants.

    python -m flexflow_tpu.utils.dcn_probe -o examples/strategies/dcn_calibration.json

Consumed by ``apps/search.py --dcn-calibration <file>`` (feeds
Topology.from_calibration) so two-tier searches of THIS rig run on
measured tier constants.  The TPU-pod DCN default in Topology remains the
documented model for real multi-slice deployments.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent('''
import json, sys, time
pid, port, half = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
import os
os.environ["XLA_FLAGS"] = \
    "--xla_force_host_platform_device_count=%d" % half
import jax
jax.config.update("jax_platforms", "cpu")
from flexflow_tpu import distributed
machine = distributed.initialize(coordinator_address="localhost:" + port,
                                 num_processes=2, process_id=pid)
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
    import inspect
    kw = {"check_vma": False} \
        if "check_vma" in inspect.signature(shard_map).parameters \
        else {"check_rep": False}
except ImportError:
    from jax.experimental.shard_map import shard_map
    kw = {"check_rep": False}
dev = np.array(jax.devices()).reshape(2, half)
mesh = Mesh(dev, ("proc", "loc"))

def timed_psum(nelem, iters=6):
    x = jnp.ones((2, half, nelem), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("proc", "loc")))
    f = jax.jit(shard_map(lambda a: lax.psum(a, "proc"), mesh=mesh,
                          in_specs=P("proc", "loc"),
                          out_specs=P(None, "loc"), **kw))
    y = f(x); y.block_until_ready()          # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(x)
    y.block_until_ready()
    return (time.perf_counter() - t0) / iters

v1, v2 = 1 << 18, 1 << 22                     # 1 MB and 16 MB per device
t1, t2 = timed_psum(v1), timed_psum(v2)
b1, b2 = 4.0 * v1, 4.0 * v2
bw = (b2 - b1) / max(t2 - t1, 1e-9)
lat = max((t1 - b1 / bw) / 2.0, 0.0)
if pid == 0:
    print("PROBE " + json.dumps({
        "t1_s": t1, "t2_s": t2, "bytes1": b1, "bytes2": b2,
        "dcn_bandwidth": bw, "dcn_latency": lat}), flush=True)
''')


def measure(half_devices: int = 4, timeout: float = 420.0) -> dict:
    """Run the 2-process probe; returns the fitted constants."""
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), port, str(half_devices)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(f"probe worker {i} failed:\n{out[-2000:]}")
    for out in outs:
        for line in out.splitlines():
            if line.startswith("PROBE "):
                return json.loads(line[len("PROBE "):])
    raise RuntimeError(f"probe printed no result:\n{outs[0][-1000:]}")


def main(argv=None):
    from flexflow_tpu.utils.flags import flag_stream

    args = list(sys.argv[1:] if argv is None else argv)
    out_path = ""
    half = 4
    for a, val in flag_stream(args):
        if a in ("-o", "--out"):
            out_path = val()
        elif a == "--half-devices":
            half = int(val())
    res = measure(half_devices=half)
    artifact = {
        "what": ("measured cross-process (DCN-tier) all-reduce constants "
                 "of the 2-process rig (gloo transport) that executes "
                 "and audits the two-tier plans; fitted to the "
                 "simulator's hierarchical all-reduce cross term "
                 "t = v/bw + 2*lat (sim/collectives._allreduce, G=2)"),
        "protocol": (f"2 procs x {half} virtual devices, psum over the "
                     f"process axis at 1 MB and 16 MB per device, "
                     f"6 timed iters after warmup; slope -> bandwidth, "
                     f"intercept -> latency"),
        **res,
    }
    print(json.dumps({k: artifact[k] for k in
                      ("dcn_bandwidth", "dcn_latency", "t1_s", "t2_s")}))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2)
        print(f"written to {out_path}")


if __name__ == "__main__":
    main()
