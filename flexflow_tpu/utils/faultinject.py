"""Deterministic fault injection — the test harness of the fault-tolerance
layer (robustness round).

``FFConfig.fault_spec`` names faults to fire at EXACT occurrence indices,
so every recovery path in the runtime — step health guard rollback
(model.py::fit), checkpoint restore cascade (utils/checkpoint.py),
retrying data sources (data/hdf5.py, data/imagenet.py) — is exercised at
reproducible points in tests and in ``make fault-smoke``.

Grammar (comma-separated entries)::

    <kind>@<at>            fire on occurrence <at>        loss_nan@120
    <kind>@<at>x<times>    fire on <at> .. <at+times-1>   data_io@50x3

Occurrences are counted per kind by the injector itself: every ``fire()``
call at a site increments the kind's counter, so ``loss_nan@120`` means
"the 120th training step of this run", ``data_io@50x3`` means "the 50th
through 52nd read attempts" (each RETRY is a new attempt — ``x3`` with a
4-attempt retry policy is a transient fault the retries absorb, a huge
``x`` count is a permanent one that forces the skip path), and
``ckpt_truncate@2`` means "the 2nd checkpoint save".  Counting attempts
instead of wall positions is what makes recovery terminate: after a
rollback the re-run steps consume FRESH occurrence indices, so a fault
pinned at one index cannot re-fire forever.

Kinds:

  * ``loss_nan``      — fit() poisons that step's recorded loss with NaN
                        (device-side; exercises the health guard);
  * ``data_io``       — the data sources raise :class:`InjectedIOError`
                        (an ``OSError``; exercises retry + skip budget);
  * ``ckpt_truncate`` — save_checkpoint truncates the just-committed
                        ``arrays.npz`` (a torn write; exercises digest
                        verification + the restore cascade);
  * ``ckpt_corrupt``  — save_checkpoint flips one byte of the committed
                        ``arrays.npz`` (a bit flip; same recovery path);
  * ``device_loss``   — fit() marks one device (the highest live ordinal)
                        as PERMANENTLY lost at that training step; the
                        elastic runtime (utils/elastic.py) must detect it
                        at the next host-sync boundary and shrink onto
                        the surviving mesh.  ``device_loss@5x2`` loses one
                        device at step 5 and another at step 6 — one
                        resize event covering both at the next boundary;
  * ``host_crash``    — fit() raises :class:`~flexflow_tpu.utils.elastic.
                        HostCrashError` at that training step, simulating
                        this whole process dying mid-run (exercises the
                        error-exit cleanup — coordinator release,
                        prefetcher shutdown — and the ``--elastic``
                        restart/rejoin protocol in distributed.py);
  * ``device_return`` — counted per elastic REGROW PROBE (the boundary
                        probe of previously-dead ordinals after a
                        shrink): on fire, the injected-dead devices
                        answer again, so after ``--regrow-probes``
                        consecutive healthy probes the run grows back
                        (``recover_grow``, utils/elastic.py);
  * ``preempt``       — counted per training step: raises the graceful-
                        drain signal path (the same SIGTERM handler fit
                        installs), so the run finishes the in-flight
                        step, commits a final verified checkpoint and
                        exits 0 within ``--drain-budget-s``;
  * ``step_hang``     — counted per training step: deterministically
                        stalls the NEXT host-sync boundary past the step
                        watchdog's deadline (``--hang-factor``,
                        utils/health.StepWatchdog), converting a wedged
                        collective into the probe/classify recovery
                        path;
  * ``replica_crash`` — serving (serve/router.py): counted per
                        decode-boundary HEALTH CHECK per live decode
                        replica (the router probes replicas in index
                        order at each boundary it steps); on fire the
                        probed replica dies — its in-flight sessions
                        lose their imported KV and re-route through the
                        ``kv_rebuild`` re-prefill path, its queued
                        handoffs retransmit, and the replica revives
                        after the router's ``restart_s``;
  * ``handoff_drop``  — counted per DISPATCHED prefill->decode handoff:
                        the priced transfer is lost in flight (the
                        payload survives host-side), so the request
                        retries the retransmit path under the router's
                        RetryPolicy;
  * ``kv_corrupt``    — counted per dispatched handoff alongside
                        ``handoff_drop``: the payload arrives but its
                        rows are untrusted — the router discards it and
                        re-materializes the session by re-prefilling
                        its carried tokens (``kv_rebuild``);
  * ``slow_replica``  — counted per DECODE-phase engine step: that step
                        takes ``SLOW_REPLICA_FACTOR`` times its virtual
                        service time (a straggler, not a death) —
                        the hedged-decode mode's p99 adversary.

One injector is installed process-globally (``install``/``get``) so data
sources running on background threads see the same schedule; ``fit()``
installs from its config and restores the previous injector on exit.
Every fired fault is emitted as a first-class ``fault`` obs record when
the injector carries a sink.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

KINDS = ("loss_nan", "data_io", "ckpt_truncate", "ckpt_corrupt",
         "device_loss", "host_crash", "device_return", "preempt",
         "step_hang", "replica_crash", "handoff_drop", "kv_corrupt",
         "slow_replica")


class FaultSpecError(ValueError):
    """Malformed ``fault_spec`` string."""


class InjectedIOError(OSError):
    """A deterministically injected transient I/O failure (``data_io``) —
    an ``OSError`` so the retry policies treat it exactly like a real
    read error."""


def parse_fault_spec(spec: str) -> Dict[str, List[Tuple[int, int]]]:
    """``"loss_nan@120,data_io@50x3"`` -> ``{kind: [(at, times), ...]}``.
    Raises :class:`FaultSpecError` on unknown kinds or bad syntax, so a
    typo'd spec fails at config time instead of silently never firing."""
    out: Dict[str, List[Tuple[int, int]]] = {}
    for raw in (spec or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise FaultSpecError(
                f"fault spec entry {entry!r} needs '<kind>@<at>[x<times>]'")
        kind, _, pos = entry.partition("@")
        kind = kind.strip()
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r}; known: {', '.join(KINDS)}")
        at_s, _, times_s = pos.partition("x")
        try:
            at = int(at_s)
            times = int(times_s) if times_s else 1
        except ValueError:
            raise FaultSpecError(
                f"fault spec entry {entry!r}: occurrence and repeat count "
                f"must be integers") from None
        if at < 1 or times < 1:
            raise FaultSpecError(
                f"fault spec entry {entry!r}: occurrence index and repeat "
                f"count are 1-based and must be >= 1")
        out.setdefault(kind, []).append((at, times))
    return out


class NullInjector:
    """The disabled injector: ``fire()`` is always False and counts
    nothing.  A single shared instance (``NULL``) is the default."""

    enabled = False

    def fire(self, kind: str, site: str = "") -> bool:
        return False

    def fired(self, kind: Optional[str] = None) -> int:
        return 0


NULL = NullInjector()


class FaultInjector:
    """Deterministic occurrence-counting injector for one run.  Thread-safe
    (data sources fire from background threads)."""

    enabled = True

    def __init__(self, spec: str, olog=None):
        self.spec = spec
        self.ranges = parse_fault_spec(spec)
        self.olog = olog
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._fired: List[Tuple[str, int, str]] = []

    def fire(self, kind: str, site: str = "") -> bool:
        """Count one occurrence of ``kind`` at ``site``; True when the
        spec schedules a fault for this occurrence.  Emits a ``fault``
        obs record (source="injected") for every fire."""
        with self._lock:
            n = self._counts.get(kind, 0) + 1
            self._counts[kind] = n
            hit = any(at <= n < at + times
                      for at, times in self.ranges.get(kind, ()))
            if hit:
                self._fired.append((kind, n, site))
        if hit and self.olog is not None:
            self.olog.event("fault", source="injected", fault=kind,
                            occurrence=n, site=site)
        return hit

    def fired(self, kind: Optional[str] = None) -> int:
        """How many faults have actually fired (optionally of one kind)."""
        with self._lock:
            if kind is None:
                return len(self._fired)
            return sum(1 for k, _, _ in self._fired if k == kind)


_current = NULL
_install_lock = threading.Lock()


def get():
    """The process-global injector (``NULL`` unless a run installed one)."""
    return _current


def install(injector):
    """Make ``injector`` the process-global one; returns the previous
    injector so the installer can restore it (``fit()`` does, in a
    ``finally``)."""
    global _current
    with _install_lock:
        prev = _current
        _current = injector if injector is not None else NULL
        return prev


def install_scoped(injector):
    """Install ``injector`` and return an IDEMPOTENT, re-entrant restore
    callable.  fit()'s graceful-drain path and its error path can both
    reach the uninstall; a second (or concurrent) call must be a no-op
    instead of clobbering whatever a later run installed."""
    prev = install(injector)
    done = [False]
    lock = threading.Lock()

    def restore() -> bool:
        with lock:
            if done[0]:
                return False
            done[0] = True
        install(prev)
        return True

    return restore


def from_config(config, olog=None):
    """A :class:`FaultInjector` for ``config.fault_spec``, or ``NULL``
    when the spec is empty/absent — the one gate ``fit()`` calls."""
    spec = getattr(config, "fault_spec", "") or ""
    return FaultInjector(spec, olog=olog) if spec.strip() else NULL


def raise_if(kind: str, site: str = "") -> None:
    """Data-source hook: raise :class:`InjectedIOError` when the global
    injector fires ``kind`` for this occurrence."""
    inj = _current
    if inj.enabled and inj.fire(kind, site=site):
        raise InjectedIOError(f"injected {kind} fault at {site or '?'}")
