"""Compiled-step HLO profiling: per-op device times from a jax.profiler
trace, classified against the compiled HLO (MXU conv/dot fusions vs
elementwise/VPU), plus the roofline ceiling analysis.

This is the deep end of the reference's ``profiling`` flag (per-task
cudaEvent ms, conv_2d.cu:514-545): under XLA the step is one fused program,
so honest per-op attribution must come from the device trace of the
compiled executable, not from isolated op timings (utils/profiling.py's
OpProfiler remains the attribution *estimate*; this module measures the
real thing).

Typical use (see apps/profile.py for the CLI):

    compiled = model.compile_train_step(*batch)
    with jax.profiler.trace(logdir):
        ... run steps ...
    times = device_op_times(logdir)          # {hlo op name: ms}
    cls = classify_ops(compiled.as_text(), times)
    report = roofline_report(compiled, seconds_per_step, cls)
"""

from __future__ import annotations

import glob
import gzip
import json
import re
from collections import defaultdict
from typing import Dict, Optional


def device_op_times(logdir: str, steps: int = 1) -> Dict[str, float]:
    """Aggregate device-side op durations (ms, divided by ``steps``) from
    the newest perfetto trace under ``logdir``.  Module-level pseudo events
    (bare numerals, jit_* wrappers) are dropped."""
    files = sorted(glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True))
    if not files:
        raise FileNotFoundError(f"no .trace.json.gz under {logdir}")
    with gzip.open(files[-1], "rt") as fh:
        tr = json.load(fh)
    pidname = {}
    for e in tr.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pidname[e["pid"]] = e["args"].get("name", "")
    devpids = {p for p, n in pidname.items()
               if "TPU" in n or "GPU" in n}
    # under SPMD every chip runs the same program: average over device
    # pids so per-op ms stays per-chip on multi-chip hosts (summing would
    # inflate class totals num_devices-fold)
    agg: Dict[str, float] = defaultdict(float)
    for e in tr.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("pid") not in devpids:
            continue
        name = e.get("name", "")
        if name.startswith("jit_") or re.fullmatch(r"\d+", name):
            continue
        agg[name] += (e.get("dur", 0) / 1e3 / max(steps, 1)
                      / max(len(devpids), 1))
    return dict(agg)


class HloIndex:
    """Fusion name -> called computation body, from ``compiled.as_text()``."""

    def __init__(self, hlo_text: str):
        self.lines = hlo_text.splitlines()
        self.calls: Dict[str, str] = {}
        for m in re.finditer(
                r'^\s*%?([\w.\-]+) = [^\n]*fusion\([^\n]*calls=%?([\w.\-]+)',
                hlo_text, re.M):
            self.calls[m.group(1)] = m.group(2)
        self.comp_start: Dict[str, int] = {}
        for j, l in enumerate(self.lines):
            m = re.match(r'^%?([\w.\-]+) \([^)]*\) -> ', l)
            if m:
                self.comp_start[m.group(1)] = j

    def body(self, op_name: str):
        comp = self.calls.get(op_name)
        if comp is None or comp not in self.comp_start:
            return None
        out = []
        for l in self.lines[self.comp_start[comp] + 1:]:
            if l.strip() == "}":
                break
            out.append(l)
        return out

    def classify(self, op_name: str) -> str:
        """'mxu' when the op's fusion body contains a convolution/dot (the
        MXU work rides there after fusion), 'raw' for unfusable HLO ops
        (select-and-scatter, bare converts/copies), else 'vpu'."""
        body = self.body(op_name)
        if body is None:
            if "convolution" in op_name or "dot" in op_name:
                return "mxu"
            return "raw"
        for l in body:
            if "convolution(" in l or " dot(" in l:
                return "mxu"
        return "vpu"


def classify_ops(hlo_text: str, times: Dict[str, float]):
    """[(ms, class, name, root-line)] sorted by time desc, plus per-class
    totals."""
    idx = HloIndex(hlo_text)
    rows = []
    totals: Dict[str, float] = defaultdict(float)
    for name, ms in sorted(times.items(), key=lambda kv: -kv[1]):
        c = idx.classify(name)
        totals[c] += ms
        root = ""
        body = idx.body(name)
        if body:
            for l in body:
                if l.strip().startswith("ROOT"):
                    root = l.strip()[5:]
                    break
        rows.append((ms, c, name, root))
    return rows, dict(totals)


def roofline_report(compiled, seconds_per_step: float,
                    class_totals: Optional[Dict[str, float]] = None,
                    perf=None, n_devices: int = 1) -> Dict:
    """Roofline ceiling analysis of the compiled step: arithmetic
    intensity vs the chip balance point, the HBM-bound step-time floor,
    and the MFU ceiling that floor implies.  ``mfu_ceiling`` is the honest
    upper bound for THIS compiled program on this chip — raising it
    requires removing bytes, not scheduling."""
    from flexflow_tpu.sim.cost_model import TpuChipPerf
    from flexflow_tpu.utils.profiling import compiled_roofline

    perf = perf or TpuChipPerf()
    # single source for flops/bytes/utilizations (incl. the GLOBAL-flops-
    # under-SPMD convention documented there)
    rl = compiled_roofline(compiled, seconds_per_step, perf, n_devices)
    flops, bytes_ = rl["flops"], rl["bytes_accessed"]
    peak = perf.peak_flops * max(n_devices, 1)
    hbm = perf.hbm_bandwidth * max(n_devices, 1)
    intensity = flops / bytes_ if bytes_ else float("inf")
    balance = peak / hbm
    floor_s = max(flops / peak, bytes_ / hbm)
    out = {
        "seconds_per_step": seconds_per_step,
        "flops_per_step": flops,
        "bytes_per_step": bytes_,
        "arithmetic_intensity_flop_per_byte": intensity,
        "chip_balance_flop_per_byte": balance,
        "bound": "hbm" if intensity < balance else "mxu",
        "step_floor_seconds": floor_s,
        "mfu": rl.get("mxu_utilization"),
        "mfu_ceiling": flops / floor_s / peak if floor_s else None,
        "hbm_utilization": rl.get("hbm_utilization"),
        "of_ceiling": floor_s / seconds_per_step if seconds_per_step else None,
    }
    if class_totals:
        out["class_ms"] = {k: round(v, 3)
                           for k, v in sorted(class_totals.items())}
        mxu_ms = class_totals.get("mxu", 0.0)
        if mxu_ms:
            out["mxu_eff_during_matmul"] = flops / (mxu_ms / 1e3) / peak
    return out
