"""Small shared utilities (flag parsing, etc.)."""
