"""Debug-dump helpers — the reference's ``print_tensor`` (cuda_helper.h:67-84)
and the ``PRINT_INTERMEDIATE_RESULT`` switch (nmt/rnn.h:25, used at
nmt/rnn.cu:640-647 to dump per-step gradients).

TPU-native design: tensors live sharded on device inside a jitted program, so
the dump is a ``jax.debug.print`` — a host callback that works under jit,
pjit, scan and across shardings (values are gathered for printing).  It
prints shape plus summary stats rather than raw elements: at framework
scale the statistics are the checkable signature of a tensor, and the full
gather of a sharded activation would be the debug tool destroying the
evidence.  Set ``FFConfig.print_intermediates`` (CLI
``--print-intermediates``) to dump every op output.
"""

from __future__ import annotations


def print_tensor(tag: str, x) -> None:
    """Print shape + summary statistics of ``x`` from inside (or outside)
    a jitted computation."""
    import jax
    import jax.numpy as jnp

    xf = x.astype("float32")
    jax.debug.print(
        "{tag}: shape={shape} dtype={dtype} "
        "mean={m:.6f} std={s:.6f} absmax={a:.6f}",
        tag=tag, shape=str(tuple(x.shape)), dtype=str(x.dtype),
        m=jnp.mean(xf), s=jnp.std(xf), a=jnp.max(jnp.abs(xf)))
