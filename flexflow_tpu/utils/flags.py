"""Shared hand-rolled flag-loop mechanics for the reference-parity parsers
(cnn.cc:539-582 / nmt/nmt.cc:235-267 style: positional scan, unknown flags
ignored).  One place for the take-a-value and error behavior used by
FFConfig.from_args, apps.nmt.parse_args, and apps.search.parse_args."""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple


def flag_stream(argv: Sequence[str]) -> Iterator[Tuple[str, "callable"]]:
    """Yield (flag, take) pairs; ``take()`` consumes and returns the next
    argument as the flag's value, raising ValueError at end-of-args.  Call
    ``take`` at most once, before advancing the iterator."""
    args = list(argv)
    i = 0
    while i < len(args):
        a = args[i]
        consumed = [False]

        def take(a=a, consumed=consumed) -> str:
            nonlocal i
            assert not consumed[0], f"take() called twice for {a!r}"
            consumed[0] = True
            i += 1
            if i >= len(args):
                raise ValueError(f"flag {a!r} expects a value")
            return args[i]

        yield a, take
        i += 1
