"""Bounded retry with exponential backoff and DETERMINISTIC jitter.

The fault-tolerance layer (robustness round) wraps the I/O seams of the
data subsystem — HDF5 chunk reads (data/hdf5.py) and ImageNet file decode
(data/imagenet.py) — so one transient read error no longer kills a
multi-hour run.  Two properties the tests pin:

  * **bounded**: a :class:`RetryPolicy` caps total attempts; the LAST
    failure re-raises unchanged (callers decide between skip / abort);
  * **deterministic**: the jitter fraction is derived from
    ``crc32(seed, attempt)`` — not ``random`` — so two runs of the same
    failing schedule back off identically and the fault-injection
    harness (utils/faultinject.py) replays bit-equal timelines.

Only exception types in ``retry_on`` are retried (default ``OSError`` —
the transient-I/O family, including the harness's ``InjectedIOError``);
anything else propagates immediately as a genuine bug.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Callable, Optional, Tuple, Type


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule: attempt ``n`` (1-based count of FAILURES so far)
    waits ``min(base_delay * multiplier**(n-1), max_delay)`` scaled by a
    deterministic jitter factor in ``[1 - jitter, 1]``."""

    attempts: int = 4          # total tries (1 initial + attempts-1 retries)
    base_delay: float = 0.05
    max_delay: float = 1.0
    multiplier: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def delay(self, failures: int) -> float:
        d = min(self.base_delay * self.multiplier ** max(failures - 1, 0),
                self.max_delay)
        if self.jitter <= 0:
            return d
        frac = zlib.crc32(f"{self.seed}:{failures}".encode()) % 1000 / 1000.0
        return d * (1.0 - self.jitter * frac)


def call_with_retry(fn: Callable, policy: Optional[RetryPolicy] = None,
                    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
                    on_retry: Optional[Callable] = None,
                    on_recover: Optional[Callable] = None,
                    sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` under ``policy``.  ``on_retry(exc, failures, delay)``
    fires before each backoff sleep; ``on_recover(failures)`` fires when a
    call succeeds AFTER at least one failure (the data sources emit their
    ``recovery`` obs record there).  The final failure re-raises the
    original exception."""
    policy = policy or RetryPolicy()
    failures = 0
    while True:
        try:
            out = fn()
        except retry_on as e:
            failures += 1
            if failures >= policy.attempts:
                raise
            d = policy.delay(failures)
            if on_retry is not None:
                on_retry(e, failures, d)
            sleep(d)
            continue
        if failures and on_recover is not None:
            on_recover(failures)
        return out
