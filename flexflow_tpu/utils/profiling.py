"""Profiling / tracing subsystem.

Reference parity (SURVEY.md §5 "Tracing / profiling"):

  * per-op flag-gated timing — the reference brackets each leaf task with
    cudaEvents when ``profiling`` is set and prints per-op ms
    (conv_2d.cu:514-545, linear.cu:380-385, nmt/lstm.cu:219).  Under XLA the
    whole training step is ONE fused program, so per-op times inside it are
    not observable from the host; the TPU-native equivalent is
    :class:`OpProfiler`, which times each op's real jitted fwd+bwd at its
    shard-local shapes (same harness the simulator's MeasuredCostModel uses,
    itself the analog of scripts/cnn.h measure_*_time) and prints a table.
  * wall-clock via execution fence + Realm clock (cnn.cc:113-128) —
    ``FFModel.fit``'s timed loop.
  * Legion ``-lg:prof`` task-level tracing — :func:`trace`, a context
    manager around ``jax.profiler`` producing TensorBoard/XProf traces of
    the actual compiled program (the authoritative per-fusion timeline).

TPU-native addition: :func:`compiled_cost` pulls FLOPs / bytes-accessed
from XLA's cost analysis of the *compiled* step, giving a roofline summary
that no isolated per-op timing can (XLA fuses across ops).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Optional


class StepClock:
    """Host-side per-step wall clock for the obs subsystem (model.fit).

    ``tick()`` appends one ``perf_counter`` delta per step — no device
    syncs, so the timed loop's async dispatch is unperturbed; under jit
    donation the host timestamps track device step time after the first
    couple of iterations (step N+1's dispatch blocks on N's buffers).
    The deltas are read AFTER the loop, when per-step records are
    written."""

    def __init__(self):
        import time as _time

        self._clock = _time.perf_counter
        self._last = self._clock()
        self.deltas: List[float] = []

    def reset(self):
        self._last = self._clock()

    def tick(self) -> None:
        now = self._clock()
        self.deltas.append(now - self._last)
        self._last = now


@contextlib.contextmanager
def trace(logdir: str):
    """XProf/TensorBoard trace of everything executed inside the block
    (Legion -lg:prof analog).  View with tensorboard --logdir=<dir>."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def time_op_shard(op, pc, dtype: str = "float32",
                  repeats: int = 3) -> Optional[float]:
    """Wall seconds of ONE shard's jitted fwd+grad for ``op`` under
    ``pc`` (shard-local shapes via ``local_clone``), min over
    ``repeats`` timed calls after a warm-up — the measured side of the
    obs ``op_time`` records (fit's sampled op-timing mode) and of the
    drift-attribution join in obs/trace.py.

    Deliberately simpler than MeasuredCostModel._measure: a single
    host-synced call per repeat, no chained-scan differencing — the
    sampler runs in-process on the training host where dispatch overhead
    is small, and attribution needs relative per-op scale, not
    protocol-v3 absolute precision.  None when the shard cannot be
    realized locally (caller falls back to the analytic roofline)."""
    import time

    import jax
    import jax.numpy as jnp

    local = op.local_clone(pc)
    if local is None:
        return None
    try:
        params = local.init_params(jax.random.PRNGKey(0))
        xs = [jnp.zeros(t.shape, "int32") if t.dtype == "int32"
              else jnp.ones(t.shape, dtype) for t in local.inputs]
        state = local.init_state()

        def loss_of(p, xs_):
            res, _ = local.forward(p, state, xs_, True)
            res = res[0] if isinstance(res, tuple) else res
            return (res.astype("float32") ** 2).sum()

        if params:
            fn = jax.jit(lambda p, xs_: jax.grad(loss_of)(p, xs_))
            args = (params, xs)
        elif op.inputs and op.inputs[0].dtype != "int32":
            fn = jax.jit(lambda xs_: jax.grad(
                lambda x: loss_of({}, x))(list(xs_)))
            args = (xs,)
        else:
            fn = jax.jit(lambda xs_: loss_of({}, xs_))
            args = (xs,)
        jax.block_until_ready(fn(*args))  # compile + warm
        best = None
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best if best and best > 0 else None
    except Exception:
        return None


@dataclasses.dataclass
class OpProfile:
    name: str
    kind: str
    grid: tuple
    out_shape: tuple
    ms: float            # measured fwd+bwd wall-ms of one shard
    gflops: float        # modeled fwd+bwd GFLOPs of one shard
    measured: bool

    @property
    def tflops_per_sec(self) -> float:
        return (self.gflops / 1e3) / (self.ms / 1e3) if self.ms > 0 else 0.0


class OpProfiler:
    """Per-op timing table for a model (the ``profiling`` flag's output).

    Each op's fwd+grad is jitted in isolation at the shapes ONE device sees
    under the op's ParallelConfig and timed on the local chip.  Isolated
    timings over-count vs the fused step (XLA fuses elementwise ops into
    neighbors), so the table is a per-op *attribution* guide, not an exact
    decomposition — the exact timeline is :func:`trace`.
    """

    def __init__(self, model, repeats: int = 3):
        self.model = model
        self.repeats = repeats

    def profile(self) -> List[OpProfile]:
        from flexflow_tpu.sim.cost_model import (AnalyticCostModel,
                                                 MeasuredCostModel,
                                                 shard_flops)

        measured = MeasuredCostModel(repeats=self.repeats)
        analytic = AnalyticCostModel()
        rows = []
        for op in self.model.layers:
            t = measured._measure(op, op.pc)
            was_measured = t is not None
            if t is None:
                t = analytic.op_cost(op, op.pc)
            gflops = shard_flops(op, op.pc) / 1e9
            rows.append(OpProfile(
                name=op.name, kind=type(op).__name__, grid=op.pc.dims,
                out_shape=op.output.shape, ms=t * 1e3, gflops=gflops,
                measured=was_measured))
        return rows

    def report(self, rows: Optional[List[OpProfile]] = None) -> str:
        rows = rows if rows is not None else self.profile()
        total = sum(r.ms for r in rows)
        lines = [
            f"{'op':<18s} {'kind':<12s} {'grid':<14s} "
            f"{'shard ms':>9s} {'GFLOP':>8s} {'TFLOP/s':>8s} {'%':>5s}",
        ]
        for r in rows:
            pct = 100.0 * r.ms / total if total else 0.0
            mark = "" if r.measured else "~"
            lines.append(
                f"{r.name:<18s} {r.kind:<12s} {str(r.grid):<14s} "
                f"{mark}{r.ms:>8.3f} {r.gflops:>8.2f} "
                f"{r.tflops_per_sec:>8.2f} {pct:>4.1f}%")
        lines.append(f"{'total (isolated, one shard)':<46s} {total:>8.3f} ms"
                     "   [~ = analytic estimate]")
        return "\n".join(lines)


def normalize_cost_analysis(compiled) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` as one flat dict (older jax returns one
    dict per device program)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return ca or {}


def compiled_cost(fn, *args) -> Dict[str, float]:
    """FLOPs / bytes for the COMPILED program (XLA cost analysis) — what the
    chip will actually run after fusion, per step."""
    import jax

    compiled = jax.jit(fn).lower(*args).compile()
    ca = normalize_cost_analysis(compiled)
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }


def compiled_roofline(compiled, seconds_per_step: Optional[float] = None,
                      perf=None, n_devices: int = 1) -> Dict[str, float]:
    """Roofline summary from an already-compiled executable (no extra
    compile): post-fusion FLOPs/bytes plus, when a measured step time is
    supplied, achieved TFLOP/s, HBM GB/s and MXU utilization.

    ``cost_analysis()`` FLOPs are GLOBAL (pre-partitioning) under SPMD, so
    pass ``n_devices`` to compare against the whole machine's peak."""
    from flexflow_tpu.sim.cost_model import TpuChipPerf

    perf = perf or TpuChipPerf()
    peak = perf.peak_flops * max(n_devices, 1)
    hbm = perf.hbm_bandwidth * max(n_devices, 1)
    ca = normalize_cost_analysis(compiled)
    cost = {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    out = dict(cost)
    out["min_step_seconds_at_peak"] = cost["flops"] / peak if peak else 0.0
    if seconds_per_step and seconds_per_step > 0:
        out["achieved_tflops"] = cost["flops"] / seconds_per_step / 1e12
        out["achieved_hbm_gbps"] = (
            cost["bytes_accessed"] / seconds_per_step / 1e9)
        out["mxu_utilization"] = cost["flops"] / seconds_per_step / peak
        out["hbm_utilization"] = (
            cost["bytes_accessed"] / seconds_per_step / hbm)
    return out


def step_roofline(fn, *args, seconds_per_step: Optional[float] = None,
                  perf=None, n_devices: int = 1) -> Dict[str, float]:
    """Roofline summary of a train step (compiles ``fn``); see
    :func:`compiled_roofline`."""
    import jax

    return compiled_roofline(jax.jit(fn).lower(*args).compile(),
                             seconds_per_step, perf, n_devices)
