"""Checkpoint / resume subsystem.

The reference has NO weight checkpointing (SURVEY.md §5: only the *strategy*
is serializable, strategy.cc:62-86) — any failure restarts training from
scratch.  A complete framework needs durable training state, so this module
adds it as a first-class subsystem:

  * a checkpoint = (iteration, params, state, opt_state) + the model's
    Strategy, so a resumed run executes under the same per-layer
    parallelization;
  * atomic directory commit (write to ``<dir>/tmp.<step>``, fsync, rename to
    ``<dir>/step_<N>``) — a killed run never leaves a half-written
    checkpoint that resume would trust;
  * restore is **sharding-aware**: when given the model, every param lands
    directly on its op's NamedSharding (same placement as ``FFModel.init``),
    so resume does not funnel large trees through one device.

Format: one ``arrays.npz`` of flattened ``a/b/c``-keyed leaves per tree,
plus ``meta.json`` recording each leaf's dtype.  Plain numpy keeps the
format dependency-free and inspectable; extension dtypes (bfloat16, fp8)
round-trip by re-viewing the raw bytes as the recorded ml_dtypes dtype on
load (np.savez alone degrades them to void).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import numpy as np

_SEP = "/"


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, Any]:
    flat = {}
    for k, v in tree.items():
        if _SEP in k:
            raise ValueError(f"checkpoint key {k!r} may not contain {_SEP!r}")
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, path + _SEP))
        else:
            flat[path] = v
    return flat


def _unflatten(flat: Dict[str, Any]) -> Dict:
    tree: Dict = {}
    for path, v in flat.items():
        keys = path.split(_SEP)
        node = tree
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = v
    return tree


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:08d}")


def _list_steps(ckpt_dir: str) -> list:
    """Sorted committed checkpoint steps in ``ckpt_dir``."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                continue
    return sorted(steps)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Highest completed checkpoint step in ``ckpt_dir``, or None."""
    steps = _list_steps(ckpt_dir)
    return steps[-1] if steps else None


def save_checkpoint(ckpt_dir: str, step: int, params: Dict, state: Dict,
                    opt_state: Dict, strategy=None, keep: int = 3) -> str:
    """Write checkpoint atomically; prune to the newest ``keep`` steps.
    Returns the committed directory."""
    os.makedirs(ckpt_dir, exist_ok=True)
    tmp = os.path.join(ckpt_dir, f"tmp.{step}")
    final = _step_dir(ckpt_dir, step)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    arrays: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for tree_name, tree in (("params", params), ("state", state or {}),
                            ("opt", opt_state or {})):
        for path, leaf in _flatten(tree, tree_name + _SEP).items():
            a = np.asarray(leaf)
            arrays[path] = a
            dtypes[path] = str(a.dtype)
    np.savez(os.path.join(tmp, "arrays.npz"), **arrays)

    meta = {"step": int(step), "format": 1, "dtypes": dtypes}
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f)
    if strategy is not None and len(strategy):
        strategy.save(os.path.join(tmp, "strategy.json"))

    # durable commit: flush file data, then the tmp dir entry, then rename,
    # then flush the parent so the rename itself is on disk
    for name in os.listdir(tmp):
        fd = os.open(os.path.join(tmp, name), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    for d in (tmp,):
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    # never delete the old committed dir before the new one is in place:
    # move it aside, rename tmp in, then drop the aside copy
    aside = None
    if os.path.exists(final):
        aside = final + ".old"
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
    os.rename(tmp, final)
    fd = os.open(ckpt_dir, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    if aside:
        shutil.rmtree(aside, ignore_errors=True)

    if keep:
        for s in _list_steps(ckpt_dir)[:-keep]:
            shutil.rmtree(_step_dir(ckpt_dir, s), ignore_errors=True)
    return final


def _restore_dtype(arr: np.ndarray, stored: Optional[str]) -> np.ndarray:
    """Re-view raw bytes as the recorded extension dtype (bfloat16/fp8 …)
    when np.load degraded it to void."""
    if stored is None or str(arr.dtype) == stored:
        return arr
    import ml_dtypes

    if hasattr(ml_dtypes, stored):
        return arr.view(np.dtype(getattr(ml_dtypes, stored)))
    return arr.astype(stored)


def restore_checkpoint(ckpt_dir: str, model=None,
                       step: Optional[int] = None
                       ) -> Tuple[int, Dict, Dict, Dict]:
    """Load (step, params, state, opt_state).  With ``model`` given, params
    and opt leaves are placed on the owning op's sharding and state on the
    op's grid, exactly as ``FFModel.init`` would place them."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir!r}")
    d = _step_dir(ckpt_dir, step)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    stored_dtypes = meta.get("dtypes", {})
    with np.load(os.path.join(d, "arrays.npz")) as z:
        flat = {k: _restore_dtype(z[k], stored_dtypes.get(k))
                for k in z.files}

    trees = {"params": {}, "state": {}, "opt": {}}
    for path, arr in flat.items():
        tree_name, rest = path.split(_SEP, 1)
        trees[tree_name][rest] = arr
    params = _unflatten(trees["params"])
    state = _unflatten(trees["state"])
    opt_state = _unflatten(trees["opt"])

    if model is not None:
        import jax

        shardings = {}
        for op in model.layers:
            if op.param_key not in shardings:
                s = op.param_shardings(model.machine)
                if s:
                    shardings[op.param_key] = s

        def place(tree):
            placed = {}
            for key, sub in tree.items():
                ops_shard = shardings.get(key, {})
                placed[key] = {
                    k: jax.device_put(v, ops_shard[k]) if k in ops_shard
                    else jax.device_put(v)
                    for k, v in sub.items()
                }
            return placed

        params = place(params)
        opt_state = place(opt_state)
        state = jax.tree.map(jax.device_put, state)
    return step, params, state, opt_state


def load_strategy(ckpt_dir: str, step: Optional[int] = None):
    """The Strategy a checkpoint was trained under, or None."""
    from flexflow_tpu.strategy import Strategy

    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
    path = os.path.join(_step_dir(ckpt_dir, step), "strategy.json")
    return Strategy.load(path) if os.path.exists(path) else None
