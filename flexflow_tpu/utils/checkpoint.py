"""Checkpoint / resume subsystem.

The reference has NO weight checkpointing (SURVEY.md §5: only the *strategy*
is serializable, strategy.cc:62-86) — any failure restarts training from
scratch.  A complete framework needs durable training state, so this module
adds it as a first-class subsystem:

  * a checkpoint = (iteration, params, state, opt_state) + the model's
    Strategy, so a resumed run executes under the same per-layer
    parallelization;
  * atomic directory commit (write to ``<dir>/tmp.<step>``, fsync, rename to
    ``<dir>/step_<N>``) — a killed run never leaves a half-written
    checkpoint that resume would trust; stale ``tmp.<step>`` /
    ``step_*.old`` directories a crash mid-save left behind are swept on
    the next save/restore instead of accumulating forever;
  * **verified integrity** (robustness round): ``meta.json`` records a
    SHA-256 digest per payload file at save; :func:`verify_checkpoint`
    re-checks them, and restore (without an explicit step) CASCADES
    latest -> older past truncated/missing/corrupt steps, emitting a
    ``ckpt_fallback`` obs record — a flipped bit in ``arrays.npz`` costs
    one checkpoint interval, not the run;
  * a **finiteness gate**: ``save_checkpoint`` refuses (by default) to
    commit non-finite float leaves over good on-disk state
    (:class:`NonFiniteCheckpointError`), and pruning never deletes the
    newest step that still verifies clean — so a diverged run cannot
    rotate every healthy checkpoint out of existence;
  * restore is **sharding-aware**: when given the model, every param lands
    directly on its op's NamedSharding (same placement as ``FFModel.init``),
    so resume does not funnel large trees through one device.

Format: one ``arrays.npz`` of flattened ``a/b/c``-keyed leaves per tree,
plus ``meta.json`` recording each leaf's dtype and the file digests.
Plain numpy keeps the format dependency-free and inspectable; extension
dtypes (bfloat16, fp8) round-trip by re-viewing the raw bytes as the
recorded ml_dtypes dtype on load (np.savez alone degrades them to void).
Pre-digest checkpoints load unchanged (verification reports them as
unverifiable rather than corrupt).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import warnings
from typing import Any, Dict, Optional, Tuple

import numpy as np

_SEP = "/"


class CheckpointError(RuntimeError):
    """Base of the checkpoint subsystem's own failures."""


class CheckpointCorruptError(CheckpointError):
    """A requested checkpoint failed integrity verification (or every
    candidate did, when cascading)."""


class NonFiniteCheckpointError(CheckpointError):
    """``save_checkpoint`` refused to commit non-finite float state over
    good on-disk checkpoints (pass ``require_finite=False`` to force)."""


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, Any]:
    flat = {}
    for k, v in tree.items():
        if _SEP in k:
            raise ValueError(f"checkpoint key {k!r} may not contain {_SEP!r}")
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, path + _SEP))
        else:
            flat[path] = v
    return flat


def _unflatten(flat: Dict[str, Any]) -> Dict:
    tree: Dict = {}
    for path, v in flat.items():
        keys = path.split(_SEP)
        node = tree
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = v
    return tree


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:08d}")


def _list_steps(ckpt_dir: str) -> list:
    """Sorted committed checkpoint steps in ``ckpt_dir``."""
    if not os.path.isdir(ckpt_dir):
        return []
    steps = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("step_") and not name.endswith(".old"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                continue
    return sorted(steps)


def _sweep_stale(ckpt_dir: str) -> None:
    """Remove leftovers of a crash mid-save: uncommitted ``tmp.<step>``
    staging dirs and ``step_*.old`` aside copies.  They were previously
    never cleaned up and accumulated forever."""
    if not os.path.isdir(ckpt_dir):
        return
    for name in os.listdir(ckpt_dir):
        if name.startswith("tmp.") or (name.startswith("step_")
                                       and name.endswith(".old")):
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Highest completed checkpoint step in ``ckpt_dir``, or None."""
    steps = _list_steps(ckpt_dir)
    return steps[-1] if steps else None


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _nonfinite_leaves(arrays: Dict[str, np.ndarray]) -> list:
    """Paths of float leaves holding NaN/Inf (int/bool leaves skipped;
    extension floats like bfloat16 are checked through their float32
    view when the ufunc lacks a native loop)."""
    bad = []
    for path, a in arrays.items():
        if a.dtype.kind in "iub":
            continue
        try:
            ok = bool(np.isfinite(a).all())
        except TypeError:
            ok = bool(np.isfinite(np.asarray(a, np.float32)).all())
        if not ok:
            bad.append(path)
    return bad


def verify_checkpoint(ckpt_dir: str, step: int) -> Tuple[bool, str]:
    """Integrity check of one committed step: directory + ``meta.json``
    present and parseable, every payload file present with a matching
    SHA-256 digest.  Returns ``(ok, reason)``; pre-digest checkpoints
    pass as ``"unverified (no digests)"`` for format compatibility."""
    d = _step_dir(ckpt_dir, step)
    if not os.path.isdir(d):
        return False, "missing directory"
    try:
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"meta.json unreadable: {e}"
    if int(meta.get("step", -1)) != int(step):
        return False, (f"meta.json names step {meta.get('step')!r}, "
                       f"directory says {step}")
    if not os.path.exists(os.path.join(d, "arrays.npz")):
        return False, "arrays.npz missing"
    digests = meta.get("digests")
    if not digests:
        return True, "unverified (no digests; pre-digest format)"
    for name, want in digests.items():
        p = os.path.join(d, name)
        if not os.path.exists(p):
            return False, f"{name} missing"
        got = _file_sha256(p)
        if got != want:
            return False, f"{name} digest mismatch ({got[:12]} != {want[:12]})"
    return True, "ok"


def save_checkpoint(ckpt_dir: str, step: int, params: Dict, state: Dict,
                    opt_state: Dict, strategy=None, keep: int = 3,
                    require_finite: bool = True) -> str:
    """Write checkpoint atomically; prune to the newest ``keep`` steps
    (never deleting the newest step that still VERIFIES clean, so a
    corrupted latest cannot rotate the last good state away).  With
    ``require_finite`` (the default) non-finite float leaves abort the
    save BEFORE anything touches disk.  Returns the committed
    directory."""
    os.makedirs(ckpt_dir, exist_ok=True)
    _sweep_stale(ckpt_dir)
    tmp = os.path.join(ckpt_dir, f"tmp.{step}")
    final = _step_dir(ckpt_dir, step)

    arrays: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}
    for tree_name, tree in (("params", params), ("state", state or {}),
                            ("opt", opt_state or {})):
        for path, leaf in _flatten(tree, tree_name + _SEP).items():
            a = np.asarray(leaf)
            arrays[path] = a
            dtypes[path] = str(a.dtype)
    if require_finite:
        bad = _nonfinite_leaves(arrays)
        if bad:
            raise NonFiniteCheckpointError(
                f"refusing to checkpoint non-finite state at step {step}: "
                f"{len(bad)} leaves, e.g. {bad[:3]} (pass "
                f"require_finite=False to force)")

    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
    if strategy is not None and len(strategy):
        strategy.save(os.path.join(tmp, "strategy.json"))
    # per-file content digests, recorded in meta.json so restore can
    # distinguish a torn/bit-flipped checkpoint from a good one
    digests = {name: _file_sha256(os.path.join(tmp, name))
               for name in sorted(os.listdir(tmp))}
    meta = {"step": int(step), "format": 2, "dtypes": dtypes,
            "digests": digests}
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f)

    # durable commit: flush file data, then the tmp dir entry, then rename,
    # then flush the parent so the rename itself is on disk
    for name in os.listdir(tmp):
        fd = os.open(os.path.join(tmp, name), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    for d in (tmp,):
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    # never delete the old committed dir before the new one is in place:
    # move it aside, rename tmp in, then drop the aside copy
    aside = None
    if os.path.exists(final):
        aside = final + ".old"
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
    os.rename(tmp, final)
    fd = os.open(ckpt_dir, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    if aside:
        shutil.rmtree(aside, ignore_errors=True)

    # deterministic fault injection (utils/faultinject.py): damage the
    # COMMITTED copy — a torn write / bit flip the digests must catch
    from flexflow_tpu.utils import faultinject

    inj = faultinject.get()
    if inj.enabled:
        ap = os.path.join(final, "arrays.npz")
        if inj.fire("ckpt_truncate", site=final):
            with open(ap, "r+b") as f:
                f.truncate(max(os.path.getsize(ap) // 2, 1))
        if inj.fire("ckpt_corrupt", site=final):
            with open(ap, "r+b") as f:
                f.seek(os.path.getsize(ap) // 2)
                b = f.read(1)
                f.seek(-1, os.SEEK_CUR)
                f.write(bytes([b[0] ^ 0xFF]))

    if keep:
        steps = _list_steps(ckpt_dir)
        protect = set(steps[-keep:])
        for s in reversed(steps):
            ok, _ = verify_checkpoint(ckpt_dir, s)
            if ok:
                protect.add(s)  # the newest verified-good step survives
                break
        for s in steps:
            if s not in protect:
                shutil.rmtree(_step_dir(ckpt_dir, s), ignore_errors=True)
    return final


def _restore_dtype(arr: np.ndarray, stored: Optional[str]) -> np.ndarray:
    """Re-view raw bytes as the recorded extension dtype (bfloat16/fp8 …)
    when np.load degraded it to void."""
    if stored is None or str(arr.dtype) == stored:
        return arr
    import ml_dtypes

    if hasattr(ml_dtypes, stored):
        return arr.view(np.dtype(getattr(ml_dtypes, stored)))
    return arr.astype(stored)


def _load_step(ckpt_dir: str, step: int, model=None
               ) -> Tuple[int, Dict, Dict, Dict]:
    """Load one committed step (no verification, no cascade)."""
    d = _step_dir(ckpt_dir, step)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    stored_dtypes = meta.get("dtypes", {})
    with np.load(os.path.join(d, "arrays.npz")) as z:
        flat = {k: _restore_dtype(z[k], stored_dtypes.get(k))
                for k in z.files}

    trees = {"params": {}, "state": {}, "opt": {}}
    for path, arr in flat.items():
        tree_name, rest = path.split(_SEP, 1)
        trees[tree_name][rest] = arr
    params = _unflatten(trees["params"])
    state = _unflatten(trees["state"])
    opt_state = _unflatten(trees["opt"])

    if model is not None:
        import jax

        shardings = {}
        for op in model.layers:
            if op.param_key not in shardings:
                s = op.param_shardings(model.machine)
                if s:
                    shardings[op.param_key] = s

        def put(v, shard):
            if shard is None:
                return jax.device_put(v)
            if getattr(shard, "is_fully_addressable", True):
                return jax.device_put(v, shard)
            # multi-host restore (elastic_rejoin): device_put cannot
            # scatter a host array onto devices owned by other
            # processes; build the global array from each process's
            # local shards instead — every host loaded the same file
            arr = np.asarray(v)
            return jax.make_array_from_callback(
                arr.shape, shard, lambda idx: arr[idx])

        def place(tree):
            placed = {}
            for key, sub in tree.items():
                ops_shard = shardings.get(key, {})
                # mixed-precision master leaves (<leaf>__master in the
                # opt tree, see model._MASTER_SUFFIX) take the base
                # param leaf's sharding — shardings are dtype-agnostic
                placed[key] = {
                    k: put(v, ops_shard.get(
                        k, ops_shard.get(k[:-len("__master")]
                                         if k.endswith("__master")
                                         else k)))
                    for k, v in sub.items()}
            return placed

        params = place(params)
        opt_state = place(opt_state)
        state = jax.tree.map(jax.device_put, state)
    return step, params, state, opt_state


def restore_checkpoint(ckpt_dir: str, model=None,
                       step: Optional[int] = None, verify: bool = True,
                       olog=None) -> Tuple[int, Dict, Dict, Dict]:
    """Load (step, params, state, opt_state).  With ``model`` given, params
    and opt leaves are placed on the owning op's sharding and state on the
    op's grid, exactly as ``FFModel.init`` would place them.

    Without an explicit ``step`` the restore CASCADES: the latest step is
    verified (digests, presence, parseability) and actually loaded; on any
    failure the next-older step is tried, a ``ckpt_fallback`` obs record
    is emitted on ``olog``, and only when EVERY committed step fails does
    this raise :class:`CheckpointCorruptError`.  An explicit ``step`` is
    verified but never cascaded (the caller asked for that one)."""
    from flexflow_tpu import obs

    olog = olog if olog is not None else obs.NULL
    _sweep_stale(ckpt_dir)
    if step is not None:
        if verify:
            ok, why = verify_checkpoint(ckpt_dir, step)
            if not ok:
                raise CheckpointCorruptError(
                    f"checkpoint step {step} under {ckpt_dir!r} failed "
                    f"verification: {why}")
        return _load_step(ckpt_dir, step, model)
    steps = _list_steps(ckpt_dir)
    if not steps:
        raise FileNotFoundError(f"no checkpoint under {ckpt_dir!r}")
    newest = steps[-1]
    failures = []
    for s in reversed(steps):
        if verify:
            ok, why = verify_checkpoint(ckpt_dir, s)
            if not ok:
                failures.append((s, why))
                continue
        try:
            out = _load_step(ckpt_dir, s, model)
        except Exception as e:  # torn npz, bad json, ... -> next candidate
            failures.append((s, f"load failed: {e}"))
            continue
        if s != newest:
            olog.event("ckpt_fallback", dir=ckpt_dir, from_step=newest,
                       to_step=s,
                       skipped=[{"step": fs, "reason": fw}
                                for fs, fw in failures])
            warnings.warn(
                f"checkpoint fallback: step {newest} -> {s} under "
                f"{ckpt_dir!r} ({'; '.join(f'step {fs}: {fw}' for fs, fw in failures)})",
                RuntimeWarning)
        return out
    raise CheckpointCorruptError(
        f"every checkpoint under {ckpt_dir!r} failed verification/load: "
        + "; ".join(f"step {fs}: {fw}" for fs, fw in failures))


def snapshot_tree(tree: Dict) -> Dict:
    """Host-side deep copy of a (possibly device-resident) checkpoint
    tree: every leaf materialized as a plain numpy array.  This is the
    async writer's consistency point — the copy happens at the caller's
    host-sync boundary, so the background serialization can never
    observe a leaf the NEXT training step has already donated/mutated."""
    out: Dict = {}
    for k, v in (tree or {}).items():
        # np.array(copy=True): np.asarray of a HOST array is a view, and
        # a view is exactly the torn-snapshot hazard this exists to close
        out[k] = snapshot_tree(v) if isinstance(v, dict) \
            else np.array(v, copy=True)
    return out


class AsyncCheckpointWriter:
    """Background checkpoint committer: serialization, digest computation
    and the fsync'd atomic directory commit run on ONE worker thread, off
    the training step's critical path.

    Contract (robustness round, elastic tentpole):

      * ``submit()`` snapshots the device trees to host numpy at the
        call site (the only part that must happen at the sync boundary —
        the next step donates those buffers) and enqueues the write; at
        most ONE save is in flight, so a submit that arrives while the
        previous write is still running first waits for it (this only
        costs anything when a write is slower than a checkpoint
        interval);
      * the committed bytes are BIT-IDENTICAL to a synchronous
        :func:`save_checkpoint` of the same state — the worker calls the
        exact same function on the snapshot;
      * a worker-side :class:`NonFiniteCheckpointError` (or any other
        save failure) never kills the run: it is counted in ``faults``,
        logged, and emitted as a ``fault`` obs record, exactly like the
        synchronous path's handling;
      * ``wait()`` blocks until the queue is drained — fit() calls it
        before a rollback restore (the restore must see the newest
        commit) and at the final save; ``close()`` waits and joins.

    ``inflight`` (0 or 1) is exported as the ``ff_ckpt_async_inflight``
    gauge.  Every completed write emits a ``ckpt_async`` obs record with
    the submit->commit latency so the overlap is auditable."""

    def __init__(self, olog=None, log=None, keep: int = 3,
                 require_finite: bool = True):
        import queue
        import threading

        from flexflow_tpu import obs

        self.olog = olog if olog is not None else obs.NULL
        self.log = log or (lambda *a: None)
        self.keep = keep
        self.require_finite = require_finite
        self.inflight = 0
        self.saves = 0
        self.faults = 0
        self.last_step: Optional[int] = None
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._worker, name="ff-ckpt-async", daemon=True)
        self._thread.start()

    # -- producer side (the training loop) ---------------------------

    def submit(self, ckpt_dir: str, step: int, params, state, opt_state,
               strategy=None) -> None:
        """Snapshot + enqueue one checkpoint write.  Blocks only if the
        PREVIOUS write has not finished (one in flight, ever)."""
        self.wait()
        import time as _time

        job = {
            "dir": ckpt_dir, "step": int(step),
            "params": snapshot_tree(params),
            "state": snapshot_tree(state),
            "opt": snapshot_tree(opt_state),
            "strategy": strategy, "t_submit": _time.perf_counter(),
        }
        with self._lock:
            self.inflight += 1
        self._idle.clear()
        self._q.put(job)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until no write is in flight.  True when drained."""
        return self._idle.wait(timeout=timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain, then stop and join the worker.  Idempotent."""
        self.wait(timeout=timeout)
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=timeout or 10.0)

    # -- worker side --------------------------------------------------

    def _worker(self):
        import time as _time

        while True:
            job = self._q.get()
            if job is None:
                self._idle.set()
                return
            try:
                try:
                    save_checkpoint(job["dir"], job["step"], job["params"],
                                    job["state"], job["opt"],
                                    job["strategy"], keep=self.keep,
                                    require_finite=self.require_finite)
                    dt = _time.perf_counter() - job["t_submit"]
                    with self._lock:
                        self.saves += 1
                        self.last_step = job["step"]
                    self.olog.event("checkpoint_save", step=job["step"],
                                    seconds=dt, dir=job["dir"],
                                    mode="async")
                    self.olog.event("ckpt_async", step=job["step"],
                                    commit_s=dt, saves=self.saves,
                                    faults=self.faults)
                except NonFiniteCheckpointError as e:
                    with self._lock:
                        self.faults += 1
                    self.olog.event("fault", source="checkpoint",
                                    fault="nonfinite_state",
                                    step=job["step"], error=str(e))
                    self.log(f"warning: skipped async checkpoint at "
                             f"iteration {job['step']}: {e}")
                except Exception as e:  # never kill the run from here
                    with self._lock:
                        self.faults += 1
                    self.olog.event("fault", source="checkpoint",
                                    fault="async_save_failed",
                                    step=job["step"], error=str(e))
                    self.log(f"warning: async checkpoint at iteration "
                             f"{job['step']} failed: {e}")
            finally:
                with self._lock:
                    self.inflight -= 1
                    if self.inflight == 0:
                        self._idle.set()


def load_strategy(ckpt_dir: str, step: Optional[int] = None):
    """The Strategy a checkpoint was trained under, or None."""
    from flexflow_tpu.strategy import Strategy

    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
    path = os.path.join(_step_dir(ckpt_dir, step), "strategy.json")
    return Strategy.load(path) if os.path.exists(path) else None
