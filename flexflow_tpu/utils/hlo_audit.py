"""Compiled-HLO collective audit: executor-grounded communication
accounting for the strategy search (round 5, VERDICT r4 #1).

The reference's simulator was grounded on both axes: per-op times were
measured on the device (ref:scripts/cnn.h:204-447) and its comm model was
the same rectangle-intersection physics its executor (Legion) performed
(ref:scripts/simulator.cc:886-959).  This repo measures op costs
(protocol v3), but its comm model prices what GSPMD *should* lower — and
round 4's audit proved GSPMD sometimes lowers something else entirely
(the transformer_2x4 falsification: simulated 1.64x win, compiled program
moved ~8x MORE cross-tier bytes than DP).  This module makes the compiled
program itself the arbiter: lower the candidate plan on a virtual mesh,
parse the optimized HLO, and count the collective bytes that cross the
ICI-group (DCN) boundary.

Two entry points:

* :func:`audit_in_process` — requires ``len(jax.devices()) >= devices``
  (tests run it on the virtual CPU mesh via conftest's machine8).
* :func:`audit_subprocess` — spawns a fresh CPU process with
  ``--xla_force_host_platform_device_count=<devices>`` so the audit runs
  from ANY parent environment (including the single-chip TPU tunnel the
  offline search runs under).  This is what ``apps/search.py``'s accept
  path calls.

The byte counter itself (:func:`collective_bytes`) is the round-4 test
mechanism (tests/test_two_tier.py) promoted to library code; the static
verifier (flexflow_tpu/verify/, round 11) consumes the structured form
(:func:`collective_summary`) and prices it with the simulator's
calibrated ring formulas (:func:`sim.collectives.priced_collectives`),
upgrading :func:`audit_consistent`'s byte heuristic to predicted seconds
(:func:`audit_consistent_time`).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from typing import List, Optional, Tuple, Union

import numpy as np

_DT = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
       "u8": 1, "pred": 1, "f64": 8, "s64": 8}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute", "all-reduce-start", "all-gather-start",
                "reduce-scatter-start", "all-to-all-start",
                "collective-permute-start")

# op-position sighting of ANY collective mnemonic (incl. the -done halves
# of async pairs, which carry no replica_groups and must not be counted
# again) — the strict-parse net under the main shape-anchored regex
_SIGHT = re.compile(
    r"(?<=[\s(])(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\(")


class AuditParseError(ValueError):
    """A line that names a collective was not parsed by the counter —
    counting gaps fail loudly instead of silently under-counting
    (round 11 corpus hardening)."""


def parse_collectives(hlo: str, group_size: int,
                      devices: Optional[int] = None) -> List[dict]:
    """Structured records for every collective in optimized HLO text::

        {"op": str,          # HLO mnemonic (incl. a -start suffix)
         "bytes": float,     # buffer moved (see volume convention below)
         "cross": bool,      # any group/pair spans ICI groups
         "groups": [[ids]],  # replica groups (or permute pairs) as
                             #  device-id lists; [] when unknowable
         "async": bool}      # -start half of an async pair

    Volume convention: a sync collective's shape IS the moved buffer and
    tuple shapes (variadic operands) sum; an async ``-start`` tuple is
    ``(operands..., results..., scratch)`` describing ONE transfer, so it
    contributes its LARGEST element (the in-flight buffer), not the sum —
    the round-11 corpus showed the old sum double-counted every async
    pair.  ``-done`` halves carry no groups and are skipped (their
    ``-start`` already counted).  A collective mnemonic on a line the
    shape-anchored regex cannot parse raises :class:`AuditParseError`
    (except an unterminated final line, which parses fine).  With no
    ``replica_groups`` in the line, the group is all ``devices`` when
    given (flattened single-group form), else unknown (``groups=[]``,
    cross=False).
    """
    out: List[dict] = []
    consumed = set()
    for m in re.finditer(
            r"= ?((?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)) ([a-z\-]+)\(",
            hlo):
        shape_s, op = m.group(1), m.group(2)
        if op not in _COLLECTIVES:
            continue
        # a collective on an unterminated final line must not raise
        bol = hlo.rfind("\n", 0, m.start()) + 1
        eol = hlo.find("\n", m.start())
        consumed.add(bol)
        line = hlo[m.start():eol if eol != -1 else len(hlo)]
        elems = []
        for sm in re.finditer(r"([a-z0-9]+)\[([0-9,]*)\]", shape_s):
            dt, dims = sm.group(1), sm.group(2)
            if dt not in _DT:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            elems.append(n * _DT[dt])
        is_async = op.endswith("-start")
        nbytes = (max(elems) if is_async else sum(elems)) if elems else 0
        groups: List[List[int]] = []
        is_cross = False
        rg = re.search(r"replica_groups=\{(\{[0-9,\}\{]*\})\}", line)
        if rg:
            for grp in re.findall(r"\{([0-9,]+)\}", rg.group(1)):
                ids = [int(x) for x in grp.split(",")]
                groups.append(ids)
                if len({i // group_size for i in ids}) > 1:
                    is_cross = True
        ri = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
                       r"(?:T\(([0-9,]+)\))?", line)
        if ri:
            ng, gs = int(ri.group(1)), int(ri.group(2))
            dims = [int(x) for x in ri.group(3).split(",")]
            arr = np.arange(int(np.prod(dims))).reshape(dims)
            if ri.group(4):
                arr = arr.transpose(
                    [int(x) for x in ri.group(4).split(",")])
            for ids in arr.reshape(ng, gs):
                ids = [int(i) for i in ids]
                groups.append(ids)
                if len({i // group_size for i in ids}) > 1:
                    is_cross = True
        stp = re.search(r"source_target_pairs=\{([0-9,\{\}]*)\}", line)
        if stp:
            for pair in re.findall(r"\{([0-9]+),([0-9]+)\}",
                                   stp.group(1)):
                s, t = int(pair[0]), int(pair[1])
                groups.append([s, t])
                if s // group_size != t // group_size:
                    is_cross = True
        if not groups and devices:
            groups = [list(range(devices))]
            is_cross = devices > group_size
        out.append({"op": op, "bytes": float(nbytes), "cross": is_cross,
                    "groups": groups, "async": is_async})
    # strict parse: any collective mnemonic at op position on a line the
    # main regex did not consume is a counting gap, not a skip
    for sm in _SIGHT.finditer(hlo):
        if sm.group(2) == "-done":
            continue
        bol = hlo.rfind("\n", 0, sm.start()) + 1
        if bol in consumed:
            continue
        eol = hlo.find("\n", sm.start())
        line = hlo[bol:eol if eol != -1 else len(hlo)].strip()
        raise AuditParseError(
            f"unparsed collective line (shape regex missed it): "
            f"{line[:200]!r}")
    return out


def collective_summary(hlo: str, group_size: int,
                       devices: Optional[int] = None) -> List[dict]:
    """JSON-safe :func:`parse_collectives` records (the audit wire form
    priced by ``sim.collectives.priced_collectives``)."""
    return parse_collectives(hlo, group_size, devices)


def collective_bytes(hlo: str, group_size: int) -> Tuple[float, float]:
    """(cross_group_bytes, intra_bytes) over all collectives in optimized
    HLO text; cross = any replica group (brace or iota form) or permute
    pair spanning ICI groups of ``group_size`` consecutive devices."""
    cross = intra = 0.0
    for rec in parse_collectives(hlo, group_size):
        if rec["cross"]:
            cross += rec["bytes"]
        else:
            intra += rec["bytes"]
    return cross, intra


# ---------------------------------------------------------------------------
# model building + lowering (one generic path for every driver family)


def _apply_overrides(cfg, overrides):
    """setattr ``overrides`` onto a model config — lets the verifier and
    tests audit SMALL shapes of the same model family (the driver-default
    transformer is far too heavy for a lint pass)."""
    for k, v in (overrides or {}).items():
        if not hasattr(cfg, k):
            raise SystemExit(
                f"override {k!r} is not a field of {type(cfg).__name__}")
        setattr(cfg, k, v)
    return cfg


def _build_model(model_name: str, machine, batch_size: Optional[int],
                 strategy_path: str, seed: int = 3,
                 dtype: str = "float32", experts: int = 0,
                 overrides: Optional[dict] = None):
    """(model, example_batch) for ``model_name`` with ``strategy_path``
    applied (empty = pure DP) — the same builders the training drivers
    use, so the audited program IS the program a user would run.  A
    strategy carrying an accepted ``__pipeline__`` block builds the SAME
    PipelinedLM the lm driver would run (round 11: accepted pipeline
    blocks get a compiled-HLO audit too)."""
    from flexflow_tpu.strategy import Strategy

    strategies = Strategy.load(strategy_path) if strategy_path else None
    if model_name == "nmt":
        from flexflow_tpu.data import synthetic_token_stream
        from flexflow_tpu.nmt.rnn_model import RnnConfig, RnnModel

        rc = _apply_overrides(RnnConfig(seed=seed, compute_dtype=dtype),
                              overrides)
        if batch_size:
            rc.batch_size = batch_size
        model = RnnModel(rc, machine, strategies)
        gen = synthetic_token_stream(machine, rc.batch_size, rc.seq_length,
                                     rc.vocab_size, seed=5, streams=2)
        return model, tuple(next(gen))
    if model_name in ("transformer", "gpt", "bert"):
        from flexflow_tpu.data import synthetic_token_stream
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     TransformerLM)

        tc = _apply_overrides(
            TransformerConfig(seed=seed, compute_dtype=dtype,
                              num_experts=experts), overrides)
        if batch_size:
            tc.batch_size = batch_size
        if model_name == "gpt":
            tc.causal = True
        # explicit None test: a pipeline-only strategy has no per-op
        # entries, so it is len()==0-falsy but must still build the
        # PipelinedLM its block describes
        pp = getattr(strategies, "pipeline", None) \
            if strategies is not None else None
        if pp:
            from flexflow_tpu.parallel.pipeline import PipelinedLM

            model = PipelinedLM(
                machine, pp["stages"], pp["microbatches"],
                num_layers=tc.num_layers, d_model=tc.d_model,
                num_heads=tc.num_heads, d_ff=tc.d_ff,
                vocab_size=tc.vocab_size, seq_length=tc.seq_length,
                batch_size=tc.batch_size, causal=tc.causal,
                compute_dtype=tc.compute_dtype, tp=pp.get("tp", 1) or 1)
        else:
            model = TransformerLM(tc, machine, strategies)
        gen = synthetic_token_stream(machine, tc.batch_size, tc.seq_length,
                                     tc.vocab_size, seed=5, streams=1)
        (toks,) = next(gen)
        return model, (toks, toks)
    from flexflow_tpu.apps.cnn import _builders
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.data import synthetic_batches

    builders = _builders()
    if model_name not in builders:
        raise SystemExit(f"unknown model {model_name!r}")
    size = 299 if model_name.startswith("inception") else 224
    b = batch_size or 16
    cfg = _apply_overrides(
        FFConfig(batch_size=b, input_height=size, input_width=size,
                 num_iterations=1, print_freq=0, seed=seed,
                 compute_dtype=dtype, strategy_file=strategy_path),
        overrides)
    model = builders[model_name](cfg, machine)
    data = synthetic_batches(machine, cfg.batch_size, cfg.input_height,
                             cfg.input_width, mode="ones")
    return model, tuple(next(data))


def _lowered_text(model, batch) -> str:
    if not hasattr(model, "init_opt_state"):
        # PipelinedLM: params-only SGD step (params, tokens, labels)
        params = model.init()
        return model.make_train_step().lower(
            params, *batch).compile().as_text()
    params, state = model.init()
    opt = model.init_opt_state(params)
    step = model.make_train_step()
    return step.lower(params, state, opt, *batch).compile().as_text()


def audit_in_process(model_name: str, devices: int, ici_group: int,
                     strategy_path: str,
                     batch_size: Optional[int] = None,
                     seed: int = 3, dtype: str = "float32",
                     dp_known: Union[Tuple[float, float], dict,
                                     None] = None,
                     experts: int = 0,
                     dcn_calibration: str = "",
                     overrides: Optional[dict] = None) -> dict:
    """Lower ``strategy_path`` AND pure DP on a ``devices``-device machine
    view with ``ici_group``-sized ICI groups; count cross-/intra-tier
    collective bytes AND the structured per-collective records
    (``searched_collectives`` / ``dp_collectives``) plus their predicted
    seconds under the (optionally calibrated) two-tier ring formulas.
    Requires that many live local devices (virtual CPU mesh in
    practice).  ``dp_known`` from an earlier audit of the SAME
    model/shape skips the (expensive, identical) DP lowering — either
    the legacy ``(cross, intra)`` tuple (bytes only, no predicted time)
    or the full audit dict of the earlier run."""
    import jax

    from flexflow_tpu.machine import MachineModel, Topology
    from flexflow_tpu.sim.collectives import priced_collectives

    if len(jax.devices()) < devices:
        raise RuntimeError(
            f"audit needs {devices} devices, process has "
            f"{len(jax.devices())} — use audit_subprocess")
    topo = (Topology.from_calibration(dcn_calibration,
                                      devices_per_ici_group=ici_group)
            if dcn_calibration
            else Topology(devices_per_ici_group=ici_group))
    machine = MachineModel(devices=jax.devices()[:devices], topology=topo)
    out = {"model": model_name, "devices": devices,
           "ici_group": ici_group}
    for key, path in (("searched", strategy_path), ("dp", "")):
        if key == "dp" and isinstance(dp_known, tuple):
            cross, intra = dp_known
            recs = None
        elif key == "dp" and isinstance(dp_known, dict):
            cross = dp_known["dp_cross_bytes"]
            intra = dp_known["dp_intra_bytes"]
            recs = dp_known.get("dp_collectives")
        else:
            model, batch = _build_model(model_name, machine, batch_size,
                                        path, seed, dtype, experts,
                                        overrides)
            recs = parse_collectives(_lowered_text(model, batch),
                                     ici_group, devices)
            cross = sum(r["bytes"] for r in recs if r["cross"])
            intra = sum(r["bytes"] for r in recs if not r["cross"])
        out[f"{key}_cross_bytes"] = cross
        out[f"{key}_intra_bytes"] = intra
        out[f"{key}_collectives"] = recs
        out[f"{key}_pred_s"] = (
            priced_collectives(recs, topo)["seconds"]
            if recs is not None else None)
    out["cross_ratio_dp_over_searched"] = (
        out["dp_cross_bytes"] / max(out["searched_cross_bytes"], 1.0))
    return out


def audit_consistent(audit: dict, simulated_speedup: float) -> bool:
    """Does the compiled program support the simulated two-tier claim?
    A cross-DCN win requires the plan to move STRICTLY fewer cross-tier
    bytes than DP; a claim of more than ~1.2x requires a clear (>=20%)
    byte reduction, not a rounding-level one.  A plan claiming NO win
    (speedup <= 1.05, e.g. the search honestly returned DP) is
    consistent as long as it moves no more than DP."""
    s, d = audit["searched_cross_bytes"], audit["dp_cross_bytes"]
    if simulated_speedup <= 1.05:
        return s <= d
    if d <= 0:
        return s <= 0  # nothing crosses the tier under DP: plan must not
    if s >= d:
        return False
    if simulated_speedup > 1.2 and s > 0.8 * d:
        return False
    return True


def audit_consistent_time(audit: dict, simulated_speedup: float,
                          topo=None,
                          dp_time_s: Optional[float] = None,
                          best_time_s: Optional[float] = None) -> dict:
    """Predicted-seconds upgrade of :func:`audit_consistent` (round 11,
    VERDICT items 3-5/9): price BOTH compiled programs' collectives with
    the calibrated two-tier ring formulas and compare seconds, not bytes.
    This covers the NMT failure mode the byte heuristic could not — a
    plan whose cross bytes look fine but whose total collective volume
    (intra rings included) swamps the claimed win.

    Rules (s/d = searched/dp predicted collective seconds):

    * speedup <= 1.05 (no win claimed): consistent iff s <= 1.05*d —
      honest-DP-like plans may not quietly pay MORE comm than DP;
    * a claimed win requires s <= d (the compiled program must actually
      save communication; d == 0 requires s == 0);
    * speedup > 1.2 with the simulated step times known: the comm saving
      must FUND at least half the claimed win, (d - s) >= 0.5 *
      (dp_time_s - best_time_s); without times, the proportional rule
      s <= 0.8*d applies.

    Falls back to the byte heuristic (mode="bytes") when either side has
    no structured collective records (legacy dp_known tuple) or no
    ``topo`` was given.  Returns {"consistent", "mode",
    "searched_pred_s", "dp_pred_s"}.
    """
    from flexflow_tpu.sim.collectives import priced_collectives

    sc, dc = audit.get("searched_collectives"), audit.get("dp_collectives")
    if sc is None or dc is None or topo is None:
        return {"consistent": audit_consistent(audit, simulated_speedup),
                "mode": "bytes",
                "searched_pred_s": audit.get("searched_pred_s"),
                "dp_pred_s": audit.get("dp_pred_s")}
    s = priced_collectives(sc, topo)["seconds"]
    d = priced_collectives(dc, topo)["seconds"]
    out = {"mode": "time", "searched_pred_s": s, "dp_pred_s": d}
    if simulated_speedup <= 1.05:
        out["consistent"] = s <= 1.05 * d + 1e-12
        return out
    if d <= 0.0:
        out["consistent"] = s <= 0.0
        return out
    if s > d:
        out["consistent"] = False
        return out
    if simulated_speedup > 1.2:
        if dp_time_s is not None and best_time_s is not None \
                and dp_time_s > best_time_s:
            win = dp_time_s - best_time_s
            out["claimed_win_s"] = win
            out["consistent"] = (d - s) >= 0.5 * win
            return out
        out["consistent"] = s <= 0.8 * d
        return out
    out["consistent"] = True
    return out


def audit_subprocess(model_name: str, devices: int, ici_group: int,
                     strategy_path: str,
                     batch_size: Optional[int] = None, seed: int = 3,
                     timeout: float = 900.0,
                     dtype: str = "float32",
                     dp_known: Union[Tuple[float, float], dict,
                                     None] = None,
                     experts: int = 0,
                     dcn_calibration: str = "",
                     overrides: Optional[dict] = None) -> dict:
    """Run :func:`audit_in_process` in a fresh CPU process with
    ``devices`` virtual host devices — callable from any parent (the
    offline search may be running against one real TPU chip, where an
    8-device mesh cannot exist)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count={devices}")
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "flexflow_tpu.utils.hlo_audit",
           model_name, "--devices", str(devices),
           "--ici-group", str(ici_group), "--seed", str(seed)]
    if strategy_path:
        cmd += ["--strategy", os.path.abspath(strategy_path)]
    if batch_size:
        cmd += ["--batch-size", str(batch_size)]
    if dtype != "float32":
        cmd += ["--dtype", dtype]
    dp_tmp = None
    if isinstance(dp_known, dict):
        # full earlier-audit dict (collectives included): too big for an
        # argv flag — hand it over through a temp file
        import tempfile

        fd, dp_tmp = tempfile.mkstemp(suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump({k: dp_known.get(k) for k in
                       ("dp_cross_bytes", "dp_intra_bytes",
                        "dp_collectives")}, f)
        cmd += ["--dp-known-json", dp_tmp]
    elif dp_known is not None:
        cmd += ["--dp-known", f"{dp_known[0]},{dp_known[1]}"]
    if experts:
        cmd += ["--experts", str(experts)]
    if dcn_calibration:
        cmd += ["--dcn-calibration", os.path.abspath(dcn_calibration)]
    if overrides:
        cmd += ["--overrides", json.dumps(overrides)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=repo)
    finally:
        if dp_tmp:
            os.unlink(dp_tmp)
    if proc.returncode != 0:
        raise RuntimeError(
            f"hlo audit subprocess failed (rc {proc.returncode}):\n"
            f"{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"hlo audit subprocess printed no JSON:\n{proc.stdout[-2000:]}")


def main(argv=None):
    from flexflow_tpu.utils.flags import flag_stream

    args = list(sys.argv[1:] if argv is None else argv)
    opts = {"model": "alexnet", "devices": 8, "ici_group": 4,
            "strategy": "", "batch_size": None, "seed": 3,
            "dtype": "float32", "dp_known": None, "experts": 0,
            "dcn_calibration": "", "overrides": None}
    if args and not args[0].startswith("-"):
        opts["model"] = args.pop(0)
    for a, val in flag_stream(args):
        if a == "--devices":
            opts["devices"] = int(val())
        elif a == "--ici-group":
            opts["ici_group"] = int(val())
        elif a == "--strategy":
            opts["strategy"] = val()
        elif a in ("-b", "--batch-size"):
            opts["batch_size"] = int(val())
        elif a == "--seed":
            opts["seed"] = int(val())
        elif a == "--dtype":
            opts["dtype"] = val()
        elif a == "--dp-known":
            c, i = val().split(",")
            opts["dp_known"] = (float(c), float(i))
        elif a == "--dp-known-json":
            with open(val()) as f:
                opts["dp_known"] = json.load(f)
        elif a == "--experts":
            opts["experts"] = int(val())
        elif a == "--dcn-calibration":
            opts["dcn_calibration"] = val()
        elif a == "--overrides":
            opts["overrides"] = json.loads(val())
    # force the virtual CPU mesh BEFORE any backend init: env vars alone
    # do not suffice under the TPU tunnel (its sitecustomize pre-imports
    # jax, same reason tests/conftest.py uses jax.config)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={opts['devices']} "
            + os.environ.get("XLA_FLAGS", ""))
    import jax

    jax.config.update("jax_platforms", "cpu")
    out = audit_in_process(opts["model"], opts["devices"],
                           opts["ici_group"], opts["strategy"],
                           opts["batch_size"], opts["seed"],
                           opts["dtype"], opts["dp_known"],
                           opts["experts"], opts["dcn_calibration"],
                           opts["overrides"])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
