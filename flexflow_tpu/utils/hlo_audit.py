"""Compiled-HLO collective audit: executor-grounded communication
accounting for the strategy search (round 5, VERDICT r4 #1).

The reference's simulator was grounded on both axes: per-op times were
measured on the device (ref:scripts/cnn.h:204-447) and its comm model was
the same rectangle-intersection physics its executor (Legion) performed
(ref:scripts/simulator.cc:886-959).  This repo measures op costs
(protocol v3), but its comm model prices what GSPMD *should* lower — and
round 4's audit proved GSPMD sometimes lowers something else entirely
(the transformer_2x4 falsification: simulated 1.64x win, compiled program
moved ~8x MORE cross-tier bytes than DP).  This module makes the compiled
program itself the arbiter: lower the candidate plan on a virtual mesh,
parse the optimized HLO, and count the collective bytes that cross the
ICI-group (DCN) boundary.

Two entry points:

* :func:`audit_in_process` — requires ``len(jax.devices()) >= devices``
  (tests run it on the virtual CPU mesh via conftest's machine8).
* :func:`audit_subprocess` — spawns a fresh CPU process with
  ``--xla_force_host_platform_device_count=<devices>`` so the audit runs
  from ANY parent environment (including the single-chip TPU tunnel the
  offline search runs under).  This is what ``apps/search.py``'s accept
  path calls.

The byte counter itself (:func:`collective_bytes`) is the round-4 test
mechanism (tests/test_two_tier.py) promoted to library code.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from typing import Optional, Tuple

import numpy as np

_DT = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
       "u8": 1, "pred": 1, "f64": 8, "s64": 8}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute", "all-reduce-start", "all-gather-start",
                "reduce-scatter-start", "all-to-all-start",
                "collective-permute-start")


def collective_bytes(hlo: str, group_size: int) -> Tuple[float, float]:
    """(cross_group_bytes, intra_bytes) over all collectives in optimized
    HLO text; cross = any replica group (brace or iota form) or permute
    pair spanning ICI groups of ``group_size`` consecutive devices."""
    cross = intra = 0.0
    for m in re.finditer(
            r"= ?((?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)) ([a-z\-]+)\(",
            hlo):
        shape_s, op = m.group(1), m.group(2)
        if op not in _COLLECTIVES:
            continue
        # a collective on an unterminated final line must not raise
        eol = hlo.find("\n", m.start())
        line = hlo[m.start():eol if eol != -1 else len(hlo)]
        nbytes = 0
        for sm in re.finditer(r"([a-z0-9]+)\[([0-9,]*)\]", shape_s):
            dt, dims = sm.group(1), sm.group(2)
            if dt not in _DT:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DT[dt]
        is_cross = False
        rg = re.search(r"replica_groups=\{(\{[0-9,\}\{]*\})\}", line)
        if rg:
            for grp in re.findall(r"\{([0-9,]+)\}", rg.group(1)):
                ids = [int(x) for x in grp.split(",")]
                if len({i // group_size for i in ids}) > 1:
                    is_cross = True
                    break
        ri = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
                       r"(?:T\(([0-9,]+)\))?", line)
        if ri:
            ng, gs = int(ri.group(1)), int(ri.group(2))
            dims = [int(x) for x in ri.group(3).split(",")]
            arr = np.arange(int(np.prod(dims))).reshape(dims)
            if ri.group(4):
                arr = arr.transpose(
                    [int(x) for x in ri.group(4).split(",")])
            for ids in arr.reshape(ng, gs):
                if len({int(i) // group_size for i in ids}) > 1:
                    is_cross = True
                    break
        stp = re.search(r"source_target_pairs=\{([0-9,\{\}]*)\}", line)
        if stp:
            for pair in re.findall(r"\{([0-9]+),([0-9]+)\}", stp.group(1)):
                if int(pair[0]) // group_size != int(pair[1]) // group_size:
                    is_cross = True
                    break
        if is_cross:
            cross += nbytes
        else:
            intra += nbytes
    return cross, intra


# ---------------------------------------------------------------------------
# model building + lowering (one generic path for every driver family)


def _build_model(model_name: str, machine, batch_size: Optional[int],
                 strategy_path: str, seed: int = 3,
                 dtype: str = "float32", experts: int = 0):
    """(model, example_batch) for ``model_name`` with ``strategy_path``
    applied (empty = pure DP) — the same builders the training drivers
    use, so the audited program IS the program a user would run."""
    from flexflow_tpu.strategy import Strategy

    strategies = Strategy.load(strategy_path) if strategy_path else None
    if model_name == "nmt":
        from flexflow_tpu.data import synthetic_token_stream
        from flexflow_tpu.nmt.rnn_model import RnnConfig, RnnModel

        rc = RnnConfig(seed=seed, compute_dtype=dtype)
        if batch_size:
            rc.batch_size = batch_size
        model = RnnModel(rc, machine, strategies)
        gen = synthetic_token_stream(machine, rc.batch_size, rc.seq_length,
                                     rc.vocab_size, seed=5, streams=2)
        return model, tuple(next(gen))
    if model_name in ("transformer", "gpt", "bert"):
        from flexflow_tpu.data import synthetic_token_stream
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     TransformerLM)

        tc = TransformerConfig(seed=seed, compute_dtype=dtype,
                               num_experts=experts)
        if batch_size:
            tc.batch_size = batch_size
        if model_name == "gpt":
            tc.causal = True
        model = TransformerLM(tc, machine, strategies)
        gen = synthetic_token_stream(machine, tc.batch_size, tc.seq_length,
                                     tc.vocab_size, seed=5, streams=1)
        (toks,) = next(gen)
        return model, (toks, toks)
    from flexflow_tpu.apps.cnn import _builders
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.data import synthetic_batches

    builders = _builders()
    if model_name not in builders:
        raise SystemExit(f"unknown model {model_name!r}")
    size = 299 if model_name.startswith("inception") else 224
    b = batch_size or 16
    cfg = FFConfig(batch_size=b, input_height=size, input_width=size,
                   num_iterations=1, print_freq=0, seed=seed,
                   compute_dtype=dtype, strategy_file=strategy_path)
    model = builders[model_name](cfg, machine)
    data = synthetic_batches(machine, b, size, size, mode="ones")
    return model, tuple(next(data))


def _lowered_text(model, batch) -> str:
    params, state = model.init()
    opt = model.init_opt_state(params)
    step = model.make_train_step()
    return step.lower(params, state, opt, *batch).compile().as_text()


def audit_in_process(model_name: str, devices: int, ici_group: int,
                     strategy_path: str,
                     batch_size: Optional[int] = None,
                     seed: int = 3, dtype: str = "float32",
                     dp_known: Optional[Tuple[float, float]] = None,
                     experts: int = 0) -> dict:
    """Lower ``strategy_path`` AND pure DP on a ``devices``-device machine
    view with ``ici_group``-sized ICI groups; count cross-/intra-tier
    collective bytes of both compiled programs.  Requires that many live
    local devices (virtual CPU mesh in practice).  ``dp_known`` =
    (cross, intra) bytes from an earlier audit of the SAME model/shape
    skips the (expensive, identical) DP lowering."""
    import jax

    from flexflow_tpu.machine import MachineModel, Topology

    if len(jax.devices()) < devices:
        raise RuntimeError(
            f"audit needs {devices} devices, process has "
            f"{len(jax.devices())} — use audit_subprocess")
    machine = MachineModel(
        devices=jax.devices()[:devices],
        topology=Topology(devices_per_ici_group=ici_group))
    out = {"model": model_name, "devices": devices,
           "ici_group": ici_group}
    for key, path in (("searched", strategy_path), ("dp", "")):
        if key == "dp" and dp_known is not None:
            cross, intra = dp_known
        else:
            model, batch = _build_model(model_name, machine, batch_size,
                                        path, seed, dtype, experts)
            cross, intra = collective_bytes(_lowered_text(model, batch),
                                            ici_group)
        out[f"{key}_cross_bytes"] = cross
        out[f"{key}_intra_bytes"] = intra
    out["cross_ratio_dp_over_searched"] = (
        out["dp_cross_bytes"] / max(out["searched_cross_bytes"], 1.0))
    return out


def audit_consistent(audit: dict, simulated_speedup: float) -> bool:
    """Does the compiled program support the simulated two-tier claim?
    A cross-DCN win requires the plan to move STRICTLY fewer cross-tier
    bytes than DP; a claim of more than ~1.2x requires a clear (>=20%)
    byte reduction, not a rounding-level one.  A plan claiming NO win
    (speedup <= 1.05, e.g. the search honestly returned DP) is
    consistent as long as it moves no more than DP."""
    s, d = audit["searched_cross_bytes"], audit["dp_cross_bytes"]
    if simulated_speedup <= 1.05:
        return s <= d
    if d <= 0:
        return s <= 0  # nothing crosses the tier under DP: plan must not
    if s >= d:
        return False
    if simulated_speedup > 1.2 and s > 0.8 * d:
        return False
    return True


def audit_subprocess(model_name: str, devices: int, ici_group: int,
                     strategy_path: str,
                     batch_size: Optional[int] = None, seed: int = 3,
                     timeout: float = 900.0,
                     dtype: str = "float32",
                     dp_known: Optional[Tuple[float, float]] = None,
                     experts: int = 0) -> dict:
    """Run :func:`audit_in_process` in a fresh CPU process with
    ``devices`` virtual host devices — callable from any parent (the
    offline search may be running against one real TPU chip, where an
    8-device mesh cannot exist)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count={devices}")
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "flexflow_tpu.utils.hlo_audit",
           model_name, "--devices", str(devices),
           "--ici-group", str(ici_group), "--seed", str(seed)]
    if strategy_path:
        cmd += ["--strategy", os.path.abspath(strategy_path)]
    if batch_size:
        cmd += ["--batch-size", str(batch_size)]
    if dtype != "float32":
        cmd += ["--dtype", dtype]
    if dp_known is not None:
        cmd += ["--dp-known", f"{dp_known[0]},{dp_known[1]}"]
    if experts:
        cmd += ["--experts", str(experts)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=repo)
    if proc.returncode != 0:
        raise RuntimeError(
            f"hlo audit subprocess failed (rc {proc.returncode}):\n"
            f"{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"hlo audit subprocess printed no JSON:\n{proc.stdout[-2000:]}")


def main(argv=None):
    from flexflow_tpu.utils.flags import flag_stream

    args = list(sys.argv[1:] if argv is None else argv)
    opts = {"model": "alexnet", "devices": 8, "ici_group": 4,
            "strategy": "", "batch_size": None, "seed": 3,
            "dtype": "float32", "dp_known": None, "experts": 0}
    if args and not args[0].startswith("-"):
        opts["model"] = args.pop(0)
    for a, val in flag_stream(args):
        if a == "--devices":
            opts["devices"] = int(val())
        elif a == "--ici-group":
            opts["ici_group"] = int(val())
        elif a == "--strategy":
            opts["strategy"] = val()
        elif a in ("-b", "--batch-size"):
            opts["batch_size"] = int(val())
        elif a == "--seed":
            opts["seed"] = int(val())
        elif a == "--dtype":
            opts["dtype"] = val()
        elif a == "--dp-known":
            c, i = val().split(",")
            opts["dp_known"] = (float(c), float(i))
        elif a == "--experts":
            opts["experts"] = int(val())
    # force the virtual CPU mesh BEFORE any backend init: env vars alone
    # do not suffice under the TPU tunnel (its sitecustomize pre-imports
    # jax, same reason tests/conftest.py uses jax.config)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={opts['devices']} "
            + os.environ.get("XLA_FLAGS", ""))
    import jax

    jax.config.update("jax_platforms", "cpu")
    out = audit_in_process(opts["model"], opts["devices"],
                           opts["ici_group"], opts["strategy"],
                           opts["batch_size"], opts["seed"],
                           opts["dtype"], opts["dp_known"],
                           opts["experts"])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
