"""Elastic training runtime: device-loss recovery on the surviving mesh.

PR 5's fault runtime made one process survive NaNs, bad bytes and corrupt
checkpoints; a permanently lost device (or a crashed host) still killed
the whole run.  This module turns that loss into a recoverable, observable
event, built on the subsystems that make elasticity cheap here:

  * the native MCMC search with delta re-simulation (sim/search.py) can
    re-search a strategy for the SURVIVING mesh in seconds, warm-started
    from the running strategy with dead-device assignments invalidated;
  * the regrid planner's cost view (parallel/regrid.py,
    ``plan_state_migration``) prices moving the live params/opt-state
    onto the new layout;
  * verified checkpoints (utils/checkpoint.py) are the fallback when the
    in-memory state is unreachable (donated buffers, state resident on
    the dead device).

The pieces, in the order a loss flows through them:

  1. **detection & classification** — ``fit()`` catches runtime errors at
     its EXISTING host-sync boundaries (the same zero-new-syncs
     discipline as ``StepHealthGuard``) and asks :func:`classify` whether
     they look like device loss; :func:`probe_devices` then re-probes
     every device with bounded backoff (utils/retry.py), splitting
     TRANSIENT hiccups (probe recovers — training continues) from
     PERMANENT loss (probe exhausts its attempts — recovery starts).
     The injected path (``device_loss@N`` in utils/faultinject.py) marks
     devices dead deterministically so CI exercises every branch;
  2. **recovery** (:func:`recover`) — shrink the machine to the live
     devices (``MachineModel.shrink``), rebuild the model graph on it
     (the driver's ``rebuild(config, machine)`` factory), re-search a
     strategy under ``--research-budget-s`` wall clock, then migrate the
     live state (:func:`gather_state` -> ``FFModel.place_state``) or
     restore the newest verified checkpoint onto the new mesh.  Exactly
     one ``elastic_resize`` obs record per event carries the whole story:
     loss detected -> re-search time -> regrid bytes/hops -> steps lost;
  3. **refusal** — shrinking below ``--min-devices`` raises
     :class:`ElasticShrinkRefused` instead of limping (a 2-device
     remnant of a 256-chip job is an outage, not a run).

``host_crash@N`` injection raises :class:`HostCrashError` mid-step,
exercising fit()'s error-exit cleanup (coordinator release via
``distributed.release`` — a crashed host must not hold the barrier until
timeout) and the ``--elastic`` restart protocol
(``distributed.elastic_rejoin``).

Elastic re-expansion + graceful drain (round 9) — the other half of the
lifecycle:

  4. **re-expansion** — after a shrink, fit() keeps a regrow context
     (:func:`make_regrow_context`) holding the out-of-service device
     OBJECTS and the pre-shrink strategy; every existing host-sync
     boundary runs one bounded probe of them (:func:`probe_regrow` —
     zero new per-step syncs).  After ``--regrow-probes`` CONSECUTIVE
     healthy probes (flapping devices are debounced; a failed probe
     resets the streak) the loop raises :class:`DeviceReturnDetected`
     and :func:`recover_grow` rebuilds the full machine
     (``MachineModel.grow``), re-searches warm-started from the
     PRE-SHRINK strategy (surviving entries fall back to the running
     shrunk one), and migrates live state — the exact inverse of
     :func:`recover`, with one ``elastic_resize`` record whose
     ``direction`` is ``"grow"``.  ``--max-regrows`` caps expansions per
     run; the injected path is ``device_return@N`` (counted per probe);
  5. **preemption-aware graceful drain** — fit() installs a
     SIGTERM/SIGINT handler (:func:`install_drain_handler`, main thread
     only, restored on every exit path) that sets a flag read at the
     same boundaries; the loop finishes the in-flight step, commits a
     final verified checkpoint within ``--drain-budget-s`` (async
     writer, sync fallback), emits one ``preempt_drain`` record,
     releases the coordinator and returns cleanly — the driver exits 0,
     which schedulers must treat as a successful drain, not a failure.
     ``preempt@N`` injection raises the same signal path.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.utils.retry import RetryPolicy, call_with_retry


class DeviceLostError(RuntimeError):
    """Permanent device loss that the run cannot (or may not) recover
    from: elasticity disabled, no usable state, or probe exhaustion with
    no recovery path."""


class HostCrashError(RuntimeError):
    """An injected ``host_crash`` fault: this process is simulated as
    dying mid-run.  Propagates out of fit() through the error-exit
    cleanup (coordinator release, prefetcher shutdown)."""


class ElasticShrinkRefused(RuntimeError):
    """The surviving mesh is smaller than ``--min-devices``."""

    def __init__(self, live: int, min_devices: int, dead: Sequence[int]):
        self.live = live
        self.min_devices = min_devices
        self.dead = list(dead)
        super().__init__(
            f"device loss left {live} live device(s) (lost ordinals "
            f"{sorted(self.dead)}), below --min-devices {min_devices}; "
            f"refusing to continue on the remnant")


class DeviceLossDetected(Exception):
    """Internal control-flow signal: fit()'s loop raises it at a host-sync
    boundary once permanent loss is established; fit()'s elastic wrapper
    catches it and runs :func:`recover`.  Carries everything recovery
    needs — the dead ordinals and the loop's live state (``params`` may
    be None when the step's donated buffers are unreachable)."""

    def __init__(self, dead: Sequence[int], step: int, params=None,
                 state=None, opt_state=None, losses=(), loss_base: int = 0,
                 injected: bool = False):
        self.dead = sorted(set(int(d) for d in dead))
        self.step = int(step)
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.losses = list(losses)
        self.loss_base = int(loss_base)
        # injected deaths have no real probe target: the regrow context
        # gates their return on the ``device_return`` injection instead
        self.injected = bool(injected)
        super().__init__(
            f"permanent device loss at step {step}: ordinals {self.dead}")


class DeviceReturnDetected(Exception):
    """Internal control-flow signal, the mirror of
    :class:`DeviceLossDetected`: fit()'s loop raises it at a host-sync
    boundary once the regrow probe has seen every out-of-service device
    answer for K consecutive probes; fit()'s elastic wrapper catches it
    and runs :func:`recover_grow`.  Raised only at HEALTHY boundaries, so
    the live state is always reachable (no checkpoint fallback needed)."""

    def __init__(self, returned: Sequence[int], step: int, params=None,
                 state=None, opt_state=None, losses=(),
                 loss_base: int = 0):
        self.returned = sorted(set(int(d) for d in returned))
        self.step = int(step)
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.losses = list(losses)
        self.loss_base = int(loss_base)
        super().__init__(
            f"device return at step {step}: ordinals {self.returned} "
            f"answering again")


# substrings (lowercased) of runtime errors that indicate the DEVICE —
# not the program — failed.  Conservative: a miss means the error
# propagates like any other bug, which is the safe default.
_LOSS_PATTERNS = (
    "device_unavailable",
    "device unavailable",
    "device lost",
    "device failure",
    "device is in an error state",
    "hardware failure",
    "chip unreachable",
    "slice health",
    "halted with",
    "tpu is in an invalid state",
    "failed to connect to device",
    "data transfer failure",
    "ici link",
)

# exception type names the XLA runtime raises device failures through
_LOSS_TYPES = ("XlaRuntimeError", "JaxRuntimeError", "InternalError",
               "UnavailableError")


def classify(exc: BaseException) -> bool:
    """Does ``exc`` look like a device/runtime loss (vs an ordinary
    program bug)?  True -> the caller should probe the devices;
    False -> re-raise, this is not elasticity's problem."""
    if isinstance(exc, (DeviceLostError, DeviceLossDetected)):
        return True
    if type(exc).__name__ not in _LOSS_TYPES:
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(p in text for p in _LOSS_PATTERNS)


def _default_probe(device) -> None:
    """One tiny host->device->host round trip; raises on a dead device."""
    import jax
    import numpy as np

    x = jax.device_put(np.ones((), np.float32), device)
    float(np.asarray(x))


def probe_devices(machine, policy: Optional[RetryPolicy] = None,
                  probe=None, olog=None,
                  sleep=time.sleep) -> Tuple[List[int], List[int],
                                             List[int]]:
    """Re-probe every device of ``machine`` with bounded backoff and
    split the outcome: ``(live, dead, transient)`` ordinal lists, where
    ``transient`` is the subset of ``live`` that failed at least once
    before recovering.  ``probe(device)`` raising marks one failed
    attempt; the policy bounds total attempts per device (default 3
    attempts, short deterministic backoff — a genuinely dead device
    costs well under a second to condemn)."""
    from flexflow_tpu import obs

    olog = olog if olog is not None else obs.NULL
    policy = policy or RetryPolicy(attempts=3, base_delay=0.05,
                                   max_delay=0.5)
    probe = probe or _default_probe
    live: List[int] = []
    dead: List[int] = []
    transient: List[int] = []
    for i, dev in enumerate(machine.devices):
        failures = {"n": 0}

        def on_retry(exc, n, delay, _f=failures):
            _f["n"] = n

        try:
            call_with_retry(lambda d=dev: probe(d), policy=policy,
                            retry_on=(Exception,), on_retry=on_retry,
                            sleep=sleep)
        except Exception as e:
            dead.append(i)
            olog.event("device_probe", device=i, outcome="dead",
                       attempts=policy.attempts, error=str(e))
            continue
        live.append(i)
        if failures["n"]:
            transient.append(i)
            olog.event("device_probe", device=i, outcome="transient",
                       failures=failures["n"])
    return live, dead, transient


# ---------------------------------------------------------------------------
# recovery


def _reassemble_trees(model, params, state, opt_state) -> Tuple[Dict,
                                                                Dict,
                                                                Dict]:
    """(params, state, opt) as FULL logical host trees: every block-/
    set-resident leaf reassembled to its op's plain layout via ``model``'s
    member views, then materialized as numpy.  Works on live device trees
    AND on raw checkpoint trees saved by ``model`` (the storage layout is
    the model's registry either way)."""
    import numpy as np

    full_p: Dict = {}
    full_s: Dict = {}
    full_o: Dict = {}
    for op in model.layers:
        key = op.param_key
        if key in (params or {}) and key not in full_p:
            full_p[key] = {k: np.asarray(v) for k, v in
                           model._member_params(params, op).items()}
            if opt_state and key in opt_state:
                full_o[key] = {k: np.asarray(v) for k, v in
                               model._member_params(opt_state, op).items()}
        if op.name in (state or {}) and op.name not in full_s:
            full_s[op.name] = {k: np.asarray(v) for k, v in
                               model._member_state(state, op).items()}
    return full_p, full_s, full_o


def gather_state(model, params, state, opt_state) -> Tuple[Dict, Dict,
                                                           Dict]:
    """Pull the LIVE train state to host as full logical trees.  Raises
    when any leaf is unreachable (buffer donated by a failed step, or
    resident on a dead device) — the caller falls back to checkpoint
    restore."""
    return _reassemble_trees(model, params, state, opt_state)


def warm_assignment(search, strategy, fallback=None) -> List[int]:
    """Candidate index per op seeding a re-search from a known-good
    strategy: entries whose (dims, devices) survive among the op's
    candidates on the new machine keep their config; everything else —
    dead-device placements, grids the new machine cannot host — falls
    back first to ``fallback`` (the RUNNING shrunk strategy on the grow
    path, where ``strategy`` is the cached pre-shrink one), then to the
    DP default (the invalidation the tentpole names)."""
    from flexflow_tpu.sim.search import _InputSource

    dp = search.dp_assignment()
    out = []
    kept = 0
    for op, cands, dflt in zip(search.ops, search.candidates, dp):
        idx = dflt
        if not isinstance(op, _InputSource):
            for strat in (strategy, fallback):
                if strat is None:
                    continue
                pc = strat.get(op.name)
                if pc is None:
                    continue
                hit = next((i for i, c in enumerate(cands)
                            if c.dims == pc.dims and c.devices == pc.devices),
                           None)
                if hit is not None:
                    idx = hit
                    kept += 1
                    break
        out.append(idx)
    return out


def research_strategy(config, rebuild, new_machine, old_strategy,
                      olog=None, log=print, fallback_strategy=None,
                      objective: str = "makespan"):
    """Re-run the native MCMC search for the resized mesh under the
    ``--research-budget-s`` wall clock, warm-started from
    ``old_strategy`` (entries missing there fall back to
    ``fallback_strategy`` — on the grow path the cached pre-shrink
    strategy is primary and the running shrunk one the fallback).
    Degrades gracefully: when the native simulator (or the search
    itself) is unavailable, the mesh trains pure-DP — a correct plan,
    just not a searched one.  Returns ``(Strategy, info dict)``;
    ``info["mode"]`` is ``"mcmc"``, ``"mcmc_decomposed"`` (when
    ``--decompose`` is set — the budget then caps the TOTAL across all
    block sub-searches), or ``"dp_fallback"``.

    ``objective`` is forwarded to :class:`StrategySearch` — the serving
    autoscaler (serve/engine.py) re-searches its resized mesh under
    ``"latency"`` (forward-step pricing) while training recovery keeps
    the ``"makespan"`` default."""
    import copy

    from flexflow_tpu.strategy import Strategy

    budget = float(getattr(config, "research_budget_s", 30.0) or 30.0)
    iters = int(getattr(config, "elastic_search_iters", 2000) or 2000)
    try:
        from flexflow_tpu.sim.search import StrategySearch

        shell_cfg = copy.copy(config)
        shell_cfg.strategies = Strategy()
        shell = rebuild(shell_cfg, new_machine)
        ss = StrategySearch(shell, machine=new_machine, obs=olog,
                            objective=objective)
        warm = old_strategy if old_strategy is not None \
            and len(old_strategy) else None
        warm_fb = fallback_strategy if fallback_strategy is not None \
            and len(fallback_strategy) else None
        start = warm_assignment(ss, warm, fallback=warm_fb) \
            if warm is not None or warm_fb is not None else None
        if getattr(config, "decompose", False):
            # block-decomposed re-search (round 19): budget_s is the
            # TOTAL wall across every block sub-search plus the
            # boundary refinement — one shared deadline, so
            # --research-budget-s means the same thing it does for the
            # flat path (a cap on the whole recovery re-search, not a
            # per-block allowance that multiplies with depth)
            strategy, info = ss.search_decomposed(
                iters=iters, seed=int(getattr(config, "seed", 0)),
                delta=getattr(config, "search_delta", "on") != "off",
                start=start, budget_s=budget,
                block_budget_s=getattr(config, "block_budget_s", 0.0)
                or None,
                boundary_refine_iters=int(getattr(
                    config, "boundary_refine_iters", 0)))
            return strategy, {"mode": "mcmc_decomposed",
                              "best_time_s": info.get("best_time"),
                              "iters": info.get("iters_done"),
                              "budget_hit": info.get("budget_hit",
                                                     False),
                              "budget_s": budget,
                              "blocks": info.get("blocks"),
                              "memo_hits": info.get("memo_hits"),
                              "objective": objective}
        strategy, info = ss.search(
            iters=iters, seed=int(getattr(config, "seed", 0)),
            chunks=8, chains=max(int(getattr(config, "search_chains", 1)),
                                 1),
            delta=getattr(config, "search_delta", "on") != "off",
            start=start, budget_s=budget)
        return strategy, {"mode": "mcmc",
                          "best_time_s": info.get("best_time"),
                          "iters": info.get("iters_done"),
                          "budget_hit": info.get("budget_hit", False),
                          "budget_s": budget, "objective": objective}
    except Exception as e:
        log(f"elastic: surviving-mesh re-search unavailable ({e}); "
            f"continuing pure-DP on {new_machine.num_devices} devices")
        return Strategy(), {"mode": "dp_fallback", "error": str(e),
                            "budget_s": budget, "objective": objective}


def recover(model, sig: DeviceLossDetected, rebuild, olog=None,
            log=print, cause: str = "fault",
            objective: str = "makespan"):
    """Full surviving-mesh recovery for one detected permanent loss.

    Returns ``(new_model, carry, prior_losses)``:

      * ``new_model`` — rebuilt on the shrunk machine under the
        re-searched strategy, its state placed and ready to train;
      * ``carry`` — the ``_fit`` elastic-resume dict (start iteration +
        placed state + resize count);
      * ``prior_losses`` — host floats of the completed steps that REMAIN
        valid after recovery (trimmed when a checkpoint fallback rewinds
        past them), for the caller's loss-continuity bookkeeping.

    Emits exactly ONE ``elastic_resize`` record per call (plus, when
    ``cause`` is ``"fault"``, the ``device_loss`` detection record and,
    on the fallback path, an ``elastic_fallback`` record).  ``cause``
    is ``"fault"`` on the classification path and ``"directed"`` when a
    coordinator imposes the target set (:func:`directed_resize`) — no
    hardware failed, so no fault record is written."""
    import copy

    import jax

    from flexflow_tpu import obs
    from flexflow_tpu.utils import checkpoint as ckpt

    olog = olog if olog is not None else obs.NULL
    t0 = time.perf_counter()
    cfg = model.config
    n_old = model.machine.num_devices
    dead = set(sig.dead)
    live = [i for i in range(n_old) if i not in dead]
    min_devices = max(int(getattr(cfg, "min_devices", 1) or 1), 1)
    if cause == "fault":
        olog.event("device_loss", step=sig.step,
                   classification="permanent", dead=sorted(dead),
                   live=len(live), devices=n_old)
        log(f"elastic: permanent device loss at iteration {sig.step} — "
            f"ordinals {sorted(dead)} dead, {len(live)}/{n_old} "
            f"surviving")
    else:
        log(f"elastic: directed shrink at iteration {sig.step} — "
            f"releasing ordinals {sorted(dead)}, keeping "
            f"{len(live)}/{n_old}")
    if len(live) < min_devices:
        olog.event("elastic_refused", step=sig.step, live=len(live),
                   min_devices=min_devices, dead=sorted(dead))
        raise ElasticShrinkRefused(len(live), min_devices, sorted(dead))
    if rebuild is None:
        raise DeviceLostError(
            "elastic recovery needs a model factory: pass "
            "rebuild=lambda cfg, machine: <build model> to fit() "
            "(the drivers do)")
    new_machine = model.machine.shrink(live)

    # losses completed so far -> host floats (best effort: with a real
    # dead device holding a loss shard this transfer itself can fail)
    try:
        prior = [float(v) for v in jax.device_get(list(sig.losses))]
    except Exception:
        prior = []

    t_search = time.perf_counter()
    strategy, research = research_strategy(
        cfg, rebuild, new_machine,
        getattr(cfg, "strategies", None), olog=olog, log=log,
        objective=objective)
    research_s = time.perf_counter() - t_search

    final_cfg = copy.copy(cfg)
    final_cfg.strategies = strategy
    try:
        new_model = rebuild(final_cfg, new_machine)
    except Exception as e:
        # the graph cannot exist on the surviving mesh (e.g. the batch
        # does not divide the survivor count) — recovery is impossible
        raise DeviceLostError(
            f"cannot rebuild the model on the {len(live)} surviving "
            f"device(s): {e} (pick a batch size divisible by every "
            f"survivable mesh, or raise --min-devices)") from e

    migrated = False
    fallback_reason = None
    mig_plan = None
    params = state = opt_state = None
    if sig.params is not None:
        try:
            full_p, full_s, full_o = gather_state(
                model, sig.params, sig.state, sig.opt_state)
            from flexflow_tpu.parallel.regrid import plan_state_migration

            mig_plan = plan_state_migration(model, new_model, full_p,
                                            full_s, full_o)
            params, state, opt_state = new_model.place_state(
                full_p, full_s, full_o)
            migrated = True
        except Exception as e:
            fallback_reason = str(e)
    else:
        fallback_reason = "live state unreachable (step failed with " \
                          "donated buffers)"

    if migrated:
        resume_step = sig.step
        steps_lost = 0
    else:
        olog.event("elastic_fallback", step=sig.step,
                   reason=fallback_reason)
        log(f"elastic: in-memory migration unavailable "
            f"({fallback_reason}); restoring the newest verified "
            f"checkpoint onto the {len(live)}-device mesh")
        ckpt_dir = getattr(cfg, "ckpt_dir", "")
        if not ckpt_dir:
            raise DeviceLostError(
                f"device loss at step {sig.step}: live state is "
                f"unreachable ({fallback_reason}) and no --ckpt-dir is "
                f"configured to restore from") from None
        # raw load (no model placement): the checkpoint holds the OLD
        # model's storage layout — reassemble to full trees through its
        # registry, then land on the new mesh like the in-memory path
        resume_step, raw_p, raw_s, raw_o = ckpt.restore_checkpoint(
            ckpt_dir, None, olog=olog)
        full_p, full_s, full_o = _reassemble_trees(model, raw_p, raw_s,
                                                   raw_o)
        params, state, opt_state = new_model.place_state(full_p, full_s,
                                                         full_o)
        opt_state = opt_state or new_model.init_opt_state(params)
        steps_lost = max(sig.step - resume_step, 0)
        # completed-loss history beyond the restore point replays
        prior = prior[:max(resume_step - sig.loss_base, 0)]

    rec = {
        "step": sig.step, "direction": "shrink", "from_devices": n_old,
        "to_devices": len(live), "dead": sorted(dead), "cause": cause,
        "research_s": research_s, "research": research,
        "migration": "in_memory" if migrated else "checkpoint",
        "resume_step": resume_step, "steps_lost": steps_lost,
        "total_s": time.perf_counter() - t0,
    }
    if mig_plan is not None:
        rec["regrid_bytes"] = mig_plan["bytes"]
        rec["regrid_hops"] = mig_plan["hops"]
        rec["regrid_predicted_s"] = mig_plan["predicted_s"]
    olog.event("elastic_resize", **rec)
    log(f"elastic: resized {n_old} -> {len(live)} devices at iteration "
        f"{sig.step} (re-search {research_s:.2f}s [{research['mode']}], "
        f"migration {rec['migration']}, resume at {resume_step}, "
        f"{steps_lost} step(s) lost)")
    carry = {"start_iter": resume_step, "params": params, "state": state,
             "opt_state": opt_state}
    return new_model, carry, prior


# ---------------------------------------------------------------------------
# re-expansion (regrow)


def make_regrow_context(model, sig: DeviceLossDetected,
                        probes_needed: int, prior=None) -> Dict:
    """The state fit() carries between boundaries while devices are out:
    the dead device OBJECTS (shrink drops them from the machine, so they
    must be captured from the PRE-shrink model) plus the pre-shrink
    strategy the grow re-search warm-starts from.  ``prior`` merges an
    earlier context (a second shrink while the first set is still out):
    the union of out-of-service devices returns together."""
    devs = []
    for o in sig.dead:
        if 0 <= o < model.machine.num_devices:
            devs.append((model.machine.devices[o], bool(sig.injected)))
    if prior:
        devs = list(prior.get("dead", ())) + devs
    ctx = {
        "dead": devs,
        "pre_strategy": getattr(model.config, "strategies", None),
        "healthy": 0,
        "probes": 0,
        "k": max(int(probes_needed), 1),
        "answering": False,
    }
    if prior and prior.get("pre_strategy") is not None:
        # the FIRST shrink's strategy describes the full machine
        ctx["pre_strategy"] = prior["pre_strategy"]
    return ctx


def _device_ordinal(dev) -> int:
    try:
        return int(getattr(dev, "id", dev))
    except (TypeError, ValueError):
        return -1


def probe_regrow(ctx: Dict, inj=None, olog=None, probe=None,
                 log=print) -> bool:
    """One boundary probe of the out-of-service devices.  Injected-dead
    devices (no real hardware went away) answer once the injector fires
    ``device_return`` — one ``fire()`` per probe, so ``device_return@2``
    means "the 2nd regrow probe".  Real dead devices get one real probe
    each (no retries here: the K-consecutive streak IS the debounce).
    All answering increments the healthy streak, any miss resets it to
    zero (flapping).  True once the streak reaches ``ctx["k"]``."""
    from flexflow_tpu import obs

    olog = olog if olog is not None else obs.NULL
    if not ctx or not ctx.get("dead"):
        return False
    ctx["probes"] += 1
    has_injected = any(is_inj for _, is_inj in ctx["dead"])
    if has_injected and inj is not None and getattr(inj, "enabled", False):
        if inj.fire("device_return", site="fit.regrow_probe"):
            ctx["answering"] = True
    probe = probe or _default_probe
    ok = True
    for dev, is_inj in ctx["dead"]:
        if is_inj:
            if not ctx["answering"]:
                ok = False
        else:
            try:
                probe(dev)
            except Exception:
                ok = False
        if not ok:
            break
    ctx["healthy"] = ctx["healthy"] + 1 if ok else 0
    ordinals = sorted(_device_ordinal(d) for d, _ in ctx["dead"])
    olog.event("device_probe", outcome="answering" if ok else "out",
               devices=ordinals, healthy_streak=ctx["healthy"],
               needed=ctx["k"], probe=ctx["probes"])
    if ok and ctx["healthy"] == 1:
        log(f"elastic: out-of-service ordinals {ordinals} answering "
            f"(streak 1/{ctx['k']})")
    return ctx["healthy"] >= ctx["k"]


def recover_grow(model, sig: DeviceReturnDetected, ctx: Dict, rebuild,
                 olog=None, log=print, cause: str = "fault",
                 objective: str = "makespan"):
    """Full re-expansion for one detected device return — the inverse of
    :func:`recover`.  Grows the machine back (``MachineModel.grow``),
    re-searches warm-started from the cached PRE-SHRINK strategy (the
    running shrunk strategy is the per-op fallback), and migrates the
    live state in memory (grow only fires at healthy boundaries, so the
    state is always reachable; a migration failure raises and the caller
    keeps training shrunk — growing is an optimization, never worth
    killing a healthy run over).

    Returns ``(new_model, carry, prior_losses)`` like :func:`recover`,
    and emits exactly ONE ``elastic_resize`` record with ``direction:
    "grow"`` (plus, when ``cause`` is ``"fault"``, the ``device_return``
    detection record — a coordinator-directed grow saw no device come
    back from a failure, so it writes none)."""
    import copy

    import jax

    from flexflow_tpu import obs

    olog = olog if olog is not None else obs.NULL
    t0 = time.perf_counter()
    cfg = model.config
    n_old = model.machine.num_devices
    returned_devs = [dev for dev, _ in ctx["dead"]]
    ordinals = sorted(_device_ordinal(d) for d in returned_devs)
    new_machine = model.machine.grow(returned_devs)
    n_new = new_machine.num_devices
    if cause == "fault":
        olog.event("device_return", step=sig.step, returned=ordinals,
                   from_devices=n_old, to_devices=n_new,
                   probes=ctx.get("probes"),
                   healthy_streak=ctx.get("healthy"))
        log(f"elastic: ordinals {ordinals} back after "
            f"{ctx.get('probes')} probe(s) — growing {n_old} -> {n_new} "
            f"devices at iteration {sig.step}")
    else:
        log(f"elastic: directed grow at iteration {sig.step} — adding "
            f"ordinals {ordinals}, {n_old} -> {n_new} devices")
    if rebuild is None:
        raise DeviceLostError(
            "elastic regrow needs a model factory: pass "
            "rebuild=lambda cfg, machine: <build model> to fit() "
            "(the drivers do)")

    try:
        prior = [float(v) for v in jax.device_get(list(sig.losses))]
    except Exception:
        prior = []

    t_search = time.perf_counter()
    strategy, research = research_strategy(
        cfg, rebuild, new_machine, ctx.get("pre_strategy"),
        olog=olog, log=log,
        fallback_strategy=getattr(cfg, "strategies", None),
        objective=objective)
    research_s = time.perf_counter() - t_search

    final_cfg = copy.copy(cfg)
    final_cfg.strategies = strategy
    new_model = rebuild(final_cfg, new_machine)

    full_p, full_s, full_o = gather_state(model, sig.params, sig.state,
                                          sig.opt_state)
    from flexflow_tpu.parallel.regrid import plan_state_migration

    mig_plan = plan_state_migration(model, new_model, full_p, full_s,
                                    full_o)
    params, state, opt_state = new_model.place_state(full_p, full_s,
                                                     full_o)

    rec = {
        "step": sig.step, "direction": "grow", "from_devices": n_old,
        "to_devices": n_new, "returned": ordinals, "cause": cause,
        "research_s": research_s, "research": research,
        "migration": "in_memory", "resume_step": sig.step,
        "steps_lost": 0, "total_s": time.perf_counter() - t0,
        "regrid_bytes": mig_plan["bytes"], "regrid_hops": mig_plan["hops"],
        "regrid_predicted_s": mig_plan["predicted_s"],
    }
    olog.event("elastic_resize", **rec)
    log(f"elastic: resized {n_old} -> {n_new} devices at iteration "
        f"{sig.step} (re-search {research_s:.2f}s [{research['mode']}], "
        f"migration in_memory, resume at {sig.step}, 0 step(s) lost)")
    carry = {"start_iter": sig.step, "params": params, "state": state,
             "opt_state": opt_state}
    return new_model, carry, prior


# ---------------------------------------------------------------------------
# directed resize (non-fault entry point for the fleet coordinator)


def directed_resize(model, *, keep=None, add=None, step: int,
                    params, state, opt_state=None, losses=(),
                    loss_base: int = 0, rebuild, pre_strategy=None,
                    olog=None, log=print, objective: str = "makespan"):
    """Resize a HEALTHY running job to an externally-imposed device set —
    the fleet coordinator's entry into the elastic machinery.  Unlike the
    fault path there is no classifier, no probe, and no detection record:
    the caller simply decides the target and this helper synthesizes the
    control-flow signal :func:`recover` / :func:`recover_grow` expect,
    invoking them with ``cause="directed"`` so each emits exactly one
    ``elastic_resize`` record and zero ``device_loss`` /
    ``device_return`` fault records.

    Exactly one of ``keep`` / ``add`` must be given:

      * ``keep`` — ordinals (into ``model.machine``'s device list) the
        job retains; the complement is released (a directed SHRINK,
        routed through :func:`recover`, which still enforces
        ``--min-devices`` via :class:`ElasticShrinkRefused`);
      * ``add`` — device OBJECTS granted to the job (a directed GROW,
        routed through :func:`recover_grow`, warm-started from
        ``pre_strategy`` when the caller cached one — e.g. the strategy
        the job ran before an earlier directed shrink).

    ``objective`` selects the re-search pricing (``"makespan"`` for
    training jobs, ``"latency"`` for serving ones).  ``opt_state`` may
    be None (serving jobs carry none).  Returns ``(new_model, carry,
    prior_losses)`` exactly like the fault-path recovery functions."""
    if (keep is None) == (add is None):
        raise ValueError(
            "directed_resize: pass exactly one of keep= (ordinals to "
            "retain -> shrink) or add= (device objects to adopt -> grow)")
    if keep is not None:
        n = model.machine.num_devices
        keep_set = {int(i) for i in keep}
        bad = [i for i in keep_set if not 0 <= i < n]
        if bad:
            raise ValueError(
                f"directed_resize: keep ordinals {sorted(bad)} out of "
                f"range for a {n}-device machine")
        dead = [i for i in range(n) if i not in keep_set]
        if not dead:
            raise ValueError(
                "directed_resize: keep covers every device — nothing "
                "to release")
        sig = DeviceLossDetected(
            dead, step, params=params, state=state, opt_state=opt_state,
            losses=losses, loss_base=loss_base)
        return recover(model, sig, rebuild, olog=olog, log=log,
                       cause="directed", objective=objective)
    devs = list(add)
    if not devs:
        raise ValueError("directed_resize: add= is empty")
    sig = DeviceReturnDetected(
        [_device_ordinal(d) for d in devs], step, params=params,
        state=state, opt_state=opt_state, losses=losses,
        loss_base=loss_base)
    ctx = {
        "dead": [(d, False) for d in devs],
        "pre_strategy": pre_strategy,
        "healthy": 1, "probes": 0, "k": 1, "answering": True,
    }
    return recover_grow(model, sig, ctx, rebuild, olog=olog, log=log,
                        cause="directed", objective=objective)


# ---------------------------------------------------------------------------
# preemption-aware graceful drain


def install_drain_handler(drain: Dict, log=print):
    """Install SIGTERM/SIGINT handlers that set ``drain["requested"]``
    (read at fit()'s existing boundaries) and return an IDEMPOTENT,
    re-entrant restore callable — the drain path and the error path can
    both reach the uninstall.  Installable only from the main thread
    (``signal.signal`` raises ValueError elsewhere); then, and when the
    runtime forbids handlers entirely, ``drain["installed"]`` stays
    False and ``preempt`` injection falls back to setting the flag
    directly."""
    import signal
    import threading

    drain.setdefault("requested", False)
    drain.setdefault("signum", None)
    drain["installed"] = False

    def _handler(signum, frame):
        if not drain["requested"]:
            drain["requested"] = True
            drain["signum"] = int(signum)
            try:
                name = signal.Signals(signum).name
            except Exception:
                name = str(signum)
            log(f"elastic: {name} received — draining at the next "
                f"host-sync boundary")

    prev: Dict = {}
    try:
        for signum in (signal.SIGTERM, signal.SIGINT):
            prev[signum] = signal.signal(signum, _handler)
        drain["installed"] = True
    except (ValueError, OSError, RuntimeError):
        # non-main thread (or a runtime that forbids handlers): roll back
        # whatever half got installed and run flag-only
        for signum, old in prev.items():
            try:
                signal.signal(signum, old)
            except Exception:
                pass
        prev = {}

    done = [False]
    lock = threading.Lock()

    def restore() -> bool:
        with lock:
            if done[0]:
                return False
            done[0] = True
        for signum, old in prev.items():
            try:
                signal.signal(signum, old)
            except Exception:
                pass
        return True

    return restore


class drain_scope:
    """Context manager over :func:`install_drain_handler`: the shared
    install/restore pattern ``apps/serve.py`` and the fleet job runners
    both need (the third hand-rolled try/finally copy this replaces).

    ::

        with drain_scope(log=log) as drain:
            ...  # loop checks drain["requested"] at its boundaries

    Yields the drain dict; restores the previous SIGTERM/SIGINT handlers
    on every exit path (idempotently — an explicit early ``restore()``
    is also safe)."""

    def __init__(self, log=print, drain: Optional[Dict] = None):
        self.drain: Dict = drain if drain is not None else {}
        self._log = log
        self._restore = None

    def __enter__(self) -> Dict:
        self._restore = install_drain_handler(self.drain, log=self._log)
        return self.drain

    def restore(self) -> bool:
        if self._restore is None:
            return False
        return self._restore()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.restore()
        return False


def request_drain(drain: Dict) -> None:
    """The ``preempt`` injection entry point: raise the REAL signal path
    when the handler is installed (so the injected fault exercises the
    exact production code), else set the flag directly."""
    import signal

    if drain.get("installed"):
        signal.raise_signal(signal.SIGTERM)
    else:
        drain["requested"] = True
        drain["signum"] = int(signal.SIGTERM)
