"""flexflow-tpu: a TPU-native deep-learning framework with per-layer
("layer-wise") auto-parallelism, re-designed from scratch for JAX/XLA.

Capability model (see SURVEY.md): every operator independently chooses a
partition grid over its tensor dimensions (sample / channel / height / width,
or batch / vocab / sequence for RNNs) plus an explicit device assignment — the
per-op "strategy" — and an execution simulator with MCMC search finds hybrid
strategies that beat pure data parallelism.

TPU-native architecture:
  * a strategy entry (``ParallelConfig``) compiles to a ``jax.sharding.Mesh``
    over its device list plus a ``NamedSharding`` — XLA/GSPMD derives all
    communication (the role Legion region deps + GASNet play in the
    reference, /root/reference/strategy.proto, conv_2d.cu:61-208);
  * operator kernels are XLA HLO (MXU matmuls/convs in bf16-friendly form)
    instead of cuDNN/cuBLAS leaf tasks;
  * gradient aggregation across replicas is XLA all-reduce over ICI instead of
    the reference's serial ``updateGAS`` (cuda_helper.cu:57-71);
  * the strategy searcher (flexflow_tpu.sim, in progress) is a task-graph
    simulator + Metropolis MCMC, cost-calibrated for MXU FLOPs and ICI/DCN
    bandwidth (reference: scripts/simulator.cc).
"""

from flexflow_tpu.config import FFConfig
from flexflow_tpu.strategy import ParallelConfig, Strategy
from flexflow_tpu.machine import MachineModel
from flexflow_tpu.model import FFModel, Tensor

__version__ = "0.1.0"

__all__ = [
    "FFConfig",
    "ParallelConfig",
    "Strategy",
    "MachineModel",
    "FFModel",
    "Tensor",
    "__version__",
]
