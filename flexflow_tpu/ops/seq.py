"""Sequence chunking: static slice of the token tensor into
LSTM_PER_NODE_LENGTH-step chunks.

The reference materializes a separate Legion region per chunk
(nmt/rnn.cu:89-126 src/dst word tensors); here chunks are static slices of
one (batch, seq_len) input inside the jit program — each chunk Tensor is
independently placeable, which is what makes per-chunk device placement
(pipeline-style operator parallelism) expressible."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class SliceSeq(Op):
    AXIS_NAMES = ("n",)

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 start: int, length: int):
        super().__init__(name, pc, [input])
        assert input.ndim == 2
        n, total = input.shape
        assert start + length <= total
        self.start = start
        self.length = length
        self.output = Tensor((n, length), input.dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None)

    def forward(self, params, state, xs: List, train: bool):
        from jax import lax

        (x,) = xs
        return lax.slice_in_dim(x, self.start, self.start + self.length,
                                axis=1), state
