"""Mixture-of-Experts FFN with expert parallelism (EP).

New capability beyond the reference (SURVEY.md §2.6 lists EP as absent —
"No MoE anywhere"); this closes the one SOAP axis the reference never had.
The op follows the same per-layer-strategy design as every other op: a
3-D grid ('e', 'c', 'n') = experts x expert-hidden channels x batch, so a
strategy file can place each MoE layer independently (pure EP, EP x TP,
EP x DP, ...).

TPU-native design (GShard/Switch-style dense dispatch):

  * routing builds static-shaped dispatch/combine tensors (one-hot over a
    fixed per-expert capacity) — no dynamic shapes, so XLA tiles every
    einsum onto the MXU;
  * the token->expert shuffle is the ``bsec,bsd->ebcd`` dispatch einsum
    under an ('e','n') sharding constraint: GSPMD lowers the resharding
    from batch-sharded tokens to expert-sharded slots as an all-to-all
    over ICI — the hand-written NCCL a2a of GPU MoE frameworks;
  * expert FFNs run as one batched einsum over the local experts
    (weights sharded P('e', ..., 'c')), combining EP with the reference's
    channel TP (linear.cu's c-axis) inside each expert;
  * the auxiliary load-balancing loss (Switch Transformer eq. 4) is a
    second op output; the model adds it to the objective.
"""

from __future__ import annotations

import math
from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class MixtureOfExperts(Op):
    """Token-routed top-k MoE FFN on (batch, seq, d_model) tensors.

    Outputs: [y (B,S,D), aux_loss ()].
    """

    AXIS_NAMES = ("e", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 num_experts: int, d_ff: int, top_k: int = 2,
                 capacity_factor: float = 2.0, machine=None):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        b, s, d = input.shape
        assert 1 <= top_k <= num_experts
        self.num_experts = num_experts
        self.d_ff = d_ff
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        # static per-expert slot count (GShard capacity); rounded up so the
        # expected balanced load always fits
        self.capacity = max(1, int(math.ceil(
            capacity_factor * top_k * s / num_experts)))
        self.d_model = d
        self.machine = machine
        self.output = Tensor(input.shape, input.dtype, self, name)
        self.aux = Tensor((), "float32", self, f"{name}_aux")
        self.outputs = [self.output, self.aux]

    # ---- parameters ----------------------------------------------------

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        e, d, f = self.num_experts, self.d_model, self.d_ff
        keys = jax.random.split(rng, 3)
        init = jax.nn.initializers.glorot_uniform(in_axis=-2, out_axis=-1)
        return {
            "wg": jax.random.normal(keys[0], (d, e), "float32") * 0.02,
            "w1": init(keys[1], (e, d, f), "float32"),
            "b1": jnp.zeros((e, f), "float32"),
            "w2": init(keys[2], (e, f, d), "float32"),
            "b2": jnp.zeros((e, d), "float32"),
        }

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        # experts sharded over 'e' (EP); expert-hidden channels over 'c'
        # (TP inside each expert); router replicated
        return {"wg": P(None, None),
                "w1": P("e", None, "c"), "b1": P("e", "c"),
                "w2": P("e", "c", None), "b2": P("e", None)}

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # tokens batch-sharded over n, replicated over (e, c); the expert
        # all-to-all is emitted inside the op from the 'e' constraints
        return [P("n", None, None)]

    def output_specs(self) -> List:
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None), None]

    def output_spec(self):
        return self.output_specs()[0]

    def validate_partitioning(self):
        super().validate_partitioning()
        pe, pc_, pn = self.pc.dims
        if self.num_experts % pe:
            raise ValueError(
                f"op {self.name!r}: {self.num_experts} experts not divisible "
                f"by expert-grid {pe}")
        if self.d_ff % pc_:
            raise ValueError(
                f"op {self.name!r}: d_ff={self.d_ff} not divisible by "
                f"channel-grid {pc_}")

    # ---- compute -------------------------------------------------------

    def _constrain(self, y, spec):
        if self.machine is not None and self.machine.num_devices > 1:
            from jax import lax

            return lax.with_sharding_constraint(
                y, self.machine.sharding(self.pc, self.AXIS_NAMES, spec))
        return y

    def _route(self, probs):
        """Static-shaped top-k routing -> (dispatch, combine, aux).

        dispatch (B,S,E,C): 0/1, token (b,s) occupies slot c of expert e.
        combine  (B,S,E,C): dispatch weighted by renormalized gate prob.
        Tokens beyond an expert's capacity are dropped for that expert
        (their combine mass is lost — standard GShard semantics).
        """
        import jax
        import jax.numpy as jnp

        b, s, e = probs.shape
        c, k = self.capacity, self.top_k
        top_p, top_i = jax.lax.top_k(probs, k)              # (B,S,k)
        if k > 1:
            top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
        # (k == 1 keeps the RAW gate prob — Switch Transformer semantics:
        # a renormalized weight would be the constant 1.0 and sever the
        # router's gradient from the task loss)
        counts = jnp.zeros((b, e), "float32")
        dispatch = jnp.zeros((b, s, e, c), "float32")
        combine = jnp.zeros((b, s, e, c), "float32")
        for i in range(k):                                   # k is tiny/static
            oh = jax.nn.one_hot(top_i[:, :, i], e, dtype="float32")
            # slot index: tokens before me routed here (this slot pass) +
            # tokens already placed by higher-priority passes
            pos = jnp.cumsum(oh, axis=1) - oh + counts[:, None, :]
            keep = oh * (pos < c)
            counts = counts + keep.sum(axis=1)
            slot = keep[..., None] * jax.nn.one_hot(
                pos.astype("int32"), c, dtype="float32")
            dispatch = dispatch + slot
            combine = combine + top_p[:, :, i][..., None, None] * slot
        # Switch aux loss: E * sum_e f_e * P_e, f from top-1 assignments
        f = jax.nn.one_hot(top_i[:, :, 0], e, dtype="float32").mean((0, 1))
        aux = e * jnp.sum(f * probs.mean((0, 1)))
        return dispatch, combine, aux

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        (x,) = xs
        # routing in float32 (router numerics are precision-sensitive)
        logits = jnp.einsum("bsd,de->bse", x.astype("float32"), params["wg"])
        dispatch, combine, aux = self._route(
            jax.nn.softmax(logits, axis=-1))
        # token -> expert-slot shuffle; the 'e'-sharding constraint makes
        # GSPMD emit the all-to-all over ICI
        xin = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(x.dtype), x,
                         preferred_element_type=jnp.float32).astype(x.dtype)
        xin = self._constrain(xin, P("e", "n", None, None))
        h = jnp.einsum("ebcd,edf->ebcf", xin, params["w1"].astype(x.dtype),
                       preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h + params["b1"][:, None, None, :]).astype(x.dtype)
        h = self._constrain(h, P("e", "n", None, "c"))
        yo = jnp.einsum("ebcf,efd->ebcd", h, params["w2"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
        yo = (yo + params["b2"][:, None, None, :]).astype(x.dtype)
        yo = self._constrain(yo, P("e", "n", None, None))
        # expert-slot -> token combine (the reverse all-to-all)
        y = jnp.einsum("bsec,ebcd->bsd", combine, yo.astype("float32"),
                       preferred_element_type=jnp.float32)
        return (y.astype(x.dtype), aux), state

    # ---- cost model ----------------------------------------------------

    def local_clone(self, pc: ParallelConfig):
        pe, pc_, pn = pc.dims
        b, s, d = self.inputs[0].shape
        if pe > 1 or pc_ > 1 or b % pn:
            return None  # analytic fallback (flops/parts is exact for e/c)
        t = Tensor((b // pn, s, d))
        return MixtureOfExperts(self.name, ParallelConfig((1, 1, 1), (0,)),
                                t, self.num_experts, self.d_ff, self.top_k,
                                self.capacity_factor)

    def flops_per_sample(self) -> float:
        s, d, f = self.output.shape[1], self.d_model, self.d_ff
        e, c = self.num_experts, self.capacity
        # router + dispatch/combine einsums + expert FFNs over E*C slots
        return (2.0 * s * d * e + 4.0 * s * e * c * d
                + 4.0 * e * c * d * f)

    def shard_flops_fwd(self, pc: ParallelConfig):
        # The three terms shard over different axes: the router is
        # replicated over (e, c); dispatch/combine shard over (e, n) only;
        # the expert FFNs shard over all of (e, c, n).  A uniform
        # flops/num_parts split would under-cost EP x TP grids.
        pe, pcc, pn = pc.dims
        b, s, d = self.inputs[0].shape
        f, e, c = self.d_ff, self.num_experts, self.capacity
        local_b = b / pn
        router = 2.0 * s * d * e * local_b
        shuffle = 4.0 * s * e * c * d * local_b / pe
        ffn = 4.0 * e * c * d * f * local_b / (pe * pcc)
        return router + shuffle + ffn

    def cost_signature(self) -> tuple:
        # expert work is invisible in the (B,S,D) input/output shapes
        return (self.num_experts, self.d_ff, self.top_k, self.capacity)

    def param_bytes(self) -> int:
        e, d, f = self.num_experts, self.d_model, self.d_ff
        return 4 * (d * e + 2 * e * d * f + e * f + e * d)
