"""Mixture-of-Experts FFN with expert parallelism (EP).

New capability beyond the reference (SURVEY.md §2.6 lists EP as absent —
"No MoE anywhere"); this closes the one SOAP axis the reference never had.
The op follows the same per-layer-strategy design as every other op: a
3-D grid ('e', 'c', 'n') = experts x expert-hidden channels x batch, so a
strategy file can place each MoE layer independently (pure EP, EP x TP,
EP x DP, ...).

TPU-native design (GShard/Switch semantics, index-based dispatch):

  * routing computes static-shaped INDEX tensors — per expert-slot the
    source token (``src``), per token its k (slot, weight) pairs — via
    cumsum positions and O(B*S*k) scatters; capacity overflow drops
    tokens exactly as GShard's dense one-hot formulation does;
  * the token->expert shuffle is a gather from the token-sharded
    activations into the ('e','n')-constrained slot tensor (and a gather
    back for combine): GSPMD lowers the resharding as collectives over
    ICI — the hand-written NCCL a2a of GPU MoE frameworks.  The classic
    dense ``bsec,bsd->ebcd`` dispatch einsum nominally costs
    2*B*S*E*C*D FLOPs just to move data; the gathers cost bytes only.
    (Measured end-to-end on v5e the two are equal — XLA evidently does
    not execute the one-hot contraction naively — but the index form
    keeps the simulator's FLOP model honest and the intent explicit;
    equivalence to the dense GShard spec is tested.)
  * expert FFNs run as one batched einsum over the local experts
    (weights sharded P('e', ..., 'c')), combining EP with the reference's
    channel TP (linear.cu's c-axis) inside each expert;
  * the auxiliary load-balancing loss (Switch Transformer eq. 4) is a
    second op output; the model adds it to the objective.
"""

from __future__ import annotations

import math
from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class MixtureOfExperts(Op):
    """Token-routed top-k MoE FFN on (batch, seq, d_model) tensors.

    Outputs: [y (B,S,D), aux_loss ()].
    """

    AXIS_NAMES = ("e", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 num_experts: int, d_ff: int, top_k: int = 2,
                 capacity_factor: float = 2.0, machine=None):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        b, s, d = input.shape
        assert 1 <= top_k <= num_experts
        self.num_experts = num_experts
        self.d_ff = d_ff
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        # static per-expert slot count (GShard capacity); rounded up so the
        # expected balanced load always fits
        self.capacity = max(1, int(math.ceil(
            capacity_factor * top_k * s / num_experts)))
        self.d_model = d
        self.machine = machine
        self.output = Tensor(input.shape, input.dtype, self, name)
        self.aux = Tensor((), "float32", self, f"{name}_aux")
        self.outputs = [self.output, self.aux]

    # ---- parameters ----------------------------------------------------

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        e, d, f = self.num_experts, self.d_model, self.d_ff
        keys = jax.random.split(rng, 3)
        init = jax.nn.initializers.glorot_uniform(in_axis=-2, out_axis=-1)
        return {
            "wg": jax.random.normal(keys[0], (d, e), "float32") * 0.02,
            "w1": init(keys[1], (e, d, f), "float32"),
            "b1": jnp.zeros((e, f), "float32"),
            "w2": init(keys[2], (e, f, d), "float32"),
            "b2": jnp.zeros((e, d), "float32"),
        }

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        # experts sharded over 'e' (EP); expert-hidden channels over 'c'
        # (TP inside each expert); router replicated
        return {"wg": P(None, None),
                "w1": P("e", None, "c"), "b1": P("e", "c"),
                "w2": P("e", "c", None), "b2": P("e", None)}

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # tokens batch-sharded over n, replicated over (e, c); the expert
        # all-to-all is emitted inside the op from the 'e' constraints
        return [P("n", None, None)]

    def output_specs(self) -> List:
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None), None]

    def output_spec(self):
        return self.output_specs()[0]

    def validate_partitioning(self):
        super().validate_partitioning()
        pe, pc_, pn = self.pc.dims
        if self.num_experts % pe:
            raise ValueError(
                f"op {self.name!r}: {self.num_experts} experts not divisible "
                f"by expert-grid {pe}")
        if self.d_ff % pc_:
            raise ValueError(
                f"op {self.name!r}: d_ff={self.d_ff} not divisible by "
                f"channel-grid {pc_}")

    # ---- compute -------------------------------------------------------

    def _constrain(self, y, spec):
        if self.machine is not None and self.machine.num_devices > 1:
            from jax import lax

            return lax.with_sharding_constraint(
                y, self.machine.sharding(self.pc, self.AXIS_NAMES, spec))
        return y

    def _route_indices(self, probs):
        """Static-shaped top-k routing as indices.

        Returns (src, slots, weights, aux):
          src     (B, E*C) int32 — token position filling each expert slot
                  (sentinel S = empty slot);
          slots   (B, S, k) int32 — flat e*C+c slot per token choice
                  (sentinel E*C = dropped);
          weights (B, S, k) f32 — renormalized gate weights (0 if dropped).
        Tokens beyond an expert's capacity are dropped for that expert
        (their combine mass is lost — standard GShard semantics)."""
        import jax
        import jax.numpy as jnp

        b, s, e = probs.shape
        c, k = self.capacity, self.top_k
        top_p, top_i = jax.lax.top_k(probs, k)              # (B,S,k)
        if k > 1:
            top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
        # (k == 1 keeps the RAW gate prob — Switch Transformer semantics:
        # a renormalized weight would be the constant 1.0 and sever the
        # router's gradient from the task loss)
        counts = jnp.zeros((b, e), "float32")
        slot_l, w_l = [], []
        for i in range(k):                                   # k is tiny/static
            e_i = top_i[:, :, i]                             # (B,S)
            oh = jax.nn.one_hot(e_i, e, dtype="float32")
            # slot index: tokens before me routed here (this pass) +
            # tokens already placed by higher-priority passes
            pos = jnp.cumsum(oh, axis=1) - oh + counts[:, None, :]
            counts = counts + (oh * (pos < c)).sum(axis=1)
            p_i = jnp.take_along_axis(pos, e_i[..., None], -1)[..., 0]
            keep = p_i < c
            slot_l.append(jnp.where(
                keep, e_i * c + p_i.astype("int32"), e * c).astype("int32"))
            w_l.append(jnp.where(keep, top_p[:, :, i], 0.0))
        slots = jnp.stack(slot_l, -1)                        # (B,S,k)
        weights = jnp.stack(w_l, -1)                         # (B,S,k)
        # invert: token position per slot (unique by construction — pos is
        # a running count offset by previous passes' placements)
        src = jnp.full((b, e * c + 1), s, "int32")
        bidx = jnp.arange(b)[:, None, None]
        sgrid = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :, None],
                                 slots.shape)
        src = src.at[bidx, slots].set(sgrid)[:, :e * c]
        # Switch aux loss: E * sum_e f_e * P_e, f from top-1 assignments
        f = jax.nn.one_hot(top_i[:, :, 0], e, dtype="float32").mean((0, 1))
        aux = e * jnp.sum(f * probs.mean((0, 1)))
        return src, slots, weights, aux

    def _route(self, probs):
        """Dense (dispatch, combine, aux) reconstructed from the index
        routing — the classic GShard one-hot form, kept as the executable
        specification the index path is tested against."""
        import jax.numpy as jnp

        b, s, e = probs.shape
        c = self.capacity
        src, slots, weights, aux = self._route_indices(probs)
        bidx = jnp.arange(b)[:, None, None]
        sidx = jnp.broadcast_to(jnp.arange(s)[None, :, None], slots.shape)
        disp = jnp.zeros((b, s, e * c + 1), "float32"
                         ).at[bidx, sidx, slots].add(1.0)
        comb = jnp.zeros((b, s, e * c + 1), "float32"
                         ).at[bidx, sidx, slots].add(weights)
        return (disp[..., :e * c].reshape(b, s, e, c),
                comb[..., :e * c].reshape(b, s, e, c), aux)

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        (x,) = xs
        b, s, d = x.shape
        e, c = self.num_experts, self.capacity
        # routing in float32 (router numerics are precision-sensitive)
        logits = jnp.einsum("bsd,de->bse", x.astype("float32"), params["wg"])
        src, slots, weights, aux = self._route_indices(
            jax.nn.softmax(logits, axis=-1))
        # token -> expert-slot shuffle: a gather (the sentinel indexes the
        # padded zero row); the 'e'-sharding constraint makes GSPMD emit
        # the collective over ICI.  The routing weight multiplies at
        # combine only, so the gather moves raw activations (GShard).
        xpad = jnp.concatenate([x, jnp.zeros((b, 1, d), x.dtype)], axis=1)
        xin = xpad[jnp.arange(b)[:, None], src]              # (B,E*C,D)
        xin = xin.reshape(b, e, c, d).transpose(1, 0, 2, 3)  # (E,B,C,D)
        xin = self._constrain(xin, P("e", "n", None, None))
        h = jnp.einsum("ebcd,edf->ebcf", xin, params["w1"].astype(x.dtype),
                       preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h + params["b1"][:, None, None, :]).astype(x.dtype)
        h = self._constrain(h, P("e", "n", None, "c"))
        yo = jnp.einsum("ebcf,efd->ebcd", h, params["w2"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
        yo = (yo + params["b2"][:, None, None, :]).astype(x.dtype)
        yo = self._constrain(yo, P("e", "n", None, None))
        # expert-slot -> token combine: gather each token's k slot outputs
        # back and mix with the gate weights (the reverse collective)
        yo_f = yo.transpose(1, 0, 2, 3).reshape(b, e * c, d)
        yo_pad = jnp.concatenate([yo_f, jnp.zeros((b, 1, d), yo_f.dtype)], 1)
        yg = yo_pad[jnp.arange(b)[:, None, None], slots]     # (B,S,k,D)
        y = (weights[..., None] * yg.astype("float32")).sum(2)
        return (y.astype(x.dtype), aux), state

    # ---- cost model ----------------------------------------------------

    def local_clone(self, pc: ParallelConfig):
        pe, pc_, pn = pc.dims
        b, s, d = self.inputs[0].shape
        if pe > 1 or pc_ > 1 or b % pn:
            return None  # analytic fallback (flops/parts is exact for e/c)
        t = Tensor((b // pn, s, d))
        return MixtureOfExperts(self.name, ParallelConfig((1, 1, 1), (0,)),
                                t, self.num_experts, self.d_ff, self.top_k,
                                self.capacity_factor)

    def flops_per_sample(self) -> float:
        s, d, f = self.output.shape[1], self.d_model, self.d_ff
        e, c = self.num_experts, self.capacity
        # router + combine mix + expert FFNs over E*C slots (the
        # dispatch/combine shuffles are index gathers — bytes, not FLOPs)
        return (2.0 * s * d * e + 2.0 * s * self.top_k * d
                + 4.0 * e * c * d * f)

    def shard_flops_fwd(self, pc: ParallelConfig):
        # The terms shard over different axes: the router/combine mix are
        # replicated over (e, c); the expert FFNs shard over all of
        # (e, c, n).  A uniform flops/num_parts split would under-cost
        # EP x TP grids.
        pe, pcc, pn = pc.dims
        b, s, d = self.inputs[0].shape
        f, e, c = self.d_ff, self.num_experts, self.capacity
        local_b = b / pn
        router = (2.0 * s * d * e + 2.0 * s * self.top_k * d) * local_b
        ffn = 4.0 * e * c * d * f * local_b / (pe * pcc)
        return router + ffn

    def cost_signature(self) -> tuple:
        # expert work is invisible in the (B,S,D) input/output shapes
        return (self.num_experts, self.d_ff, self.top_k, self.capacity)

    def param_bytes(self) -> int:
        e, d, f = self.num_experts, self.d_model, self.d_ff
        return 4 * (d * e + 2 * e * d * f + e * f + e * d)
