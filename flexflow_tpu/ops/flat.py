"""Flat — the grid-transition op bridging the 4-D conv grid to the 2-D FC
grid.

Reference: flat.cu builds a projection region of Rect<2> values and a
``create_partition_by_image_range`` to derive the FC-side partition of the
flattened tensor (flat.cu:82-126).  On TPU this entire mechanism is a
reshape plus a sharding constraint on the result — GSPMD computes the
resharding (the "image" of the old partition under flattening) itself.

Layout note: activations are NHWC here, so flatten order is (h, w, c) rather
than the reference's NCHW (c, h, w); weights are initialized in this layout
so the model is equivalent up to a fixed permutation of FC input features.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Flat(Op):
    AXIS_NAMES = ("c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor):
        super().__init__(name, pc, [input])
        assert input.ndim == 4
        n, h, w, c = input.shape
        self.output = Tensor((n, h * w * c), input.dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        # features stay unsharded across 'c' (the FC grid's c-axis shards
        # *output* channels of the next linear, not flat's features)
        return P("n", None)

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None, None)]  # local reshape per batch shard

    def placement_signature(self):
        return ("flat",)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None, None)]

    def forward(self, params, state, xs: List, train: bool):
        (x,) = xs
        return x.reshape(x.shape[0], -1), state
