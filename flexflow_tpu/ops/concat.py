"""Concat along the channel dim (reference: concat.cu — per-input
cudaMemcpyAsync, requiring all inputs to share the op's partition,
concat.cu:93-98).  On TPU: jnp.concatenate on the channel axis; inputs with
different producer grids are resharded to this op's grid by GSPMD first —
the constraint the reference asserts is handled, not required."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Concat(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, inputs: List[Tensor]):
        super().__init__(name, pc, inputs)
        assert len(inputs) >= 2
        n, h, w, _ = inputs[0].shape
        for t in inputs:
            assert t.ndim == 4 and t.shape[0] == n and t.shape[1] == h \
                and t.shape[2] == w, "concat inputs must agree on N,H,W"
        c_total = sum(t.shape[3] for t in inputs)
        self.output = Tensor((n, h, w, c_total), inputs[0].dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "h", "w", "c")

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        pc = pc or self.pc
        if pc.dims[2] != 1:
            return None  # channel-split would break the local concat
        return [P("n", "h", "w", None) for _ in self.inputs]

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # channel-dim concat: per-input channel counts need not divide the
        # 'c' grid, so inputs arrive channel-replicated
        return [P("n", "h", "w", None)] * len(self.inputs)

    def placement_signature(self):
        return ("concat", len(self.inputs))

    def forward(self, params, state, xs: List, train: bool):
        import jax.numpy as jnp

        return jnp.concatenate(xs, axis=3), state
