"""Pool2D (reference: pool_2d.cu, cudnnPoolingForward/Backward).

``lax.reduce_window`` max/avg in NHWC; the {w,h,c,n} grid shards the
activation, and XLA handles window halos under spatial partitioning.
Defaults mirror the reference API: ``pool2d(..., POOL_MAX, relu=True)``
(model.h:133-139, pool_2d.cu:50-56)."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig

POOL_MAX = "max"
POOL_AVG = "avg"


class Pool2D(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int,
                 pool_type: str = POOL_MAX, relu: bool = True):
        super().__init__(name, pc, [input])
        assert input.ndim == 4
        n, h, w, c = input.shape
        self.kernel_h, self.kernel_w = kernel_h, kernel_w
        self.stride_h, self.stride_w = stride_h, stride_w
        self.padding_h, self.padding_w = padding_h, padding_w
        self.pool_type = pool_type
        self.relu = relu
        out_h = 1 + (h + 2 * padding_h - kernel_h) // stride_h
        out_w = 1 + (w + 2 * padding_w - kernel_w) // stride_w
        self.output = Tensor((n, out_h, out_w, c), input.dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "h", "w", "c")

    def _spatial_placeable(self, pc) -> bool:
        """Placed spatial grids for AVG pools of the SAME/stride-1 family
        (Inception's in-block 3x3 pools): the halo prelude exchanges both
        the activation and a validity mask, reproducing the canonical
        count-of-valid-positions semantics exactly.  MAX pools are
        excluded from spatial placement (ppermute fills boundary halos
        with zeros, not -inf)."""
        pw, ph, pcc, pn = pc.dims
        if self.pool_type != POOL_AVG:
            return False
        n, h, w, _ = self.inputs[0].shape
        for parts, extent, k, s, p in (
                (ph, h, self.kernel_h, self.stride_h, self.padding_h),
                (pw, w, self.kernel_w, self.stride_w, self.padding_w)):
            if parts == 1:
                continue
            if s != 1 or k % 2 == 0 or p != (k - 1) // 2:
                return False
            if extent % parts or (k - 1) // 2 > extent // parts:
                return False
        return True

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        pc = pc or self.pc
        pw, ph, pcc, pn = pc.dims
        n, _, _, c = self.inputs[0].shape
        cs = "c" if pcc > 1 else None
        if (pcc > 1 and c % pcc) or n % pn:
            return None
        if (pw, ph) == (1, 1):
            # batch (and optionally channel — pooling is per-channel)
            return [P("n", None, None, cs)]
        if self._spatial_placeable(pc):
            return [P("n", "h", "w", cs)]
        return None

    def placed_prelude(self, xs, train: bool):
        """Halo exchange for placed spatial AVG pools: the activation gets
        real neighbor halos (shared exchange_halo); the validity mask that
        reproduces the canonical count-of-valid-positions denominator is
        built LOCALLY from the shard's grid position (zero halo iff
        boundary shard) — no extra communication."""
        import jax.numpy as jnp
        from jax import lax

        from flexflow_tpu.ops.base import exchange_halo

        pw, ph, _pc, _pn = self.pc.dims
        if ph == 1 and pw == 1:
            return None
        (x,) = xs
        ones = jnp.ones_like(x)

        def mask_halo(t, axis_name, parts, k, dim):
            r = (k - 1) // 2
            if r == 0 or parts == 1:
                return t
            idx = lax.axis_index(axis_name)
            edge = lax.slice_in_dim(t, 0, r, axis=dim)
            lo = edge * (idx > 0).astype(t.dtype)
            hi = edge * (idx < parts - 1).astype(t.dtype)
            return jnp.concatenate([lo, t, hi], axis=dim)

        for axis_name, parts, k, dim in (("h", ph, self.kernel_h, 1),
                                         ("w", pw, self.kernel_w, 2)):
            x = exchange_halo(x, axis_name, parts, k, dim)
            ones = mask_halo(ones, axis_name, parts, k, dim)
        return x, ones

    def sharded_forward(self, params, state, xs, train: bool, aux=None):
        """Placed-grid forward: VALID avg pool over the pre-haloed
        activation, divided by the pre-haloed validity count."""
        import jax
        from jax import lax

        if aux is None:
            return self.forward(params, state, xs, train)
        x, ones = aux
        pw, ph, _pc, _pn = self.pc.dims
        pad_h = 0 if ph > 1 else self.padding_h
        pad_w = 0 if pw > 1 else self.padding_w
        window = (1, self.kernel_h, self.kernel_w, 1)
        strides = (1, self.stride_h, self.stride_w, 1)
        pads = ((0, 0), (pad_h, pad_h), (pad_w, pad_w), (0, 0))
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        y = s / cnt
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def placement_signature(self):
        return (self.kernel_h, self.kernel_w, self.stride_h, self.stride_w,
                self.padding_h, self.padding_w, self.pool_type, self.relu)

    def placed_local(self) -> bool:
        # point-local exactly when no spatial halos are needed
        pw, ph, _pc, _pn = self.pc.dims
        return pw == 1 and ph == 1

    def point_placeable(self) -> bool:
        # Set-family dispatch computes each point from the FULL
        # (replicated) input: halo rows are static slices, boundary
        # semantics are exact via fill values (-inf for MAX — lifting
        # the block/stride families' AVG-only restriction — zeros +
        # validity count for AVG).  Any stride/kernel/padding.
        return True

    def point_forward(self, params, state, xs, idx, sizes, train):
        """One grid point from the full input: pad with the pool's
        neutral fill, slice the fixed-size halo window, reduce VALID.
        AVG divides by the count of valid (un-padded) positions —
        identical to the canonical forward's semantics."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        (x,) = xs
        _, oh, ow, _ = self.output.shape
        pn, pcc = sizes.get("n", 1), sizes.get("c", 1)
        ph, pw = sizes.get("h", 1), sizes.get("w", 1)
        if pn > 1:
            bs = x.shape[0] // pn
            x = x[idx["n"] * bs:(idx["n"] + 1) * bs]
        if pcc > 1:
            cs = x.shape[3] // pcc
            x = x[..., idx["c"] * cs:(idx["c"] + 1) * cs]
        if ph == 1 and pw == 1:
            res, _ = self.forward(params, {}, [x], train)
            return (res,), {}
        pads2 = ((0, 0), (self.padding_h, self.padding_h),
                 (self.padding_w, self.padding_w), (0, 0))
        fill = -jnp.inf if self.pool_type == POOL_MAX else 0.0
        ones = jnp.pad(jnp.ones_like(x), pads2)
        x = jnp.pad(x, pads2, constant_values=fill)
        oh_l, ow_l = oh // ph, ow // pw
        h0 = idx["h"] * oh_l * self.stride_h
        hl = (oh_l - 1) * self.stride_h + self.kernel_h
        w0 = idx["w"] * ow_l * self.stride_w
        wl = (ow_l - 1) * self.stride_w + self.kernel_w
        x = x[:, h0:h0 + hl, w0:w0 + wl, :]
        ones = ones[:, h0:h0 + hl, w0:w0 + wl, :]
        window = (1, self.kernel_h, self.kernel_w, 1)
        strides = (1, self.stride_h, self.stride_w, 1)
        vp = ((0, 0),) * 4
        if self.pool_type == POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, vp)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, vp)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, vp)
            y = s / cnt
        if self.relu:
            y = jax.nn.relu(y)
        return (y,), {}

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", "h", "w", "c")]

    def _use_pallas(self, x) -> bool:
        """Route single-device LARGE max pools through the Pallas kernel
        pair (ops/pallas/maxpool.py): backward reads dy + a selection
        plane instead of running XLA's unvectorized select_and_scatter,
        and the pool input drops out of the VJP residuals.  Small deep
        pools (and multi-device grids) keep the XLA path: measured on the
        compiled Inception step, XLA's fwd reduce_window there rides
        producer fusions for ~free, which a standalone kernel pass cannot
        beat (see the maxpool module docstring).

        AVG pools with exactly-tiling windows (stride == kernel, or the
        global pool) route through ops/pallas/avgpool.py under their own
        gate — there the backward is a pure block upsample of dy."""
        if len(self.pc.devices) > 1 or any(d != 1 for d in self.pc.dims):
            return False
        _, h, w, _ = self.inputs[0].shape
        if self.pool_type == POOL_AVG:
            from flexflow_tpu.ops.pallas import avgpool_enabled
            from flexflow_tpu.ops.pallas.avgpool import supported as avg_ok

            return (avgpool_enabled()
                    and avg_ok(self.kernel_h, self.kernel_w, self.stride_h,
                               self.stride_w, self.padding_h, self.padding_w,
                               h, w))
        from flexflow_tpu.ops.pallas import (maxpool_cost_gated,
                                             maxpool_enabled)
        from flexflow_tpu.ops.pallas.maxpool import (
            roofline_predicted_win_ms, supported)

        if not (maxpool_enabled()
                and supported(self.kernel_h, self.kernel_w, self.stride_h,
                              self.stride_w, self.padding_h,
                              self.padding_w, self.pool_type)):
            return False
        if maxpool_cost_gated():
            # --pallas auto: the per-geometry HBM roofline predictor
            # replaces the old min(h, w) >= 48 size guess — route only
            # when pricing BOTH the backward win and the forward
            # sel-plane pass comes out ahead
            nb, hb, wb, cb = self.inputs[0].shape
            from flexflow_tpu.sim.cost_model import dtype_bytes as _db

            return roofline_predicted_win_ms(
                nb, hb, wb, cb, self.kernel_h, self.padding_h,
                _db(str(self.inputs[0].dtype))) > 0.0
        return True

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp
        from jax import lax

        (x,) = xs
        if self._use_pallas(x):
            if self.pool_type == POOL_AVG:
                from flexflow_tpu.ops.pallas.avgpool import avgpool2d

                return avgpool2d(x, self.kernel_h, self.kernel_w,
                                 self.stride_h, self.stride_w,
                                 self.padding_h, self.padding_w,
                                 relu=self.relu), state
            from flexflow_tpu.ops.pallas.maxpool import maxpool2d

            return maxpool2d(x, self.kernel_h, self.kernel_w,
                             self.padding_h, self.padding_w,
                             relu=self.relu), state
        window = (1, self.kernel_h, self.kernel_w, 1)
        strides = (1, self.stride_h, self.stride_w, 1)
        pads = ((0, 0), (self.padding_h, self.padding_h),
                (self.padding_w, self.padding_w), (0, 0))
        if self.pool_type == POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            ones = jnp.ones_like(x)
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            y = s / cnt
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def local_clone(self, pc: ParallelConfig):
        pw, ph, pc_, pn = pc.dims
        n, h, w, c = self.inputs[0].shape
        if n % pn or h % ph or w % pw or c % pc_:
            return None
        t = Tensor((n // pn, h // ph, w // pw, c // pc_))
        return Pool2D(self.name, ParallelConfig((1, 1, 1, 1), (0,)), t,
                      self.kernel_h, self.kernel_w, self.stride_h,
                      self.stride_w, self.padding_h, self.padding_w,
                      self.pool_type, self.relu)

    def flops_per_sample(self) -> float:
        _, oh, ow, c = self.output.shape
        return float(oh * ow * c * self.kernel_h * self.kernel_w)
