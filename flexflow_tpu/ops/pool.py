"""Pool2D (reference: pool_2d.cu, cudnnPoolingForward/Backward).

``lax.reduce_window`` max/avg in NHWC; the {w,h,c,n} grid shards the
activation, and XLA handles window halos under spatial partitioning.
Defaults mirror the reference API: ``pool2d(..., POOL_MAX, relu=True)``
(model.h:133-139, pool_2d.cu:50-56)."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig

POOL_MAX = "max"
POOL_AVG = "avg"


class Pool2D(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int,
                 pool_type: str = POOL_MAX, relu: bool = True):
        super().__init__(name, pc, [input])
        assert input.ndim == 4
        n, h, w, c = input.shape
        self.kernel_h, self.kernel_w = kernel_h, kernel_w
        self.stride_h, self.stride_w = stride_h, stride_w
        self.padding_h, self.padding_w = padding_h, padding_w
        self.pool_type = pool_type
        self.relu = relu
        out_h = 1 + (h + 2 * padding_h - kernel_h) // stride_h
        out_w = 1 + (w + 2 * padding_w - kernel_w) // stride_w
        self.output = Tensor((n, out_h, out_w, c), input.dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "h", "w", "c")

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        pc = pc or self.pc
        if pc.dims[:3] != (1, 1, 1):
            return None  # batch-only inner grids (as Conv2D)
        return [P("n", None, None, None)]

    def placement_signature(self):
        return (self.kernel_h, self.kernel_w, self.stride_h, self.stride_w,
                self.padding_h, self.padding_w, self.pool_type, self.relu)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", "h", "w", "c")]

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp
        from jax import lax

        (x,) = xs
        window = (1, self.kernel_h, self.kernel_w, 1)
        strides = (1, self.stride_h, self.stride_w, 1)
        pads = ((0, 0), (self.padding_h, self.padding_h),
                (self.padding_w, self.padding_w), (0, 0))
        if self.pool_type == POOL_MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        else:
            ones = jnp.ones_like(x)
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            y = s / cnt
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def local_clone(self, pc: ParallelConfig):
        pw, ph, pc_, pn = pc.dims
        n, h, w, c = self.inputs[0].shape
        if n % pn or h % ph or w % pw or c % pc_:
            return None
        t = Tensor((n // pn, h // ph, w // pw, c // pc_))
        return Pool2D(self.name, ParallelConfig((1, 1, 1, 1), (0,)), t,
                      self.kernel_h, self.kernel_w, self.stride_h,
                      self.stride_w, self.padding_h, self.padding_w,
                      self.pool_type, self.relu)

    def flops_per_sample(self) -> float:
        _, oh, ow, c = self.output.shape
        return float(oh * ow * c * self.kernel_h * self.kernel_w)
