"""Op / Tensor base abstractions.

Reference equivalents: ``Tensor`` (model.h:85-89) and ``Op``
(model.h:101-119).  Differences by design:

  * a Tensor here is *symbolic* (shape/dtype/producer); concrete values flow
    through the functional ``forward`` — there are no regions or partitions
    to materialize, XLA/GSPMD owns physical layout;
  * ``Op.forward`` is pure: ``(params, state, inputs) -> (output, state)``.
    backward() and update() have no per-op code — they are jax.grad plus the
    optimizer, with cross-replica reductions inserted by GSPMD (the role of
    the reference's per-op backward tasks and ``updateGAS``,
    cuda_helper.cu:57-71);
  * activations use NHWC (TPU/MXU-preferred), while the strategy grid keeps
    the reference's (w, h, c, n) dim order (conv_2d.cu:69-75) for
    strategy-file compatibility — the mapping lives in ``output_spec``.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.strategy import ParallelConfig

_tensor_ids = itertools.count()


class Tensor:
    """Symbolic tensor: static shape + dtype + producing op (model.h:85-89
    analog; ``adim`` -> shape, region/part -> sharding owned by the op)."""

    def __init__(self, shape: Tuple[int, ...], dtype: str = "float32",
                 producer: Optional["Op"] = None, name: str = ""):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = dtype
        self.producer = producer
        self.name = name
        self.tid = next(_tensor_ids)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def size(self) -> int:
        return math.prod(self.shape)

    def __repr__(self):
        p = self.producer.name if self.producer else "input"
        return f"Tensor(name={self.name!r}, shape={self.shape}, from={p})"


def point_slice(arr, spec, sizes, idx):
    """Static slice of one grid point's block of ``arr`` per its
    PartitionSpec (single-axis-or-None entries — the set-family
    eligibility bar, parallel/placement.py _set_eligible).  ``sizes``
    maps axis name -> parts, ``idx`` maps axis name -> this point's
    index."""
    entries = tuple(spec) + (None,) * (arr.ndim - len(tuple(spec)))
    sl = []
    for d, e in enumerate(entries):
        parts = sizes.get(e, 1) if e is not None else 1
        if parts == 1:
            sl.append(slice(None))
        else:
            n = arr.shape[d] // parts
            sl.append(slice(idx[e] * n, (idx[e] + 1) * n))
    return arr[tuple(sl)]


def exchange_halo(x, axis_name: str, parts: int, k: int, dim: int):
    """Borrow the (k-1)/2 edge rows of each neighbor along mesh axis
    ``axis_name`` via ppermute and concatenate them onto tensor dim
    ``dim``.  Boundary shards receive ppermute's zeros — the zero padding
    of SAME-padded convs/pools.  Shared by every placed-grid op that
    needs halos (Conv2D, Pool2D), so boundary semantics can never
    diverge.  Must run OUTSIDE placement-group branch switches (see
    Op.placed_prelude)."""
    import jax.numpy as jnp
    from jax import lax

    r = (k - 1) // 2
    if r == 0 or parts == 1:
        return x
    fwd = [(i, i + 1) for i in range(parts - 1)]
    bwd = [(i + 1, i) for i in range(parts - 1)]
    lo = lax.ppermute(
        lax.slice_in_dim(x, x.shape[dim] - r, x.shape[dim], axis=dim),
        axis_name, fwd)
    hi = lax.ppermute(lax.slice_in_dim(x, 0, r, axis=dim),
                      axis_name, bwd)
    return jnp.concatenate([lo, x, hi], axis=dim)


class Op:
    """Base operator: named, with inputs, one output, a ParallelConfig, and
    a pure functional forward.  (model.h:101-119 analog.)"""

    #: mesh axis names for this op's grid, innermost (grid dim 0) first;
    #: subclasses override, e.g. ("w", "h", "c", "n") for 4-D CNN ops.
    AXIS_NAMES: Tuple[str, ...] = ("n",)

    def __init__(self, name: str, pc: ParallelConfig,
                 inputs: Sequence[Tensor]):
        if len(pc.dims) != len(self.AXIS_NAMES):
            raise ValueError(
                f"op {name!r}: ParallelConfig rank {pc.ndims} does not match "
                f"op grid rank {len(self.AXIS_NAMES)} ({self.AXIS_NAMES})"
            )
        self.name = name
        self.pc = pc
        self.inputs: List[Tensor] = list(inputs)
        self.output: Tensor = None  # set by subclass
        #: extra outputs (e.g. LSTM hy/cy); forward then returns a tuple
        self.outputs: List[Tensor] = None
        #: params-dict key; ops sharing a key share weights (the reference's
        #: SharedVariable across chunk ops, nmt/rnn.h:37-51) — the first op
        #: with a key initializes, gradients sum automatically in jax.grad
        self.param_key: str = name

    # ---- parameters ----------------------------------------------------

    def init_params(self, rng) -> Dict:
        """Init trainable params (reference: per-op INIT_PARA tasks, e.g.
        conv_2d.cu:374-419). {} for parameterless ops."""
        return {}

    def init_state(self) -> Dict:
        """Non-trainable state (e.g. batch-norm running stats)."""
        return {}

    # ---- compute -------------------------------------------------------

    def forward(self, params: Dict, state: Dict, xs: List, train: bool):
        """Pure forward. Returns (output, new_state)."""
        raise NotImplementedError

    # ---- sharding ------------------------------------------------------

    def output_spec(self):
        """PartitionSpec of the output over AXIS_NAMES."""
        raise NotImplementedError

    def output_specs(self) -> List:
        """One spec per output (multi-output ops override)."""
        return [self.output_spec()]

    def all_outputs(self) -> List[Tensor]:
        """Every output tensor (the single ``output`` unless the op sets
        ``outputs``)."""
        return self.outputs if self.outputs else [self.output]

    def param_specs(self) -> Dict:
        """PartitionSpec per param leaf (same tree structure as
        init_params)."""
        return {}

    # ---- explicit placement hooks (parallel/placement.py) --------------

    def input_specs(self, pc: "ParallelConfig" = None):
        """PartitionSpec per input over AXIS_NAMES, for executing this op
        under an explicit device-subset placement (shard_map group
        execution).  ``pc`` defaults to the op's own config; the strategy
        search passes candidates to ask whether a grid is placeable.
        None -> op does not support placed execution (under that grid)."""
        return None

    def placement_signature(self):
        """Hyperparameters determining this op's computation beyond its
        input/output shapes.  Two ops may share a placement group (execute
        concurrently on disjoint device subsets) only when their signatures
        match.  None -> op does not support placed execution."""
        return None

    def placed_prelude(self, xs: List, train: bool):
        """The COLLECTIVE part of placed execution, run OUTSIDE the
        placement group's branch switch (collectives inside lax.switch
        branches are illegal SPMD — non-owning device blocks would never
        reach them; member inputs are replicated over the group axis, so
        the prelude is uniform across blocks and therefore legal).
        Returns an aux value handed to :meth:`sharded_forward`.  Default:
        nothing to exchange."""
        return None

    def sharded_forward(self, params, state, xs: List, train: bool,
                        aux=None):
        """Forward as executed INSIDE a placement-group shard_map branch,
        where the op's grid axes (AXIS_NAMES with pc.dims > 1) are live
        mesh axes.  MUST be collective-free (see placed_prelude — Conv2D's
        halo exchange and BatchNorm's cross-shard statistics live there).
        Default: the plain forward."""
        return self.forward(params, state, xs, train)

    def placed_local(self) -> bool:
        """True when this op's placed execution under ITS grid is point-
        local (no collective prelude; sharded_forward == forward) — the
        eligibility bar for set-family per-device dispatch
        (parallel/placement.py).  Ops that don't override the placed
        hooks are local by construction; overriders refine per grid
        (e.g. conv/pool: spatial parts == 1)."""
        cls = type(self)
        return (cls.placed_prelude is Op.placed_prelude
                and cls.sharded_forward is Op.sharded_forward)

    def point_placeable(self) -> bool:
        """Can this op execute as per-device grid POINTS in a set-family
        placement group (parallel/placement.py _run_group_set)?  The
        runner replicates operands, so a point computes from the FULL
        inputs — an op overriding :meth:`point_forward` may slice
        arbitrary windows (halos WITHOUT collectives, round 5: the full
        input is available on every device, so the neighbor exchange
        that gates block/stride spatial placement is just a static
        slice here).  Default: the point-local bar (the round-4
        behavior)."""
        return self.placed_local()

    def point_forward(self, params, state, xs, idx, sizes, train):
        """One grid point's computation from FULL (replicated) operands:
        slice + compute, returning ``(tuple of this point's output
        blocks, new state dict)``.  ``params`` (and ``state``) arrive
        already point-sliced; ``idx``/``sizes`` map axis name -> point
        index / parts.  Default: point-slice the inputs by input_specs
        and run the plain forward — correct for point-local ops; ops
        with neighborhood dependencies (spatial conv/pool) override to
        slice halo windows, stateful ops (BatchNorm) to compute global
        statistics from the full input."""
        xs_pt = [point_slice(x, s, sizes, idx)
                 for x, s in zip(xs, self.input_specs())]
        res, new_state = self.forward(params, state, xs_pt, train)
        return (res if isinstance(res, tuple) else (res,)), new_state

    def state_specs(self):
        """PartitionSpec per state leaf for PLACED execution (state
        stacked over the placement-group axis like params).  None -> a
        stateful op cannot execute placed (the round-2 exclusion);
        stateless ops return {}."""
        return None if self.init_state() else {}

    def regrid_input_specs(self):
        """PartitionSpec per input (over AXIS_NAMES, under ``self.pc``)
        that this op's compute wants its inputs in — used by FFModel.apply
        to decompose producer->consumer grid changes into single-axis-move
        resharding steps GSPMD lowers without full rematerialization (the
        reference's implicit repartitioning, conv_2d.cu:171-208).  None ->
        no preference (GSPMD chooses); a None entry skips that input."""
        return None

    def output_sharding(self, machine):
        return machine.sharding(self.pc, self.AXIS_NAMES, self.output_spec())

    def validate_partitioning(self):
        """Grid dims must divide the tensor dims they partition — the
        equivalent of the reference's disjoint/complete partition asserts
        (conv_2d.cu:108-109).  Spatial (h, w) dims may split UNEVENLY
        (parts <= extent): XLA pads the short shard, mirroring the
        reference's restriction transform (conv_2d.cu:95-113) — this is
        what admits 2-way splits of Inception's 35/17 extents."""
        sizes = dict(zip(self.AXIS_NAMES, self.pc.dims))
        for t, spec in zip(self.all_outputs(), self.output_specs()):
            if spec is None:
                continue
            for d, entry in enumerate(spec):
                if entry is None:
                    continue
                axes = entry if isinstance(entry, tuple) else (entry,)
                parts = 1
                for a in axes:
                    parts *= sizes.get(a, 1)
                if t.shape[d] % parts == 0:
                    continue
                from flexflow_tpu.strategy import uneven_spatial_ok

                if all(a in ("h", "w") for a in axes) \
                        and uneven_spatial_ok(t.shape[d], parts):
                    continue  # uneven spatial split, padded by XLA
                raise ValueError(
                    f"op {self.name!r}: output dim {d} of size "
                    f"{t.shape[d]} not divisible by its partition "
                    f"count {parts} (grid {self.pc.dims})")

    def param_shardings(self, machine) -> Dict:
        """Shardings for placing params as jit inputs (canonical device
        assignment; see MachineModel.input_sharding)."""
        return {
            k: machine.input_sharding(self.pc, self.AXIS_NAMES, spec)
            for k, spec in self.param_specs().items()
        }

    def local_clone(self, pc: ParallelConfig):
        """A new op instance at *shard-local* shapes under ``pc`` — what one
        device computes.  Used by MeasuredCostModel to time real shard work
        (the reference measures each partition count the same way,
        scripts/cnn.h).  None -> analytic fallback."""
        return None

    # ---- cost model hooks (consumed by the simulator) ------------------

    def cost_signature(self) -> tuple:
        """Extra compute-determining hyperparameters that do NOT appear in
        input/output shapes (e.g. MoE expert count / hidden width).  Folded
        into MeasuredCostModel's cache key so ops with identical shapes but
        different internal work are never conflated."""
        return ()

    def flops_per_sample(self) -> float:
        """Forward FLOPs per sample (fwd+bwd modeled as 3x by the sim)."""
        return 0.0

    def shard_flops_fwd(self, pc: ParallelConfig):
        """Forward FLOPs of ONE shard under ``pc``, for ops whose work does
        not divide uniformly over the grid (terms sharded over different
        axes).  None -> flops_per_sample * batch / num_parts."""
        return None

    def param_bytes(self) -> int:
        return 0

    def __repr__(self):
        return (f"{type(self).__name__}(name={self.name!r}, grid={self.pc.dims}, "
                f"out={self.output.shape if self.output else None})")
