"""Softmax + cross-entropy loss (fused), data-parallel over the batch.

Reference: softmax.cu — 1-D grid over batch only (softmax.cu:19-26),
cudnnSoftmaxForward, and a backward that is the fused CE gradient
(probs - onehot)/batch (softmax.cu:210-217, 271-278).

TPU-native: log-softmax + NLL with jax.grad providing the same fused
gradient.  Normalization fix (SURVEY.md §7 "hard parts"): the reference
scales by 1/local-batch per shard; we define the loss as the mean over the
*global* batch, which is shard-count invariant — the property the
strategy-invariance tests rely on.

Unlike the reference (which never reports loss — SURVEY.md §5), forward also
returns the scalar loss for metrics.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Softmax(Op):
    AXIS_NAMES = ("n",)
    is_loss = True

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor):
        super().__init__(name, pc, [input])
        assert input.ndim == 2
        self.num_classes = input.shape[1]
        self.output = Tensor(input.shape, input.dtype, self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None)]

    def forward(self, params, state, xs: List, train: bool):
        import jax

        (x,) = xs
        return jax.nn.log_softmax(x.astype("float32"), axis=-1), state

    def loss(self, log_probs, labels):
        """Mean NLL over the global batch; labels are int class ids."""
        import jax.numpy as jnp

        nll = -jnp.take_along_axis(log_probs, labels[:, None], axis=1)
        return jnp.mean(nll)

    def flops_per_sample(self) -> float:
        return 5.0 * self.num_classes
