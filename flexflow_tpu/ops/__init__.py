"""Operator library: TPU-native equivalents of the reference's per-op CUDA
files (conv_2d.cu, pool_2d.cu, batch_norm.cu, linear.cu, flat.cu, softmax.cu,
concat.cu, nmt/{embed,lstm,linear,softmax_data_parallel}.cu).

Each op is a factory + pure-functional forward; partitioning is expressed as
a GSPMD sharding derived from the op's ParallelConfig rather than Legion
index partitions, and backward/update paths are derived by jax.grad + XLA
collectives rather than hand-written leaf tasks.
"""

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.ops.conv import Conv2D
from flexflow_tpu.ops.pool import Pool2D
from flexflow_tpu.ops.norm import BatchNorm
from flexflow_tpu.ops.linear import Linear
from flexflow_tpu.ops.flat import Flat
from flexflow_tpu.ops.softmax import Softmax
from flexflow_tpu.ops.concat import Concat

__all__ = [
    "Op", "Tensor", "Conv2D", "Pool2D", "BatchNorm", "Linear", "Flat",
    "Softmax", "Concat",
]
