"""Non-overlapping average-pool backward as a Pallas TPU kernel (+ plain
XLA forward).

Why this kernel exists: after the maxpool round, the pool family's
remaining "raw" (unvectorized) residue in the step profile is the AVG
side — Inception's global ``AveragePool 8x8`` tail over (8, 8, 2048) and
any stride==kernel tiling.  For exactly the *non-overlapping* geometries
(stride == kernel, padding 0 — which includes the global pool) every
input position belongs to one window, so the backward collapses from
XLA's padded window-transpose into a pure block upsample:

    dx[h, w] = dy[h // kh, w // kw] / (kh * kw)

one VMEM pass, no windows, no pad arithmetic.  The FORWARD stays plain
XLA (``reduce_window`` add is fully fusible — the maxpool lesson: a
standalone kernel forward loses the producer fusion, see
ops/pallas/maxpool.py).  The fused-ReLU variant masks dy by ``y > 0``
in-kernel from the pooled-output residual (OH x OW x C — tiny), so the
pool *input* never enters the VJP residuals.

Like maxpool, kernel operands are processed in **(H, W, C, N)** logical
order — N on lanes, C on sublanes; the bracketing transposes are layout
bitcasts on TPU for these N-minor conv activations — and the kernel runs
compiled via Mosaic on TPU, interpreter mode elsewhere so the CPU suite
exercises the identical code path (tests/test_pallas.py parity vs
lax.reduce_window autodiff).  Gated opt-in (FLEXFLOW_TPU_AVGPOOL=1,
ops.pallas.avgpool_enabled): an attribution candidate pending an
end-to-end TPU measurement — the maxpool experience says per-op wins can
vanish inside fusions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def supported(kh, kw, sh, sw, ph, pw, h, w, pool_type="avg") -> bool:
    """Static gate: unpadded geometries whose windows tile the input
    exactly — stride == kernel with no remainder rows, or the global
    pool (kernel == extent, any stride; the single window makes the
    stride irrelevant).  Everything else (overlap, remainders, padding)
    needs the count-of-valid-positions denominator and window-transpose
    scatter, and stays on the XLA path."""
    if pool_type != "avg" or (ph, pw) != (0, 0):
        return False
    if (kh, kw) == (h, w):
        return True  # global pool: one window, output 1x1
    return (sh, sw) == (kh, kw) and h % kh == 0 and w % kw == 0


def _ceil(a, b):
    return -(-a // b)


def _bwd_kernel(*refs, OH, OW, kh, kw, scale, relu):
    if relu:
        g_ref, y_ref, dx_ref = refs
    else:
        g_ref, dx_ref = refs
    g = g_ref[...].astype(jnp.float32)                 # (OH, OW, bc, bn)
    if relu:
        # compares run in f32 with full-array operands (the 32-bit
        # vector-compare constraint, see maxpool's module docstring)
        g = jnp.where(y_ref[...].astype(jnp.float32) > 0.0, g,
                      jnp.zeros_like(g))
    g = g * scale
    bc, bn = g.shape[2], g.shape[3]
    # block upsample: every input position is in exactly ONE window, so
    # dx is dy broadcast over the (kh, kw) tile — a major-dim broadcast +
    # reshape, both supported by Mosaic (no strided scatter)
    up = jnp.broadcast_to(g[:, None, :, None], (OH, kh, OW, kw, bc, bn))
    dx_ref[...] = up.reshape(OH * kh, OW * kw, bc, bn).astype(dx_ref.dtype)


def _pick_blocks(H, W, C, N):
    """N on lanes (128), C on sublanes; the dx block spans the full
    spatial extent (these geometries are small — the zoo's candidates
    are the 8x8 global tail and coarse tilings), so bc is budgeted to
    keep the block under the scoped-VMEM default."""
    bn = min(N, 128)
    cap = max(8, (6 * 1024 * 1024) // (H * W * bn * 4))
    return min(C, cap - cap % 8), bn


@functools.lru_cache(maxsize=None)
def _make_avgpool(shape, dtype_name, kh, kw, relu, interpret):
    N, H, W, C = shape
    OH, OW = H // kh, W // kw
    scale = 1.0 / float(kh * kw)
    bc, bn = _pick_blocks(H, W, C, N)
    gn, gc = _ceil(N, bn), _ceil(C, bc)

    bwd_kernel = functools.partial(_bwd_kernel, OH=OH, OW=OW, kh=kh, kw=kw,
                                   scale=scale, relu=relu)

    def bmap(ni, ci):
        return (0, 0, ci, ni)

    def bwd_call(gt, yt):
        dy_spec = pl.BlockSpec((OH, OW, bc, bn), bmap)
        return pl.pallas_call(
            bwd_kernel,
            grid=(gn, gc),
            in_specs=[dy_spec, dy_spec] if relu else [dy_spec],
            out_specs=pl.BlockSpec((H, W, bc, bn), bmap),
            out_shape=jax.ShapeDtypeStruct((H, W, C, N), gt.dtype),
            interpret=interpret,
        )(*((gt, yt) if relu else (gt,)))

    def fwd_xla(x):
        """Plain XLA: with padding 0 every window holds kh*kw valid
        positions, so the canonical sum/count divide is a constant
        scale.  Fully fusible — rides the producer fusions."""
        s = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, kh, kw, 1), (1, kh, kw, 1),
            ((0, 0),) * 4)
        y = s * jnp.asarray(scale, s.dtype)
        if relu:
            y = jax.nn.relu(y)
        # stored transposed so the backward reads it with N on lanes
        return y, jnp.transpose(y, (1, 2, 3, 0))

    @jax.custom_vjp
    def pool(x):
        return fwd_xla(x)[0]

    if relu:
        def pool_fwd(x):
            y, yt = fwd_xla(x)
            return y, (yt,)

        def pool_bwd(res, g):
            (yt,) = res
            gt = jnp.transpose(g, (1, 2, 3, 0))        # (OH, OW, C, N)
            return (jnp.transpose(bwd_call(gt, yt), (3, 0, 1, 2)),)
    else:
        def pool_fwd(x):
            return fwd_xla(x)[0], ()

        def pool_bwd(res, g):
            gt = jnp.transpose(g, (1, 2, 3, 0))
            return (jnp.transpose(bwd_call(gt, None), (3, 0, 1, 2)),)

    pool.defvjp(pool_fwd, pool_bwd)
    return pool


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def avgpool2d(x, kh, kw, sh, sw, ph, pw, relu=False, interpret=None):
    """Non-overlapping average pool (optionally fused ReLU) of NHWC
    ``x``; numerically identical to the canonical sum/count
    ``reduce_window`` pair under jax autodiff for the supported
    (exact-tiling) geometries."""
    n, h, w, c = x.shape
    assert supported(kh, kw, sh, sw, ph, pw, h, w)
    if (kh, kw) == (h, w):
        kh, kw = h, w  # global pool: stride is irrelevant, tile is H x W
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_avgpool(tuple(x.shape), x.dtype.name, kh, kw,
                      bool(relu), interpret)
    return f(x)
