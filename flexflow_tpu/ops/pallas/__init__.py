"""Pallas TPU kernels for the hot ops.

These are the hand-scheduled compute paths of the framework (the analog of
the reference's hand-written CUDA kernels, e.g. nmt/embed.cu's gather /
scatter-add and the cuDNN leaf tasks): XLA fuses most elementwise work into
the MXU matmuls on its own, so Pallas is reserved for the ops where manual
VMEM tiling beats the compiler — attention's O(S^2) score matrix, which a
flash kernel never materializes in HBM.

Kernels run compiled (Mosaic) on TPU and in interpreter mode elsewhere, so
the same code path is exercised by the CPU test suite.

Routing policy (round 13): one ``--pallas auto|on|off`` switch
(:func:`set_policy`, wired from FFConfig by FFModel) replaces ad-hoc
per-kernel defaults.  ``auto`` routes a kernel only when its
``supported()`` gate holds AND its HBM cost model predicts a win on the
concrete geometry (e.g. maxpool.roofline_predicted_win_ms); ``on``
forces every supported kernel; ``off`` keeps the stock XLA paths.  The
per-kernel env vars (``FLEXFLOW_TPU_{FLASH,MAXPOOL,AVGPOOL,BNRELU}``
= 0/1) still override the policy for that one kernel — the test suite's
and single-experiment escape hatch.
"""

import os

from flexflow_tpu.ops.pallas.flash_attention import flash_attention

_POLICY = "auto"


def set_policy(policy: str) -> None:
    """Install the process-wide kernel routing policy (FFConfig.pallas).
    Validates eagerly — a typo'd policy fails at model construction, not
    silently at the first pool."""
    global _POLICY
    if policy not in ("auto", "on", "off"):
        raise ValueError(f"pallas policy must be auto|on|off, "
                         f"got {policy!r}")
    _POLICY = policy


def get_policy() -> str:
    return _POLICY


def _env_gate(name: str):
    """Tri-state per-kernel env override: True / False / None (defer to
    the policy)."""
    v = os.environ.get(name, "").lower()
    if v in ("0", "false"):
        return False
    if v in ("1", "true"):
        return True
    return None


def flash_enabled() -> bool:
    """Policy gate for the flash kernel: under ``auto``, on on TPU
    (compiled via Mosaic — the measured-win kernel of round 3), off
    elsewhere (interpret mode is for tests, too slow for training).
    FLEXFLOW_TPU_FLASH=0/1 overrides."""
    env = _env_gate("FLEXFLOW_TPU_FLASH")
    if env is not None:
        return env
    if _POLICY != "auto":
        return _POLICY == "on"
    import jax

    return jax.default_backend() == "tpu"


def tpu_compiler_params():
    """The pallas-TPU compiler-params class under whichever name this
    jax release exports it (``TPUCompilerParams`` was renamed
    ``CompilerParams``); None when neither exists.  The capability gate
    for kernels that must raise the scoped-VMEM cap (maxpool) and for
    the tests that exercise them — a None here means "skip with a
    reason", not an AttributeError mid-kernel."""
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)


def maxpool_enabled() -> bool:
    """Candidacy gate for the Pallas max-pool backward.  Per-op it beats
    XLA's select_and_scatter ~2x (2.9 vs 5.0 ms on Inception's two big
    pools, compiled-step profile), but end-to-end the swap measures
    inside the run-to-run jitter band or slightly negative (1926-1942 vs
    1946 img/s across three full designs, round 4): the forward sel
    plane costs a second pass over x that XLA's fused reduce_window
    pipeline never pays.  Under ``auto`` the kernel is therefore only a
    CANDIDATE on TPU — Pool2D._use_pallas makes the final call with
    maxpool.roofline_predicted_win_ms on the concrete geometry, which
    prices that sel pass honestly.  ``on`` / FLEXFLOW_TPU_MAXPOOL=1
    force every supported geometry (the measurement escape)."""
    env = _env_gate("FLEXFLOW_TPU_MAXPOOL")
    if env is not None:
        return env
    if _POLICY != "auto":
        return _POLICY == "on"
    import jax

    return jax.default_backend() == "tpu"


def maxpool_cost_gated() -> bool:
    """True when the routing decision should consult the per-geometry
    cost model (policy ``auto`` with no env override); forced modes
    route every supported geometry unconditionally."""
    return _env_gate("FLEXFLOW_TPU_MAXPOOL") is None and _POLICY == "auto"


def avgpool_enabled() -> bool:
    """Policy gate for the Pallas avg-pool backward (ops/pallas/avgpool
    .py — non-overlapping/global geometries only).  No measured or
    modeled win yet (the maxpool experience — per-op 2x, end-to-end
    jitter-band — sets the evidence bar), so ``auto`` keeps it OFF;
    ``on`` / FLEXFLOW_TPU_AVGPOOL=1 force it."""
    env = _env_gate("FLEXFLOW_TPU_AVGPOOL")
    if env is not None:
        return env
    return _POLICY == "on"


def bnrelu_enabled() -> bool:
    """Policy gate for the fused batchnorm-normalize+ReLU kernel pair
    (ops/pallas/bn_act.py): same pending-measurement status as
    avgpool_enabled — ``auto`` keeps it off, ``on`` /
    FLEXFLOW_TPU_BNRELU=1 force it."""
    env = _env_gate("FLEXFLOW_TPU_BNRELU")
    if env is not None:
        return env
    return _POLICY == "on"


__all__ = ["avgpool_enabled", "bnrelu_enabled", "flash_attention",
           "flash_enabled", "get_policy", "maxpool_cost_gated",
           "maxpool_enabled", "set_policy", "tpu_compiler_params"]
