"""Pallas TPU kernels for the hot ops.

These are the hand-scheduled compute paths of the framework (the analog of
the reference's hand-written CUDA kernels, e.g. nmt/embed.cu's gather /
scatter-add and the cuDNN leaf tasks): XLA fuses most elementwise work into
the MXU matmuls on its own, so Pallas is reserved for the ops where manual
VMEM tiling beats the compiler — attention's O(S^2) score matrix, which a
flash kernel never materializes in HBM.

Kernels run compiled (Mosaic) on TPU and in interpreter mode elsewhere, so
the same code path is exercised by the CPU test suite.
"""

import os

from flexflow_tpu.ops.pallas.flash_attention import flash_attention


def flash_enabled() -> bool:
    """Policy gate for the flash kernel: on by default on TPU (compiled via
    Mosaic), off elsewhere (interpret mode is for tests, too slow for
    training).  FLEXFLOW_TPU_FLASH=0/1 overrides."""
    env = os.environ.get("FLEXFLOW_TPU_FLASH", "").lower()
    if env in ("0", "false"):
        return False
    if env in ("1", "true"):
        return True
    import jax

    return jax.default_backend() == "tpu"


def tpu_compiler_params():
    """The pallas-TPU compiler-params class under whichever name this
    jax release exports it (``TPUCompilerParams`` was renamed
    ``CompilerParams``); None when neither exists.  The capability gate
    for kernels that must raise the scoped-VMEM cap (maxpool) and for
    the tests that exercise them — a None here means "skip with a
    reason", not an AttributeError mid-kernel."""
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)


def maxpool_enabled() -> bool:
    """Policy gate for the Pallas max-pool backward: OFF by default.
    Per-op it beats XLA's select_and_scatter ~2x (2.9 vs 5.0 ms on
    Inception's two big pools, compiled-step profile), but end-to-end the
    swap measures inside the run-to-run jitter band or slightly negative
    (1926-1942 vs 1946 img/s across three full designs, round 4): the
    forward sel plane costs a second pass over x that XLA's fused
    reduce_window pipeline never pays.  Kept opt-in
    (FLEXFLOW_TPU_MAXPOOL=1) as the measured-evidence answer to the
    "write the pool kernel" roofline question — see the maxpool module
    docstring and examples/profiles/README.md."""
    return os.environ.get("FLEXFLOW_TPU_MAXPOOL", "").lower() \
        in ("1", "true")


def avgpool_enabled() -> bool:
    """Policy gate for the Pallas avg-pool backward (ops/pallas/avgpool
    .py — non-overlapping/global geometries only): OFF by default, opt-in
    FLEXFLOW_TPU_AVGPOOL=1.  An attribution candidate from the MFU
    waterfall's per-op residue pending an end-to-end TPU measurement —
    the maxpool experience (per-op 2x, end-to-end jitter-band) sets the
    evidence bar for flipping a kernel default."""
    return os.environ.get("FLEXFLOW_TPU_AVGPOOL", "").lower() \
        in ("1", "true")


def bnrelu_enabled() -> bool:
    """Policy gate for the fused batchnorm-normalize+ReLU kernel pair
    (ops/pallas/bn_act.py): OFF by default, opt-in FLEXFLOW_TPU_BNRELU=1.
    Same pending-measurement status as avgpool_enabled."""
    return os.environ.get("FLEXFLOW_TPU_BNRELU", "").lower() \
        in ("1", "true")


__all__ = ["avgpool_enabled", "bnrelu_enabled", "flash_attention",
           "flash_enabled", "maxpool_enabled", "tpu_compiler_params"]
