"""Pallas TPU kernels for the hot ops.

These are the hand-scheduled compute paths of the framework (the analog of
the reference's hand-written CUDA kernels, e.g. nmt/embed.cu's gather /
scatter-add and the cuDNN leaf tasks): XLA fuses most elementwise work into
the MXU matmuls on its own, so Pallas is reserved for the ops where manual
VMEM tiling beats the compiler — attention's O(S^2) score matrix, which a
flash kernel never materializes in HBM.

Kernels run compiled (Mosaic) on TPU and in interpreter mode elsewhere, so
the same code path is exercised by the CPU test suite.
"""

import os

from flexflow_tpu.ops.pallas.flash_attention import flash_attention


def flash_enabled() -> bool:
    """Policy gate for the flash kernel: on by default on TPU (compiled via
    Mosaic), off elsewhere (interpret mode is for tests, too slow for
    training).  FLEXFLOW_TPU_FLASH=0/1 overrides."""
    env = os.environ.get("FLEXFLOW_TPU_FLASH", "").lower()
    if env in ("0", "false"):
        return False
    if env in ("1", "true"):
        return True
    import jax

    return jax.default_backend() == "tpu"


__all__ = ["flash_attention", "flash_enabled"]
