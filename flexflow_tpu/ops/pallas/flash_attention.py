"""Flash attention as Pallas TPU kernels (forward + custom-VJP backward).

The O(S^2) score matrix never leaves VMEM: the kernel streams K/V blocks
through the MXU against a resident Q block, maintaining the numerically
stable running max / denominator (same math as
parallel/ring_attention._stream_block, which is the XLA fallback path).
Backward is the standard flash recomputation: softmax probabilities are
rebuilt per tile from the saved log-sum-exp, so residual memory is O(S)
per row (out + lse) instead of O(S^2).

Layout/tiling (per /opt/skills/guides/pallas_guide.md): grid = (batch*heads,
S_q/block_q, S_k/block_k) with the K dimension innermost, so the
(block_q, d) output block is revisited across K steps and accumulated in
f32 VMEM scratch; blocks default to 512x512 score tiles (measured fastest
on v5e; clamped down for short sequences, always 128-aligned); the running
max/denominator live in (block_q, 128)-lane scratch; per-row lse/delta are
carried as (S, 1) column tensors so no lane<->sublane relayout is needed.
Causal tiles strictly above the diagonal skip their matmuls entirely.

On TPU the kernels compile via Mosaic; elsewhere they run in interpreter
mode, so the identical code path is exercised by the CPU test suite.

This is the framework's hand-written-kernel layer — the role the CUDA leaf
tasks play in the reference (e.g. conv_2d.cu:523-536), applied to the one
op family the reference lacks (attention, SURVEY.md §2.6) where manual VMEM
scheduling beats XLA.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 512
_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _block_mask(q_off, k_off, shape, sk: int, causal: bool):
    """Validity mask for one (block_q, block_k) score tile: mask padded K
    columns (kpos >= sk) and, when causal, future positions."""
    kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    valid = kpos < sk
    if causal:
        qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        valid = jnp.logical_and(valid, qpos >= kpos)
    return valid


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, sk, block_q, block_k):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = pl.program_id(1) * block_q
    k_off = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    live = q_off + block_q - 1 >= k_off if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _block_mask(q_off, k_off, s.shape, sk, causal)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # fully-masked rows keep m = -inf; exp(-inf - -inf) would be nan
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(valid, jnp.exp(s - safe_m), 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
        l_new = l_scr[:, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        m = m_scr[:, 0:1]
        l = jnp.maximum(l_scr[:, 0:1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(jnp.isfinite(m), m + jnp.log(l), _NEG_INF)


def _fwd_call(q, k, v, scale, causal, sk, block_q, block_k, interpret):
    """sk is the UNPADDED key length (mask bound); array shapes are padded."""
    bh, sq, d = q.shape
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               sk=sk, block_q=block_q, block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, sq // block_q, k.shape[1] // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward: recompute p per tile from saved lse; delta = rowsum(do * o)


def _p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_off, k_off,
          scale, sk, causal):
    """Recompute probabilities p and score-gradient ds for one tile."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    valid = _block_mask(q_off, k_off, s.shape, sk, causal)
    lse = lse_ref[0]                     # (block_q, 1)
    safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
    p = jnp.where(valid, jnp.exp(s - safe_lse), 0.0)
    do = do_ref[0]
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0]) * scale
    return p, ds, do, q


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, sk, block_q, block_k):
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    q_off = qi * block_q
    k_off = pl.program_id(1) * block_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[:] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    live = q_off + block_q - 1 >= k_off if causal else True

    @pl.when(live)
    def _compute():
        p, ds, do, q = _p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             q_off, k_off, scale, sk, causal)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, scale, causal, sk, block_q, block_k):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = pl.program_id(1) * block_q
    k_off = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    live = q_off + block_q - 1 >= k_off if causal else True

    @pl.when(live)
    def _compute():
        _, ds, _, _ = _p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            q_off, k_off, scale, sk, causal)
        k = k_ref[0]
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_call(q, k, v, do, lse, delta, scale, causal, sk, block_q, block_k,
              interpret):
    """sk is the UNPADDED key length (mask bound); array shapes are padded."""
    bh, sq, d = q.shape
    sk_p = k.shape[1]
    common = dict(scale=scale, causal=causal, sk=sk,
                  block_q=block_q, block_k=block_k)
    # dk/dv: K blocks outer, Q innermost (accumulated across Q in scratch)
    dkv_spec = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, sk_p // block_k, sq // block_q),
        in_specs=dkv_spec,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, jnp.float32),
            jax.ShapeDtypeStruct(v.shape, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dq_spec = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, sq // block_q, sk_p // block_k),
        in_specs=dq_spec,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op: (B, H, S, d) -> (B, H, Sq, d) float32, differentiable


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _make_flash(q_shape, k_shape, qdt, kdt, vdt, causal, block_q, block_k,
                interpret, with_lse=False):
    """Build a custom-VJP flash op specialized for one static configuration
    (shapes/dtypes/blocks are Python constants closed over by the kernels;
    the VJP residuals are pure arrays).

    With ``with_lse`` the op returns ``(out, lse)`` — the *partial*
    attention form used by ring/context parallelism, where per-chunk
    results are merged by log-sum-exp weighting.  The lse cotangent folds
    into the backward kernels for free: d lse/d s_ij = p_ij, so passing
    ``delta - g_lse`` where the kernels expect ``delta`` yields
    ds = p (dp - delta + g_lse) — no kernel changes."""
    b, h, sq, d = q_shape
    sk = k_shape[2]
    scale = 1.0 / math.sqrt(d)
    if interpret:
        bq = min(block_q, _round_up(sq, 8))
        bk = min(block_k, _round_up(sk, 8))
        d_p = d
    else:
        # on hardware, lane dims (d) want full 128 tiles; clamp blocks so a
        # short sequence is not padded all the way to the default block
        bq = min(block_q, _round_up(sq, 128))
        bk = min(block_k, _round_up(sk, 128))
        d_p = _round_up(d, 128)
    sq_p, sk_p = _round_up(sq, bq), _round_up(sk, bk)

    def prep(x, s_p):
        # (B,H,S,d) -> (B*H, S_pad, d_pad); zero d-columns do not change
        # scores, padded K rows are masked via sk, padded Q rows sliced off
        x = x.reshape(b * h, x.shape[2], d)
        return jnp.pad(x, ((0, 0), (0, s_p - x.shape[1]), (0, d_p - d)))

    def run_fwd(q, k, v):
        qp, kp, vp = prep(q, sq_p), prep(k, sk_p), prep(v, sk_p)
        out, lse = _fwd_call(qp, kp, vp, scale, causal, sk, bq, bk, interpret)
        return out, lse, (qp, kp, vp, lse, out)

    def run_bwd(res, g, g_lse=None):
        qp, kp, vp, lse, out = res
        do = jnp.pad(g.astype(jnp.float32).reshape(b * h, sq, d),
                     ((0, 0), (0, sq_p - sq), (0, d_p - d)))
        do_k = do.astype(qdt)  # kernel operand in the primal compute dtype
        # delta is zero on padded Q rows (do = 0 there), so they contribute
        # nothing to dk/dv even though their lse is arbitrary
        delta = jnp.sum(do * out, axis=-1, keepdims=True)
        if g_lse is not None:
            glse_p = jnp.pad(g_lse.astype(jnp.float32).reshape(b * h, sq, 1),
                             ((0, 0), (0, sq_p - sq), (0, 0)))
            delta = delta - glse_p  # ds = p (dp - delta + g_lse)
        dq, dk, dv = _bwd_call(qp, kp, vp, do_k, lse, delta, scale, causal,
                               sk, bq, bk, interpret)
        return (dq[:, :sq, :d].reshape(b, h, sq, d).astype(qdt),
                dk[:, :sk, :d].reshape(b, h, sk, d).astype(kdt),
                dv[:, :sk, :d].reshape(b, h, sk, d).astype(vdt))

    if not with_lse:

        @jax.custom_vjp
        def flash(q, k, v):
            out, _, _ = run_fwd(q, k, v)
            return out[:, :sq, :d].reshape(b, h, sq, d)

        def flash_fwd(q, k, v):
            out, _, res = run_fwd(q, k, v)
            return out[:, :sq, :d].reshape(b, h, sq, d), res

        def flash_bwd(res, g):
            return run_bwd(res, g)

        flash.defvjp(flash_fwd, flash_bwd)
        return flash

    def unpack(out, lse):
        return (out[:, :sq, :d].reshape(b, h, sq, d),
                lse[:, :sq, 0].reshape(b, h, sq))

    @jax.custom_vjp
    def flash_p(q, k, v):
        out, lse, _ = run_fwd(q, k, v)
        return unpack(out, lse)

    def flash_p_fwd(q, k, v):
        out, lse, res = run_fwd(q, k, v)
        return unpack(out, lse), res

    def flash_p_bwd(res, gs):
        g, g_lse = gs
        return run_bwd(res, g, g_lse)

    flash_p.defvjp(flash_p_fwd, flash_p_bwd)
    return flash_p


def flash_attention(q, k, v, causal=False, block_q=DEFAULT_BLOCK,
                    block_k=DEFAULT_BLOCK, interpret=None):
    """softmax(q kᵀ / sqrt(d) [+ causal mask]) v without materializing the
    score matrix.  q, k, v: (B, H, S, d); returns float32 (B, H, Sq, d)."""
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_flash(tuple(q.shape), tuple(k.shape), q.dtype.name,
                    k.dtype.name, v.dtype.name, bool(causal), block_q,
                    block_k, interpret)
    return f(q, k, v)


def flash_attention_partial(q, k, v, causal=False, block_q=DEFAULT_BLOCK,
                            block_k=DEFAULT_BLOCK, interpret=None):
    """Partial attention over one K/V chunk: returns ``(out, lse)`` where
    ``out`` is the chunk-normalized attention and ``lse`` (B, H, Sq) the
    log-sum-exp of its scores.  Chunks merge exactly via
    :func:`combine_partials` — the building block of the Pallas ring-
    attention path (each ring step attends Q against the resident K/V
    block, then results merge by lse weight).  Differentiable in both
    outputs."""
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_flash(tuple(q.shape), tuple(k.shape), q.dtype.name,
                    k.dtype.name, v.dtype.name, bool(causal), block_q,
                    block_k, interpret, with_lse=True)
    return f(q, k, v)


def combine_partials(o1, lse1, o2, lse2):
    """Merge two chunk-normalized partial attentions by log-sum-exp weight:
    softmax over the union of their key sets.  Fully-masked partials
    (lse = -inf, o = 0) drop out; if both are masked the result is 0."""
    m = jnp.maximum(lse1, lse2)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(lse1 - safe_m), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(lse2 - safe_m), 0.0)
    tot = w1 + w2
    lse = jnp.where(tot > 0, safe_m + jnp.log(jnp.maximum(tot, 1e-30)),
                    _NEG_INF)
    denom = jnp.maximum(tot, 1e-30)[..., None]
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / denom
    return o, lse
