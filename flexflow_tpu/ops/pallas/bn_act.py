"""Fused batch-norm normalize + ReLU as a Pallas TPU kernel pair.

Why this kernel exists: BatchNorm.forward already folds the statistics
and affine into per-channel (inv, shift) f32 vectors and runs the
normalize as ONE compute-dtype elementwise pass (the HBM-bound fold,
ops/norm.py).  What XLA cannot be told is how to schedule the BACKWARD:
the VJP of ``relu(x * inv + shift)`` needs dx plus two per-channel
reductions (d_inv = sum(dy*x), d_shift = sum(dy)), and the profile shows
the reductions splitting off the elementwise producer into separate
passes over x and dy.  The kernel here emits all three outputs from a
single VMEM pass per block — x and dy are read exactly once — with the
ReLU mask recomputed from (x, inv, shift) so the activation ``y`` never
enters the residuals.

Layout: operands are flattened to (M, C) with C on lanes — the natural
C-minor layout of NHWC activations, so the reshape is free — and the
channel vectors ride (1, C) blocks (the TPU 2-D operand requirement).
The grid walks channel blocks outer, row blocks inner; the per-channel
sums accumulate across the inner (sequential) grid steps into a
revisited (1, C) output block.  All math is f32 (32-bit vector
compares), cast once at the stores.

Runs compiled via Mosaic on TPU, interpreter mode elsewhere so the CPU
suite exercises the identical path (tests/test_pallas.py parity vs the
unfused XLA chain under autodiff).  Gated opt-in (FLEXFLOW_TPU_BNRELU=1,
ops.pallas.bnrelu_enabled): an attribution candidate pending an
end-to-end TPU measurement, same honesty bar as maxpool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_bm(M):
    """Largest power-of-two row block (>= 8 sublanes) dividing M."""
    for bm in (1024, 512, 256, 128, 64, 32, 16, 8):
        if M % bm == 0:
            return bm
    return None


def supported(n, h, w, c) -> bool:
    """Static gate: the flattened row count must split into whole row
    blocks — out-of-bounds rows would pollute the channel-sum
    accumulators, so ragged M is refused rather than masked.  (Ragged C
    is fine: garbage lanes stay in garbage lanes and are cropped at the
    store.)"""
    return _pick_bm(n * h * w) is not None


def _ceil(a, b):
    return -(-a // b)


def _fwd_kernel(x_ref, inv_ref, shift_ref, y_ref, *, relu):
    y = x_ref[...].astype(jnp.float32) * inv_ref[...] + shift_ref[...]
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, inv_ref, shift_ref, g_ref, dx_ref, dinv_ref,
                dshift_ref, *, relu):
    mi = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)                 # (bm, bc)
    g = g_ref[...].astype(jnp.float32)
    inv = inv_ref[...]                                 # (1, bc) f32
    if relu:
        # mask recomputed from the residuals — y never materializes
        pre = x * inv + shift_ref[...]
        g = jnp.where(pre > 0.0, g, jnp.zeros_like(g))
    dx_ref[...] = (g * inv).astype(dx_ref.dtype)
    dinv_p = jnp.sum(g * x, axis=0, keepdims=True)
    dshift_p = jnp.sum(g, axis=0, keepdims=True)

    # the (1, bc) sum blocks are revisited across the inner (row) grid
    # steps — sequential on TPU — accumulating the partials in place
    @pl.when(mi == 0)
    def _init():
        dinv_ref[...] = dinv_p
        dshift_ref[...] = dshift_p

    @pl.when(mi > 0)
    def _acc():
        dinv_ref[...] += dinv_p
        dshift_ref[...] += dshift_p


@functools.lru_cache(maxsize=None)
def _make_bn_act(M, C, dtype_name, relu, interpret):
    dt = jnp.dtype(dtype_name)
    bm = _pick_bm(M)
    assert bm is not None
    bc = min(C, 128)
    gm, gc = M // bm, _ceil(C, bc)

    fwd_kernel = functools.partial(_fwd_kernel, relu=relu)
    bwd_kernel = functools.partial(_bwd_kernel, relu=relu)

    def xmap(ci, mi):
        return (mi, ci)

    def cmap(ci, mi):
        return (0, ci)

    x_spec = pl.BlockSpec((bm, bc), xmap)
    c_spec = pl.BlockSpec((1, bc), cmap)

    def fwd_call(x2, inv2, shift2):
        return pl.pallas_call(
            fwd_kernel,
            grid=(gc, gm),
            in_specs=[x_spec, c_spec, c_spec],
            out_specs=x_spec,
            out_shape=jax.ShapeDtypeStruct((M, C), dt),
            interpret=interpret,
        )(x2, inv2, shift2)

    def bwd_call(x2, inv2, shift2, g2):
        return pl.pallas_call(
            bwd_kernel,
            grid=(gc, gm),
            in_specs=[x_spec, c_spec, c_spec, x_spec],
            out_specs=[x_spec, c_spec, c_spec],
            out_shape=[jax.ShapeDtypeStruct((M, C), dt),
                       jax.ShapeDtypeStruct((1, C), jnp.float32),
                       jax.ShapeDtypeStruct((1, C), jnp.float32)],
            interpret=interpret,
        )(x2, inv2, shift2, g2)

    @jax.custom_vjp
    def f(x2, inv2, shift2):
        return fwd_call(x2, inv2, shift2)

    def f_fwd(x2, inv2, shift2):
        return fwd_call(x2, inv2, shift2), (x2, inv2, shift2)

    def f_bwd(res, g2):
        x2, inv2, shift2 = res
        return bwd_call(x2, inv2, shift2, g2)

    f.defvjp(f_fwd, f_bwd)
    return f


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def bn_act(x, inv, shift, relu=True, interpret=None):
    """Fused per-channel scale-shift(-ReLU) of NHWC ``x``:
    ``relu(x * inv + shift)`` with a one-pass backward producing dx and
    both per-channel sums.  ``inv``/``shift`` are the folded f32 (C,)
    vectors from BatchNorm.forward; gradients flow back to them (and
    through them to scale/bias/mean/var) via jax autodiff of the fold."""
    n, h, w, c = x.shape
    assert supported(n, h, w, c)
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_bn_act(n * h * w, c, x.dtype.name, bool(relu), interpret)
    y2 = f(x.reshape(n * h * w, c),
           inv.astype(jnp.float32).reshape(1, c),
           shift.astype(jnp.float32).reshape(1, c))
    return y2.reshape(x.shape)
