"""Stride-2 max-pool backward as a Pallas TPU kernel (+ a selection-plane
forward in plain XLA).

Why this kernel exists: XLA lowers max-pool backward to
``select_and_scatter``, which the v5e profile classes as "raw"
(unvectorized) code — 5.0 ms of the measured 130 ms Inception step on the
two large pools alone — and whose unfusable operand forces a second
materialization of the pool inputs (examples/profiles/README.md).  The
reference leans on cuDNN for exactly this op (pool_2d.cu:214-218
cudnnPoolingBackward); this module beats XLA the same way the
flash-attention and fused-CE kernels do — by hand-scheduling VMEM.

Architecture (settled by per-op measurement of three full designs on the
compiled Inception step, round 4):

* The FORWARD is plain XLA: ``reduce_window`` for the max plus an
  elementwise fold over the k*k strided window slices producing ``sel``
  — the window-iteration-order rank of the first maximal element (the
  tie rule of select_and_scatter's GE select), sentinel where a fused
  ReLU clamps.  Every piece (pad/slice/compare/select) is fusible, so
  XLA melts the whole forward into neighboring fusions.  A Pallas
  forward (built and measured: 4.4 ms for the two big pools) loses
  ~1 ms/pool to exactly that fusion, and a backward that re-derives the
  argmax from x in-kernel (also built and measured: 7.2 ms) pays the
  x re-read plus the argmax arithmetic at dy-rate — SURVEY §7's
  "isolated timings mislead" warning, relearned with kernels.
* The BACKWARD is the Pallas kernel: reads dy + sel, writes dx — no x,
  no select_and_scatter (measured 2.9 ms vs 5.0 on the two big pools) —
  and the pool input drops out of the VJP residuals, removing its
  second materialization.
* Kernel operands are processed in **(H, W, C, N)** logical order so N
  rides the lane dimension and C the sublanes.  XLA already picks
  N-minor layouts (``{0,3,2,1}``) for these conv activations on TPU, so
  the transposes bracketing the kernel are layout bitcasts, not copies;
  and with the spatial dims in untiled (major) positions the stride-2
  scatter decomposition becomes pure reshapes (Mosaic supports splitting
  a major dim; it does NOT support strided slices, which lower to
  gathers).
* The H grid walks dx row-blocks with **VMEM carries**: each step keeps
  the previous dy/sel blocks (plus one-row tails) in scratch, so every
  HBM byte is read exactly once — no halo re-fetch.  The dx index map
  lags the grid by one block (a window reaches one row past its block);
  the hi=0 garbage block is overwritten at hi=1.
* Compares/selects run in f32 with full-array operands: the target has
  only 32-bit vector compares (neither bf16 cmpf nor int16 cmpi lower),
  and an i1 mask cannot be relayouted onto operands of another bitwidth
  nor onto broadcast-scalar branches.

Geometry support is the zoo's max pools (stride 2, k in {2,3}, pad in
{0,1}); ``Pool2D._use_pallas`` gates per layer.  On TPU the kernel
compiles via Mosaic; elsewhere it runs in interpreter mode so the CPU
test suite exercises the identical code path (tests/test_pallas.py
ties/geometry parity vs lax.reduce_window autodiff).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SENTINEL = 100.0  # sel value matching no window rank (e.g. ReLU-clamped)


def supported(kh, kw, sh, sw, ph, pw, pool_type="max") -> bool:
    """Static gate: exactly the geometries the parity tests pin down —
    the zoo's max pools (3x3/2 pad 0 or 1, 2x2/2 pad 0;
    pool_2d.cu:50-56 family).  Asymmetric kernels and 2x2/pad-1 would
    exercise untested offset arithmetic, so they stay on the XLA path."""
    return (pool_type == "max" and (sh, sw) == (2, 2) and kh == kw
            and ph == pw and (kh, ph) in ((3, 0), (3, 1), (2, 0)))


def _out_dim(size, k, p):
    return 1 + (size + 2 * p - k) // 2


# XLA's select_and_scatter runs "raw" (unvectorized): measured 5.0 ms on
# Inception's two big pools vs this kernel's 2.9 ms backward.  Expressed
# against the byte volumes below (XLA bwd moves 2.25x the input plane,
# the kernel bwd 1.5x), that A/B puts the raw path at ~1.15x the
# kernel's achieved bytes/s deficit — the calibration constant of the
# predictor.  (5.0/2.9) * (1.5/2.25) = 1.149.
_XLA_RAW_PENALTY = 1.15


def roofline_predicted_win_ms(n, h, w, c, kh, ph, dtype_bytes=2,
                              perf=None) -> float:
    """Predicted end-to-end win (ms, positive = kernel faster) of
    routing one pool layer through the Pallas backward, from the HBM
    roofline — the per-geometry cost model behind ``--pallas auto``
    (Pool2D._use_pallas), replacing the old ``min(h, w) >= 48`` guess.

    Honest accounting of BOTH sides of the measured round-4 trade:

    * XLA backward (select_and_scatter): reads x and dy, writes dx —
      ``2*x + dy`` bytes, at the raw-class bandwidth deficit
      (``_XLA_RAW_PENALTY``, calibrated from the 5.0 vs 2.9 ms A/B).
    * Kernel path: backward reads dy + sel and writes dx, PLUS the
      forward sel plane costs one extra pass over x (read x, write a
      bf16 sel) that XLA's fused reduce_window pipeline never pays —
      the term that made the end-to-end swap measure jitter-band
      neutral despite the 2x per-op win.

    With both sides priced, stride-2 pools come out slightly negative
    (the recorded measurement), so ``auto`` correctly declines what
    ``on`` can still force for measurement runs."""
    if perf is None:
        from flexflow_tpu.sim.cost_model import TpuChipPerf

        perf = TpuChipPerf()
    oh, ow = _out_dim(h, kh, ph), _out_dim(w, kh, ph)
    x_b = float(n * h * w * c * dtype_bytes)
    dy_b = float(n * oh * ow * c * dtype_bytes)
    sel_b = float(n * oh * ow * c * 2)          # sel is bf16 by design
    bw = perf.hbm_bandwidth
    xla_ms = (2 * x_b + dy_b) / bw * 1e3 * _XLA_RAW_PENALTY
    kernel_ms = (dy_b + sel_b + x_b) / bw * 1e3 \
        + (x_b + sel_b) / bw * 1e3              # fwd sel-plane pass
    return xla_ms - kernel_ms


def _offsets(kh, kw, ph, pw):
    """Static per-window-offset geometry: rank in window iteration order,
    the (row-pair shift, row parity) and (col shift, col parity) of input
    position 2t-p+j relative to window t."""
    out = []
    for jh in range(kh):
        qh, rh = divmod(jh - ph, 2)
        for jw in range(kw):
            qw, rw = divmod(jw - pw, 2)
            out.append((jh * kw + jw, qh, rh, qw, rw))
    return out


def _bwd_kernel(g_ref, s_ref, dx_ref, cg, cs, tg, ts,
                *, H, OH, W, OW, kh, kw, ph, pw, bh, bc, bn):
    hi = pl.program_id(2)
    dt = g_ref.dtype
    gcur, scur = g_ref[...], s_ref[...]                # (bh, OW, bc, bn)
    # compares/selects run uniformly in f32 (see module docstring); the
    # accumulators are f32 too, cast once at the dx store
    gwork = jnp.concatenate([tg[...], cg[...], gcur],
                            axis=0).astype(jnp.float32)
    swork = jnp.concatenate([ts[...], cs[...], scur],
                            axis=0).astype(jnp.float32)
    # output rows t in [(hi-1)bh - 1, (hi+1)bh) ; zero invalid rows' grads
    trow = bh * hi - bh - 1 + jax.lax.broadcasted_iota(
        jnp.int32, (2 * bh + 1, OW, bc, bn), 0)
    gwork = jnp.where((trow >= 0) & (trow < OH), gwork,
                      jnp.zeros_like(gwork))
    zpad = jnp.zeros((2 * bh + 1, 2, bc, bn), jnp.float32)
    spad = jnp.full((2 * bh + 1, 2, bc, bn), _SENTINEL, jnp.float32)
    gwork = jnp.concatenate([zpad, gwork, zpad], axis=1)
    swork = jnp.concatenate([spad, swork, spad], axis=1)

    W2 = (W + 1) // 2
    acc = [[jnp.zeros((bh, (W - rw + 1) // 2, bc, bn), jnp.float32)
            for rw in (0, 1)] for _ in (0, 1)]
    for rank, qh, rh, qw, rw in _offsets(kh, kw, ph, pw):
        Wr = (W - rw + 1) // 2
        rank_a = jnp.full(swork.shape, float(rank), jnp.float32)
        c = jnp.where(swork == rank_a, gwork, jnp.zeros_like(gwork))
        acc[rh][rw] = acc[rh][rw] + c[1 - qh:1 - qh + bh,
                                      2 - qw:2 - qw + Wr]
    rows = []
    for rh in (0, 1):
        even, odd = acc[rh]
        if odd.shape[1] < W2:
            odd = jnp.concatenate(
                [odd, jnp.zeros((bh, W2 - odd.shape[1], bc, bn),
                                jnp.float32)], axis=1)
        inter = jnp.stack([even, odd], axis=2).reshape(bh, 2 * W2, bc, bn)
        rows.append(inter[:, :W])
    dx = jnp.stack(rows, axis=1).reshape(2 * bh, W, bc, bn)
    dx_ref[...] = dx.astype(dt)

    tg[...] = cg[bh - 1:]
    ts[...] = cs[bh - 1:]
    cg[...] = gcur
    cs[...] = scur


def _pick_blocks(H, W, C, N, OH, itemsize):
    """Block sizes: N on lanes (128), C on sublanes, bh=2 — measured
    fastest on v5e across the zoo's pool shapes (147^2x64 .. 17^2x768);
    bh >= 2 also avoids a Mosaic relayout bug on size-1 leading dims."""
    bn = min(N, 128)
    bc = min(C, 32 if W < 96 else 32 // itemsize)
    return 2, bc, bn


def _ceil(a, b):
    return -(-a // b)


@functools.lru_cache(maxsize=None)
def _make_maxpool(shape, dtype_name, kh, kw, ph, pw, relu, interpret):
    N, H, W, C = shape
    dt = jnp.dtype(dtype_name)
    OH, OW = _out_dim(H, kh, ph), _out_dim(W, kw, pw)
    assert OH >= 1 and OW >= 1
    bh, bc, bn = _pick_blocks(H, W, C, N, OH, dt.itemsize)
    nxb, nyb = _ceil(H, 2 * bh), _ceil(OH, bh)
    gn, gc = _ceil(N, bn), _ceil(C, bc)

    # the pool1 working set (full-width rows + f32 compare temps) exceeds
    # the 16 MB scoped-vmem default; raise the cap for this kernel.
    # CompilerParams/TPUCompilerParams per the jax release (the class was
    # renamed); a jax with neither cannot run this kernel at all.
    from flexflow_tpu.ops.pallas import tpu_compiler_params

    cparams_cls = tpu_compiler_params()
    if cparams_cls is None:
        raise NotImplementedError(
            "pallas TPU compiler-params API unavailable in this jax "
            "(neither pltpu.CompilerParams nor pltpu.TPUCompilerParams)")
    cparams = cparams_cls(vmem_limit_bytes=48 * 1024 * 1024)

    bwd_kernel = functools.partial(
        _bwd_kernel, H=H, OH=OH, W=W, OW=OW, kh=kh, kw=kw, ph=ph, pw=pw,
        bh=bh, bc=bc, bn=bn)

    def dy_map(ni, ci, hi):
        return (jnp.minimum(hi, nyb - 1), 0, ci, ni)

    def dx_map(ni, ci, hi):
        return (jnp.maximum(hi - 1, 0), 0, ci, ni)

    def bwd_call(gt, sel, gdt):
        return pl.pallas_call(
            bwd_kernel,
            grid=(gn, gc, nxb + 1),
            in_specs=[pl.BlockSpec((bh, OW, bc, bn), dy_map),
                      pl.BlockSpec((bh, OW, bc, bn), dy_map)],
            out_specs=pl.BlockSpec((2 * bh, W, bc, bn), dx_map),
            out_shape=jax.ShapeDtypeStruct((H, W, C, N), gdt),
            scratch_shapes=[pltpu.VMEM((bh, OW, bc, bn), gdt),
                            pltpu.VMEM((bh, OW, bc, bn), jnp.bfloat16),
                            pltpu.VMEM((1, OW, bc, bn), gdt),
                            pltpu.VMEM((1, OW, bc, bn), jnp.bfloat16)],
            compiler_params=cparams,
            interpret=interpret,
        )(gt, sel)

    def fwd_xla(x):
        """y and the selection plane as plain XLA: reduce_window for the
        max, then an elementwise fold over the k*k strided window slices
        for the first-max rank.  Everything here is fusible (pad, strided
        slice, compare, select), so XLA melts it into the neighboring
        fusions — measured on the compiled Inception step, a standalone
        Pallas forward pass lost ~1 ms/pool to exactly this fusion."""
        m = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, kh, kw, 1), (1, 2, 2, 1),
            ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        hi_h = 2 * (OH - 1) + kh  # padded extent the window slices reach
        hi_w = 2 * (OW - 1) + kw
        xp = jnp.pad(x, ((0, 0), (ph, max(0, hi_h - H - ph)),
                         (pw, max(0, hi_w - W - pw)), (0, 0)),
                     constant_values=-jnp.inf)
        sel = jnp.full(m.shape, _SENTINEL, jnp.float32)
        mf = m.astype(jnp.float32)
        for jh in range(kh):
            for jw in range(kw):
                sl = jax.lax.slice(
                    xp, (0, jh, jw, 0),
                    (xp.shape[0], jh + 2 * (OH - 1) + 1,
                     jw + 2 * (OW - 1) + 1, xp.shape[3]),
                    (1, 2, 2, 1))
                rank = float(jh * kw + jw)
                # first max == min rank among maxima (ranks ascend in
                # window iteration order — XLA select_and_scatter's GE
                # tie rule)
                sel = jnp.minimum(
                    sel, jnp.where(sl.astype(jnp.float32) == mf,
                                   rank, _SENTINEL))
        if relu:
            sel = jnp.where(mf > 0, sel, _SENTINEL)
            m = jnp.maximum(m, jnp.zeros_like(m))
        # sel is stored transposed so the backward kernel reads it with N
        # on lanes, like its dy operand
        return m, jnp.transpose(sel.astype(jnp.bfloat16), (1, 2, 3, 0))

    @jax.custom_vjp
    def pool(x):
        y, _ = fwd_xla(x)
        return y

    def pool_fwd(x):
        y, sel = fwd_xla(x)
        return y, (sel,)

    def pool_bwd(res, g):
        (sel,) = res
        gt = jnp.transpose(g, (1, 2, 3, 0))            # (OH, OW, C, N)
        dxt = bwd_call(gt, sel, gt.dtype)
        return (jnp.transpose(dxt, (3, 0, 1, 2)),)

    pool.defvjp(pool_fwd, pool_bwd)
    return pool


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def maxpool2d(x, kh, kw, ph, pw, relu=False, interpret=None):
    """Stride-2 max pool (optionally fused ReLU) of NHWC ``x``; numerically
    identical — including gradient tie-breaking — to
    ``relu(lax.reduce_window(x, -inf, max, (1,kh,kw,1), (1,2,2,1), pad))``
    under jax autodiff (up to bf16 summation order for inputs that receive
    gradient from several overlapping windows)."""
    assert supported(kh, kw, 2, 2, ph, pw)
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_maxpool(tuple(x.shape), x.dtype.name, kh, kw, ph, pw,
                      bool(relu), interpret)
    return f(x)
