"""Fused vocab-projection + softmax-cross-entropy as Pallas TPU kernels.

The (N, V) logits matrix — the largest tensor in an LM/NMT training step
(e.g. 16x512 tokens x 32k vocab = 1 GB in f32) — never reaches HBM: each
(block_n, block_v) logits tile is computed on the MXU from the resident
activation block and streamed through a running log-sum-exp, exactly the
flash-attention recipe applied to the classifier head.  The backward pass
recomputes each tile from the saved per-row lse and forms
``g * (softmax - onehot)`` on the fly for dx/dw/db.

Replaces the unfused pair RnnLinear -> SoftmaxDP (reference:
nmt/linear.cu + nmt/softmax_data_parallel.cu, which materialize the full
logits region between the two task launches) when the FFModel apply-time
fusion pass fires — see FFModel._lm_head_fusion.

Compiled via Mosaic on TPU; interpreter mode elsewhere (CPU test suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward: per-token nll = lse(x@w + b) - (x@w + b)[label]


def _fwd_kernel(x_ref, w_ref, b_ref, lab_ref, nll_ref, lse_ref,
                m_scr, l_scr, corr_scr, *, vocab, block_v):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)
    v_off = vi * block_v

    @pl.when(vi == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        corr_scr[:] = jnp.zeros(corr_scr.shape, corr_scr.dtype)

    logits = jax.lax.dot_general(x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    logits = logits + b_ref[:].astype(jnp.float32)
    vpos = v_off + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = vpos < vocab
    s = jnp.where(valid, logits, _NEG_INF)
    m_prev = m_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    # '& valid' so a remote shard's label landing in [vocab, v_pad) can
    # never match a padded column (robust even if pads were nonzero)
    corr_mask = jnp.logical_and(vpos == lab_ref[:], valid)
    corr_scr[:, 0:1] += jnp.sum(jnp.where(corr_mask, logits, 0.0),
                                axis=-1, keepdims=True)
    scale = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0:1] * scale + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(vi == nv - 1)
    def _finish():
        lse = m_scr[:, 0:1] + jnp.log(jnp.maximum(l_scr[:, 0:1], 1e-30))
        lse_ref[:] = lse
        nll_ref[:] = lse - corr_scr[:, 0:1]


def _fwd_call(x, w, b2, lab2, vocab, block_n, block_v, interpret):
    n_p, d_p = x.shape
    v_p = w.shape[1]
    kernel = functools.partial(_fwd_kernel, vocab=vocab, block_v=block_v)
    return pl.pallas_call(
        kernel,
        grid=(n_p // block_n, v_p // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d_p), lambda i, j: (i, 0)),
            pl.BlockSpec((d_p, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 128), jnp.float32),
            pltpu.VMEM((block_n, 128), jnp.float32),
            pltpu.VMEM((block_n, 128), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, b2, lab2)


# ---------------------------------------------------------------------------
# backward: dlogits = g * (softmax - onehot); dx = dlogits @ wT,
# dw = xT @ dlogits, db = sum_rows(dlogits) — logits tiles recomputed


def _tile_dlogits(x_ref, w_ref, b_ref, lab_ref, lse_ref, gp_ref, goh_ref,
                  v_off, vocab):
    """dlogits tile = g_p * softmax - g_oh * onehot.  For the plain CE op
    g_p == g_oh == g; the partial (vocab-sharded) form folds the lse
    cotangent into g_p (d lse/d logits = softmax)."""
    logits = jax.lax.dot_general(x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    logits = logits + b_ref[:].astype(jnp.float32)
    vpos = v_off + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    valid = vpos < vocab
    p = jnp.where(valid, jnp.exp(logits - lse_ref[:]), 0.0)
    onehot = jnp.where(jnp.logical_and(vpos == lab_ref[:], valid), 1.0, 0.0)
    return gp_ref[:] * p - goh_ref[:] * onehot   # (bn, bv) f32


def _bwd_dx_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, gp_ref, goh_ref,
                   dx_ref, dx_scr, *, vocab, block_v):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        dx_scr[:] = jnp.zeros(dx_scr.shape, dx_scr.dtype)

    t = _tile_dlogits(x_ref, w_ref, b_ref, lab_ref, lse_ref, gp_ref,
                      goh_ref, vi * block_v, vocab)
    dx_scr[:] += jax.lax.dot_general(
        t.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _finish():
        dx_ref[:] = dx_scr[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, b_ref, lab_ref, lse_ref, gp_ref, goh_ref,
                   dw_ref, db_ref, dw_scr, db_scr, *, vocab, block_v):
    ni = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _init():
        dw_scr[:] = jnp.zeros(dw_scr.shape, dw_scr.dtype)
        db_scr[:] = jnp.zeros(db_scr.shape, db_scr.dtype)

    t = _tile_dlogits(x_ref, w_ref, b_ref, lab_ref, lse_ref, gp_ref,
                      goh_ref, pl.program_id(0) * block_v, vocab)
    x = x_ref[:]
    dw_scr[:] += jax.lax.dot_general(
        x, t.astype(x.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_scr[:] += jnp.sum(t, axis=0, keepdims=True)

    @pl.when(ni == nn - 1)
    def _finish():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[:] = db_scr[:].astype(db_ref.dtype)


def _bwd_call(x, w, b2, lab2, lse, gp2, goh2, vocab, block_n, block_v,
              interpret):
    n_p, d_p = x.shape
    v_p = w.shape[1]
    common = dict(vocab=vocab, block_v=block_v)
    # dx: token blocks outer, vocab innermost (accumulated in scratch)
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, **common),
        grid=(n_p // block_n, v_p // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d_p), lambda i, j: (i, 0)),
            pl.BlockSpec((d_p, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d_p), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_p, d_p), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_n, d_p), jnp.float32)],
        interpret=interpret,
    )(x, w, b2, lab2, lse, gp2, goh2)
    # dw/db: vocab blocks outer, token blocks innermost
    dw, db = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, **common),
        grid=(v_p // block_v, n_p // block_n),
        in_specs=[
            pl.BlockSpec((block_n, d_p), lambda j, i: (i, 0)),
            pl.BlockSpec((d_p, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((1, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d_p, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((1, block_v), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d_p, v_p), jnp.float32),
            jax.ShapeDtypeStruct((1, v_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((d_p, block_v), jnp.float32),
            pltpu.VMEM((1, block_v), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, b2, lab2, lse, gp2, goh2)
    return dx, dw, db


# ---------------------------------------------------------------------------
# public op


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _make_fused(x_shape, v, xdt, wdt, bdt, block_n, block_v, interpret,
                with_lse=False):
    n, d = x_shape
    if interpret:
        bn = min(block_n, _round_up(n, 8))
        bv = min(block_v, _round_up(v, 8))
        d_p = d
    else:
        bn = min(block_n, _round_up(n, 128))
        d_p = _round_up(d, 128)
        # the dw kernel holds a (d_p, bv) f32 accumulator plus double-
        # buffered (d_p, bv) weight blocks in VMEM — cap bv so large d
        # (e.g. NMT's 2048 hidden) stays under the ~16 MB scoped limit
        bv_cap = max(128, (2 * 1024 * 1024) // (d_p * 4) // 128 * 128)
        bv = min(block_v, bv_cap, _round_up(v, 128))
    n_p, v_p = _round_up(n, bn), _round_up(v, bv)

    def prep(x, w, b, labels):
        xp = jnp.pad(x, ((0, n_p - n), (0, d_p - d)))
        wp = jnp.pad(w.astype(x.dtype), ((0, d_p - d), (0, v_p - v)))
        b2 = jnp.pad(b.astype(jnp.float32), (0, v_p - v)).reshape(1, v_p)
        lab2 = jnp.pad(labels, (0, n_p - n)).reshape(n_p, 1)
        return xp, wp, b2, lab2

    def run_fwd(x, w, b, labels):
        xp, wp, b2, lab2 = prep(x, w, b, labels)
        nll, lse = _fwd_call(xp, wp, b2, lab2, v, bn, bv, interpret)
        return nll, lse, (xp, wp, b2, lab2, lse)

    def run_bwd(res, g_nll, g_lse=None):
        xp, wp, b2, lab2, lse = res
        goh = jnp.pad(g_nll.astype(jnp.float32),
                      (0, n_p - n)).reshape(n_p, 1)
        if g_lse is None:
            gp = goh          # plain CE: dlogits = g (softmax - onehot)
        else:
            # nll = lse - corr and d lse/d logits = softmax, so the lse
            # cotangent joins the softmax term: gp = g_nll + g_lse
            gp = goh + jnp.pad(g_lse.astype(jnp.float32),
                               (0, n_p - n)).reshape(n_p, 1)
        dx, dw, db = _bwd_call(xp, wp, b2, lab2, lse, gp, goh, v, bn, bv,
                               interpret)
        return (dx[:n, :d].astype(xdt), dw[:d, :v].astype(wdt),
                db[0, :v].astype(bdt), None)

    if not with_lse:

        @jax.custom_vjp
        def fused(x, w, b, labels):
            nll, _, _ = run_fwd(x, w, b, labels)
            return nll[:n, 0]

        def fused_fwd(x, w, b, labels):
            nll, _, res = run_fwd(x, w, b, labels)
            return nll[:n, 0], res

        def fused_bwd(res, g):
            return run_bwd(res, g)

        fused.defvjp(fused_fwd, fused_bwd)
        return fused

    @jax.custom_vjp
    def fused_p(x, w, b, labels):
        nll, lse, _ = run_fwd(x, w, b, labels)
        return nll[:n, 0], lse[:n, 0]

    def fused_p_fwd(x, w, b, labels):
        nll, lse, res = run_fwd(x, w, b, labels)
        return (nll[:n, 0], lse[:n, 0]), res

    def fused_p_bwd(res, gs):
        return run_bwd(res, gs[0], gs[1])

    fused_p.defvjp(fused_p_fwd, fused_p_bwd)
    return fused_p


def fused_linear_ce(x, w, b, labels, block_n=256, block_v=512,
                    interpret=None):
    """Per-token NLL of ``softmax(x @ w + b)`` at ``labels`` without
    materializing the (N, V) logits.  x: (N, d); w: (d, V); b: (V,);
    labels: (N,) int32.  Returns float32 (N,); differentiable in x/w/b."""
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_fused(tuple(x.shape), w.shape[1], x.dtype.name, w.dtype.name,
                    b.dtype.name, block_n, block_v, interpret)
    return f(x, w, b, labels)


def fused_linear_ce_partial(x, w, b, labels, block_n=256, block_v=512,
                            interpret=None):
    """Vocab-shard form: returns ``(nll_local, lse_local)`` over this
    shard's vocab slice (labels must be pre-localized; out-of-range labels
    — negative or >= V, including any landing inside the 128-padded vocab
    tail — match nothing, giving nll_local = lse_local).  Shards combine
    exactly:
    lse_g = logsumexp_c(lse_c), corr_g = sum_c(lse_c - nll_c),
    nll_g = lse_g - corr_g.  Differentiable in both outputs (the lse
    cotangent folds into the backward kernels' softmax term)."""
    interpret = _should_interpret() if interpret is None else interpret
    f = _make_fused(tuple(x.shape), w.shape[1], x.dtype.name, w.dtype.name,
                    b.dtype.name, block_n, block_v, interpret,
                    with_lse=True)
    return f(x, w, b, labels)
