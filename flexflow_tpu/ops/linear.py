"""Linear / fully-connected — the tensor-parallel op.

Reference: linear.cu (748 LoC).  Its 2-D (c, n) task grid splits output
channels and batch (linear.cu:38-41); weights are column-partitioned per
c-shard (linear.cu:112-118); the input gradient needs a cross-c-shard
reduction implemented as replica regions + a BWD2 sum task
(linear.cu:570-603, 656-671); batch-replicated weight grads are aggregated by
``updateGAS`` (linear.cu:680-721).

TPU-native: one jnp.dot with weights sharded P(None, 'c') and activations
P('n', 'c') on a ("c","n") mesh.  GSPMD's backward pass inserts exactly the
two reductions the reference hand-rolls: an all-reduce over 'c' for dL/dx
(BWD2) and an all-reduce over 'n' for dL/dW (updateGAS).
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Linear(Op):
    AXIS_NAMES = ("c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 out_channels: int, relu: bool = True):
        super().__init__(name, pc, [input])
        assert input.ndim == 2, "linear input must be (batch, features)"
        n, d = input.shape
        self.in_channels = d
        self.out_channels = out_channels
        self.relu = relu
        self.output = Tensor((n, out_channels), input.dtype, self, name)

    def init_params(self, rng) -> Dict:
        import jax

        kernel = jax.nn.initializers.glorot_uniform()(
            rng, (self.in_channels, self.out_channels), "float32")
        bias = jax.numpy.zeros((self.out_channels,), "float32")
        return {"kernel": kernel, "bias": bias}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"kernel": P(None, "c"), "bias": P("c")}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "c")

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        # each c-shard reads the full input slice (the reference's aliased
        # input partition, linear.cu:166-173): batch over n, replicated
        # over c
        return [P("n", None)]

    def placement_signature(self):
        return (self.in_channels, self.out_channels, self.relu)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None)]

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp

        (x,) = xs
        y = jnp.dot(x, params["kernel"].astype(x.dtype),
                    preferred_element_type=jnp.float32)
        y = (y + params["bias"]).astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def local_clone(self, pc: ParallelConfig):
        pc_, pn = pc.dims
        n, d = self.inputs[0].shape
        if n % pn or self.out_channels % pc_:
            return None
        t = Tensor((n // pn, d))
        return Linear(self.name, ParallelConfig((1, 1), (0,)), t,
                      self.out_channels // pc_, self.relu)

    def flops_per_sample(self) -> float:
        return 2.0 * self.in_channels * self.out_channels

    def param_bytes(self) -> int:
        return 4 * (self.in_channels * self.out_channels + self.out_channels)
