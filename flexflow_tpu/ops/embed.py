"""Embedding (reference: nmt/embed.cu — custom gather forward kernel
:151-165, scatter-add backward via atomicAdd :167-180).

TPU-native: ``jnp.take`` on the table; the scatter-add backward is jax's
gather VJP.  1-D grid over batch.  The reference requires power-of-2
output_size (shift arithmetic in its kernels) — no such restriction here.
Chunk ops share one table via param_key (srcEmbed/dstEmbed SharedVariables,
nmt/rnn.cu:159-194)."""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Embed(Op):
    AXIS_NAMES = ("n",)

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 vocab_size: int, embed_size: int,
                 param_key: str = None, compute_dtype: str = "float32"):
        super().__init__(name, pc, [input])
        assert input.ndim == 2, "embed input must be (batch, length) int ids"
        self.vocab_size = vocab_size
        self.embed_size = embed_size
        # token models have no float graph input to cast, so the model's
        # compute_dtype is applied HERE, at the source of the float path —
        # every downstream seq op follows x.dtype (the CNN path's analog
        # is make_train_step's image.astype)
        self.compute_dtype = compute_dtype
        if param_key:
            self.param_key = param_key
        n, length = input.shape
        self.output = Tensor((n, length, embed_size), compute_dtype, self,
                             name)

    def init_params(self, rng) -> Dict:
        import jax

        # normal(0.01) like reference's rnn_randomize (uniform small init)
        table = jax.random.normal(
            rng, (self.vocab_size, self.embed_size), "float32") * 0.05
        return {"table": table}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"table": P(None, None)}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None, None)

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        return [P("n", None)]

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None)]

    def placement_signature(self):
        # embeds pinned to distinct devices (the reference's explicit
        # GPU-0/1 placement, nmt/nmt.cc:273-299) group when table geometry
        # matches
        return (self.vocab_size, self.embed_size, self.compute_dtype)

    def forward(self, params, state, xs: List, train: bool):
        import jax.numpy as jnp

        (ids,) = xs
        # gather first, cast after: avoids materializing a whole-vocab
        # low-precision table copy, and the autodiff transpose (scatter-
        # add of token gradients) then accumulates in the table's f32
        return (jnp.take(params["table"], ids, axis=0)
                .astype(self.compute_dtype)), state

    def param_bytes(self) -> int:
        return 4 * self.vocab_size * self.embed_size
