"""Chunked LSTM (reference: nmt/lstm.cu — one op = 1 layer x
LSTM_PER_NODE_LENGTH timesteps x batch-shard, executed by
cudnnRNNForwardTraining/Backward on the chunk, nmt/lstm.cu:323, 489-498).

TPU-native: ``lax.scan`` over the chunk's timesteps; the two gate matmuls
are batched MXU GEMMs.  Inputs (x, hx, cx), outputs (y, hy, cy) exactly as
the reference (nmt/lstm.cu:137-144); hidden state flows to the next chunk op
as a plain tensor dependency, giving the same wavefront/pipeline execution
across chunks placed on different devices (SURVEY.md §2.6 PP).  All chunk
ops of one layer share weights via param_key (SharedVariable encoders[i]/
decoders[i], nmt/rnn.cu:196-233)."""

from __future__ import annotations

import functools
from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


@functools.cache
def _lstm_chunk_core():
    """The chunk recurrence with a hand-written VJP.

    jax.grad through the plain ``lax.scan`` transposes the scan-invariant
    ``w_hh`` into a per-step gradient ACCUMULATOR: every backward step
    reads+writes the full fp32 (H, 4H) buffer (67 MB for H=2048 — ~134 MB
    of HBM traffic per timestep), which measured 7.5x the forward cost on
    v5e (4.3 ms vs 0.57 ms per chunk).  This VJP instead stacks the
    per-step pre-activation gate gradients during the backward scan and
    forms ``dW_hh`` as ONE (H, L*B)x(L*B, 4H) GEMM afterwards; per step
    only the unavoidable W_hh stream (dh = dgates @ W^T) remains.
    Measured: chunk fwd+bwd 4.3 ms -> 1.2 ms; NMT end-to-end 2,030 ->
    4,060 sentences/s (see PARITY.md)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fwd_scan(xg, w_hh, b, hx, cx, save_residuals):
        def step(carry, xg_t):
            h_t, c_t = carry
            gates = xg_t + jnp.dot(h_t, w_hh,
                                   preferred_element_type=jnp.float32
                                   ).astype(xg.dtype) + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * c_t + i * g
            y = o * jnp.tanh(c)
            out = (y, c, jnp.concatenate([i, f, g, o], -1)) \
                if save_residuals else y
            return (y, c), out

        return lax.scan(step, (hx, cx), jnp.swapaxes(xg, 0, 1))

    @jax.custom_vjp
    def core(xg, w_hh, b, hx, cx):
        (hy, cy), ys = fwd_scan(xg, w_hh, b, hx, cx, False)
        return jnp.swapaxes(ys, 0, 1), hy, cy

    def core_fwd(xg, w_hh, b, hx, cx):
        (hy, cy), (ys, cs, ifgo) = fwd_scan(xg, w_hh, b, hx, cx, True)
        return (jnp.swapaxes(ys, 0, 1), hy, cy), \
            (w_hh, hx, cx, ys, cs, ifgo)

    def core_bwd(res, cts):
        w_hh, hx, cx, ys, cs, ifgo = res
        d_ys, d_hy, d_cy = cts
        # time-major stacks of the PREVIOUS step's state
        h_prev = jnp.concatenate([hx[None], ys[:-1]], 0)
        c_prev = jnp.concatenate([cx[None], cs[:-1]], 0)
        w_T = w_hh.T

        def step(carry, inp):
            dh, dc = carry
            dy_t, c_t, c_p, ifgo_t = inp
            i, f, g, o = jnp.split(ifgo_t, 4, axis=-1)
            dh = dh + dy_t
            tc = jnp.tanh(c_t)
            do = dh * tc
            dc = dc + dh * o * (1.0 - tc * tc)
            di = dc * g
            dg = dc * i
            df = dc * c_p
            dc_prev = dc * f
            dpre = jnp.concatenate(
                [di * i * (1.0 - i), df * f * (1.0 - f),
                 dg * (1.0 - g * g), do * o * (1.0 - o)], -1)
            dh_prev = jnp.dot(dpre, w_T,
                              preferred_element_type=jnp.float32
                              ).astype(dh.dtype)
            return (dh_prev, dc_prev), dpre

        (dhx, dcx), dpre_stack = lax.scan(
            step, (d_hy, d_cy),
            (jnp.swapaxes(d_ys, 0, 1), cs, c_prev, ifgo),
            reverse=True)
        # the deferred weight gradient: one big GEMM over all timesteps
        d_w = jnp.einsum("lbh,lbg->hg", h_prev, dpre_stack,
                         preferred_element_type=jnp.float32
                         ).astype(w_hh.dtype)
        d_b = dpre_stack.sum((0, 1))
        d_xg = jnp.swapaxes(dpre_stack, 0, 1)
        return d_xg, d_w, d_b, dhx, dcx

    core.defvjp(core_fwd, core_bwd)
    return core


class LSTMChunk(Op):
    AXIS_NAMES = ("n",)

    def __init__(self, name: str, pc: ParallelConfig, x: Tensor,
                 hx: Tensor, cx: Tensor, hidden_size: int,
                 param_key: str = None):
        inputs = [x] + ([hx, cx] if hx is not None else [])
        super().__init__(name, pc, inputs)
        assert x.ndim == 3, "lstm x must be (batch, chunk_len, input_size)"
        n, length, in_size = x.shape
        self.has_initial_state = hx is not None
        self.input_size = in_size
        self.hidden_size = hidden_size
        if param_key:
            self.param_key = param_key
        self.output = Tensor((n, length, hidden_size), "float32", self,
                             f"{name}.y")
        self.hy = Tensor((n, hidden_size), "float32", self, f"{name}.hy")
        self.cy = Tensor((n, hidden_size), "float32", self, f"{name}.cy")
        self.outputs = [self.output, self.hy, self.cy]

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(rng)
        h = self.hidden_size
        w_ih = jax.nn.initializers.glorot_uniform()(
            k1, (self.input_size, 4 * h), "float32")
        w_hh = jax.nn.initializers.orthogonal()(
            k2, (h, 4 * h), "float32")
        # forget-gate bias 1.0 (gate order: i, f, g, o)
        b = jnp.zeros((4 * h,), "float32").at[h:2 * h].set(1.0)
        return {"w_ih": w_ih, "w_hh": w_hh, "b": b}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"w_ih": P(None, None), "w_hh": P(None, None), "b": P(None)}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None, None)

    def output_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None), P("n", None), P("n", None)]

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        specs = [P("n", None, None)]
        if self.has_initial_state:
            specs += [P("n", None), P("n", None)]
        return specs

    def placement_signature(self):
        # chunk ops on disjoint devices along a DAG antidiagonal execute
        # concurrently — the reference's wavefront pipelining
        # (nmt/rnn.cu:298-326)
        return (self.input_size, self.hidden_size, self.has_initial_state)

    def forward(self, params, state, xs: List, train: bool):
        import jax.numpy as jnp

        x = xs[0]
        n = x.shape[0]
        h = self.hidden_size
        if self.has_initial_state:
            hx, cx = xs[1], xs[2]
        else:
            hx = jnp.zeros((n, h), x.dtype)
            cx = jnp.zeros((n, h), x.dtype)
        w_ih = params["w_ih"].astype(x.dtype)
        w_hh = params["w_hh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)

        # hoist the input projection out of the scan: one big MXU GEMM
        # (B, L, E) @ (E, 4H) for the whole chunk; the recurrence runs
        # under the deferred-dW custom VJP (_lstm_chunk_core).
        # NOTE: scan unroll was tried and measured SLOWER on v5e (1072 vs
        # 1534 sentences/s NMT at unroll=4) — the recurrent GEMM is
        # weight-streaming-bound and unrolling only bloats the program.
        xg = jnp.einsum("ble,eg->blg", x, w_ih,
                        preferred_element_type=jnp.float32).astype(x.dtype)
        y, hy, cy = _lstm_chunk_core()(xg, w_hh, b, hx, cx)
        return (y, hy, cy), state

    def local_clone(self, pc: ParallelConfig):
        (pn,) = pc.dims
        n, length, e = self.inputs[0].shape
        if n % pn:
            return None
        x = Tensor((n // pn, length, e))
        hx = Tensor((n // pn, self.hidden_size)) \
            if self.has_initial_state else None
        cx = Tensor((n // pn, self.hidden_size)) \
            if self.has_initial_state else None
        return LSTMChunk(self.name, ParallelConfig((1,), (0,)), x, hx, cx,
                         self.hidden_size)

    def flops_per_sample(self) -> float:
        length = self.output.shape[1]
        return 2.0 * length * 4 * self.hidden_size * (
            self.input_size + self.hidden_size)

    def param_bytes(self) -> int:
        h = self.hidden_size
        return 4 * (self.input_size * 4 * h + h * 4 * h + 4 * h)
