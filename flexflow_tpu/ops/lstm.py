"""Chunked LSTM (reference: nmt/lstm.cu — one op = 1 layer x
LSTM_PER_NODE_LENGTH timesteps x batch-shard, executed by
cudnnRNNForwardTraining/Backward on the chunk, nmt/lstm.cu:323, 489-498).

TPU-native: ``lax.scan`` over the chunk's timesteps; the two gate matmuls
are batched MXU GEMMs.  Inputs (x, hx, cx), outputs (y, hy, cy) exactly as
the reference (nmt/lstm.cu:137-144); hidden state flows to the next chunk op
as a plain tensor dependency, giving the same wavefront/pipeline execution
across chunks placed on different devices (SURVEY.md §2.6 PP).  All chunk
ops of one layer share weights via param_key (SharedVariable encoders[i]/
decoders[i], nmt/rnn.cu:196-233)."""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class LSTMChunk(Op):
    AXIS_NAMES = ("n",)

    def __init__(self, name: str, pc: ParallelConfig, x: Tensor,
                 hx: Tensor, cx: Tensor, hidden_size: int,
                 param_key: str = None):
        inputs = [x] + ([hx, cx] if hx is not None else [])
        super().__init__(name, pc, inputs)
        assert x.ndim == 3, "lstm x must be (batch, chunk_len, input_size)"
        n, length, in_size = x.shape
        self.has_initial_state = hx is not None
        self.input_size = in_size
        self.hidden_size = hidden_size
        if param_key:
            self.param_key = param_key
        self.output = Tensor((n, length, hidden_size), "float32", self,
                             f"{name}.y")
        self.hy = Tensor((n, hidden_size), "float32", self, f"{name}.hy")
        self.cy = Tensor((n, hidden_size), "float32", self, f"{name}.cy")
        self.outputs = [self.output, self.hy, self.cy]

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(rng)
        h = self.hidden_size
        w_ih = jax.nn.initializers.glorot_uniform()(
            k1, (self.input_size, 4 * h), "float32")
        w_hh = jax.nn.initializers.orthogonal()(
            k2, (h, 4 * h), "float32")
        # forget-gate bias 1.0 (gate order: i, f, g, o)
        b = jnp.zeros((4 * h,), "float32").at[h:2 * h].set(1.0)
        return {"w_ih": w_ih, "w_hh": w_hh, "b": b}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"w_ih": P(None, None), "w_hh": P(None, None), "b": P(None)}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None, None)

    def output_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None), P("n", None), P("n", None)]

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        specs = [P("n", None, None)]
        if self.has_initial_state:
            specs += [P("n", None), P("n", None)]
        return specs

    def placement_signature(self):
        # chunk ops on disjoint devices along a DAG antidiagonal execute
        # concurrently — the reference's wavefront pipelining
        # (nmt/rnn.cu:298-326)
        return (self.input_size, self.hidden_size, self.has_initial_state)

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp
        from jax import lax

        x = xs[0]
        n = x.shape[0]
        h = self.hidden_size
        if self.has_initial_state:
            hx, cx = xs[1], xs[2]
        else:
            hx = jnp.zeros((n, h), x.dtype)
            cx = jnp.zeros((n, h), x.dtype)
        w_ih = params["w_ih"].astype(x.dtype)
        w_hh = params["w_hh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)

        # hoist the input projection out of the scan: one big MXU GEMM
        # (B, L, E) @ (E, 4H) for the whole chunk
        xg = jnp.einsum("ble,eg->blg", x, w_ih,
                        preferred_element_type=jnp.float32).astype(x.dtype)

        def step(carry, xg_t):
            h_t, c_t = carry
            gates = xg_t + jnp.dot(h_t, w_hh,
                                   preferred_element_type=jnp.float32
                                   ).astype(x.dtype) + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c = f * c_t + i * g
            y = o * jnp.tanh(c)
            return (y, c), y

        (hy, cy), ys = lax.scan(step, (hx, cx),
                                jnp.swapaxes(xg, 0, 1))  # (L, B, 4H)
        y = jnp.swapaxes(ys, 0, 1)  # (B, L, H)
        return (y, hy, cy), state

    def local_clone(self, pc: ParallelConfig):
        (pn,) = pc.dims
        n, length, e = self.inputs[0].shape
        if n % pn:
            return None
        x = Tensor((n // pn, length, e))
        hx = Tensor((n // pn, self.hidden_size)) \
            if self.has_initial_state else None
        cx = Tensor((n // pn, self.hidden_size)) \
            if self.has_initial_state else None
        return LSTMChunk(self.name, ParallelConfig((1,), (0,)), x, hx, cx,
                         self.hidden_size)

    def flops_per_sample(self) -> float:
        length = self.output.shape[1]
        return 2.0 * length * 4 * self.hidden_size * (
            self.input_size + self.hidden_size)

    def param_bytes(self) -> int:
        h = self.hidden_size
        return 4 * (self.input_size * 4 * h + h * 4 * h + 4 * h)
