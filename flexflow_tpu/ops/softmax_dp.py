"""Data-parallel softmax + CE for RNN chunks (reference:
nmt/softmax_data_parallel.cu — explicitly repartitions vocab-sharded logits
to batch-only sharding :85-100, then cudnnSoftmaxForward + fused CE backward
:198-310).

Here the repartition is the output sharding constraint (P over 'n' only);
GSPMD converts the producer's vocab-sharded layout.  Labels are a graph
input (the same chunk's dst tokens — reference parity: predicts the current
token, nmt/rnn.cu:330-333, no shift).  ``loss`` returns the SUM of NLL over
the chunk; the RnnModel normalizes by total target tokens."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class SoftmaxDP(Op):
    AXIS_NAMES = ("n",)
    is_loss = True

    def __init__(self, name: str, pc: ParallelConfig, logits: Tensor,
                 labels: Tensor):
        super().__init__(name, pc, [logits, labels])
        assert logits.ndim == 3 and labels.ndim == 2
        assert logits.shape[:2] == labels.shape
        self.labels_tensor = labels
        self.output = Tensor(logits.shape, "float32", self, name)

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None, None)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # the reference's explicit logit repartition to batch-only sharding
        # (nmt/softmax_data_parallel.cu:85-100)
        return [P("n", None, None), P("n", None)]

    def forward(self, params, state, xs: List, train: bool):
        import jax

        logits, _ = xs
        return jax.nn.log_softmax(logits.astype("float32"), axis=-1), state

    def loss(self, log_probs, labels):
        """Sum of NLL over non-ignored tokens (label -1 = no target, e.g.
        the final position of a causal next-token shift)."""
        import jax.numpy as jnp

        valid = labels >= 0
        if log_probs.ndim == labels.ndim:
            # fused path (FFModel._lm_head_fusion): the op's value is
            # already per-token NLL from the Pallas projection+CE kernel
            return jnp.sum(jnp.where(valid, log_probs, 0.0))
        nll = -jnp.take_along_axis(log_probs,
                                   jnp.where(valid, labels, 0)[..., None],
                                   axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, nll, 0.0))
