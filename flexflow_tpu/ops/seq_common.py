"""Sequence-model elementwise ops: LayerNorm, residual Add, learned
positional embedding — all on (batch, seq, d) tensors with an ('s', 'n')
grid (sequence + sample parallelism).  Capability extensions beyond the
reference (needed for the transformer family; the reference has no
attention models)."""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class _SeqElementwise(Op):
    """Shared (s, n)-grid elementwise base: output and preferred input
    layouts are batch-over-n, sequence-over-s, features replicated."""

    AXIS_NAMES = ("s", "n")

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "s", None)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", "s", None)] * len(self.inputs)


class LayerNormSeq(_SeqElementwise):
    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 eps: float = 1e-5):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        self.eps = eps
        self.d = input.shape[2]
        self.output = Tensor(input.shape, input.dtype, self, name)

    def init_params(self, rng) -> Dict:
        import jax.numpy as jnp

        return {"scale": jnp.ones((self.d,), "float32"),
                "bias": jnp.zeros((self.d,), "float32")}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"scale": P(None), "bias": P(None)}

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp

        (x,) = xs
        xf = x.astype("float32")
        mu = xf.mean(axis=-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), state

    def flops_per_sample(self) -> float:
        return 8.0 * self.output.shape[1] * self.d

    def param_bytes(self) -> int:
        return 8 * self.d


class AddSeq(_SeqElementwise):
    def __init__(self, name: str, pc: ParallelConfig, inputs: List[Tensor]):
        super().__init__(name, pc, inputs)
        assert len(inputs) == 2 and inputs[0].shape == inputs[1].shape
        self.output = Tensor(inputs[0].shape, inputs[0].dtype, self, name)

    def forward(self, params, state, xs: List, train: bool):
        return xs[0] + xs[1], state

    def flops_per_sample(self) -> float:
        import math

        return float(math.prod(self.output.shape[1:]))


class GeluSeq(_SeqElementwise):
    def __init__(self, name: str, pc: ParallelConfig, input: Tensor):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        self.output = Tensor(input.shape, input.dtype, self, name)

    def forward(self, params, state, xs: List, train: bool):
        import jax

        return jax.nn.gelu(xs[0]), state

    def flops_per_sample(self) -> float:
        import math

        return 8.0 * float(math.prod(self.output.shape[1:]))


class PosEmbed(_SeqElementwise):
    """Learned positional embedding added to the token embedding."""

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        self.seq_len = input.shape[1]
        self.d = input.shape[2]
        self.output = Tensor(input.shape, input.dtype, self, name)

    def init_params(self, rng) -> Dict:
        import jax

        return {"table": jax.random.normal(
            rng, (self.seq_len, self.d), "float32") * 0.02}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"table": P("s", None)}

    def forward(self, params, state, xs: List, train: bool):
        (x,) = xs
        return x + params["table"].astype(x.dtype), state

    def param_bytes(self) -> int:
        return 4 * self.seq_len * self.d
