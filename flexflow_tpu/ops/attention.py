"""Multi-head attention with a full SOAP grid: ('s', 'h', 'n') = sequence
(context parallelism) x heads (tensor parallelism) x batch (data
parallelism).

Execution paths:
  * s-parts == 1 on TPU: the hand-written Pallas flash kernel
    (ops/pallas/flash_attention.py) — scores stay in VMEM, blocks stream
    through the MXU; multi-device grids run it per-shard under shard_map
    (head/batch sharding is embarrassingly parallel).
  * s-parts == 1 elsewhere (or shapes the kernel can't shard): blockwise
    (flash-style streaming-softmax) attention in plain XLA; head/batch
    sharding handled by GSPMD from the specs.
  * s-parts > 1 on a canonical full-device grid: explicit ring attention
    (shard_map + ppermute over the 's' mesh axis, see
    parallel/ring_attention.py) — K/V blocks rotate on neighbor links, O(S/P)
    memory per chip.

New capability relative to the reference (which has no attention ops,
SURVEY.md §2.6); cited rows: CP/ring-attention, SP."""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class MultiHeadAttention(Op):
    AXIS_NAMES = ("s", "h", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 num_heads: int, causal: bool = False, machine=None):
        super().__init__(name, pc, [input])
        assert input.ndim == 3
        n, s, d = input.shape
        assert d % num_heads == 0, "d_model must divide into heads"
        self.num_heads = num_heads
        self.head_dim = d // num_heads
        self.d_model = d
        self.causal = causal
        self.machine = machine  # needed for the explicit ring-attention mesh
        self.output = Tensor(input.shape, input.dtype, self, name)

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        d = self.d_model
        keys = jax.random.split(rng, 4)
        init = jax.nn.initializers.glorot_uniform()
        return {
            "wq": init(keys[0], (d, d), "float32"),
            "wk": init(keys[1], (d, d), "float32"),
            "wv": init(keys[2], (d, d), "float32"),
            "wo": init(keys[3], (d, d), "float32"),
            "bo": jnp.zeros((d,), "float32"),
        }

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        # q/k/v projections column-sharded by heads, output row-sharded
        return {"wq": P(None, "h"), "wk": P(None, "h"), "wv": P(None, "h"),
                "wo": P("h", None), "bo": P(None)}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "s", None)

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # batch over n, sequence over s, d replicated over h (the q/k/v
        # projections are column-sharded by head)
        return [P("n", "s", None)]

    def _use_ring(self) -> bool:
        s_parts = self.pc.dims[0]
        return (s_parts > 1 and self.machine is not None
                and self.machine.is_canonical(self.pc))

    def forward(self, params, state, xs: List, train: bool):
        import jax.numpy as jnp

        from flexflow_tpu.parallel.ring_attention import ring_attention

        (x,) = xs
        b, s, d = x.shape
        h, hd = self.num_heads, self.head_dim

        def proj(w):
            y = jnp.einsum("bsd,de->bse", x, w.astype(x.dtype),
                           preferred_element_type=jnp.float32).astype(x.dtype)
            return y.reshape(b, s, h, hd).transpose(0, 2, 1, 3)  # (B,H,S,hd)

        q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
        if self._use_ring():
            mesh = self.machine.mesh_for(self.pc, self.AXIS_NAMES)
            out = ring_attention(q, k, v, mesh, "s", self.causal)
        else:
            out = self._flash_or_blockwise(q, k, v, s)
        out = out.astype(x.dtype).transpose(0, 2, 1, 3).reshape(b, s, d)
        if (self.machine is not None and self.machine.num_devices > 1
                and self.pc.dims[1] > 1):
            # head TP: keep the merged activation head-sharded along d so
            # the wo projection is row-parallel (contraction dim sharded,
            # GSPMD psums partial products — the Megatron pair to the
            # column-parallel q/k/v).  Without this the activation arrives
            # batch-sharded and the wo weight-grad dot forces a
            # full-rematerialization reshard in the backward pass.
            from jax import lax
            from jax.sharding import PartitionSpec as P

            out = lax.with_sharding_constraint(
                out, self.machine.sharding(self.pc, self.AXIS_NAMES,
                                           P("n", "s", "h")))
        y = jnp.einsum("bsd,de->bse", out, params["wo"].astype(x.dtype),
                       preferred_element_type=jnp.float32).astype(x.dtype)
        return y + params["bo"].astype(x.dtype), state

    def _flash_or_blockwise(self, q, k, v, s: int):
        """Non-ring attention body: the Pallas flash kernel on TPU (direct
        on one device; per-shard under shard_map on a canonical multi-device
        grid, where head/batch sharding is embarrassingly parallel),
        otherwise the XLA streaming-softmax path with GSPMD sharding."""
        from flexflow_tpu.ops.pallas import flash_attention, flash_enabled
        from flexflow_tpu.parallel.ring_attention import blockwise_attention

        if flash_enabled():
            nd = self.machine.num_devices if self.machine is not None else 1
            if nd == 1 or len(self.pc.devices) == 1:
                return flash_attention(q, k, v, self.causal)
            _, ph, pn = self.pc.dims
            b, h = q.shape[0], q.shape[1]
            if (self.machine.is_canonical(self.pc)
                    and b % max(pn, 1) == 0 and h % max(ph, 1) == 0):
                from jax.sharding import PartitionSpec as P

                from flexflow_tpu.parallel.ring_attention import \
                    unchecked_shard_map

                mesh = self.machine.mesh_for(self.pc, self.AXIS_NAMES)
                spec = P("n" if pn > 1 else None, "h" if ph > 1 else None,
                         None, None)
                return unchecked_shard_map(
                    lambda ql, kl, vl: flash_attention(ql, kl, vl,
                                                       self.causal),
                    mesh, (spec, spec, spec), spec)(q, k, v)
        return blockwise_attention(q, k, v, self.causal,
                                   block_size=min(s, 512))

    def local_clone(self, pc: ParallelConfig):
        ps, ph, pn = pc.dims
        n, s, d = self.inputs[0].shape
        if ps > 1 or ph > 1:
            # A standalone shard-shaped clone cannot represent ring-CP
            # ((S/ps) x S scores against full-length K/V) or head-TP
            # (d x d/ph projections) — it would under-measure by ps / ph.
            # Fall back to the analytic roofline, whose flops/num_parts
            # division IS exact for these grids (total work is preserved).
            return None
        if n % pn:
            return None
        t = Tensor((n // pn, s, d))
        return MultiHeadAttention(self.name, ParallelConfig((1, 1, 1), (0,)),
                                  t, self.num_heads, self.causal)

    def flops_per_sample(self) -> float:
        s, d = self.output.shape[1], self.d_model
        return 8.0 * s * d * d + 4.0 * s * s * d

    def param_bytes(self) -> int:
        return 4 * (4 * self.d_model * self.d_model + self.d_model)
