"""BatchNorm (reference: batch_norm.cu, cudnnBatchNormalizationForward
Training/Backward in SPATIAL mode; scale init 1.0, bias init 0.0,
batch_norm.cu:225-239).

Design divergence, on purpose: the reference computes batch statistics *per
task shard* (each Legion task calls cuDNN BN on its local slice — no
cross-shard sync), which makes training dynamics depend on the partition
grid.  We compute **global** batch statistics: ``jnp.mean`` over sharded
axes makes XLA insert the cross-shard reduction, i.e. sync-BN over the
{n,h,w} grid axes.  This preserves the framework's key invariant — identical
loss trajectories under any strategy (SURVEY.md §4) — which local BN breaks.
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class BatchNorm(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 relu: bool = True, eps: float = 1e-5, momentum: float = 0.9):
        super().__init__(name, pc, [input])
        assert input.ndim == 4
        self.channels = input.shape[3]
        self.relu = relu
        self.eps = eps
        self.momentum = momentum
        self.output = Tensor(input.shape, input.dtype, self, name)

    def init_params(self, rng) -> Dict:
        import jax.numpy as jnp

        return {"scale": jnp.ones((self.channels,), "float32"),
                "bias": jnp.zeros((self.channels,), "float32")}

    def init_state(self) -> Dict:
        import jax.numpy as jnp

        return {"mean": jnp.zeros((self.channels,), "float32"),
                "var": jnp.ones((self.channels,), "float32")}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"scale": P("c"), "bias": P("c")}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "h", "w", "c")

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", "h", "w", "c")]

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        pc = pc or self.pc
        pw, ph, pcc, pn = pc.dims
        if pcc != 1:
            return None  # placed c-split would shard the running stats
        n, h, w, _ = self.inputs[0].shape
        if n % pn or h % ph or w % pw:
            return None
        return [P("n", "h", "w", None)]

    def placement_signature(self):
        # round 3: BatchNorm may join placement groups — its state is
        # threaded through run_group (state_specs) and its statistics are
        # grid-global via sharded_forward
        return (self.channels, self.relu, self.eps, self.momentum)

    def state_specs(self):
        from jax.sharding import PartitionSpec as P

        # per-channel running stats, replicated within the block (the
        # placed grid never splits c — input_specs rejects that)
        return {"mean": P(), "var": P()}

    def point_placeable(self) -> bool:
        # Set-family dispatch replicates the input, so GLOBAL batch
        # statistics need no collective — any batch/spatial grid
        # qualifies (round 5, closing the "BatchNorm on an irregular
        # list silently normalizes" gap).  c stays unsplit, matching
        # input_specs' reasoning (running stats shard with c).
        return self.pc.dims[2] == 1

    def point_forward(self, params, state, xs, idx, sizes, train):
        """One grid point from the FULL input: compute global batch
        statistics directly (every device holds the whole batch — the
        canonical semantics with zero collectives), update the running
        stats, normalize, and slice this point's output block."""
        import jax
        import jax.numpy as jnp

        from flexflow_tpu.ops.base import point_slice

        (x,) = xs
        if train:
            xf = x.astype("float32")
            mean = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = dict(state)
        inv = jax.lax.rsqrt(var + self.eps) * params["scale"]
        shift = params["bias"] - mean * inv
        y = x * inv.astype(x.dtype) + shift.astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        # the point's block: the slice fuses into the elementwise chain
        y = point_slice(y, self.output_spec(), sizes, idx)
        return (y,), new_state

    def placed_prelude(self, xs, train: bool):
        """Batch statistics over the WHOLE placed block, not the local
        shard: lax.pmean over the live grid axes keeps the framework
        invariant (identical loss trajectories under any strategy) that
        per-shard stats would break (the documented divergence from the
        reference's per-task cuDNN stats).  Runs outside the group switch
        (collectives are illegal inside branches)."""
        import jax.numpy as jnp
        from jax import lax

        live = tuple(name for name, size in
                     zip(self.AXIS_NAMES, self.pc.dims) if size > 1)
        if not live or not train:
            return None
        (x,) = xs
        xf = x.astype("float32")
        mean = lax.pmean(jnp.mean(xf, axis=(0, 1, 2)), live)
        mean2 = lax.pmean(jnp.mean(jnp.square(xf), axis=(0, 1, 2)), live)
        var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
        return mean, var

    def sharded_forward(self, params, state, xs, train: bool, aux=None):
        """Placed-grid forward: normalize with the block-global statistics
        from placed_prelude (collective-free branch body)."""
        import jax
        import jax.numpy as jnp

        if aux is None:
            return self.forward(params, state, xs, train)
        (x,) = xs
        mean, var = aux
        m = self.momentum
        state = {"mean": m * state["mean"] + (1 - m) * mean,
                 "var": m * state["var"] + (1 - m) * var}
        inv = jax.lax.rsqrt(var + self.eps) * params["scale"]
        shift = params["bias"] - mean * inv
        y = x * inv.astype(x.dtype) + shift.astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def _use_pallas(self, x) -> bool:
        """Route the single-device normalize+ReLU through the fused
        Pallas kernel pair (ops/pallas/bn_act.py): the backward emits dx
        and both per-channel sums from one pass over (x, dy), where
        XLA's VJP splits the reductions off the elementwise producer.
        The statistics (and their VJP chain) stay in XLA either way."""
        from flexflow_tpu.ops.pallas import bnrelu_enabled
        from flexflow_tpu.ops.pallas.bn_act import supported

        return (bnrelu_enabled()
                and supported(*x.shape)
                and len(self.pc.devices) <= 1
                and all(d == 1 for d in self.pc.dims))

    def forward(self, params, state, xs: List, train: bool):
        import jax
        import jax.numpy as jnp

        (x,) = xs
        if train:
            xf = x.astype("float32")
            mean = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
            m = self.momentum
            state = {"mean": m * state["mean"] + (1 - m) * mean,
                     "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
        # Fold stats+affine into per-channel scale/shift in fp32, then
        # normalize as ONE compute-dtype pass (y = x*inv + shift, ReLU
        # fused).  The training step is HBM-bound (measured 79% HBM util at
        # 33% MFU, batch 256); the previous fp32 elementwise chain made the
        # normalize+relu traffic — and the residuals its backward re-reads
        # — twice as wide as the activations.  Stats stay fp32 (the
        # reductions are read-only and cheap); per-channel vectors are tiny.
        inv = jax.lax.rsqrt(var + self.eps) * params["scale"]
        shift = params["bias"] - mean * inv
        if self._use_pallas(x):
            from flexflow_tpu.ops.pallas.bn_act import bn_act

            return bn_act(x, inv, shift, relu=self.relu), state
        y = x * inv.astype(x.dtype) + shift.astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def local_clone(self, pc: ParallelConfig):
        pw, ph, pc_, pn = pc.dims
        n, h, w, c = self.inputs[0].shape
        if n % pn or h % ph or w % pw or c % pc_:
            return None
        t = Tensor((n // pn, h // ph, w // pw, c // pc_))
        return BatchNorm(self.name, ParallelConfig((1, 1, 1, 1), (0,)), t,
                         self.relu, self.eps, self.momentum)

    def flops_per_sample(self) -> float:
        _, h, w, c = self.output.shape
        return 8.0 * h * w * c

    def param_bytes(self) -> int:
        return 4 * 2 * self.channels
