"""Elementwise Add (residual connections).

The reference has NO elementwise op — its "ResNet-101" BottleneckBlock is a
plain conv stack with the residual adds absent (inception.h:122-132, bn
layers commented out).  We mirror that topology for parity, but also provide
this op so true residual networks are expressible — a capability extension,
not a port."""

from __future__ import annotations

from typing import List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Add(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, inputs: List[Tensor],
                 relu: bool = False):
        super().__init__(name, pc, inputs)
        assert len(inputs) == 2
        assert inputs[0].shape == inputs[1].shape, (
            f"add inputs must match: {inputs[0].shape} vs {inputs[1].shape}")
        self.relu = relu
        self.output = Tensor(inputs[0].shape, inputs[0].dtype, self, name)

    def _spec(self):
        """Rank-adaptive spec: NHWC activations (4-D), or batch-major
        feature tensors of any other rank — (n, c) linear features,
        (n, t, c) sequence residuals — with batch and the minor feature
        dim on the grid axes."""
        from jax.sharding import PartitionSpec as P

        if self.output.ndim == 4:
            return P("n", "h", "w", "c")
        if self.output.ndim == 1:
            return P("n")
        return P("n", *([None] * (self.output.ndim - 2)), "c")

    def output_spec(self):
        return self._spec()

    def input_specs(self, pc=None):
        # elementwise: any inner grid is local when both inputs share it
        return [self._spec(), self._spec()]

    def regrid_input_specs(self):
        return [self._spec()] * len(self.inputs)

    def placement_signature(self):
        return (self.relu,)

    def forward(self, params, state, xs: List, train: bool):
        import jax

        y = xs[0] + xs[1]
        if self.relu:
            y = jax.nn.relu(y)
        return y, state

    def flops_per_sample(self) -> float:
        import math

        return float(math.prod(self.output.shape[1:]))
