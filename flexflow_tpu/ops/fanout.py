"""Balanced-tree gradient accumulation for multi-consumer tensors.

The Inception profile's #1 vpu residual row is ``add_any`` — when a
tensor feeds n consumers (every inception block input feeds 4 branch
stacks), JAX's transpose accumulates the n branch cotangents PAIRWISE at
the points where they become available, so XLA sees a chain of n-1
two-operand ``add_any`` fusions scattered across the backward program:
3(n-1) HBM traffic units (two reads + one write each) for a sum whose
information content is n+1 units.  ``fusion.22`` alone holds 3.5 ms of
the 130 ms step (examples/profiles/inception_v3_roofline.json).

:func:`grad_fanout` rewrites the accumulation POINT, not the math: the
forward hands each consumer its own alias of ``x``, so all n cotangents
arrive at one ``custom_vjp`` backward, which emits a single balanced
n-ary tree sum — adjacent adds XLA folds into one (n+1)-operand
elementwise fusion (one pass: n reads, 1 write).

Numerics: floating addition is commutative but not associative.  The
balanced tree reduces leftmost-pairs-first, which reproduces JAX's
left-to-right chain exactly for n <= 3 ((a+b)+c both ways) and
reassociates for n >= 4 ((a+b)+(c+d) vs ((a+b)+c)+d) — tolerance-level,
not bit-level, equality there.  FFConfig.grad_fanout = "off" restores
the stock chain (the A/B arm of tests/test_fanout.py).
"""

from __future__ import annotations

import functools


def tree_sum(xs):
    """Balanced pairwise sum of a non-empty sequence, leftmost pairs
    first: [a,b,c] -> (a+b)+c, [a,b,c,d] -> (a+b)+(c+d)."""
    xs = list(xs)
    if not xs:
        raise ValueError("tree_sum of no operands")
    while len(xs) > 1:
        nxt = [xs[i] + xs[i + 1] for i in range(0, len(xs) - 1, 2)]
        if len(xs) % 2:
            nxt.append(xs[-1])
        xs = nxt
    return xs[0]


@functools.lru_cache(maxsize=None)
def _fan(n: int):
    import jax

    @jax.custom_vjp
    def fan(x):
        return (x,) * n

    def fwd(x):
        return (x,) * n, None

    def bwd(_, cts):
        return (tree_sum(cts),)

    fan.defvjp(fwd, bwd)
    return fan


def grad_fanout(x, n: int):
    """n aliases of ``x``, one per consumer; their cotangents re-join as
    ONE balanced tree sum at this point instead of JAX's scattered
    pairwise chain.  n < 2 is the identity."""
    if n < 2:
        return (x,)
    return _fan(n)(x)
