"""Vocab projection for RNN chunks (reference: nmt/linear.cu — 2-D (c, n)
grid over 3-D tensors: c shards the 20-32k vocab (tensor parallelism over
the projection), n shards batch; replica-grad + backward2 cross-shard
reduction nmt/linear.cu:413-446, here GSPMD's psum).  One weight shared by
all chunk ops (SharedVariable `linear` with bbox-ed per-GPU partial
gradients, nmt/rnn.cu:234-296 — here: jax.grad sums chunk contributions,
GSPMD reduces across shards)."""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class RnnLinear(Op):
    AXIS_NAMES = ("c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 out_channels: int, param_key: str = None):
        super().__init__(name, pc, [input])
        assert input.ndim == 3, "rnn linear input must be (batch, len, d)"
        n, length, d = input.shape
        self.in_channels = d
        self.out_channels = out_channels
        if param_key:
            self.param_key = param_key
        self.output = Tensor((n, length, out_channels), "float32", self, name)

    def init_params(self, rng) -> Dict:
        import jax
        import jax.numpy as jnp

        kernel = jax.nn.initializers.glorot_uniform()(
            rng, (self.in_channels, self.out_channels), "float32")
        bias = jnp.zeros((self.out_channels,), "float32")
        return {"kernel": kernel, "bias": bias}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"kernel": P(None, "c"), "bias": P("c")}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", None, "c")

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None)]

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        return [P("n", None, None)]

    def placement_signature(self):
        return (self.in_channels, self.out_channels)

    def forward(self, params, state, xs: List, train: bool):
        import jax.numpy as jnp

        (x,) = xs
        y = jnp.einsum("bld,dv->blv", x, params["kernel"].astype(x.dtype),
                       preferred_element_type=jnp.float32)
        return (y + params["bias"]).astype(x.dtype), state

    def local_clone(self, pc: ParallelConfig):
        pc_, pn = pc.dims
        n, length, d = self.inputs[0].shape
        if n % pn or self.out_channels % pc_:
            return None
        t = Tensor((n // pn, length, d))
        return RnnLinear(self.name, ParallelConfig((1, 1), (0,)), t,
                         self.out_channels // pc_)

    def flops_per_sample(self) -> float:
        return 2.0 * self.output.shape[1] * self.in_channels * self.out_channels

    def param_bytes(self) -> int:
        return 4 * (self.in_channels * self.out_channels + self.out_channels)
