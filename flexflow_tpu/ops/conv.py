"""Conv2D (reference: conv_2d.cu, 874 LoC of Legion partitions + cuDNN).

TPU-native: one ``lax.conv_general_dilated`` in NHWC/HWIO form (MXU path),
with the op's {w,h,c,n} partition grid applied as a GSPMD sharding.  The
reference's machinery maps as follows:

  * 4-D task grid (conv_2d.cu:61-75)      -> mesh axes ("w","h","c","n")
  * output partition-by-restriction        -> NamedSharding P(n,h,w,c)
  * halo-free input re-partitioning        -> GSPMD spatial partitioning
    (conv_2d.cu:171-208)                      (XLA inserts halo exchanges)
  * replicated kernel/bias + updateGAS     -> weights sharded over 'c',
    (conv_2d.cu:115-131, 747-814)             replicated over n/h/w; GSPMD
                                              psums the gradient
  * Xavier-uniform init (conv_2d.cu:399)   -> glorot_uniform
  * fused bias + optional ReLU             -> same fusion, by XLA
    (conv_2d.cu:523-536)
"""

from __future__ import annotations

from typing import Dict, List

from flexflow_tpu.ops.base import Op, Tensor
from flexflow_tpu.strategy import ParallelConfig


class Conv2D(Op):
    AXIS_NAMES = ("w", "h", "c", "n")

    def __init__(self, name: str, pc: ParallelConfig, input: Tensor,
                 out_channels: int, kernel_h: int, kernel_w: int,
                 stride_h: int, stride_w: int, padding_h: int, padding_w: int,
                 relu: bool = False):
        super().__init__(name, pc, [input])
        assert input.ndim == 4, "conv2d input must be NHWC"
        n, h, w, cin = input.shape
        self.in_channels = cin
        self.out_channels = out_channels
        self.kernel_h, self.kernel_w = kernel_h, kernel_w
        self.stride_h, self.stride_w = stride_h, stride_w
        self.padding_h, self.padding_w = padding_h, padding_w
        self.relu = relu
        # output extents: conv_2d.cu:65-68
        out_h = 1 + (h + 2 * padding_h - kernel_h) // stride_h
        out_w = 1 + (w + 2 * padding_w - kernel_w) // stride_w
        self.output = Tensor((n, out_h, out_w, out_channels),
                             input.dtype, self, name)

    def _spatial_placeable(self, pc) -> bool:
        """Can this conv run under a manual (shard_map) spatial/channel
        grid?  Channel splits need no exchange at all: the input is
        replicated over 'c' (the grid's c splits OUTPUT channels,
        conv_2d.cu:72), each shard convolves its kernel slice, and
        shard_map's transpose inserts the dL/dx psum over 'c' — the
        reference's replica regions + BWD2 (linear.cu:570-603) for free.
        Spatial splits are supported for SAME-padded stride-1 convs (odd
        kernel, p = (k-1)/2) — the halo exchange then reduces to 'borrow
        (k-1)/2 edge rows from each neighbor, zeros at the boundary',
        exactly the conv's own zero padding (placed_prelude).  Everything
        else keeps the batch-only placed form or the canonical GSPMD path
        (XLA's own halo machinery)."""
        pw, ph, pcc, pn = pc.dims
        if pcc > 1 and self.out_channels % pcc:
            return False
        n, h, w, _ = self.inputs[0].shape
        for parts, extent, k, s, p in (
                (ph, h, self.kernel_h, self.stride_h, self.padding_h),
                (pw, w, self.kernel_w, self.stride_w, self.padding_w)):
            if parts == 1:
                continue
            if s != 1 or k % 2 == 0 or p != (k - 1) // 2:
                return False
            if extent % parts:
                return False
            if (k - 1) // 2 > extent // parts:
                return False  # halo radius exceeds the local shard — the
                # single-hop ppermute exchange can't reach past neighbors
        return self.output.shape[0] % pc.dims[3] == 0

    def input_specs(self, pc=None):
        from jax.sharding import PartitionSpec as P

        pc = pc or self.pc
        # placed execution (shard_map on a device block): batch-only
        # grids always; channel grids via the kernel's own 'c' sharding;
        # spatial grids for the SAME/stride-1 family via the manual halo
        # exchange in placed_prelude.  The input never shards over 'c'
        # (replicated — the grid's c splits OUTPUT channels).
        if pc.dims[:3] == (1, 1, 1):
            return [P("n", None, None, None)]
        if self._spatial_placeable(pc):
            return [P("n", "h", "w", None)]
        return None

    def placed_prelude(self, xs: List, train: bool):
        """Spatial halo exchange for placed grids: borrow the (k-1)/2 edge
        rows/cols from each neighbor via ppermute — boundary shards
        receive ppermute's zeros, which ARE the conv's zero padding.  Runs
        outside the group switch (collectives are illegal inside); the
        reference exchanges the same halos through Legion's restriction
        partitions (conv_2d.cu:93-113)."""
        from flexflow_tpu.ops.base import exchange_halo

        pw, ph, _pc, _pn = self.pc.dims
        if ph == 1 and pw == 1:
            return None
        (x,) = xs
        x = exchange_halo(x, "h", ph, self.kernel_h, 1)
        x = exchange_halo(x, "w", pw, self.kernel_w, 2)
        return x

    def sharded_forward(self, params, state, xs: List, train: bool,
                        aux=None):
        """Placed-grid forward: consume the pre-haloed input from
        placed_prelude and convolve VALID on the sharded axes (their zero
        padding arrived with the halo)."""
        if aux is None:
            return self.forward(params, state, xs, train)
        pw, ph, _pc, _pn = self.pc.dims
        pad_h = 0 if ph > 1 else self.padding_h
        pad_w = 0 if pw > 1 else self.padding_w
        return self._conv_bias_relu(params, aux, pad_h, pad_w), state

    def placement_signature(self):
        return (self.in_channels, self.out_channels, self.kernel_h,
                self.kernel_w, self.stride_h, self.stride_w,
                self.padding_h, self.padding_w, self.relu)

    def placed_local(self) -> bool:
        # point-local exactly when no spatial halos are needed
        pw, ph, _pc, _pn = self.pc.dims
        return pw == 1 and ph == 1

    def point_placeable(self) -> bool:
        # Set-family per-device dispatch replicates the input, so halo
        # rows are STATIC slices of the full tensor — every spatial grid
        # qualifies, any stride/kernel/padding (round 5, widening the
        # block/stride bar of SAME/stride-1 only; the reference ran any
        # conv on any named GPU, nmt/rnn_mapper.cc:28-41).  Divisibility
        # of the assembled output is checked by _set_eligible.
        return True

    def point_forward(self, params, state, xs, idx, sizes, train):
        """One spatial/channel/batch grid point from the FULL input: pad
        once (the conv's own zero padding), slice the fixed-size halo
        window for this point's output tile, convolve VALID.  Identical
        window sizes across points keep the per-device switch's avals
        equal."""
        import jax.numpy as jnp

        (x,) = xs
        n, oh, ow, _ = self.output.shape
        pn, pcc = sizes.get("n", 1), sizes.get("c", 1)
        ph, pw = sizes.get("h", 1), sizes.get("w", 1)
        if pn > 1:
            bs = n // pn
            x = x[idx["n"] * bs:(idx["n"] + 1) * bs]
        if ph > 1 or pw > 1:
            x = jnp.pad(x, ((0, 0), (self.padding_h, self.padding_h),
                            (self.padding_w, self.padding_w), (0, 0)))
            oh_l, ow_l = oh // ph, ow // pw
            h0 = idx["h"] * oh_l * self.stride_h
            hl = (oh_l - 1) * self.stride_h + self.kernel_h
            w0 = idx["w"] * ow_l * self.stride_w
            wl = (ow_l - 1) * self.stride_w + self.kernel_w
            x = x[:, h0:h0 + hl, w0:w0 + wl, :]
            pad_h = pad_w = 0
        else:
            pad_h, pad_w = self.padding_h, self.padding_w
        del pcc  # params arrive already c-sliced (kernel/bias over 'c')
        return (self._conv_bias_relu(params, x, pad_h, pad_w),), {}

    def regrid_input_specs(self):
        from jax.sharding import PartitionSpec as P

        # input channels are never split (the grid's c splits OUTPUT
        # channels, conv_2d.cu:72): replicated over 'c', spatial/batch per
        # the own grid (XLA adds halo exchanges for the h/w shards)
        return [P("n", "h", "w", None)]

    def init_params(self, rng) -> Dict:
        import jax

        kshape = (self.kernel_h, self.kernel_w,
                  self.in_channels, self.out_channels)
        kernel = jax.nn.initializers.glorot_uniform(in_axis=(0, 1, 2),
                                                    out_axis=3)(
            rng, kshape, "float32")
        bias = jax.numpy.zeros((self.out_channels,), "float32")
        return {"kernel": kernel, "bias": bias}

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return {"kernel": P(None, None, None, "c"), "bias": P("c")}

    def output_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("n", "h", "w", "c")

    def _conv_bias_relu(self, params, x, pad_h: int, pad_w: int):
        """The one conv/bias/relu body shared by the canonical forward and
        the placed (pre-haloed) path, so the two can never diverge."""
        import jax
        from jax import lax

        kernel = params["kernel"].astype(x.dtype)
        y = lax.conv_general_dilated(
            x, kernel,
            window_strides=(self.stride_h, self.stride_w),
            padding=((pad_h, pad_h), (pad_w, pad_w)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y + params["bias"].astype(y.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        return y

    def forward(self, params, state, xs: List, train: bool):
        (x,) = xs
        return self._conv_bias_relu(params, x, self.padding_h,
                                    self.padding_w), state

    def local_clone(self, pc: ParallelConfig):
        pw, ph, pc_, pn = pc.dims
        n, h, w, cin = self.inputs[0].shape
        if n % pn or h % ph or w % pw or self.out_channels % pc_:
            return None
        t = Tensor((n // pn, h // ph, w // pw, cin))
        return Conv2D(self.name, ParallelConfig((1, 1, 1, 1), (0,)), t,
                      self.out_channels // pc_, self.kernel_h, self.kernel_w,
                      self.stride_h, self.stride_w, self.padding_h,
                      self.padding_w, self.relu)

    def flops_per_sample(self) -> float:
        _, oh, ow, oc = self.output.shape
        return 2.0 * oh * ow * oc * self.kernel_h * self.kernel_w * self.in_channels

    def param_bytes(self) -> int:
        return 4 * (self.kernel_h * self.kernel_w * self.in_channels
                    * self.out_channels + self.out_channels)
