"""RnnModel: seq2seq NMT trainer (reference: nmt/rnn.h:100-379,
nmt/rnn.cu:61-336, driver nmt/nmt.cc).

DAG parity (nmt/rnn.cu:298-326): the sequence is chopped into chunks of
``lstm_per_node_length`` steps; each (layer, chunk) LSTM is an independent
op with its own ParallelConfig; hidden state flows chunk -> chunk, outputs
flow layer -> layer; decoder chunk 0 receives the last encoder chunk's
state.  Per-chunk vocab projections share one weight; softmaxDP computes the
chunk loss against the same chunk's dst tokens.

Weight sharing (the reference's SharedVariable with its 2-level hand-rolled
hierarchical allreduce, nmt/rnn.cu:650-703) is expressed by param_key
sharing: jax.grad sums the chunk ops' contributions, and GSPMD emits the
hierarchical reduction over ICI/DCN.

Update rule parity: the reference applies ``w += -0.1 * grad_sum``
(nmt/rnn.cu:684-702, rate -0.1, no normalization).  We keep SGD with the
model's learning rate on the *summed* (not averaged) chunk gradients, and
normalize the loss by total target tokens instead — document once, apply
everywhere (SURVEY.md §7 normalization note)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from flexflow_tpu.config import FFConfig
from flexflow_tpu.machine import MachineModel
from flexflow_tpu.model import FFModel
from flexflow_tpu.ops.base import Tensor
from flexflow_tpu.ops.embed import Embed
from flexflow_tpu.ops.lstm import LSTMChunk
from flexflow_tpu.ops.rnn_linear import RnnLinear
from flexflow_tpu.ops.seq import SliceSeq
from flexflow_tpu.ops.softmax_dp import SoftmaxDP
from flexflow_tpu.strategy import ParallelConfig, Strategy


@dataclasses.dataclass
class RnnConfig:
    """nmt/nmt.cc:34-44 defaults."""

    batch_size: int = 64
    num_layers: int = 2
    seq_length: int = 20
    hidden_size: int = 2048
    embed_size: int = 2048
    vocab_size: int = 20 * 1024
    lstm_per_node_length: int = 10   # LSTM_PER_NODE_LENGTH, nmt/rnn.h:23
    learning_rate: float = 0.1       # reference applies rate -0.1 updates
    num_iterations: int = 10
    compute_dtype: str = "float32"
    # parameter storage dtype ("bfloat16" = mixed precision with f32
    # masters in the optimizer state; forwarded to FFConfig)
    param_dtype: str = "float32"
    # Pallas kernel routing policy auto|on|off (forwarded to FFConfig;
    # ops/pallas/__init__.set_policy)
    pallas: str = "auto"
    seed: int = 0
    # verification mechanisms (forwarded to FFConfig; SURVEY.md §4)
    params_init: str = "default"
    print_intermediates: bool = False
    dry_compile: bool = False
    # run telemetry (forwarded to FFConfig; obs subsystem)
    obs_dir: str = ""
    run_id: str = ""
    # sampled per-op timing + live metrics export (MFU-waterfall round)
    op_time_every: int = 0
    metrics_path: str = ""
    # execution performance (forwarded to FFConfig; round 6)
    regrid_planner: str = "on"
    prefetch_depth: int = 2
    placed_overlap: str = "on"
    # fault tolerance (forwarded to FFConfig; robustness round)
    ckpt_dir: str = ""
    ckpt_freq: int = 0
    on_divergence: str = "halt"
    max_rollbacks: int = 3
    fault_spec: str = ""
    # elastic training + async checkpointing (forwarded to FFConfig)
    elastic: bool = False
    min_devices: int = 1
    research_budget_s: float = 30.0
    # decomposed re-search (round 19, forwarded to FFConfig)
    decompose: bool = False
    block_budget_s: float = 0.0
    boundary_refine_iters: int = 0
    ckpt_async: bool = False
    # elastic re-expansion / graceful drain / step watchdog (round 9)
    max_regrows: int = 1
    regrow_probes: int = 2
    drain_budget_s: float = 60.0
    hang_factor: float = 0.0
    hang_min_s: float = 60.0
    transient_reset_steps: int = 16
    # static plan analyzer (verify/plan.py): demote degradation
    # diagnostics to warnings (old degrade-and-continue behavior)
    allow_degraded: bool = False

    @property
    def chunks_per_seq(self) -> int:
        return (self.seq_length + self.lstm_per_node_length - 1) \
            // self.lstm_per_node_length


def default_global_config(cfg: RnnConfig, machine: MachineModel) -> Strategy:
    """set_global_config parity (nmt/nmt.cc:269-308): LSTMs/linear/softmax
    data-parallel over all devices; embeds pinned (src -> device 0,
    dst -> device 1)."""
    s = Strategy()
    n = machine.num_devices
    devs = tuple(range(n))
    npc = cfg.chunks_per_seq
    for i in range(2 * npc):
        pinned = 0 if i < npc else min(1, n - 1)
        s[f"embed{i}"] = ParallelConfig((1,), (pinned,))
    for l in range(cfg.num_layers):
        for j in range(2 * npc):
            s[f"lstm{l}_{j}"] = ParallelConfig((n,), devs)
    for j in range(npc):
        s[f"linear{j}"] = ParallelConfig((1, n), devs)
        s[f"softmax{j}"] = ParallelConfig((n,), devs)
    return s


def pipeline_stage_strategy(cfg: RnnConfig, machine: MachineModel,
                            num_stages: int) -> Strategy:
    """Pipeline-parallel strategy: LSTM layer ``l`` placed on aligned device
    block ``l % num_stages`` (stage = device block — the reference's own
    pipeline representation, per-op-instance device lists in
    nmt/nmt.cc:269-308).  Chunk ops of adjacent layers on different blocks
    form DAG antidiagonals that the placement scheduler merges into
    concurrent shard_map groups (parallel/placement.py): layer l works on
    chunk j while layer l+1 works on chunk j-1 — wavefront/GPipe-style
    pipelining compiled into ONE SPMD step, from a plain strategy file.

    Embeds feed stage 0 and pin to its block; the vocab projections and
    losses stay data-parallel over the whole machine (they consume every
    stage's output)."""
    n = machine.num_devices
    if num_stages < 1 or n % num_stages:
        raise ValueError(
            f"{num_stages} stages do not divide the {n}-device machine")
    per = n // num_stages
    blocks = [tuple(range(g * per, (g + 1) * per))
              for g in range(num_stages)]
    devs = tuple(range(n))
    npc = cfg.chunks_per_seq
    s = Strategy()
    for i in range(2 * npc):
        s[f"embed{i}"] = ParallelConfig((per,), blocks[0])
    for l in range(cfg.num_layers):
        blk = blocks[l % num_stages]
        for j in range(2 * npc):
            s[f"lstm{l}_{j}"] = ParallelConfig((per,), blk)
    for j in range(npc):
        s[f"linear{j}"] = ParallelConfig((1, n), devs)
        s[f"softmax{j}"] = ParallelConfig((n,), devs)
    return s


class RnnModel(FFModel):
    def __init__(self, rnn_config: RnnConfig = None,
                 machine: Optional[MachineModel] = None,
                 strategies: Optional[Strategy] = None):
        self.rnn = rnn_config or RnnConfig()
        machine = machine or MachineModel()
        if strategies is None:
            strategies = default_global_config(self.rnn, machine)
        ff_cfg = FFConfig(
            batch_size=self.rnn.batch_size,
            learning_rate=self.rnn.learning_rate,
            weight_decay=0.0,
            num_iterations=self.rnn.num_iterations,
            compute_dtype=self.rnn.compute_dtype,
            param_dtype=self.rnn.param_dtype,
            pallas=self.rnn.pallas,
            seed=self.rnn.seed,
            params_init=self.rnn.params_init,
            print_intermediates=self.rnn.print_intermediates,
            dry_compile=self.rnn.dry_compile,
            obs_dir=self.rnn.obs_dir,
            run_id=self.rnn.run_id,
            op_time_every=self.rnn.op_time_every,
            metrics_path=self.rnn.metrics_path,
            regrid_planner=self.rnn.regrid_planner,
            prefetch_depth=self.rnn.prefetch_depth,
            placed_overlap=self.rnn.placed_overlap,
            ckpt_dir=self.rnn.ckpt_dir,
            ckpt_freq=self.rnn.ckpt_freq,
            on_divergence=self.rnn.on_divergence,
            max_rollbacks=self.rnn.max_rollbacks,
            fault_spec=self.rnn.fault_spec,
            elastic=self.rnn.elastic,
            min_devices=self.rnn.min_devices,
            research_budget_s=self.rnn.research_budget_s,
            decompose=self.rnn.decompose,
            block_budget_s=self.rnn.block_budget_s,
            boundary_refine_iters=self.rnn.boundary_refine_iters,
            ckpt_async=self.rnn.ckpt_async,
            max_regrows=self.rnn.max_regrows,
            regrow_probes=self.rnn.regrow_probes,
            drain_budget_s=self.rnn.drain_budget_s,
            hang_factor=self.rnn.hang_factor,
            hang_min_s=self.rnn.hang_min_s,
            transient_reset_steps=self.rnn.transient_reset_steps,
            allow_degraded=self.rnn.allow_degraded,
            strategies=strategies,
        )
        super().__init__(ff_cfg, machine)
        self._build()

    # ------------------------------------------------------------------

    def _build(self):
        cfg = self.rnn
        npc = cfg.chunks_per_seq
        L = cfg.lstm_per_node_length
        B = cfg.batch_size

        self.src_tokens = self.create_input((B, cfg.seq_length), "int32",
                                            "src_tokens")
        self.dst_tokens = self.create_input((B, cfg.seq_length), "int32",
                                            "dst_tokens")

        def pc(name, ndims):
            return self._pc(name, ndims)

        # chunk slices (reference: per-chunk word regions, nmt/rnn.cu:89-126)
        srcs, dsts = [], []
        for i in range(npc):
            start = i * L
            length = min(L, cfg.seq_length - start)
            srcs.append(self._add(SliceSeq(
                f"src_chunk{i}", pc(f"src_chunk{i}", 1), self.src_tokens,
                start, length)))
            dsts.append(self._add(SliceSeq(
                f"dst_chunk{i}", pc(f"dst_chunk{i}", 1), self.dst_tokens,
                start, length)))

        # embeddings: chunks share srcEmbed / dstEmbed tables
        embeds: List[Tensor] = []
        for i in range(2 * npc):
            tok = srcs[i] if i < npc else dsts[i - npc]
            key = "srcEmbed" if i < npc else "dstEmbed"
            embeds.append(self._add(Embed(
                f"embed{i}", pc(f"embed{i}", 1), tok,
                cfg.vocab_size, cfg.embed_size, param_key=key,
                compute_dtype=cfg.compute_dtype)))

        # LSTM grid: lstm[layer][chunk] (nmt/rnn.cu:298-318)
        lstm_out = [[None] * (2 * npc) for _ in range(cfg.num_layers)]
        lstm_ops = [[None] * (2 * npc) for _ in range(cfg.num_layers)]
        for i in range(cfg.num_layers):
            for j in range(2 * npc):
                x = embeds[j] if i == 0 else lstm_out[i - 1][j]
                if j == 0:
                    hx = cx = None  # zero initial state (zero[i], rnn.cu:127)
                else:
                    prev = lstm_ops[i][j - 1]
                    hx, cx = prev.hy, prev.cy
                key = f"encoder{i}" if j < npc else f"decoder{i}"
                op = LSTMChunk(f"lstm{i}_{j}", pc(f"lstm{i}_{j}", 1),
                               x, hx, cx, cfg.hidden_size, param_key=key)
                self.layers.append(op)
                lstm_ops[i][j] = op
                lstm_out[i][j] = op.output

        # vocab projection + per-chunk DP softmax loss (decoder side)
        self.loss_ops = []
        for j in range(npc):
            logit = self._add(RnnLinear(
                f"linear{j}", pc(f"linear{j}", 2),
                lstm_out[cfg.num_layers - 1][npc + j],
                cfg.vocab_size, param_key="linear"))
            sm = SoftmaxDP(f"softmax{j}", pc(f"softmax{j}", 1),
                           logit, dsts[j])
            self.layers.append(sm)
            self.loss_ops.append(sm)

        for op in self.layers:
            op.validate_partitioning()

    # ------------------------------------------------------------------

    def loss_fn(self, params, state, src, dst, train: bool = True):
        """Mean NLL per target token over all decoder chunks."""
        inputs = {self.src_tokens.tid: src, self.dst_tokens.tid: dst}
        values, new_state = self.apply(params, state, inputs, train)
        total = 0.0
        for op in self.loss_ops:
            total = total + op.loss(values[op.output.tid],
                                    values[op.labels_tensor.tid])
        ntokens = self.rnn.batch_size * self.rnn.seq_length
        return total / ntokens, new_state

    def make_train_step(self):
        """Plain SGD on summed chunk grads (reference rate*grad updates,
        nmt/rnn.cu:684-702) — shared factory in FFModel."""
        return self.make_sgd_step(self.rnn.learning_rate)

    def init_opt_state(self, params):
        # plain SGD carries no momentum buffers; mixed-precision mode
        # still needs the float32 masters (None in float32 mode)
        return self.master_opt_state(params)

    def fit(self, data_iter, num_iterations: Optional[int] = None,
            warmup: int = 1, log=print, rebuild=None):
        out = super().fit(data_iter,
                          num_iterations or self.rnn.num_iterations,
                          warmup, log, rebuild=rebuild)
        out["sentences_per_sec"] = out["images_per_sec"]
        return out


def synthetic_token_batches(machine: MachineModel, batch_size: int,
                            seq_length: int, vocab_size: int, seed: int = 0):
    """Random (src, dst) token pairs, batch-sharded (reference inits word
    tensors with a constant; random avoids degenerate instant
    memorization)."""
    from flexflow_tpu.data import synthetic_token_stream

    return synthetic_token_stream(machine, batch_size, seq_length,
                                  vocab_size, seed, streams=2)
