"""NMT / RNN subsystem — TPU-native equivalent of the reference's second
application (nmt/, self-contained seq2seq trainer)."""

from flexflow_tpu.nmt.rnn_model import RnnConfig, RnnModel, default_global_config

__all__ = ["RnnConfig", "RnnModel", "default_global_config"]
