"""Multi-tenant fleet: many jobs on one device pool, with the strategy
search as the scheduler (the ROADMAP capstone).

The elastic runtime already speaks a scheduler's language — graceful
drain exits 0 on SIGTERM, :func:`~flexflow_tpu.utils.elastic.recover` /
``recover_grow`` resize a live mesh, checkpoints are verified and async.
This package builds the layer above:

  * :mod:`fleet.job` — :class:`JobSpec` (workload kind, model builder,
    priority, min/max devices) plus the lifecycle state machine
    (pending -> placing -> running -> draining -> resized -> done /
    failed) wrapping the existing training-step machinery and
    :class:`~flexflow_tpu.serve.engine.ServeEngine`;
  * :mod:`fleet.arbiter` — placement as search: candidate slice
    assignments priced per job through the NATIVE simulator
    (``sim.search.price_on_slice`` — a warm-started budget-capped
    re-search under the job's objective, makespan for train / latency
    for serve), with a deterministic DP proxy when the native lib is
    absent; the chosen packing minimizes weighted predicted cost over
    the work-conserving (Pareto-maximal) packings;
  * :mod:`fleet.coordinator` — the event loop: admit jobs onto disjoint
    ``MachineModel.slice_of`` slices, round-robin each running job a
    quantum of steps, re-pack when demand shifts, and issue DIRECTED
    resizes (``utils.elastic.directed_resize`` — the non-fault entry
    into the elastic machinery) so preemption is a routine economy, not
    a fault.

Obs kinds: ``fleet_job`` (one per lifecycle transition, vts-stamped),
``fleet_placement`` (one per arbiter packing), ``fleet_rebalance`` (one
per executed re-packing), ``fleet_wait`` (one per finished job: its
life decomposed into wait/placement/run/drain/resize virtual seconds),
``fleet_util`` (one per round: every device-step accounted busy/idle/
resizing under the exact :func:`~flexflow_tpu.fleet.coordinator.
check_fleet_util` invariant), ``fleet_summary`` (one per coordinator
run).  Per-job streams live in ``obs_dir/<job_id>/`` so concurrent
jobs never interleave one run file.  ``apps/fleet.py`` is the driver;
``make fleet-smoke`` is the deterministic two-jobs-trade-devices CPU
scenario, and ``apps/fleetsim.py`` (``make fleetsim-smoke``) is the
trace-driven fleet simulation that benchmarks scheduler policy the way
kernels are benchmarked (FLEET_r01.json).
"""

from flexflow_tpu.fleet.arbiter import Arbiter
from flexflow_tpu.fleet.coordinator import (FleetCoordinator,
                                            VirtualClock,
                                            check_fleet_util)
from flexflow_tpu.fleet.job import Job, JobSpec

__all__ = ["Arbiter", "FleetCoordinator", "Job", "JobSpec",
           "VirtualClock", "check_fleet_util"]
