"""Fleet jobs: one spec + lifecycle state machine per tenant, wrapping
the existing execution machinery.

A :class:`JobSpec` names WHAT runs (workload kind, the ``build(config,
machine)`` model factory the elastic path already uses, the payload) and
under WHAT terms (priority, min/max devices, the serve demand
watermark).  A :class:`Job` is one admitted instance: the coordinator
moves it through the lifecycle

    pending -> placing -> running -> (draining -> resized -> running)*
            -> done | failed

where the parenthesized loop is one DIRECTED resize (coordinator-
imposed, ``utils.elastic.directed_resize`` — never the fault
classifier): the job drains to its next step boundary, the elastic
machinery regrids its live state onto the new slice, and it resumes.
A failed resize leg takes the ABORT edge ``draining -> running``: the
job resumes on the slice the completed legs left it holding (the
exception still propagates so the coordinator can re-pack).

Two runner shapes:

  * **train** — a compact version of ``_fit``'s step core: jitted
    ``make_train_step`` over host numpy batches placed with the CURRENT
    slice's batch sharding (after a resize the same host ring re-places
    onto the new mesh — the elastic continuation pattern).  Losses stay
    on device between syncs; loss CONTINUITY across resizes rides the
    same ``prior_losses`` mechanism fault recovery uses.
  * **serve** — a :class:`~flexflow_tpu.serve.engine.ServeEngine`
    session driven through ``start()`` / ``step_once()`` so the
    coordinator can interleave decode steps with other jobs' quanta.
    The engine's own watermark autoscaler is DISABLED (``queue_hi=0``,
    ``idle_boundaries=0``): the coordinator is the only resizer, and
    the engine adopts each directed resize via ``adopt_resize``.

Every job logs to its OWN obs stream (``obs_dir/<job_id>/``), so the
``elastic_resize`` records a directed resize emits land in the job's
file while the coordinator's ``fleet_*`` records land in the pool's.

**Lifecycle attribution (round 18).**  The coordinator attaches its
:class:`~flexflow_tpu.fleet.coordinator.VirtualClock` at admission
(:meth:`Job.attach_clock`); from then on every ``fleet_job`` transition
record carries a virtual timestamp ``vts`` and the time spent in the
state being LEFT is accumulated into one of five buckets — wait
(pending), placement (placing), run, drain, resize — so that when the
job reaches ``done``/``failed`` a single ``fleet_wait`` record
decomposes its whole life, bit-exactly, into those buckets
(``wait_s + placement_s + run_s + drain_s + resize_s == total_s``).

**Sim mode (apps/fleetsim.py).**  ``JobSpec.sim_steps > 0`` makes the
job a SYNTHETIC trace job: the full lifecycle / arbiter / rebalance
machinery runs for real, but ``place`` builds no model and each quantum
just burns virtual steps — so hundreds of jobs over a virtual day cost
CPU-milliseconds.  A sim serve job's engine is a :class:`_SimBacklog`
stub whose queue depth is its remaining steps, so the ``queue_hi``
demand watermark drives real rebalances.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

# lifecycle states and the legal transitions between them
STATES = ("pending", "placing", "running", "draining", "resized",
          "done", "failed")
_TRANSITIONS = {
    "pending": ("placing", "failed"),
    "placing": ("running", "failed"),
    "running": ("draining", "done", "failed"),
    # draining -> running is the resize ABORT path: a leg failed, the
    # job resumes on whatever slice the completed legs left it holding
    "draining": ("resized", "running", "done", "failed"),
    "resized": ("running", "failed"),
    "done": (),
    "failed": (),
}


class JobStateError(RuntimeError):
    """An illegal lifecycle transition (a coordinator bug, not a user
    error — the state machine is the contract)."""


# which fleet_wait bucket the time spent in each state accrues to: the
# bucket is keyed by the state being LEFT at a transition
_STATE_BUCKET = {
    "pending": "wait_s",
    "placing": "placement_s",
    "running": "run_s",
    "draining": "drain_s",
    "resized": "resize_s",
}


class _SimBacklog:
    """Serve-demand stub for sim jobs: queue depth is the job's
    remaining virtual steps, so a backlogged sim serve job bids
    ``max_devices`` until it burns below its ``queue_hi`` watermark —
    the same demand shift a real engine's queue drives."""

    def __init__(self, job: "Job"):
        self._job = job

    def queue_depth(self) -> int:
        return max(int(self._job._sim_left), 0)


@dataclasses.dataclass
class JobSpec:
    """Everything the coordinator needs to admit one tenant.

    ``build(config, machine)`` is the SAME factory shape fit()'s elastic
    path takes; ``config`` is the job's FFConfig (batch size, iteration
    count, seed, elastic knobs).  ``payload`` is workload input: a
    host-batch iterable factory ``() -> iterator`` for train jobs, a
    request list for serve jobs.  ``min_devices``/``max_devices`` bound
    the slice the arbiter may assign; ``priority`` weights the job's
    predicted cost in the packing objective.  ``queue_hi`` is the serve
    job's DEMAND watermark: queue depth at or above it makes the job
    bid for ``max_devices`` (0 keeps demand at ``min_devices``)."""

    job_id: str
    kind: str                      # "train" | "serve"
    build: object                  # (config, machine) -> model
    config: object                 # FFConfig
    payload: object = None
    priority: float = 1.0
    min_devices: int = 1
    max_devices: int = 0           # 0 = no cap beyond the pool
    queue_hi: int = 0              # serve demand watermark
    strategy_path: str = ""        # pre-searched strategy artifact
    search_iters: int = 200        # arbiter pricing proposals per slice
    #: disaggregated serving demand tier (serve/router.py): "" is the
    #: classic single-pool serve job; "prefill" prices its slice under
    #: the latency objective (full prompt pass), "decode" under the
    #: decode objective (single-token step + KV stream) — so a
    #: disaggregated deployment admits as TWO JobSpecs, one per pool
    serve_phase: str = ""
    #: virtual-step trace mode (apps/fleetsim.py): >0 makes this a
    #: SYNTHETIC job that consumes exactly ``sim_steps`` quantum steps
    #: with no model build — lifecycle, arbiter pricing, and rebalances
    #: all run for real, only the runner is simulated
    sim_steps: int = 0

    def __post_init__(self):
        if self.kind not in ("train", "serve"):
            raise ValueError(f"job {self.job_id}: kind must be 'train' "
                             f"or 'serve', got {self.kind!r}")
        if self.serve_phase not in ("", "prefill", "decode"):
            raise ValueError(f"job {self.job_id}: serve_phase must be "
                             f"'', 'prefill' or 'decode', got "
                             f"{self.serve_phase!r}")
        if self.serve_phase and self.kind != "serve":
            raise ValueError(f"job {self.job_id}: serve_phase "
                             f"{self.serve_phase!r} needs kind='serve'")
        if self.min_devices < 1:
            raise ValueError(f"job {self.job_id}: min_devices >= 1")
        if self.max_devices and self.max_devices < self.min_devices:
            raise ValueError(f"job {self.job_id}: max_devices "
                             f"{self.max_devices} < min_devices "
                             f"{self.min_devices}")
        if self.sim_steps < 0:
            raise ValueError(f"job {self.job_id}: sim_steps >= 0")


class Job:
    """One admitted job: spec + lifecycle + the live runner state."""

    def __init__(self, spec: JobSpec, olog=None, log=print):
        from flexflow_tpu import obs

        self.spec = spec
        self.olog = olog if olog is not None else obs.NULL
        self.log = log
        self.state = "pending"
        self.ordinals: List[int] = []   # pool ordinals currently held
        self.model = None
        self.engine = None              # serve jobs
        self.strategy = None            # the strategy the job runs under
        self.result: Optional[Dict] = None
        self.error: Optional[str] = None
        # train runner state
        self._step = None
        self._params = self._state = self._opt = None
        self._batches = None
        self._sharding = None
        self._loss_hist: List[float] = []   # host floats, synced
        self._loss_dev: List = []           # device losses since sync
        self.iters_done = 0
        # virtual-clock attribution (attach_clock wires the clock; all
        # vts stamping / fleet_wait emission is gated on it being set)
        self.clock = None
        self.submit_v: Optional[float] = None
        self._last_v: Optional[float] = None
        self.vtimes: Dict[str, float] = {
            "wait_s": 0.0, "placement_s": 0.0, "run_s": 0.0,
            "drain_s": 0.0, "resize_s": 0.0}
        #: steps actually executed in the most recent step_quantum call
        #: (the coordinator's per-round busy-device-steps accounting)
        self.last_quantum_steps = 0
        #: decode replicas this serve job has currently lost (the
        #: resilience round's degraded-capacity signal): while > 0 the
        #: job bids ``max_devices`` so the coordinator re-prices the
        #: fleet around the loss; a directed resize clears it
        self.degraded = 0
        # sim mode: remaining virtual steps (0 for real jobs)
        self._sim_left = int(getattr(spec, "sim_steps", 0) or 0)
        if self._sim_left > 0 and spec.kind == "serve":
            self.engine = _SimBacklog(self)

    # ------------------------------------------------------------------
    # lifecycle

    def attach_clock(self, clock) -> None:
        """Wire the coordinator's virtual clock in at admission: from
        now on every transition is vts-stamped and per-state durations
        accrue into ``vtimes`` (the ``fleet_wait`` decomposition)."""
        self.clock = clock
        self.submit_v = clock.now()
        self._last_v = self.submit_v

    def to_state(self, new: str, **detail) -> None:
        """One legal transition, recorded as a ``fleet_job`` event on the
        JOB's stream (the coordinator mirrors it on the pool stream).
        With a clock attached the record carries the virtual timestamp
        ``vts``, the time spent in the state being left accrues to its
        ``vtimes`` bucket, and a terminal transition additionally emits
        the job's ``fleet_wait`` decomposition record."""
        if new not in STATES:
            raise JobStateError(f"unknown state {new!r}")
        if new not in _TRANSITIONS[self.state]:
            raise JobStateError(
                f"job {self.spec.job_id}: illegal transition "
                f"{self.state} -> {new}")
        old, self.state = self.state, new
        if self.clock is not None:
            vts = self.clock.now()
            bucket = _STATE_BUCKET.get(old)
            if bucket is not None and self._last_v is not None:
                self.vtimes[bucket] += vts - self._last_v
            self._last_v = vts
            detail = dict(detail, vts=vts)
        # "workload", not "kind" — the obs record's own kind field is
        # "fleet_job" and must not be shadowed
        self.olog.event("fleet_job", job=self.spec.job_id,
                        workload=self.spec.kind, state=new,
                        from_state=old, devices=len(self.ordinals),
                        **detail)
        if self.clock is not None and new in ("done", "failed"):
            vt = self.vtimes
            self.olog.event(
                "fleet_wait", job=self.spec.job_id,
                workload=self.spec.kind, state=new,
                devices=len(self.ordinals),
                wait_s=vt["wait_s"], placement_s=vt["placement_s"],
                run_s=vt["run_s"], drain_s=vt["drain_s"],
                resize_s=vt["resize_s"],
                total_s=(vt["wait_s"] + vt["placement_s"] + vt["run_s"]
                         + vt["drain_s"] + vt["resize_s"]),
                submit_v=self.submit_v, done_v=detail["vts"])

    @property
    def active(self) -> bool:
        return self.state in ("placing", "running", "draining",
                              "resized")

    def fail(self, err: str) -> None:
        self.error = err
        if self.state not in ("done", "failed"):
            self.to_state("failed", error=err)

    # ------------------------------------------------------------------
    # demand: what slice size the job currently bids for

    def mark_degraded(self, lost: int,
                      reason: str = "replica_crash") -> None:
        """A serve job lost ``lost`` decode replica(s): record the
        degraded capacity (one job-labeled ``replica_down`` event per
        call) and raise the job's bid to ``max_devices`` so the next
        ``_demands()`` key change drives the coordinator through a
        directed re-price.  ``lost=0`` clears the flag explicitly (a
        successful :meth:`resize` also clears it — restored capacity
        ends the emergency bid)."""
        if self.spec.kind != "serve":
            raise JobStateError(
                f"job {self.spec.job_id}: only serve jobs report "
                f"degraded replica capacity")
        self.degraded = max(0, int(lost))
        if self.degraded:
            detail = {}
            if self.clock is not None:
                detail["vts"] = self.clock.now()
            self.olog.event(
                "replica_down", job=self.spec.job_id, pool="serve",
                replica=None, replicas_lost=self.degraded,
                reason=reason, devices=len(self.ordinals), **detail)
            self.log(f"fleet: job {self.spec.job_id} DEGRADED — "
                     f"{self.degraded} replica(s) down ({reason}), "
                     f"bidding max capacity for recovery")

    def demand(self, pool_size: int) -> int:
        """The size this job currently WANTS (the arbiter caps candidate
        slices at it): train jobs always bid their max (more devices is
        a faster step); serve jobs yield down to ``min_devices`` while
        the queue is calm and bid ``max_devices`` once depth crosses the
        ``queue_hi`` watermark — that demand shift is what triggers the
        coordinator's rebalances.  A DEGRADED serve job (lost replicas,
        :meth:`mark_degraded`) bids max regardless of its queue: it is
        serving the same load on less hardware."""
        cap = self.spec.max_devices or pool_size
        if self.spec.kind == "train":
            return min(cap, pool_size)
        if self.spec.kind == "serve" and self.degraded > 0:
            return min(cap, pool_size)
        if (self.spec.queue_hi > 0 and self.engine is not None
                and self.engine.queue_depth() >= self.spec.queue_hi):
            return min(cap, pool_size)
        return self.spec.min_devices

    def feasible_sizes(self, pool_size: int) -> List[int]:
        """Slice sizes this job can run on, ascending: within
        [min_devices, max_devices] and dividing the job's batch (the
        compiled rectangle must shard evenly over the slice)."""
        cap = min(self.spec.max_devices or pool_size, pool_size)
        batch = int(getattr(self.spec.config, "batch_size", 0) or 0)
        out = []
        for s in range(self.spec.min_devices, cap + 1):
            if batch and batch % s:
                continue
            out.append(s)
        return out

    def candidate_sizes(self, pool_size: int) -> List[int]:
        """The sizes the arbiter may actually assign this job right now:
        feasible sizes capped at the current demand — and for a
        BACKLOGGED serve job the bid is binding (only the largest
        feasible size at the bid), because handing a backlogged server
        one spare device is not relief, it is churn.  Train jobs stay
        flexible across their whole feasible range so the packing can
        trade them down when a serve bid arrives."""
        sizes = self.feasible_sizes(pool_size)
        want = self.demand(pool_size)
        capped = [s for s in sizes if s <= want] or sizes[:1]
        if self.spec.kind == "serve" and want > self.spec.min_devices:
            capped = capped[-1:]
        return capped

    # ------------------------------------------------------------------
    # placement

    def place(self, pool, ordinals: Sequence[int], strategy=None,
              drain: Optional[Dict] = None) -> None:
        """Build the job's model on its pool slice and start the runner.
        ``strategy`` is the arbiter's priced plan for this slice size
        (None = pure DP)."""
        import copy

        from flexflow_tpu.strategy import Strategy

        self.to_state("placing", ordinals=sorted(int(i) for i in ordinals))
        self.ordinals = sorted(int(i) for i in ordinals)
        if self.clock is not None:
            # placement costs virtual time: the placing -> running gap
            # is what fleet_wait's placement_s bucket measures
            self.clock.advance(self.clock.resize_steps)
        if self.spec.sim_steps > 0:
            # sim mode: no model, no slice — the lifecycle walk and the
            # arbiter's DP-proxy pricing are the whole point
            self.strategy = strategy
            self.to_state("running")
            return
        machine = pool.slice_of(self.ordinals)
        cfg = copy.copy(self.spec.config)
        # the elastic shrink path enforces cfg.min_devices — align it
        # with the spec so a directed shrink below the floor is refused
        cfg.min_devices = self.spec.min_devices
        cfg.strategies = strategy if strategy is not None else Strategy()
        self.strategy = cfg.strategies
        self.model = self.spec.build(cfg, machine)
        if self.spec.kind == "train":
            self._start_train()
        else:
            self._start_serve(drain)
        self.to_state("running")

    def _start_train(self) -> None:
        from flexflow_tpu.data.synthetic import _batch_sharding

        model = self.model
        self._params, self._state = model.init(model.config.seed)
        self._opt = model.init_opt_state(self._params)
        self._step = model.make_train_step()
        self._sharding = _batch_sharding(model.machine)
        self._batches = self.spec.payload()
        self.iters_done = 0

    def _start_serve(self, drain: Optional[Dict]) -> None:
        from flexflow_tpu.serve.engine import ServeEngine

        # the coordinator is the only resizer: watermarks off
        self.engine = ServeEngine(self.model, None, olog=self.olog,
                                  log=self.log, queue_hi=0,
                                  idle_boundaries=0)
        self.engine.start(list(self.spec.payload), drain=drain)

    # ------------------------------------------------------------------
    # stepping

    def step_quantum(self, n: int, drain: Optional[Dict] = None) -> bool:
        """Up to ``n`` steps (train iterations / decode boundaries).
        Returns True while the job has work left; on exhaustion the job
        transitions to ``done`` with its result attached."""
        self.last_quantum_steps = 0
        if self.state != "running":
            return self.active
        try:
            if self.spec.sim_steps > 0:
                return self._sim_quantum(n, drain)
            if self.spec.kind == "train":
                return self._train_quantum(n, drain)
            return self._serve_quantum(n)
        except Exception as e:  # noqa: BLE001 — one job must not kill the fleet
            self.fail(f"{type(e).__name__}: {e}")
            raise

    def _train_quantum(self, n: int, drain: Optional[Dict]) -> bool:
        import jax

        total = int(self.model.config.num_iterations)
        for _ in range(n):
            if self.iters_done >= total:
                break
            if drain is not None and drain.get("requested"):
                break
            batch = next(self._batches)
            placed = tuple(jax.device_put(np.asarray(x), self._sharding)
                           for x in batch)
            self._params, self._state, self._opt, loss = self._step(
                self._params, self._state, self._opt, *placed)
            self._loss_dev.append(loss)
            self.iters_done += 1
            self.last_quantum_steps += 1
        drained = bool(drain is not None and drain.get("requested"))
        if self.iters_done >= total or drained:
            self._sync_losses()
            self.result = {
                "loss": list(self._loss_hist),
                "iters": self.iters_done,
                "devices": self.model.machine.num_devices,
                "drained": drained and self.iters_done < total,
            }
            self.to_state("done", iters=self.iters_done,
                          drained=self.result["drained"])
            return False
        return True

    def _sim_quantum(self, n: int, drain: Optional[Dict]) -> bool:
        """Burn up to ``n`` virtual steps of the synthetic trace."""
        for _ in range(n):
            if self._sim_left <= 0:
                break
            if drain is not None and drain.get("requested"):
                break
            self._sim_left -= 1
            self.iters_done += 1
            self.last_quantum_steps += 1
        drained = bool(drain is not None and drain.get("requested"))
        if self._sim_left <= 0 or drained:
            self.result = {"iters": self.iters_done, "sim": True,
                           "devices": len(self.ordinals),
                           "drained": drained and self._sim_left > 0}
            self.to_state("done", iters=self.iters_done,
                          drained=self.result["drained"])
            return False
        return True

    def _serve_quantum(self, n: int) -> bool:
        eng = self.engine
        for _ in range(n):
            if not eng.step_once():
                break
            self.last_quantum_steps += 1
        if not eng.pending():
            self.result = eng.finish()
            self.to_state("done",
                          completed=self.result["completed"],
                          unserved=self.result["unserved"])
            return False
        return True

    def _sync_losses(self) -> None:
        import jax

        if self._loss_dev:
            self._loss_hist.extend(
                float(v) for v in jax.device_get(self._loss_dev))
            self._loss_dev = []

    # ------------------------------------------------------------------
    # directed resize (the coordinator's preemption economy)

    def resize(self, pool, new_ordinals: Sequence[int]) -> List[Dict]:
        """Move this RUNNING job to ``new_ordinals`` (pool ordinals) via
        the elastic machinery's directed entry point.  A nested change
        is one shrink or one grow; a sideways move (partial overlap)
        decomposes into shrink-to-intersection + grow — each leg emits
        one ``elastic_resize`` record on the job's stream.  Walks the
        lifecycle running -> draining -> resized -> running.

        A failed leg re-raises, but FIRST resumes the job running on
        the slice the completed legs actually left it holding (each leg
        swaps the model only on success, so that slice is live) and
        updates ``self.ordinals`` to match — the job is never stranded
        in ``draining``, and the coordinator can see which devices the
        failed move really freed."""
        new = sorted(int(i) for i in new_ordinals)
        old = list(self.ordinals)
        if new == old:
            return []
        if not set(new) & set(old):
            raise JobStateError(
                f"job {self.spec.job_id}: target slice {new} shares no "
                f"device with the current {old} — a fleet repack must "
                f"keep every job anchored (nested or overlapping moves "
                f"only)")
        self.to_state("draining", target=new)
        if self.clock is not None:
            # the drain-to-boundary span (draining -> resized gap)
            self.clock.advance(self.clock.resize_steps)
        legs = []
        inter = sorted(set(new) & set(old))
        if self.spec.sim_steps > 0:
            # sim mode: the lifecycle walk + clock cost of a move, with
            # no live state to regrid
            if inter != old:
                legs.append({"direction": "shrink",
                             "devices": len(inter)})
            if new != inter:
                legs.append({"direction": "grow", "devices": len(new)})
            self.ordinals = new
        else:
            try:
                if inter != old:      # release what the target drops
                    legs.append(self._resize_leg(pool, inter, old))
                    self.ordinals = inter
                if new != inter:      # adopt what the target adds
                    legs.append(self._resize_leg(pool, new, inter))
                self.ordinals = new
            except Exception as e:  # noqa: BLE001 — abort, resume in place
                self.to_state("running",
                              resize_failed=f"{type(e).__name__}",
                              ordinals=list(self.ordinals))
                raise
        self.to_state("resized", ordinals=new,
                      directions=[r["direction"] for r in legs])
        if self.clock is not None:
            # the regrid span (resized -> running gap)
            self.clock.advance(self.clock.resize_steps)
        self.to_state("running")
        # a completed directed move restored the job's capacity — the
        # degraded emergency bid (mark_degraded) ends here
        self.degraded = 0
        return legs

    def _resize_leg(self, pool, target: List[int],
                    cur: List[int]) -> Dict:
        """One pure shrink or pure grow leg, through
        ``utils.elastic.directed_resize``."""
        from flexflow_tpu.utils.elastic import directed_resize

        if set(target) < set(cur):
            keep = [cur.index(o) for o in target]
            kw = {"keep": keep}
        else:
            added = [o for o in target if o not in cur]
            kw = {"add": pool.devices_at(added),
                  "pre_strategy": self.strategy}
        if self.spec.kind == "train":
            self._sync_losses()
            step = self.iters_done
            new_model, carry, prior = directed_resize(
                self.model, step=step, params=self._params,
                state=self._state, opt_state=self._opt,
                losses=(), loss_base=step, rebuild=self.spec.build,
                olog=self.olog, log=self.log, objective="makespan",
                **kw)
            self.model = new_model
            self._params = carry["params"]
            self._state = carry["state"]
            self._opt = carry["opt_state"] \
                or new_model.init_opt_state(carry["params"])
            self._step = new_model.make_train_step()
            from flexflow_tpu.data.synthetic import _batch_sharding

            self._sharding = _batch_sharding(new_model.machine)
        else:
            eng = self.engine
            step = eng.session_steps()
            new_model, carry, _ = directed_resize(
                self.model, step=step, params=eng.params,
                state=eng.state, opt_state=None, losses=(),
                rebuild=self.spec.build, olog=self.olog, log=self.log,
                objective="latency", **kw)
            self.model = new_model
            eng.adopt_resize(new_model, carry)
        self.strategy = getattr(self.model.config, "strategies", None)
        return {"direction": "shrink" if "keep" in kw else "grow",
                "devices": self.model.machine.num_devices}

    # ------------------------------------------------------------------

    def losses(self) -> List[float]:
        """Synced host loss history (train jobs)."""
        self._sync_losses()
        return list(self._loss_hist)
