"""The fleet event loop: admit jobs, pack the pool, round-robin quanta,
and rebalance when demand shifts.

One :class:`FleetCoordinator` owns one device pool (a
:class:`~flexflow_tpu.machine.MachineModel` over every device) and N
jobs on disjoint ``slice_of`` slices of it.  The loop is deliberately
boring — determinism is the feature:

  1. **Admit** — each submitted :class:`~flexflow_tpu.fleet.job.JobSpec`
     gets its own obs stream at ``obs_dir/<job_id>/`` (concurrent jobs
     must never interleave one run file; ``apps/report.py`` recurses
     into the subdirectories) and joins the admission-ordered list.
  2. **Pack** — the :class:`~flexflow_tpu.fleet.arbiter.Arbiter` prices
     each job on each candidate slice size and picks the packing
     (``fleet_placement`` record per packing).
  3. **Quantum loop** — every running job gets ``quantum`` steps per
     round (train iterations / decode boundaries), so one process
     timeshares the pool the way the pool timeshares devices.
  4. **Rebalance** — after each round the coordinator recomputes every
     job's demand (train: max; serve: min while calm, max while the
     queue is at/above its watermark; done jobs: gone).  A changed
     demand vector triggers a re-pack; if the assignment actually
     changes, a ``fleet_rebalance`` record is written and the moves
     execute as DIRECTED resizes — all shrinks before all grows, so the
     pool never oversubscribes mid-transition.  Every move is checked
     against the ordinals OTHER jobs actually hold before it executes:
     if an earlier move failed (the job aborted back to running on its
     old slice), dependent moves are deferred and the next round
     re-packs from the true pool state rather than the stale plan.

Drain rides the same dict the elastic runtime uses: SIGTERM sets
``drain["requested"]``, every job winds down at its next boundary, and
the driver exits 0 (the scheduler contract — see README "Elastic").
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from flexflow_tpu.fleet.arbiter import Arbiter
from flexflow_tpu.fleet.job import Job, JobSpec


class FleetCoordinator:
    """Owns the pool, the jobs, and the rebalance economy."""

    def __init__(self, pool, *, obs_dir: str = "", olog=None,
                 metrics=None, quantum: int = 4, budget_s: float = 30.0,
                 iters: int = 200, seed: int = 0, pricer=None,
                 log=print):
        from flexflow_tpu import obs

        self.pool = pool
        self.obs_dir = obs_dir
        self.metrics = metrics
        self.quantum = max(int(quantum), 1)
        self.seed = int(seed)
        self.log = log
        if olog is not None:
            self.olog = olog
        elif obs_dir:
            self.olog = obs.RunLog(
                os.path.join(obs_dir, "fleet.jsonl"), surface="fleet",
                meta={"pool_devices": pool.num_devices})
        else:
            self.olog = obs.NULL
        self.arbiter = Arbiter(pool.num_devices, pricer=pricer,
                               budget_s=budget_s, iters=iters, seed=seed,
                               olog=self.olog, log=log)
        self.jobs: List[Job] = []
        self.rebalances = 0
        self._packs = 0
        self._demand_key = None

    # ------------------------------------------------------------------
    # admission

    def submit(self, spec: JobSpec) -> Job:
        """Admit one job: open its private obs stream and queue it
        pending (placement happens at the next pack)."""
        from flexflow_tpu import obs

        if any(j.spec.job_id == spec.job_id for j in self.jobs):
            raise ValueError(f"fleet: duplicate job id {spec.job_id!r}")
        if self.obs_dir:
            jdir = os.path.join(self.obs_dir, spec.job_id)
            jlog = obs.RunLog(
                os.path.join(jdir, f"{spec.job_id}.jsonl"),
                surface="serve" if spec.kind == "serve" else "fit",
                meta={"fleet_job": spec.job_id,
                      "workload": spec.kind})
        else:
            jlog = obs.NULL
        job = Job(spec, olog=jlog, log=self.log)
        self.jobs.append(job)
        self.olog.event("fleet_job", job=spec.job_id,
                        workload=spec.kind, state="pending",
                        priority=spec.priority,
                        min_devices=spec.min_devices,
                        max_devices=spec.max_devices)
        return job

    # ------------------------------------------------------------------
    # packing

    def _placeable(self) -> List[Job]:
        return [j for j in self.jobs
                if j.state in ("pending", "running")]

    def _current_sizes(self) -> Dict[str, int]:
        return {j.spec.job_id: len(j.ordinals) for j in self.jobs
                if j.ordinals and j.active}

    def _current_ordinals(self) -> Dict[str, List[int]]:
        return {j.spec.job_id: list(j.ordinals) for j in self.jobs
                if j.ordinals and j.active}

    def _demands(self) -> tuple:
        return tuple((j.spec.job_id, j.demand(self.pool.num_devices))
                     for j in self._placeable())

    def _held_by_others(self, job) -> set:
        """Pool ordinals ACTUALLY held right now by every active job
        except ``job`` — the ground truth a planned move must be
        disjoint from before it executes (a failed earlier move means
        the plan's assumptions about freed devices no longer hold)."""
        held: set = set()
        for j in self.jobs:
            if j is not job and j.active:
                held.update(j.ordinals)
        return held

    def _pack(self) -> Dict[str, int]:
        jobs = self._placeable()
        sizes = self.arbiter.pack(jobs, current=self._current_sizes())
        self._packs += 1
        self.olog.event(
            "fleet_placement", pack=self._packs,
            demands={jid: d for jid, d in self._demands()},
            sizes=sizes, pool=self.pool.num_devices,
            native_prices=self.arbiter.native_prices,
            proxy_prices=self.arbiter.proxy_prices)
        return sizes

    # ------------------------------------------------------------------
    # the loop

    def run(self, drain: Optional[Dict] = None) -> Dict:
        """Place everything submitted so far, then round-robin quanta
        (rebalancing on demand shifts) until every job is done or
        failed.  Returns the fleet summary (also the ``fleet_summary``
        record)."""
        t0 = time.perf_counter()
        self._drain = drain
        self._place_initial(drain)
        round_ = 0
        while True:
            running = [j for j in self.jobs if j.state == "running"]
            if not running:
                break
            round_ += 1
            for job in running:
                if job.state != "running":
                    continue
                try:
                    job.step_quantum(self.quantum, drain=drain)
                except Exception as e:  # noqa: BLE001
                    self.log(f"fleet: job {job.spec.job_id} failed: {e}")
            if drain is not None and drain.get("requested"):
                # jobs wind down at their own boundaries; no rebalances
                # during a drain — keep stepping until everyone exits
                continue
            self._maybe_rebalance()
        return self._finish(time.perf_counter() - t0)

    def _place_initial(self, drain: Optional[Dict]) -> None:
        self._demand_key = self._demands()
        sizes = self._pack()
        ordinals = self.arbiter.assign_ordinals(
            self._placeable(), sizes, current=self._current_ordinals())
        for job in self._placeable():
            ords = ordinals.get(job.spec.job_id, [])
            if not ords:
                self.log(f"fleet: job {job.spec.job_id} does not fit — "
                         f"left pending")
                continue
            job.place(self.pool, ords,
                      strategy=self.arbiter.priced_strategy(
                          job, len(ords)),
                      drain=drain)
        self._update_metrics()

    def _maybe_rebalance(self) -> None:
        key = self._demands()
        if key == self._demand_key:
            return
        self._demand_key = key
        sizes = self._pack()
        cur = self._current_ordinals()
        target = self.arbiter.assign_ordinals(
            self._placeable(), sizes, current=cur)
        moves = []
        placements = []
        for job in self._placeable():
            jid = job.spec.job_id
            new = sorted(target.get(jid, []))
            if job.state == "running" and new and new != job.ordinals:
                moves.append((job, new))
            elif job.state == "pending" and new:
                placements.append((job, new))
        if not moves and not placements:
            return
        degraded = False
        if moves:
            self.rebalances += 1
            # the rebalance record precedes the elastic_resize records
            # it causes, in every merged ts-ordering
            self.olog.event(
                "fleet_rebalance", rebalance=self.rebalances,
                moves=[{"job": j.spec.job_id, "from": list(j.ordinals),
                        "to": new} for j, new in moves],
                sizes=sizes)
            self.log(f"fleet: rebalance #{self.rebalances}: "
                     + ", ".join(f"{j.spec.job_id} "
                                 f"{len(j.ordinals)}->{len(new)}"
                                 for j, new in moves))
            # shrinks release devices before grows claim them
            moves.sort(key=lambda m: (len(m[1]) - len(m[0].ordinals),
                                      m[0].spec.job_id))
            for job, new in moves:
                # the plan was priced against devices earlier moves
                # were to free; if one failed, its devices were never
                # released — defer any move that would oversubscribe
                conflict = set(new) & self._held_by_others(job)
                if conflict:
                    self.log(f"fleet: deferring resize of "
                             f"{job.spec.job_id} -> {new}: ordinals "
                             f"{sorted(conflict)} still held by "
                             f"another job")
                    degraded = True
                    continue
                try:
                    job.resize(self.pool, new)
                except Exception as e:  # noqa: BLE001
                    # Job.resize aborts back to running on the slice
                    # its completed legs left it holding
                    self.log(f"fleet: resize of {job.spec.job_id} "
                             f"failed ({e}); job resumes on its "
                             f"{len(job.ordinals)}-device slice")
                    degraded = True
        # queued jobs admitted by the re-pack place after the shrinks
        # that freed their devices
        for job, ords in placements:
            conflict = set(ords) & self._held_by_others(job)
            if conflict:
                self.log(f"fleet: deferring placement of "
                         f"{job.spec.job_id}: ordinals "
                         f"{sorted(conflict)} still held by another "
                         f"job")
                degraded = True
                continue
            job.place(self.pool, ords,
                      strategy=self.arbiter.priced_strategy(
                          job, len(ords)),
                      drain=self._drain)
        if degraded:
            # the pool is not in the packed shape — force a re-pack at
            # the next round instead of waiting for a demand shift
            self._demand_key = None
        if self.metrics is not None:
            self.metrics.update(fleet_rebalances_total=self.rebalances)
        self._update_metrics()

    def _finish(self, wall_s: float) -> Dict:
        by_state: Dict[str, int] = {}
        for j in self.jobs:
            by_state[j.state] = by_state.get(j.state, 0) + 1
        jobs_out = []
        for j in self.jobs:
            entry = {"job": j.spec.job_id, "kind": j.spec.kind,
                     "state": j.state, "devices": len(j.ordinals)}
            if j.spec.kind == "train" and j.result:
                entry["iters"] = j.result["iters"]
                entry["final_loss"] = (j.result["loss"][-1]
                                       if j.result["loss"] else None)
            if j.spec.kind == "serve" and j.result:
                entry["completed"] = j.result["completed"]
                entry["unserved"] = j.result["unserved"]
            if j.error:
                entry["error"] = j.error
            jobs_out.append(entry)
        summary = {
            "pool_devices": self.pool.num_devices,
            "jobs": jobs_out, "by_state": by_state,
            "rebalances": self.rebalances, "packs": self._packs,
            "native_prices": self.arbiter.native_prices,
            "proxy_prices": self.arbiter.proxy_prices,
            "wall_s": round(wall_s, 3),
        }
        self.olog.event("fleet_summary", **summary)
        self._update_metrics()
        for j in self.jobs:
            if j.olog is not self.olog:
                j.olog.close()
        return summary

    # ------------------------------------------------------------------

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        counts: Dict[str, int] = {}
        for j in self.jobs:
            counts[j.state] = counts.get(j.state, 0) + 1
        self.metrics.update(fleet_jobs=len(self.jobs))
        for state, n in counts.items():
            self.metrics.update_labeled("fleet_jobs", {"state": state},
                                        n)
        total = 0
        for j in self.jobs:
            n = len(j.ordinals) if j.active else 0
            total += n
            self.metrics.update_labeled("fleet_job_devices",
                                        {"job": j.spec.job_id}, n)
        self.metrics.update(fleet_job_devices=total)
        self.metrics.write()
