"""The fleet event loop: admit jobs, pack the pool, round-robin quanta,
and rebalance when demand shifts.

One :class:`FleetCoordinator` owns one device pool (a
:class:`~flexflow_tpu.machine.MachineModel` over every device) and N
jobs on disjoint ``slice_of`` slices of it.  The loop is deliberately
boring — determinism is the feature:

  1. **Admit** — each submitted :class:`~flexflow_tpu.fleet.job.JobSpec`
     gets its own obs stream at ``obs_dir/<job_id>/`` (concurrent jobs
     must never interleave one run file; ``apps/report.py`` recurses
     into the subdirectories) and joins the admission-ordered list.
  2. **Pack** — the :class:`~flexflow_tpu.fleet.arbiter.Arbiter` prices
     each job on each candidate slice size and picks the packing
     (``fleet_placement`` record per packing).
  3. **Quantum loop** — every running job gets ``quantum`` steps per
     round (train iterations / decode boundaries), so one process
     timeshares the pool the way the pool timeshares devices.
  4. **Rebalance** — after each round the coordinator recomputes every
     job's demand (train: max; serve: min while calm, max while the
     queue is at/above its watermark; done jobs: gone).  A changed
     demand vector triggers a re-pack; if the assignment actually
     changes, a ``fleet_rebalance`` record is written and the moves
     execute as DIRECTED resizes — all shrinks before all grows, so the
     pool never oversubscribes mid-transition.  Every move is checked
     against the ordinals OTHER jobs actually hold before it executes:
     if an earlier move failed (the job aborted back to running on its
     old slice), dependent moves are deferred and the next round
     re-packs from the true pool state rather than the stale plan.

Drain rides the same dict the elastic runtime uses: SIGTERM sets
``drain["requested"]``, every job winds down at its next boundary, and
the driver exits 0 (the scheduler contract — see README "Elastic").

**Virtual time + utilization accounting (round 18).**  The coordinator
owns a :class:`VirtualClock` (one tick per quantum step) and attaches
it to every admitted job, so lifecycle records are stamped in virtual
time and each job's ``fleet_wait`` decomposition is exact.  Every round
emits a ``fleet_util`` record that accounts EVERY device-step in the
pool — busy (a running job executed a step on a held device), resizing
(a placement or directed resize advanced the clock while devices were
in motion), idle (the remainder) — under the budget.py-style provable
invariant checked by :func:`check_fleet_util`:

    busy_steps + idle_steps + resizing_steps == pool_devices x span_steps

as EXACT integer equality, at every round.  The loop decomposes into
public :meth:`FleetCoordinator.start` / :meth:`~FleetCoordinator.
step_round` / :meth:`~FleetCoordinator.finish` so a driver
(apps/fleetsim.py) can interleave mid-run admissions and
:meth:`~FleetCoordinator.idle_advance` gaps between rounds;
:meth:`~FleetCoordinator.run` composes them unchanged.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from flexflow_tpu.fleet.arbiter import Arbiter
from flexflow_tpu.fleet.job import Job, JobSpec


class VirtualClock:
    """Integer step counter + seconds-per-step scale: the fleet's
    virtual time base.  Jobs and the coordinator only ever ``advance``
    by whole steps, so device-second accounting stays exact integer
    arithmetic (``check_fleet_util``); ``now()`` is the float seconds
    view the obs records carry."""

    def __init__(self, step_time_s: float = 0.05, resize_steps: int = 1):
        if step_time_s <= 0:
            raise ValueError("step_time_s must be > 0")
        self.step_time_s = float(step_time_s)
        #: virtual steps one placement / one drain / one regrid costs
        self.resize_steps = max(int(resize_steps), 1)
        self.steps = 0

    def now(self) -> float:
        return self.steps * self.step_time_s

    def advance(self, steps: int) -> None:
        self.steps += max(int(steps), 0)


def check_fleet_util(rec: Dict) -> List[str]:
    """Violations of the fleet_util invariant (empty list = OK): the
    three buckets are non-negative ints summing EXACTLY to pool
    capacity x round span, and the derived seconds fields match
    ``steps x step_time_s``.  The obs/budget.py ``check_budget``
    contract, for device-seconds instead of step wall time."""
    problems: List[str] = []
    for k in ("pool_devices", "span_steps", "busy_steps", "idle_steps",
              "resizing_steps"):
        v = rec.get(k)
        if not isinstance(v, int) or isinstance(v, bool):
            problems.append(f"{k} must be an int, got {v!r}")
        elif v < 0:
            problems.append(f"{k} must be >= 0, got {v}")
    if problems:
        return problems
    cap = rec["pool_devices"] * rec["span_steps"]
    total = (rec["busy_steps"] + rec["idle_steps"]
             + rec["resizing_steps"])
    if total != cap:
        problems.append(
            f"buckets sum to {total} device-steps but pool capacity x "
            f"round span is {cap} ({rec['pool_devices']} devices x "
            f"{rec['span_steps']} steps)")
    st = rec.get("step_time_s")
    if isinstance(st, (int, float)) and not isinstance(st, bool) \
            and st > 0:
        for name in ("busy", "idle", "resizing"):
            sec = rec.get(f"{name}_s")
            want = rec[f"{name}_steps"] * st
            if sec is not None and \
                    abs(sec - want) > 1e-9 * max(1.0, abs(want)):
                problems.append(
                    f"{name}_s {sec} != {name}_steps x step_time_s "
                    f"{want}")
    return problems


class FleetCoordinator:
    """Owns the pool, the jobs, and the rebalance economy."""

    def __init__(self, pool, *, obs_dir: str = "", olog=None,
                 metrics=None, quantum: int = 4, budget_s: float = 30.0,
                 iters: int = 200, seed: int = 0, pricer=None,
                 step_time_s: float = 0.05, resize_steps: int = 1,
                 log=print):
        from flexflow_tpu import obs

        self.pool = pool
        self.obs_dir = obs_dir
        self.metrics = metrics
        self.quantum = max(int(quantum), 1)
        self.seed = int(seed)
        self.log = log
        self.clock = VirtualClock(step_time_s=step_time_s,
                                  resize_steps=resize_steps)
        if olog is not None:
            self.olog = olog
        elif obs_dir:
            self.olog = obs.RunLog(
                os.path.join(obs_dir, "fleet.jsonl"), surface="fleet",
                meta={"pool_devices": pool.num_devices})
        else:
            self.olog = obs.NULL
        self.arbiter = Arbiter(pool.num_devices, pricer=pricer,
                               budget_s=budget_s, iters=iters, seed=seed,
                               olog=self.olog, log=log)
        self.jobs: List[Job] = []
        self.rebalances = 0
        self._packs = 0
        self._demand_key = None
        self._round = 0
        self._resizing_steps = 0     # device-steps in motion this round
        self._drain = None
        self._t0 = None
        self._waits_seen: set = set()

    # ------------------------------------------------------------------
    # admission

    def submit(self, spec: JobSpec) -> Job:
        """Admit one job: open its private obs stream and queue it
        pending (placement happens at the next pack)."""
        from flexflow_tpu import obs

        if any(j.spec.job_id == spec.job_id for j in self.jobs):
            raise ValueError(f"fleet: duplicate job id {spec.job_id!r}")
        if self.obs_dir:
            jdir = os.path.join(self.obs_dir, spec.job_id)
            jlog = obs.RunLog(
                os.path.join(jdir, f"{spec.job_id}.jsonl"),
                surface="serve" if spec.kind == "serve" else "fit",
                meta={"fleet_job": spec.job_id,
                      "workload": spec.kind})
        else:
            # no private obs dir: the job shares the pool stream, so a
            # stream-level driver (fleetsim) still captures every
            # fleet_job / fleet_wait record
            jlog = self.olog
        job = Job(spec, olog=jlog, log=self.log)
        job.attach_clock(self.clock)
        self.jobs.append(job)
        self.olog.event("fleet_job", job=spec.job_id,
                        workload=spec.kind, state="pending",
                        priority=spec.priority,
                        min_devices=spec.min_devices,
                        max_devices=spec.max_devices,
                        vts=self.clock.now())
        return job

    # ------------------------------------------------------------------
    # packing

    def _placeable(self) -> List[Job]:
        return [j for j in self.jobs
                if j.state in ("pending", "running")]

    def _current_sizes(self) -> Dict[str, int]:
        return {j.spec.job_id: len(j.ordinals) for j in self.jobs
                if j.ordinals and j.active}

    def _current_ordinals(self) -> Dict[str, List[int]]:
        return {j.spec.job_id: list(j.ordinals) for j in self.jobs
                if j.ordinals and j.active}

    def _demands(self) -> tuple:
        return tuple((j.spec.job_id, j.demand(self.pool.num_devices))
                     for j in self._placeable())

    def _held_by_others(self, job) -> set:
        """Pool ordinals ACTUALLY held right now by every active job
        except ``job`` — the ground truth a planned move must be
        disjoint from before it executes (a failed earlier move means
        the plan's assumptions about freed devices no longer hold)."""
        held: set = set()
        for j in self.jobs:
            if j is not job and j.active:
                held.update(j.ordinals)
        return held

    def _pack(self) -> Dict[str, int]:
        jobs = self._placeable()
        sizes = self.arbiter.pack(jobs, current=self._current_sizes())
        self._packs += 1
        self.olog.event(
            "fleet_placement", pack=self._packs,
            demands={jid: d for jid, d in self._demands()},
            sizes=sizes, pool=self.pool.num_devices,
            native_prices=self.arbiter.native_prices,
            proxy_prices=self.arbiter.proxy_prices)
        return sizes

    # ------------------------------------------------------------------
    # the loop

    def run(self, drain: Optional[Dict] = None) -> Dict:
        """Place everything submitted so far, then round-robin quanta
        (rebalancing on demand shifts) until every job is done or
        failed.  Returns the fleet summary (also the ``fleet_summary``
        record)."""
        self.start(drain)
        while self.step_round(drain):
            pass
        return self.finish()

    def start(self, drain: Optional[Dict] = None) -> None:
        """Initial placement of everything submitted so far, accounted
        as a round-0 ``fleet_util`` record (placement device-steps are
        'resizing', the rest of the span is idle)."""
        self._t0 = time.perf_counter()
        self._drain = drain
        v0 = self.clock.steps
        self._resizing_steps = 0
        self._place_initial(drain)
        self._emit_util(v0, busy=0, phase="start")

    def step_round(self, drain: Optional[Dict] = None) -> bool:
        """ONE quantum round: step every running job, advance the
        virtual clock by the quantum, rebalance on demand shifts, emit
        the round's ``fleet_util`` accounting.  Returns False when no
        job is running (the loop's exit condition)."""
        if drain is None:
            drain = self._drain
        running = [j for j in self.jobs if j.state == "running"]
        if not running:
            return False
        self._round += 1
        v0 = self.clock.steps
        self._resizing_steps = 0
        busy = 0
        for job in running:
            if job.state != "running":
                continue
            held = len(job.ordinals)
            try:
                job.step_quantum(self.quantum, drain=drain)
            except Exception as e:  # noqa: BLE001
                self.log(f"fleet: job {job.spec.job_id} failed: {e}")
            busy += held * min(int(job.last_quantum_steps),
                               self.quantum)
        self.clock.advance(self.quantum)
        if not (drain is not None and drain.get("requested")):
            # jobs wind down at their own boundaries during a drain; no
            # rebalances — keep stepping until everyone exits
            self._maybe_rebalance()
        self._emit_util(v0, busy=busy, phase="round")
        self._observe_waits()
        return True

    def place_pending(self) -> int:
        """Re-pack and place queued jobs WITHOUT stepping anyone —
        fleetsim's entry point when arrivals land in an empty pool
        (``step_round`` exits before rebalancing when nothing runs).
        Placement device-steps are accounted as a 'place'-phase
        ``fleet_util`` record; if the pack moved nothing (no feasible
        placement) the clock did not advance and no record is emitted.
        Returns the number of running jobs afterwards."""
        v0 = self.clock.steps
        self._resizing_steps = 0
        self._maybe_rebalance()
        if self.clock.steps > v0:
            self._emit_util(v0, busy=0, phase="place")
        else:
            self._resizing_steps = 0
        return sum(1 for j in self.jobs if j.state == "running")

    def idle_advance(self, steps: int) -> None:
        """Fast-forward across a gap with nothing runnable (fleetsim's
        inter-arrival gaps): the whole pool sits idle for the span,
        recorded as an all-idle ``fleet_util`` round so the accounting
        still covers every device-second of the day."""
        steps = int(steps)
        if steps <= 0:
            return
        v0 = self.clock.steps
        self._resizing_steps = 0
        self.clock.advance(steps)
        self._emit_util(v0, busy=0, phase="idle")

    def _emit_util(self, v0: int, busy: int, phase: str) -> None:
        clk = self.clock
        span = clk.steps - v0
        pool = self.pool.num_devices
        resizing = self._resizing_steps
        idle = pool * span - busy - resizing
        st = clk.step_time_s
        rec = {"round": self._round, "phase": phase, "vts": v0 * st,
               "pool_devices": pool, "span_steps": span,
               "busy_steps": busy, "idle_steps": idle,
               "resizing_steps": resizing, "step_time_s": st,
               "busy_s": busy * st, "idle_s": idle * st,
               "resizing_s": resizing * st,
               "util": (busy / (pool * span)) if span else 0.0}
        self.olog.event("fleet_util", **rec)
        self._resizing_steps = 0
        if self.metrics is not None:
            self.metrics.update(fleet_util=rec["util"])

    def _observe_waits(self) -> None:
        """Each newly-terminal job's queue wait lands in the
        ``ff_fleet_job_wait_s`` histogram exactly once."""
        if self.metrics is None:
            return
        for j in self.jobs:
            if j.state in ("done", "failed") \
                    and j.spec.job_id not in self._waits_seen:
                self._waits_seen.add(j.spec.job_id)
                self.metrics.observe("fleet_job_wait_s",
                                     j.vtimes["wait_s"])

    def _place_initial(self, drain: Optional[Dict]) -> None:
        self._demand_key = self._demands()
        sizes = self._pack()
        ordinals = self.arbiter.assign_ordinals(
            self._placeable(), sizes, current=self._current_ordinals())
        for job in self._placeable():
            ords = ordinals.get(job.spec.job_id, [])
            if not ords:
                self.log(f"fleet: job {job.spec.job_id} does not fit — "
                         f"left pending")
                continue
            v_before = self.clock.steps
            job.place(self.pool, ords,
                      strategy=self.arbiter.priced_strategy(
                          job, len(ords)),
                      drain=drain)
            self._resizing_steps += \
                (self.clock.steps - v_before) * len(ords)
        self._update_metrics()

    def _maybe_rebalance(self) -> None:
        """Re-pack and issue directed resizes when the demand key
        shifts — a serve job crossing its queue watermark, a job
        arriving/finishing, or a DEGRADED serve job
        (:meth:`~flexflow_tpu.fleet.job.Job.mark_degraded`) raising
        its bid to max after losing replicas: the emergency bid
        changes ``_demands()`` and drives the fleet through the same
        directed-resize path, and a successful resize clears it."""
        key = self._demands()
        if key == self._demand_key:
            return
        self._demand_key = key
        sizes = self._pack()
        cur = self._current_ordinals()
        target = self.arbiter.assign_ordinals(
            self._placeable(), sizes, current=cur)
        moves = []
        placements = []
        for job in self._placeable():
            jid = job.spec.job_id
            new = sorted(target.get(jid, []))
            if job.state == "running" and new and new != job.ordinals:
                moves.append((job, new))
            elif job.state == "pending" and new:
                placements.append((job, new))
        if not moves and not placements:
            return
        degraded = False
        if moves:
            self.rebalances += 1
            # the rebalance record precedes the elastic_resize records
            # it causes, in every merged ts-ordering
            self.olog.event(
                "fleet_rebalance", rebalance=self.rebalances,
                moves=[{"job": j.spec.job_id, "from": list(j.ordinals),
                        "to": new} for j, new in moves],
                sizes=sizes, vts=self.clock.now())
            self.log(f"fleet: rebalance #{self.rebalances}: "
                     + ", ".join(f"{j.spec.job_id} "
                                 f"{len(j.ordinals)}->{len(new)}"
                                 for j, new in moves))
            # shrinks release devices before grows claim them
            moves.sort(key=lambda m: (len(m[1]) - len(m[0].ordinals),
                                      m[0].spec.job_id))
            for job, new in moves:
                # the plan was priced against devices earlier moves
                # were to free; if one failed, its devices were never
                # released — defer any move that would oversubscribe
                conflict = set(new) & self._held_by_others(job)
                if conflict:
                    self.log(f"fleet: deferring resize of "
                             f"{job.spec.job_id} -> {new}: ordinals "
                             f"{sorted(conflict)} still held by "
                             f"another job")
                    degraded = True
                    continue
                v_before = self.clock.steps
                affected = len(set(new) | set(job.ordinals))
                try:
                    job.resize(self.pool, new)
                except Exception as e:  # noqa: BLE001
                    # Job.resize aborts back to running on the slice
                    # its completed legs left it holding
                    self.log(f"fleet: resize of {job.spec.job_id} "
                             f"failed ({e}); job resumes on its "
                             f"{len(job.ordinals)}-device slice")
                    degraded = True
                self._resizing_steps += \
                    (self.clock.steps - v_before) * affected
        # queued jobs admitted by the re-pack place after the shrinks
        # that freed their devices
        for job, ords in placements:
            conflict = set(ords) & self._held_by_others(job)
            if conflict:
                self.log(f"fleet: deferring placement of "
                         f"{job.spec.job_id}: ordinals "
                         f"{sorted(conflict)} still held by another "
                         f"job")
                degraded = True
                continue
            v_before = self.clock.steps
            job.place(self.pool, ords,
                      strategy=self.arbiter.priced_strategy(
                          job, len(ords)),
                      drain=self._drain)
            self._resizing_steps += \
                (self.clock.steps - v_before) * len(ords)
        if degraded:
            # the pool is not in the packed shape — force a re-pack at
            # the next round instead of waiting for a demand shift
            self._demand_key = None
        if self.metrics is not None:
            self.metrics.update(fleet_rebalances_total=self.rebalances)
        self._update_metrics()

    def finish(self, wall_s: Optional[float] = None) -> Dict:
        """Close out the run: the ``fleet_summary`` record, final
        metrics, and every private job stream closed."""
        if wall_s is None:
            wall_s = time.perf_counter() - (self._t0 or
                                            time.perf_counter())
        return self._finish(wall_s)

    def _finish(self, wall_s: float) -> Dict:
        by_state: Dict[str, int] = {}
        for j in self.jobs:
            by_state[j.state] = by_state.get(j.state, 0) + 1
        jobs_out = []
        for j in self.jobs:
            entry = {"job": j.spec.job_id, "kind": j.spec.kind,
                     "state": j.state, "devices": len(j.ordinals)}
            if j.spec.kind == "train" and j.result:
                entry["iters"] = j.result.get("iters")
                entry["final_loss"] = (j.result["loss"][-1]
                                       if j.result.get("loss")
                                       else None)
            if j.spec.kind == "serve" and j.result:
                # sim-mode serve jobs report steps, not requests
                if "completed" in j.result:
                    entry["completed"] = j.result["completed"]
                    entry["unserved"] = j.result["unserved"]
                else:
                    entry["iters"] = j.result.get("iters")
            if j.error:
                entry["error"] = j.error
            jobs_out.append(entry)
        summary = {
            "pool_devices": self.pool.num_devices,
            "jobs": jobs_out, "by_state": by_state,
            "rebalances": self.rebalances, "packs": self._packs,
            "native_prices": self.arbiter.native_prices,
            "proxy_prices": self.arbiter.proxy_prices,
            "wall_s": round(wall_s, 3),
            "virtual_s": self.clock.now(),
        }
        self.olog.event("fleet_summary", **summary)
        self._observe_waits()
        self._update_metrics()
        for j in self.jobs:
            if j.olog is not self.olog:
                j.olog.close()
        return summary

    # ------------------------------------------------------------------

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        counts: Dict[str, int] = {}
        for j in self.jobs:
            counts[j.state] = counts.get(j.state, 0) + 1
        self.metrics.update(fleet_jobs=len(self.jobs))
        for state, n in counts.items():
            self.metrics.update_labeled("fleet_jobs", {"state": state},
                                        n)
        total = 0
        for j in self.jobs:
            n = len(j.ordinals) if j.active else 0
            total += n
            self.metrics.update_labeled("fleet_job_devices",
                                        {"job": j.spec.job_id}, n)
        self.metrics.update(fleet_job_devices=total)
        self.metrics.write()
