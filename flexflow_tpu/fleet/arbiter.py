"""Placement arbiter: the strategy search as a device-pool scheduler.

Given the pool size and the fleet's current DEMANDS (each job's
feasible slice sizes, capped at what it currently bids for), the
arbiter packs one slice size per job.  Three rules order the search:

  1. **No preemption by omission** — a job that currently HOLDS devices
     is running on them and there is no evict path, so 0 is never one of
     its options.  When none of its demand-capped candidates fits
     at-or-below what it holds (a backlogged binding bid the pool cannot
     meet), staying at its current size becomes the option — every held
     job therefore always has a choice <= held, so a feasible packing
     always exists; a calm job still yields down to its demand.  Only
     jobs holding nothing may be left unplaced (the coordinator queues
     them).
  2. **Work conservation** — only Pareto-MAXIMAL packings compete: a
     packing is discarded if another feasible packing gives every job at
     least as many devices and some job strictly more.  A pool with idle
     devices while a job bids for them is never chosen, which also makes
     each rebalance's outcome structurally determined when demand tiers
     leave a single maximal packing (the deterministic smoke relies on
     exactly this).
  3. **Weighted predicted cost** — among the maximal packings, minimize
     ``sum(priority_j * price(job_j, size_j))`` where ``price`` is the
     job's PREDICTED per-step cost on a slice of that size, from the
     native simulator via :func:`sim.search.price_on_slice` — a
     warm-started, budget-capped re-search under the job's objective
     (step makespan for train, forward-step latency for serve).  When
     the native library is absent the arbiter degrades to a
     deterministic DP proxy (cost proportional to ``1/size``), keeping
     CPU-only CI and the smoke runnable.

The packing itself is a grouped-knapsack DP over (devices used, minimum
bump-to-next-option) states, polynomial in pool size and job count —
NOT an enumeration of the Cartesian product of per-job options, which is
exponential in job count.  It is exact: per-job options are independent
and the only coupling is ``sum(sizes) <= pool``, so a packing is
Pareto-dominated iff some SINGLE job can be raised to its next larger
option within the free capacity — tracking the minimum such bump
increment alongside devices-used decides maximality per DP state, and
the score ``(unplaced, Σ priority·price, churn, lexicographic)`` is a
per-job sum compared lexicographically, which suffix-extension
preserves (tests cross-check the DP against brute-force enumeration on
randomized small instances).

Prices are cached per ``(job_id, size)`` — a job's model does not
change shape between rebalances, so each (job, size) pair is priced at
most once per coordinator run.  Ties between packings break on the
lexicographically smallest assignment vector (jobs in admission order),
so a fixed seed reproduces the identical packing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class Arbiter:
    """Prices (job, slice-size) pairs and packs jobs onto the pool.

    ``pricer`` overrides the cost function (tests inject stubs); the
    default tries the native simulator and falls back to the DP proxy.
    ``budget_s`` caps each native pricing re-search; ``iters`` bounds
    its proposals so a fixed seed is deterministic even when the budget
    never binds."""

    def __init__(self, pool_size: int, *, pricer=None,
                 budget_s: float = 30.0, iters: int = 200,
                 seed: int = 0, olog=None, log=print):
        from flexflow_tpu import obs

        self.pool_size = int(pool_size)
        self.pricer = pricer
        self.budget_s = float(budget_s)
        self.iters = int(iters)
        self.seed = int(seed)
        self.olog = olog if olog is not None else obs.NULL
        self.log = log
        self._price_cache: Dict[Tuple[str, int], float] = {}
        self._strategy_cache: Dict[Tuple[str, int], object] = {}
        self.native_prices = 0
        self.proxy_prices = 0

    # ------------------------------------------------------------------
    # pricing

    def price(self, job, size: int) -> float:
        """Predicted per-step cost of ``job`` on a ``size``-device slice
        (seconds under the native simulator, dimensionless under the
        proxy — only relative order within one pricer matters)."""
        key = (job.spec.job_id, int(size))
        if key in self._price_cache:
            return self._price_cache[key]
        if self.pricer is not None:
            cost = float(self.pricer(job, size))
        else:
            cost = self._price_native(job, size)
        self._price_cache[key] = cost
        return cost

    @staticmethod
    def _objective_for(spec) -> str:
        """The simulator objective a job's slice is priced under:
        decode-pool serve jobs price the single-token step (decode),
        other serve jobs (single-pool or prefill pool) the forward pass
        (latency), train jobs the full step (makespan)."""
        if spec.kind != "serve":
            return "makespan"
        return "decode" if spec.serve_phase == "decode" else "latency"

    def _price_native(self, job, size: int) -> float:
        from flexflow_tpu.sim.search import price_on_slice

        spec = job.spec
        objective = self._objective_for(spec)
        try:
            cost, strategy, _info = price_on_slice(
                spec.build, spec.config, size, objective=objective,
                iters=min(self.iters, spec.search_iters or self.iters),
                seed=self.seed, warm_strategy=job.strategy,
                budget_s=self.budget_s)
            self._strategy_cache[(spec.job_id, int(size))] = strategy
            self.native_prices += 1
            return float(cost)
        except Exception as e:  # native lib absent / sim unavailable
            self.proxy_prices += 1
            self.log(f"fleet: native pricing unavailable for "
                     f"{spec.job_id}@{size} ({type(e).__name__}); "
                     f"using DP proxy")
            return self._price_proxy(job, size)

    @staticmethod
    def _price_proxy(job, size: int) -> float:
        """Deterministic data-parallel proxy: per-step cost scales as
        1/size (perfect DP speedup) plus a small per-device sync term so
        larger slices are never free."""
        return 1.0 / float(size) + 0.001 * float(size)

    # the DP proxy as a PUBLIC injectable pricer: pass
    # ``pricer=Arbiter.proxy_pricer`` to skip native pricing entirely
    # (apps/fleetsim.py's no-jit CPU-fast mode — jax never loads)
    proxy_pricer = _price_proxy

    def priced_strategy(self, job, size: int) -> Optional[object]:
        """The strategy the native pricing search found for this (job,
        size), if any — handed to ``Job.place`` so the job runs under
        the plan it was priced with."""
        return self._strategy_cache.get((job.spec.job_id, int(size)))

    # ------------------------------------------------------------------
    # packing

    def pack(self, jobs: Sequence, *,
             current: Optional[Dict[str, int]] = None) -> Dict[str, int]:
        """Choose a slice size per active job.

        ``jobs`` is the admission-ordered list of jobs to place;
        ``current`` (job_id -> size) marks sizes already held: a held
        job is RUNNING on its slice, so 0 is never one of its options
        (no silent preemption — its devices must not be handed away
        while it keeps running), and staying at its current size is an
        option exactly when no candidate fits at-or-below it; held
        sizes also feed the churn tie-break (prefer the packing closest
        to the incumbent among equal-cost maximal packings).  Returns
        ``{job_id: size}``; a job holding nothing that cannot fit at
        its minimum is assigned 0 (the coordinator queues it)."""
        jobs = list(jobs)
        if not jobs:
            return {}
        pool = self.pool_size
        cur_vec = tuple(int((current or {}).get(j.spec.job_id, 0))
                        for j in jobs)
        options: List[List[int]] = []
        for job, held in zip(jobs, cur_vec):
            sizes = job.candidate_sizes(pool)
            if held:
                # never 0; and when no candidate fits at-or-below the
                # held size (a backlogged binding bid the pool cannot
                # meet), staying put is the option — so every held job
                # always has a choice <= held and a feasible packing
                # exists.  Demand-capped candidates are NOT extended
                # otherwise: a calm serve job must still yield down.
                if not any(s <= held for s in sizes):
                    sizes = sorted(set(sizes) | {held})
                options.append(sizes)
            else:
                # 0 = "not placed" — an option only for jobs holding
                # nothing, so one oversized job cannot make the whole
                # fleet infeasible
                options.append([0] + sizes)

        # Grouped-knapsack DP, one group per job in admission order.
        # State: (devices used, min bump) where "bump" is the smallest
        # increment that would raise ONE chosen job to its next larger
        # option — a final packing is Pareto-maximal iff its min bump
        # exceeds the free capacity.  Value: the partial score
        # (unplaced, Σ priority·price, churn, combo-prefix); keeping
        # the minimum per state is exact because the score is additive
        # and suffix-extension preserves its lexicographic order.
        INF = pool + 1   # caps bump: anything > pool acts as "no bump"
        states: Dict[Tuple[int, int], tuple] = {(0, INF): (0, 0.0, 0, ())}
        for idx, (job, opts) in enumerate(zip(jobs, options)):
            nxt: Dict[Tuple[int, int], tuple] = {}
            for (used, bump), val in states.items():
                for i, s in enumerate(opts):
                    nu = used + s
                    if nu > pool:
                        break               # opts ascend: rest too big
                    nb = min(bump, min(opts[i + 1] - s, INF)
                             if i + 1 < len(opts) else INF)
                    if s:
                        nval = (val[0],
                                val[1] + job.spec.priority
                                * self.price(job, s),
                                val[2] + (s != cur_vec[idx]),
                                val[3] + (s,))
                    else:
                        nval = (val[0] + 1, val[1],
                                val[2] + (cur_vec[idx] != 0),
                                val[3] + (0,))
                    key = (nu, nb)
                    if key not in nxt or nval < nxt[key]:
                        nxt[key] = nval
            states = nxt
        # work conservation: only maximal finals compete (some always
        # exist — the all-current/all-zero packing is feasible, and the
        # best value at any maximal packing's state is itself maximal)
        best = min((val for (used, bump), val in states.items()
                    if bump > pool - used), default=None)
        if best is None:     # unreachable; insurance over a crash
            best = min(states.values())
        return {j.spec.job_id: s for j, s in zip(jobs, best[3])}

    def assign_ordinals(self, jobs: Sequence, sizes: Dict[str, int],
                        *, current: Optional[Dict[str, List[int]]] = None
                        ) -> Dict[str, List[int]]:
        """Turn a size packing into concrete pool ordinals.

        Jobs keep as much of their CURRENT interval as possible (a
        directed resize must stay anchored — the elastic path regrids
        live state, it does not relocate wholesale): a shrinking job
        keeps a prefix of its ordinals, a growing job keeps all of them
        and extends from the free pool, lowest ordinal first.  New jobs
        take contiguous runs of what remains, in admission order."""
        current = dict(current or {})
        taken: set = set()
        out: Dict[str, List[int]] = {}
        # pass 0: a job that still holds devices but was packed at 0
        # keeps its slice, reserved — it is RUNNING there and there is
        # no evict path, so handing its ordinals to anyone else would
        # silently oversubscribe the pool.  pack() never produces this
        # (held jobs have no 0 option); guard it anyway.
        for job in jobs:
            jid = job.spec.job_id
            held = sorted(current.get(jid, []))
            if held and not sizes.get(jid, 0):
                self.log(f"fleet: packing assigned 0 to running job "
                         f"{jid}; it keeps its {len(held)}-device slice")
                out[jid] = held
                taken.update(held)
        # pass 1: shrinking / steady jobs keep a prefix
        for job in jobs:
            jid = job.spec.job_id
            size = sizes.get(jid, 0)
            held = sorted(current.get(jid, []))
            if held and size and size <= len(held):
                out[jid] = held[:size]
                taken.update(out[jid])
        # reserve growing jobs' held ordinals before anyone extends
        for job in jobs:
            jid = job.spec.job_id
            held = current.get(jid, [])
            if held and sizes.get(jid, 0) > len(held):
                taken.update(held)
        # pass 2: growing jobs keep everything and extend
        for job in jobs:
            jid = job.spec.job_id
            size = sizes.get(jid, 0)
            held = sorted(current.get(jid, []))
            if held and size > len(held):
                grown = list(held)
                avail = [o for o in range(self.pool_size)
                         if o not in taken and o not in grown]
                grown += avail[:size - len(held)]
                if len(grown) < size:
                    raise RuntimeError(
                        f"fleet: cannot grow {jid} to {size} — pool "
                        f"exhausted (arbiter bug: packing exceeded the "
                        f"pool)")
                out[jid] = sorted(grown)
                taken.update(out[jid])
        # pass 3: new placements take contiguous runs of the remainder
        for job in jobs:
            jid = job.spec.job_id
            if jid in out:
                continue
            size = sizes.get(jid, 0)
            if not size:
                out[jid] = []
                continue
            avail = [o for o in range(self.pool_size) if o not in taken]
            if len(avail) < size:
                raise RuntimeError(
                    f"fleet: packing for {jid} wants {size} of "
                    f"{len(avail)} free devices (arbiter bug)")
            out[jid] = avail[:size]
            taken.update(out[jid])
        # the disjointness contract: no ordinal in two jobs' slices —
        # violating it is the one bug class worse than a crash
        seen: set = set()
        for jid, ords in out.items():
            dup = seen & set(ords)
            if dup:
                raise RuntimeError(
                    f"fleet: assignment oversubscribes ordinals "
                    f"{sorted(dup)} (job {jid}) — arbiter bug")
            seen.update(ords)
        return out
