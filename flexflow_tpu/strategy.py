"""Per-operator parallelization strategies.

A strategy maps each named operator to a :class:`ParallelConfig`: an N-D
partition grid over the operator's parallelizable dimensions plus an explicit
device assignment for every grid point.  This is the same abstraction as the
reference's ``ParallelConfig`` (/root/reference/config.h:36-39) and its
protobuf serialization (/root/reference/strategy.proto) — and strategy files
written by either framework are wire-compatible (see :func:`save_proto` /
:func:`load_proto`).

Dimension-order convention (inherited from the reference, which uses
Legion's innermost-first ordering — conv_2d.cu:69-75):

  * 4-D CNN ops (conv2d / pool2d / batch_norm): ``dims = (w, h, c, n)``
  * 2-D linear: ``dims = (c, n)`` — c splits output channels (tensor
    parallelism), n splits the batch (linear.cu:38-41)
  * 1-D ops (softmax, lstm chunk): ``dims = (n,)``

``devices`` is linearized with dim 0 varying fastest, matching Legion's
``Rect<N>`` iteration order consumed by the mappers (cnn_mapper.cc:43-82,
nmt/rnn_mapper.cc:28-41).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Mapping, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """One operator's parallelization: partition grid + device assignment.

    Equivalent of the reference ``ParallelConfig {nDims, dim[], gpu[]}``
    (config.h:36-39).  ``devices[i]`` is the device ordinal executing grid
    point ``i`` (dim 0 fastest).
    """

    dims: Tuple[int, ...]
    devices: Tuple[int, ...]

    def __post_init__(self):
        if len(self.dims) == 0:
            raise ValueError("ParallelConfig needs at least one grid dim")
        for d in self.dims:
            if d < 1:
                raise ValueError(f"grid dims must be >= 1, got {self.dims}")
        n = math.prod(self.dims)
        if len(self.devices) != n:
            raise ValueError(
                f"devices list has {len(self.devices)} entries but grid "
                f"{self.dims} has {n} points"
            )

    @property
    def ndims(self) -> int:
        return len(self.dims)

    @property
    def num_parts(self) -> int:
        return math.prod(self.dims)

    @staticmethod
    def data_parallel(ndims: int, num_devices: int,
                      devices: Sequence[int] | None = None) -> "ParallelConfig":
        """Pure data parallelism: partition only the batch (last grid dim),
        one part per device.  The reference's default when no strategy file is
        given (cnn.cc:76-86)."""
        dims = (1,) * (ndims - 1) + (num_devices,)
        devs = tuple(devices) if devices is not None else tuple(range(num_devices))
        return ParallelConfig(dims=dims, devices=devs)

    def grid_device_array(self):
        """devices as an ndarray of shape ``dims`` (dim0 fastest / Fortran
        order), for building a ``jax.sharding.Mesh``."""
        import numpy as np

        return np.asarray(self.devices, dtype=np.int64).reshape(
            self.dims, order="F"
        )


def uneven_spatial_ok(extent: int, parts: int) -> bool:
    """May a spatial extent split ``parts`` ways UNEVENLY (XLA pads the
    short shard — the reference's restriction transform,
    conv_2d.cu:95-113)?  Requires every ceil-sized shard non-empty:
    near-extent splits would leave empty shards whose zero-byte comm edges
    underprice a plan the hardware still pads everywhere.  Shared by the
    search's candidate admission (sim/search.py) and the executor's
    partition validation (ops/base.py) so the two can never disagree."""
    return parts <= extent and (parts - 1) * -(-extent // parts) < extent


class Strategy(dict):
    """Mapping of op name -> ParallelConfig for a whole model.

    Equivalent of ``FFConfig::strategies`` (config.h:53) with the
    load/save logic of strategy.cc:22-86.  Two on-disk formats:

      * JSON (native, human-readable)
      * proto2 binary, wire-compatible with the reference's
        ``FFProtoBuf.Strategy`` (strategy.proto) so strategy files can be
        exchanged with the reference implementation.
    """

    #: optional GPipe block the drivers consume (round 4, VERDICT r3 #5):
    #: {"stages": S, "microbatches": M} — emitted by the searcher's
    #: propose_pipeline, honored by apps/lm.py (and ignored by per-op
    #: execution, which has no scheduler role).  JSON-only: the proto2
    #: wire format stays byte-compatible with the reference, which has
    #: no scheduler to describe (SURVEY §2.6 PP).
    pipeline = None

    #: optional simulator prediction carried on the artifact (obs
    #: subsystem): {"best_time_s": s, "dp_time_s": s, "devices": n, ...}
    #: written by apps/search.py so a consuming ``fit()`` can emit the
    #: ``sim_drift`` gauge (measured vs simulated step time — the
    #: calibration signal behind the round-4 transformer_2x4
    #: falsification) without rebuilding the simulator.  JSON-only, like
    #: ``pipeline``.
    predicted = None

    # ---------- JSON ----------

    def to_json(self) -> str:
        obj = {
            name: {"dims": list(pc.dims), "devices": list(pc.devices)}
            for name, pc in self.items()
        }
        if self.pipeline:
            obj["__pipeline__"] = {
                "stages": int(self.pipeline["stages"]),
                "microbatches": int(self.pipeline["microbatches"]),
                "tp": int(self.pipeline.get("tp", 1))}
        if self.predicted:
            obj["__predicted__"] = dict(self.predicted)
        return json.dumps(obj, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Strategy":
        obj = json.loads(text)
        s = cls()
        pp = obj.pop("__pipeline__", None)
        if pp:
            s.pipeline = {"stages": int(pp["stages"]),
                          "microbatches": int(pp["microbatches"]),
                          "tp": int(pp.get("tp", 1))}
        pred = obj.pop("__predicted__", None)
        if pred:
            s.predicted = dict(pred)
        for name, d in obj.items():
            s[name] = ParallelConfig(tuple(d["dims"]), tuple(d["devices"]))
        return s

    # ---------- proto2 wire format (strategy.proto parity) ----------
    #
    # message Op { required string name = 1; required int32 nDims = 2;
    #              repeated int32 dims = 3; repeated int32 devices = 4; }
    # message Strategy { repeated Op ops = 1; }
    #
    # Hand-rolled codec: the schema is 4 fields, and hand-rolling avoids a
    # protoc build step.  Serializer emits unpacked repeated ints (proto2
    # default, what the reference's protoc-generated C++ writes); the parser
    # accepts packed as well.

    def to_proto_bytes(self) -> bytes:
        out = bytearray()
        for name in sorted(self.keys()):  # std::map iteration order = sorted
            pc = self[name]
            op = bytearray()
            name_b = name.encode("utf-8")
            op += b"\x0a" + _varint(len(name_b)) + name_b          # field 1
            op += b"\x10" + _varint(pc.ndims)                      # field 2
            for d in pc.dims:                                      # field 3
                op += b"\x18" + _varint(d)
            for g in pc.devices:                                   # field 4
                op += b"\x20" + _varint(g)
            out += b"\x0a" + _varint(len(op)) + op                 # ops = 1
        return bytes(out)

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Strategy":
        s = cls()
        pos = 0
        while pos < len(data):
            tag, pos = _read_varint(data, pos)
            if tag >> 3 != 1 or tag & 7 != 2:
                raise ValueError(f"unexpected tag {tag:#x} in Strategy message")
            ln, pos = _read_varint(data, pos)
            name, ndims, dims, devices = _parse_op(data[pos:pos + ln])
            pos += ln
            if ndims != len(dims):
                raise ValueError(
                    f"op {name!r}: nDims={ndims} but {len(dims)} dims entries"
                )
            s[name] = ParallelConfig(tuple(dims), tuple(devices))
        return s

    # ---------- file I/O (FFConfig::load/save_strategy_file parity) ----------

    def save(self, path: str) -> None:
        if path.endswith(".json"):
            with open(path, "w") as f:
                f.write(self.to_json())
        else:
            with open(path, "wb") as f:
                f.write(self.to_proto_bytes())

    @classmethod
    def load(cls, path: str) -> "Strategy":
        with open(path, "rb") as f:
            raw = f.read()
        stripped = raw.lstrip()
        if stripped.startswith(b"{"):
            return cls.from_json(raw.decode("utf-8"))
        return cls.from_proto_bytes(raw)


# ---------------------------------------------------------------------------
# proto2 wire helpers


def _varint(v: int) -> bytes:
    if v < 0:  # proto int32 negatives: 10-byte two's-complement varint
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:  # negative int32/int64
                result -= 1 << 64
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _parse_op(data: bytes):
    name = None
    ndims = None
    dims = []
    devices = []
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:
            ln, pos = _read_varint(data, pos)
            name = data[pos:pos + ln].decode("utf-8")
            pos += ln
        elif field == 2 and wire == 0:
            ndims, pos = _read_varint(data, pos)
        elif field in (3, 4) and wire == 0:
            v, pos = _read_varint(data, pos)
            (dims if field == 3 else devices).append(v)
        elif field in (3, 4) and wire == 2:  # packed repeated
            ln, pos = _read_varint(data, pos)
            end = pos + ln
            while pos < end:
                v, pos = _read_varint(data, pos)
                (dims if field == 3 else devices).append(v)
        else:
            raise ValueError(f"unexpected field {field} wire {wire} in Op")
    if name is None or ndims is None:
        raise ValueError("Op message missing required fields")
    return name, ndims, dims, devices


def validate_strategy(strategy: Mapping[str, ParallelConfig],
                      num_devices: int) -> None:
    """Sanity checks mirroring the reference's partition asserts
    (disjoint/complete checks, conv_2d.cu:108-109; device-range implicit in
    the mappers)."""
    for name, pc in strategy.items():
        for dev in pc.devices:
            if not 0 <= dev < num_devices:
                raise ValueError(
                    f"op {name!r}: device {dev} out of range "
                    f"[0, {num_devices})"
                )
