"""Static plan analyzer: the strategy typechecker (round 12).

PR 11's verifier lints the *compiled step*; this pass checks the *plan
itself* — a (model graph, strategy, machine) triple — without compiling
or simulating anything.  Every case the executor today degrades with a
one-shot warning (``machine.MachineModel.sharding``'s "repl"/"norm"
fallbacks, ``parallel/placement.placement_slot``'s None returns) is
promoted to a structured :class:`~flexflow_tpu.verify.findings.Finding`
(error by default; ``--allow-degraded`` keeps the old
degrade-and-continue behavior by demoting them to warnings), alongside
the hard illegalities that would otherwise surface as mid-compile
tracebacks (rank/divisibility/device-list errors) and the whole-program
OOMs no per-op check can see (:mod:`flexflow_tpu.verify.memory`).

Diagnostic codes (the README's legality rule table renders
:data:`CODE_RULES`):

===================== ======== ==========================================
code                  severity rule
===================== ======== ==========================================
parse                 error    strategy file does not parse
bad_dims              error    grid dims must be integers >= 1
grid_size             error    len(devices) != prod(dims)
rank                  error    grid rank != the op's grid rank
device_range          error    device id outside [0, num_devices)
device_dup            error    duplicate device ids in one grid
divisibility          error    partitioned tensor dim not divisible by
                               its grid (spatial h/w may split unevenly
                               per ``uneven_spatial_ok``)
degraded_replicated   error*   grid does not divide the machine; op
                               would run fully replicated
degraded_normalized   error*   device list not honored placed; would be
                               normalized onto canonical order
regrid_unreachable    error    grid does not decompose over the machine
                               prime factors — outside the regrid hop
                               vocabulary, transitions full-rematerialize
pipeline              error    __pipeline__ stage/microbatch/tp
                               divisibility (mirrors PipelinedLM)
oom                   error    predicted per-device peak HBM exceeds
                               capacity (verify/memory.py)
regrid_greedy         warning  greedy regrid decomposition fails for a
                               producer/consumer pair (the planner still
                               reaches via gather+re-split)
unknown_op            warning  strategy entry names no model op
===================== ======== ==========================================

(*) demoted to warning under ``allow_degraded``.

The same checks back three surfaces: the drivers' strategy-load
fail-fast (:func:`check_plan`), the search's pre-sim feasibility gate
(:func:`candidate_findings` — sim/search.py filters candidates before
any native-sim table is built and reports the tally in the ``plan_gate``
obs record), and the ``plan`` pass of ``python -m flexflow_tpu.apps.lint``
(PR 11's exemption-id policy: ``plan:<code>:<where>``).
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Mapping, Optional, Tuple

from flexflow_tpu.ops.base import Op
from flexflow_tpu.strategy import (ParallelConfig, Strategy,
                                   uneven_spatial_ok)
from flexflow_tpu.verify.findings import Finding

PASS = "plan"

#: code -> (default severity, one-line rule) — the README table and the
#: lint pass's rendering share this single source.
CODE_RULES: Dict[str, Tuple[str, str]] = {
    "parse": ("error", "strategy file does not parse (JSON or proto2)"),
    "bad_dims": ("error", "grid dims must be integers >= 1"),
    "grid_size": ("error", "len(devices) != prod(dims)"),
    "rank": ("error", "grid rank != the op's grid rank (AXIS_NAMES)"),
    "device_range": ("error", "device id outside [0, num_devices)"),
    "device_dup": ("error", "duplicate device ids in one grid"),
    "divisibility": ("error",
                     "partitioned tensor dim not divisible by its grid "
                     "(spatial h/w may split unevenly)"),
    "degraded_replicated": ("error",
                            "grid does not divide the machine; op would "
                            "run fully replicated (1-device speed)"),
    "degraded_normalized": ("error",
                            "device list not honored placed (duplicates "
                            "or no placed support); would be normalized "
                            "onto the canonical order"),
    "regrid_unreachable": ("error",
                           "grid does not decompose over the machine's "
                           "prime factors — outside the regrid hop "
                           "vocabulary, every transition "
                           "full-rematerializes"),
    "pipeline": ("error",
                 "__pipeline__ stage/microbatch/tp inconsistency "
                 "(mirrors PipelinedLM's divisibility contract)"),
    "oom": ("error",
            "predicted per-device peak HBM exceeds capacity"),
    "regrid_greedy": ("warning",
                      "greedy regrid decomposition fails for a "
                      "producer/consumer pair (planner reaches via "
                      "gather + re-split)"),
    "unknown_op": ("warning", "strategy entry names no model op"),
}


def _f(code: str, where: str, message: str,
       severity: Optional[str] = None) -> Finding:
    return Finding(PASS, code, severity or CODE_RULES[code][0], where,
                   message)


# ---------------------------------------------------------------------------
# raw (pre-ParallelConfig) structural checks — ParallelConfig.__post_init__
# raises on these, so a file has to be vetted BEFORE construction to
# produce a diagnostic list instead of a single traceback


def strategy_file_findings(path: str, where_prefix: Optional[str] = None
                           ) -> Tuple[List[Finding], Optional[Strategy]]:
    """Structural vetting of a strategy FILE: parse + per-entry dims/
    devices shape + ``__pipeline__`` field types.  Returns the findings
    plus a Strategy built from the well-formed entries (None when the
    file does not parse at all), so semantic checks can continue past
    individual bad entries."""
    prefix = (where_prefix if where_prefix is not None
              else os.path.basename(path) + ":")
    findings: List[Finding] = []
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return [_f("parse", prefix.rstrip(":"), f"cannot read: {e}")], None
    if not raw.lstrip().startswith(b"{"):
        # proto2 wire format: no partial recovery — parse or fail whole
        try:
            return findings, Strategy.from_proto_bytes(raw)
        except (ValueError, UnicodeDecodeError) as e:
            return [_f("parse", prefix.rstrip(":"),
                       f"proto strategy does not parse: {e}")], None
    try:
        obj = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        return [_f("parse", prefix.rstrip(":"),
                   f"JSON strategy does not parse: {e}")], None
    if not isinstance(obj, dict):
        return [_f("parse", prefix.rstrip(":"),
                   f"top level must be an object, got "
                   f"{type(obj).__name__}")], None
    s = Strategy()
    pp = obj.pop("__pipeline__", None)
    if pp is not None:
        ok = isinstance(pp, dict)
        for k in ("stages", "microbatches"):
            if ok and not (isinstance(pp.get(k), int) and pp[k] >= 1):
                findings.append(_f(
                    "pipeline", prefix + "__pipeline__",
                    f"{k!r} must be an integer >= 1, got {pp.get(k)!r}"))
                ok = False
        if ok and not (isinstance(pp.get("tp", 1), int)
                       and pp.get("tp", 1) >= 1):
            findings.append(_f(
                "pipeline", prefix + "__pipeline__",
                f"'tp' must be an integer >= 1, got {pp.get('tp')!r}"))
            ok = False
        if not isinstance(pp, dict):
            findings.append(_f("pipeline", prefix + "__pipeline__",
                               f"must be an object, got {pp!r}"))
        elif ok:
            s.pipeline = {"stages": pp["stages"],
                          "microbatches": pp["microbatches"],
                          "tp": pp.get("tp", 1)}
    pred = obj.pop("__predicted__", None)
    if pred:
        s.predicted = dict(pred)
    for name, d in obj.items():
        where = prefix + name
        if not isinstance(d, dict) or "dims" not in d or "devices" not in d:
            findings.append(_f("parse", where,
                               "entry must be {\"dims\": [...], "
                               "\"devices\": [...]}"))
            continue
        dims, devices = d["dims"], d["devices"]
        if (not isinstance(dims, list) or not dims
                or any(not isinstance(x, int) or x < 1 for x in dims)):
            findings.append(_f("bad_dims", where,
                               f"grid dims must be integers >= 1, "
                               f"got {dims!r}"))
            continue
        if (not isinstance(devices, list)
                or any(not isinstance(x, int) for x in devices)):
            findings.append(_f("grid_size", where,
                               f"devices must be a list of integers, "
                               f"got {devices!r}"))
            continue
        n = math.prod(dims)
        if len(devices) != n:
            findings.append(_f(
                "grid_size", where,
                f"devices list has {len(devices)} entries but grid "
                f"{tuple(dims)} has {n} points"))
            continue
        s[name] = ParallelConfig(tuple(dims), tuple(devices))
    return findings, s


# ---------------------------------------------------------------------------
# per-op legality — the unit the search gate reuses per candidate


def op_findings(op: Op, pc: ParallelConfig, machine, *,
                allow_degraded: bool = False,
                where_prefix: str = "") -> List[Finding]:
    """Legality findings for running ``op`` under ``pc`` on ``machine``:
    rank / device list / divisibility errors, the promoted degradation
    diagnostics, and hop-vocabulary (global mesh) reachability."""
    from flexflow_tpu.parallel.placement import placement_slot

    out: List[Finding] = []
    where = where_prefix + op.name
    n = machine.num_devices
    deg_sev = "warning" if allow_degraded else "error"
    if len(pc.dims) != len(op.AXIS_NAMES):
        out.append(_f("rank", where,
                      f"ParallelConfig rank {pc.ndims} does not match op "
                      f"grid rank {len(op.AXIS_NAMES)} "
                      f"({op.AXIS_NAMES})"))
        return out  # nothing downstream is meaningful
    dev_bad = False
    bad = sorted({d for d in pc.devices if d < 0 or d >= n})
    if bad:
        out.append(_f("device_range", where,
                      f"device ids {bad} out of range [0, {n})"))
        dev_bad = True
    if len(set(pc.devices)) != pc.num_parts:
        dups = sorted({d for d in pc.devices if pc.devices.count(d) > 1})
        out.append(_f("device_dup", where,
                      f"duplicate device ids {dups} in grid {pc.dims} "
                      f"(every grid point needs its own device)"))
        dev_bad = True
    # divisibility — Op.validate_partitioning's rule applied to the
    # CANDIDATE pc (the op keeps its own config untouched)
    sizes = dict(zip(op.AXIS_NAMES, pc.dims))
    try:
        tensors = list(zip(op.all_outputs(), op.output_specs()))
    except Exception:
        tensors = []
    for t, spec in tensors:
        if spec is None:
            continue
        for d, entry in enumerate(spec):
            if entry is None or d >= len(t.shape):
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            parts = 1
            for a in axes:
                parts *= sizes.get(a, 1)
            if parts <= 1 or t.shape[d] % parts == 0:
                continue
            if all(a in ("h", "w") for a in axes) \
                    and uneven_spatial_ok(t.shape[d], parts):
                continue  # uneven spatial split, padded by XLA
            out.append(_f(
                "divisibility", where,
                f"output dim {d} of size {t.shape[d]} not divisible by "
                f"its partition count {parts} (grid {pc.dims})"))
    if dev_bad:
        # an unusable device list already implies the "norm" degradation;
        # reporting it again would double-count one defect
        return out
    if not machine.is_canonical(pc):
        if placement_slot(op, n, pc) is None:
            if n % pc.num_parts != 0:
                out.append(_f(
                    "degraded_replicated", where,
                    f"strategy grid {pc.dims} does not divide the "
                    f"{n}-device machine; op would run fully replicated "
                    f"(1-device speed)", severity=deg_sev))
            else:
                out.append(_f(
                    "degraded_normalized", where,
                    f"devices {pc.devices} for grid {pc.dims}: op cannot "
                    f"execute placed under this grid; the device list "
                    f"would be normalized onto the canonical order "
                    f"(placement not honored — see parallel/placement.py "
                    f"placement_slot)", severity=deg_sev))
        # placed groups dispatch themselves; a degraded op replicates —
        # neither participates in global-mesh regrids, so the hop-
        # vocabulary check below applies to canonical grids only
        return out
    if pc.num_parts > 1 \
            and machine.global_assign(pc, op.AXIS_NAMES) is None:
        facs = [s for _, s in machine.global_factors()]
        out.append(_f(
            "regrid_unreachable", where,
            f"grid {pc.dims} does not decompose over the machine's "
            f"prime factors {facs}: the op leaves the global-mesh hop "
            f"vocabulary (parallel/regrid.py), so every producer/"
            f"consumer transition full-rematerializes"))
    return out


def candidate_findings(op: Op, pc: ParallelConfig, machine
                       ) -> List[Finding]:
    """The search gate's unit: error-severity legality findings for one
    candidate (degradations stay errors — the simulator must never price
    a grid the executor would silently replicate)."""
    return [f for f in op_findings(op, pc, machine, allow_degraded=False)
            if f.severity == "error"]


# ---------------------------------------------------------------------------
# pipeline block — mirrors PipelinedLM.__init__'s raises (pipeline.py)


def pipeline_findings(pp: Mapping, model, machine,
                      where_prefix: str = "") -> List[Finding]:
    out: List[Finding] = []
    where = where_prefix + "__pipeline__"
    s, m = int(pp.get("stages", 0)), int(pp.get("microbatches", 0))
    tp = int(pp.get("tp", 1))
    if s < 1 or m < 1 or tp < 1:
        out.append(_f("pipeline", where,
                      f"stages={s} microbatches={m} tp={tp}: all must "
                      f"be >= 1"))
        return out
    n = machine.num_devices
    if n % (s * tp):
        out.append(_f("pipeline", where,
                      f"{n} devices not divisible into {s} stages x "
                      f"{tp} tp"))
        return out
    dp = n // (s * tp)
    batch = getattr(getattr(model, "config", None), "batch_size", 0) or 0
    if batch:
        if batch % m:
            out.append(_f("pipeline", where,
                          f"batch {batch} not divisible by "
                          f"{m} microbatches"))
        elif (batch // m) % dp:
            out.append(_f("pipeline", where,
                          f"microbatch size {batch // m} not divisible "
                          f"by the data-parallel axis ({dp} devices)"))
    t = getattr(model, "t", None)  # TransformerConfig, when one exists
    layers = getattr(t, "num_layers", 0) or 0
    heads = getattr(t, "num_heads", 0) or 0
    d_ff = getattr(t, "d_ff", 0) or 0
    if layers and layers % s:
        out.append(_f("pipeline", where,
                      f"{layers} layers not divisible into {s} stages"))
    if heads and heads % tp:
        out.append(_f("pipeline", where,
                      f"tp={tp} must divide num_heads ({heads})"))
    if d_ff and d_ff % tp:
        out.append(_f("pipeline", where,
                      f"tp={tp} must divide d_ff ({d_ff})"))
    return out


# ---------------------------------------------------------------------------
# whole-plan analysis


def plan_findings(model, strategy=None, machine=None, *,
                  allow_degraded: bool = False,
                  check_memory: bool = True,
                  hbm_capacity: Optional[float] = None,
                  where_prefix: str = ""
                  ) -> Tuple[List[Finding], dict]:
    """Analyze the whole plan: every op's legality under its effective
    pc, producer/consumer regrid reachability, the ``__pipeline__``
    block, and the per-device HBM fit.  ``strategy`` (op name ->
    ParallelConfig, or a :class:`Strategy`) overrides the pcs the model
    was built with; None checks the built-in plan.  Returns
    ``(findings, summary)`` — summary carries per-code counts and the
    memory report for rendering."""
    from flexflow_tpu.verify.memory import device_memory_report

    machine = machine or model.machine
    findings: List[Finding] = []
    op_names = {op.name for op in model.layers}
    if strategy is not None:
        for name in strategy:
            if name not in op_names:
                findings.append(_f(
                    "unknown_op", where_prefix + name,
                    f"strategy entry {name!r} names no op of this model "
                    f"({len(op_names)} ops)"))

    def eff(op):
        if strategy is not None:
            pc = strategy.get(op.name)
            if pc is not None:
                return pc
        return op.pc

    flagged = set()
    for op in model.layers:
        fs = op_findings(op, eff(op), machine,
                         allow_degraded=allow_degraded,
                         where_prefix=where_prefix)
        if fs:
            flagged.add(op.name)
        findings.extend(fs)

    # producer/consumer reachability inside the hop vocabulary: when both
    # endpoints express as global-mesh entries plan_hops always reaches
    # (parallel/regrid.py), so the pairwise check only flags pairs the
    # GREEDY decomposition cannot serve (priced worse, never fatal);
    # endpoints OUTSIDE the vocabulary were flagged regrid_unreachable
    # above
    regrid_pairs = 0
    for op in model.layers:
        pc = eff(op)
        if op.name in flagged or len(pc.dims) != len(op.AXIS_NAMES):
            continue  # already-diagnosed ops would only add echo noise
        try:
            ispecs = op.input_specs(pc)
        except Exception:
            ispecs = None
        if ispecs is None:
            continue
        for i, t in enumerate(op.inputs):
            prod = t.producer
            if prod is None or i >= len(ispecs) or ispecs[i] is None \
                    or prod.name in flagged:
                continue
            ppc = eff(prod)
            if len(ppc.dims) != len(prod.AXIS_NAMES):
                continue
            try:
                oi = [x.tid for x in prod.all_outputs()].index(t.tid)
                ospec = prod.output_specs()[oi]
            except Exception:
                continue
            src = machine.global_entries(ppc, prod.AXIS_NAMES, ospec,
                                         rank=t.ndim)
            dst = machine.global_entries(pc, op.AXIS_NAMES, ispecs[i],
                                         rank=t.ndim)
            if src is None or dst is None:
                continue
            regrid_pairs += 1
            if src != dst and machine.regrid_steps(src, dst) is None:
                findings.append(_f(
                    "regrid_greedy",
                    where_prefix + f"{prod.name}->{op.name}",
                    f"greedy regrid {src} -> {dst} has no single-axis "
                    f"decomposition; the planner reaches it via gather "
                    f"+ re-split at extra cost"))

    pp = getattr(strategy, "pipeline", None) if strategy is not None \
        else None
    if pp:
        findings.extend(pipeline_findings(pp, model, machine,
                                          where_prefix=where_prefix))

    # a SERVING strategy (apps/search.py --serve stamps
    # __predicted__.objective == "latency", or "decode" for a
    # disaggregated decode pool) is vetted forward-only: no optimizer
    # state or gradient cotangents in the peak, activation factor 1.0,
    # and the KV cache charged per device.  Under disaggregation the
    # cache is charged to the DECODE pool only: a prefill-phase
    # strategy (serve.phase == "prefill") streams its K/V straight into
    # the handoff export and holds no ring, so its HBM peak carries
    # kv_bytes == 0.
    pred = getattr(strategy, "predicted", None) if strategy is not None \
        else None
    serving = bool(pred) and pred.get("objective") in ("latency",
                                                       "decode")
    kv_bytes = 0.0
    serve_phase = ""
    if serving:
        serve = pred.get("serve") or {}
        serve_phase = serve.get("phase") or \
            ("decode" if pred.get("objective") == "decode" else "")
        if serve_phase != "prefill":
            kv_bytes = float(serve.get("kv_cache_bytes_per_device",
                                       0.0))
            if not kv_bytes:
                from flexflow_tpu.serve.kv_cache import kv_cache_bytes

                batch = serve.get("max_batch") \
                    or getattr(getattr(model, "config", None),
                               "batch_size", 1)
                kv_bytes = float(kv_cache_bytes(model, batch,
                                                strategy=strategy))

    mem = None
    if check_memory:
        mem = device_memory_report(model, strategy, machine,
                                   hbm_capacity=hbm_capacity,
                                   forward_only=serving,
                                   kv_cache_bytes=kv_bytes)
        for dev, total in mem["over"]:
            b = mem["per_device"][dev]
            kv = b.get("kv_cache", 0.0)
            kv_part = f" + kv_cache {kv / 1e9:.2f}" if kv else ""
            findings.append(_f(
                "oom", where_prefix + f"device{dev}",
                f"predicted peak {total / 1e9:.2f} GB exceeds "
                f"{mem['capacity'] / 1e9:.2f} GB HBM (params "
                f"{b['params'] / 1e9:.2f} + opt {b['opt'] / 1e9:.2f} + "
                f"grads {b['grads'] / 1e9:.2f} + activations "
                f"{b['activations'] / 1e9:.2f} + inputs "
                f"{b['inputs'] / 1e9:.2f}{kv_part} GB)"))

    by_code: Dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    summary = {
        "ops": len(model.layers),
        "devices": machine.num_devices,
        "regrid_pairs": regrid_pairs,
        "by_code": by_code,
        "allow_degraded": allow_degraded,
    }
    if serving:
        summary["serving"] = {"forward_only": True,
                              "kv_cache_bytes_per_device": kv_bytes}
        if serve_phase:
            summary["serving"]["phase"] = serve_phase
    if mem is not None:
        peak = max((b["total"] for b in mem["per_device"].values()),
                   default=0.0)
        summary["memory"] = {"capacity": mem["capacity"],
                             "max_device_bytes": peak,
                             "over_devices": len(mem["over"])}
    return findings, summary


def format_findings(findings: List[Finding]) -> str:
    lines = []
    for f in findings:
        tag = "EXEMPT" if f.exempted else f.severity.upper()
        lines.append(f"[{tag}] {f.ident()}: {f.message}"
                     + (f" (exempt: {f.reason})" if f.exempted else ""))
    return "\n".join(lines)


def check_plan(model, strategy, machine=None, *,
               allow_degraded: bool = False,
               check_memory: bool = True,
               hbm_capacity: Optional[float] = None,
               label: str = "strategy") -> List[Finding]:
    """Driver-side fail-fast: run :func:`plan_findings` and raise
    ``SystemExit(2)`` with the full diagnostic list when any error
    remains — the strategy-load replacement for mid-compile tracebacks.
    Warnings print and continue (matching the executor's historical
    degrade-with-a-warning under ``allow_degraded``)."""
    import sys

    findings, _summary = plan_findings(
        model, strategy, machine, allow_degraded=allow_degraded,
        check_memory=check_memory, hbm_capacity=hbm_capacity)
    errors = [f for f in findings
              if f.severity == "error" and not f.exempted]
    if findings:
        print(f"plan check ({label}):\n{format_findings(findings)}",
              file=sys.stderr)
    if errors:
        print(f"plan check: {len(errors)} error(s) — refusing to run "
              f"(pass --allow-degraded to keep the old degrade-and-"
              f"continue behavior for degradation findings)",
              file=sys.stderr)
        raise SystemExit(2)
    return findings


def regrid_edge_cost(tensor_shape, src_pc: ParallelConfig,
                     dst_pc: ParallelConfig, machine,
                     itemsize: int = 4) -> float:
    """Price of resharding one boundary tensor from its producer's grid
    to its consumer's grid — the regrid planner's cost view of a
    block-stitch edge (round 19).  Uses the SAME ring formulas the
    planner prices hops with (``parallel/regrid.py`` imports
    ``_allreduce``/``_alltoall`` from ``sim/collectives``), so the
    decomposed search's ``search_stitch`` record reports boundary
    layouts in the executor's own cost terms rather than a parallel
    model that can drift.

    Equal grids cost zero; a mismatch is priced as one all-to-all of
    the full tensor over the union of the two device sets — the upper
    bound of the planner's hop chain (every element leaves its source
    shard at most once)."""
    from flexflow_tpu.sim.collectives import _alltoall

    if (tuple(src_pc.dims) == tuple(dst_pc.dims)
            and tuple(src_pc.devices) == tuple(dst_pc.devices)):
        return 0.0
    devs = tuple(sorted(set(src_pc.devices) | set(dst_pc.devices)))
    if len(devs) <= 1:
        return 0.0
    vol = float(itemsize) * float(math.prod(tensor_shape))
    return float(_alltoall(vol, devs, machine.topology))
