"""Finding records + the exemption-file policy for the verifier.

A finding's identity (``pass:code:where``) is line-number-free so
exemptions survive unrelated edits; the message carries the line.  Every
exemption MUST carry a non-empty reason string — the same policy
``tools/check_flag_forwarding.py`` applies to its CNN_ONLY table — and
an exemption that matches nothing is itself an error (stale exemptions
rot into blanket ones).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Tuple

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    pass_name: str      # "sync" | "donation" | "predicted"
    code: str           # e.g. "device_get", "non_donated", "host_callback"
    severity: str       # error | warning | info
    where: str          # stable locus, e.g. "model.py:_fit:device_get"
    message: str        # human detail (line numbers, sizes, seconds)
    exempted: bool = False
    reason: str = ""    # the exemption's reason when exempted

    def ident(self) -> str:
        return f"{self.pass_name}:{self.code}:{self.where}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def load_exemptions(path: str) -> Dict[str, str]:
    """``{ident: reason}`` from an exemption file.  Format::

        {"exemptions": [{"id": "sync:device_get:model.py:_fit",
                         "reason": "loss fetch at the log boundary"}]}

    Every entry needs a non-empty ``reason`` — a reasonless exemption is
    a config error, not a quieter finding."""
    with open(path) as f:
        data = json.load(f)
    out: Dict[str, str] = {}
    for e in data.get("exemptions", []):
        ident, reason = e.get("id", ""), str(e.get("reason", "")).strip()
        if not ident:
            raise ValueError(f"exemption without an id: {e!r}")
        if not reason:
            raise ValueError(
                f"exemption {ident!r} has no reason string — every "
                f"exemption must say WHY it is approved")
        if ident in out:
            raise ValueError(f"duplicate exemption {ident!r}")
        out[ident] = reason
    return out


def apply_exemptions(findings: List[Finding],
                     exemptions: Dict[str, str]) -> Tuple[List[Finding],
                                                          List[str]]:
    """Mark exempted findings in place; return (findings, unused_ids).
    An id ending in ``*`` prefix-matches (one exemption for a family of
    loci); unused exemptions are reported so they get pruned."""
    used = set()
    for f in findings:
        ident = f.ident()
        reason = exemptions.get(ident)
        matched = ident if reason is not None else None
        if reason is None:
            for eid, r in exemptions.items():
                if eid.endswith("*") and ident.startswith(eid[:-1]):
                    reason, matched = r, eid
                    break
        if reason is not None:
            f.exempted, f.reason = True, reason
            used.add(matched)
    unused = sorted(set(exemptions) - used)
    return findings, unused


def counts(findings: List[Finding]) -> dict:
    """Severity tally of NON-exempt findings plus the exempted count."""
    out = {"error": 0, "warning": 0, "info": 0, "exempted": 0}
    for f in findings:
        if f.exempted:
            out["exempted"] += 1
        else:
            out[f.severity] += 1
    return out
