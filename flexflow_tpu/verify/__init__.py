"""Compile-time strategy verifier (round 11): a multi-pass static
analyzer over the jaxpr and optimized HLO of a jitted train step, plus
the source of the fit hot path.

Three passes:

* **sync** (:mod:`.sync_lint`) — host round-trips: ``device_get`` /
  ``block_until_ready`` / implicit ``float()`` concretization in the
  per-step source region, host callbacks and infeed/outfeed in the
  traced jaxpr and compiled HLO.  The "zero added per-step syncs"
  invariant every robustness PR asserted in prose becomes a failing
  check.
* **donation** (:mod:`.donation_lint`) — the compiled executable's
  input-output aliasing: large non-donated buffers whose shape matches
  an output (an update that round-trips through a copy), plus a
  retrace count per step function.
* **predicted** (:mod:`.predicted`) — the grounded-accept audit in
  predicted seconds: price both the searched and the DP compiled
  programs' collectives with the calibrated two-tier ring formulas and
  require the comm saving to fund the simulated claim
  (``utils.hlo_audit.audit_consistent_time``).

Entry point: ``python -m flexflow_tpu.apps.lint`` (``make lint``), with
an exemption file where every exemption carries a reason string
(:func:`findings.load_exemptions`).
"""

from flexflow_tpu.verify.findings import (Finding, apply_exemptions,
                                          load_exemptions)

__all__ = ["Finding", "apply_exemptions", "load_exemptions"]
