"""Pass 2 — donation / recompile lint.

The compiled executable's ``input_output_alias`` header is the ground
truth of buffer donation: a large parameter that is NOT aliased but
whose shape matches an output element is an update that round-trips
through a fresh allocation every step (ROADMAP names threading donation
through the train step).  The retrace check catches the other silent
per-step cost: a step function whose jit cache grows past one entry is
recompiling (weak-type / dtype / shape wobble between calls).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import List, Optional, Tuple

from flexflow_tpu.verify.findings import Finding

_DT = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
       "u8": 1, "pred": 1, "f64": 8, "s64": 8}

_SHAPE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _nbytes(dt: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DT.get(dt, 0)


def parse_entry_shapes(hlo: str) -> Tuple[List[Tuple[str, str, str]],
                                          List[Tuple[str, str]]]:
    """(params, outputs) of the ENTRY computation: params as
    ``(name, dtype, dims)`` in argument order, outputs as
    ``(dtype, dims)`` tuple elements."""
    m = re.search(r"^ENTRY [^\n(]*\((?P<p>.*)\)\s*->\s*(?P<o>.*?)\s*\{",
                  hlo, re.M)
    if not m:
        raise ValueError("no ENTRY computation line in HLO text")
    params = []
    for pm in re.finditer(r"([\w.\-]+):\s*([a-z0-9]+)\[([0-9,]*)\]",
                          m.group("p")):
        params.append((pm.group(1), pm.group(2), pm.group(3)))
    outputs = [(sm.group(1), sm.group(2))
               for sm in _SHAPE.finditer(m.group("o"))]
    return params, outputs


def parse_donated_params(hlo: str) -> set:
    """Parameter numbers the executable aliases to outputs:
    ``input_output_alias={ {0}: (0, {}, may-alias), ... }``."""
    # entries are '{out_idx}: (param, {}, may-alias)' — one nesting level
    m = re.search(r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}", hlo)
    if not m:
        return set()
    return {int(g) for g in
            re.findall(r"\}:\s*\((\d+),", m.group(1))}


def donation_findings(hlo: str, min_bytes: int = 1 << 20,
                      label: str = "step",
                      enforce: bool = False) -> List[Finding]:
    """Flag non-donated entry parameters of at least ``min_bytes`` whose
    (dtype, dims) matches an output element not already claimed by a
    donated buffer — the updated-but-copied case.  Non-matching large
    inputs (the batch) are reported at info level only — unless
    ``enforce`` (round 13, ``make lint``): there EVERY large non-aliased
    entry param is an error, so a new un-donated buffer breaks the build
    and the few legitimate copies carry exemption ids.  Enforced
    large_input loci are keyed by SHAPE (``step:f32[2,224,224,3]``) not
    param position, so an exemption names the actual buffer it approves
    and survives parameter reordering instead of silently shifting to a
    different tensor."""
    params, outputs = parse_entry_shapes(hlo)
    donated = parse_donated_params(hlo)
    # output shape budget: donated params consume their matching output
    budget = Counter(outputs)
    for i in donated:
        if i < len(params):
            key = (params[i][1], params[i][2])
            if budget[key] > 0:
                budget[key] -= 1
    out: List[Finding] = []
    for i, (name, dt, dims) in enumerate(params):
        if i in donated:
            continue
        size = _nbytes(dt, dims)
        if size < min_bytes:
            continue
        key = (dt, dims)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            out.append(Finding(
                "donation", "non_donated", "error",
                f"{label}:param{i}",
                f"entry param {i} ({name}: {dt}[{dims}], "
                f"{size / 1e6:.1f} MB) is not donated but an output of "
                f"the same shape exists — the update copies instead of "
                f"aliasing"))
        else:
            out.append(Finding(
                "donation", "large_input",
                "error" if enforce else "info",
                f"{label}:{dt}[{dims}]" if enforce
                else f"{label}:param{i}",
                f"entry param {i} ({name}: {dt}[{dims}], "
                f"{size / 1e6:.1f} MB) is not donated (no matching "
                f"output shape — likely a batch input)"
                + (" — exempt the shape or donate it" if enforce
                   else "")))
    return out


def retrace_findings(jitted, max_traces: int = 1,
                     label: str = "step") -> List[Finding]:
    """A jit cache deeper than ``max_traces`` after warm steps means the
    step retraces per call (shape/dtype/weak-type wobble)."""
    try:
        n = jitted._cache_size()
    except Exception as e:
        return [Finding("donation", "retrace_unknown", "info",
                        f"{label}:cache",
                        f"cannot read jit cache size ({e})")]
    if n > max_traces:
        return [Finding(
            "donation", "retrace", "error", f"{label}:cache",
            f"step function holds {n} traces after warm calls "
            f"(expected <= {max_traces}) — it recompiles per step")]
    return [Finding("donation", "retrace_ok", "info", f"{label}:cache",
                    f"jit cache holds {n} trace(s)")]


def donation_summary(hlo: str) -> dict:
    """Machine-readable aliasing totals for the lint report."""
    params, _ = parse_entry_shapes(hlo)
    donated = parse_donated_params(hlo)
    total = sum(_nbytes(dt, dims) for _, dt, dims in params)
    don = sum(_nbytes(dt, dims) for i, (_, dt, dims) in enumerate(params)
              if i in donated)
    return {"params": len(params), "donated": len(donated),
            "param_bytes": total, "donated_bytes": don}


def first_nondonated(hlo: str,
                     min_bytes: int = 1 << 20) -> Optional[str]:
    """Convenience for tests: the first error-level donation finding's
    locus, or None when the program donates everything it updates."""
    for f in donation_findings(hlo, min_bytes):
        if f.severity == "error":
            return f.where
    return None
