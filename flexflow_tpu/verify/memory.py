"""Static per-device HBM-fit prediction for a (model, strategy) plan.

The searcher's per-candidate check (``sim/search.py shard_hbm_bytes``)
prices ONE op's worst shard; a plan can pass it op-by-op and still OOM
because residency is a WHOLE-PROGRAM property: every layer's saved
activations are live at the backward's start, and the optimizer state
rides along for the entire step.  This module predicts the peak
resident bytes of each device from the plan alone — no compile, no
simulator — with the same dtype conventions the executor uses
(model.py mixed-precision: params stored in ``config.param_dtype``,
float32 momentum + float32 masters in the two-level opt state).

Accounting, per device (see README "Static verification" for the
measured error bar against compiled ``memory_analysis``):

  * params       — ``Op.param_bytes()`` (float32 convention) x
                   ``param_byte_scale`` x the grid's param-shard
                   fraction, once per ``param_key`` (shared weights);
  * opt state    — float32 momentum (1x pb) plus, under mixed
                   precision, the float32 masters (another 1x pb),
                   mirroring ``FFModel.init_opt_state``;
  * grads        — one cotangent per param at storage dtype (an XLA
                   temp live through the optimizer update);
  * activations  — the high-water residual set: every op's per-device
                   output tile (``sim/search.op_geometry``) at compute
                   dtype is saved for the backward, so the sum — not
                   the max — is live when the backward starts;
  * inputs       — the batch shard each device holds;
  * donation     — the executor donates params+opt into the step
                   (model.py make_train_step); ``donated=False`` adds
                   the double-buffered updated copies back.

Shard-to-device attribution replicates :meth:`MachineModel.sharding`'s
normalization: a full-machine canonical grid puts shard ``i`` on device
``devices[i]``; sub-machine/permuted lists are charged at the same
shard fraction on EVERY device (the normalized realization replicates
over the unused devices — an upper bound that is exact for canonical
grids, which is what the error bar is pinned on).
"""

from __future__ import annotations

import math
from typing import Dict, List, Mapping, Optional

from flexflow_tpu.ops.base import Op
from flexflow_tpu.sim.cost_model import (dtype_bytes, param_byte_scale,
                                         param_shard_fraction)
from flexflow_tpu.strategy import ParallelConfig

#: multiplier on the activation residual term covering the backward's
#: transient cotangent chain and fusion workspace XLA keeps alive on top
#: of the saved forward activations.  Calibrated against compiled
#: ``memory_analysis`` peaks (tests/test_plan_memory.py pins the error
#: bar; README documents the measured numbers).
ACTIVATION_FACTOR = 2.0


def _effective_pc(op: Op, strategy: Optional[Mapping[str, ParallelConfig]]):
    """The pc this plan runs ``op`` under: the strategy's entry when one
    names the op (and matches its grid rank — rank mismatches are the
    plan checker's ``rank`` finding, not a memory question), else the
    op's own config."""
    if strategy is not None:
        pc = strategy.get(op.name)
        if pc is not None and len(pc.dims) == len(op.AXIS_NAMES):
            return pc
    return op.pc


def _per_device_out_tiles(op: Op, pc: ParallelConfig,
                          num_devices: int) -> Dict[int, int]:
    """{device: output-tile elements} for one op under ``pc``.  Falls
    back to an even split over the listed devices for op kinds the
    geometry table does not know."""
    from flexflow_tpu.sim.search import _rect_vol, op_geometry

    tiles: Dict[int, int] = {}
    try:
        pts = op_geometry(op, pc)
    except Exception:
        per = sum(t.size() for t in op.all_outputs()) / max(pc.num_parts, 1)
        for d in set(pc.devices):
            if 0 <= d < num_devices:
                tiles[d] = tiles.get(d, 0) + int(per)
        return tiles
    for dev, out_rect, _ins in pts:
        if 0 <= dev < num_devices:
            tiles[dev] = tiles.get(dev, 0) + _rect_vol(out_rect)
    return tiles


def device_memory_report(model, strategy=None, machine=None, *,
                         hbm_capacity: Optional[float] = None,
                         donated: bool = True,
                         forward_only: bool = False,
                         kv_cache_bytes: float = 0.0) -> dict:
    """Predict each device's peak resident HBM bytes for ``model`` under
    ``strategy`` (op name -> ParallelConfig overrides; None = the pcs
    the model was built with).

    ``forward_only=True`` prices the SERVING residency instead of the
    training step: no optimizer state, no gradient cotangents, and the
    activation term drops to factor 1.0 — nothing is saved for a
    backward, only the live inter-op tiles — while ``kv_cache_bytes``
    (per device, from serve/kv_cache.py) is added as its own bucket.
    Under disaggregated serving the ring cache lives on the DECODE
    pool only, so verify/plan.py passes ``kv_cache_bytes=0`` when
    vetting a prefill-phase strategy (``serve.phase == "prefill"``)
    and the decode layout's bytes for the decode pool.

    Returns ``{"per_device": {dev: {params, opt, grads, activations,
    inputs, kv_cache, total}}, "capacity": bytes, "over": [(dev, total),
    ...], "assumptions": {...}}`` — ``over`` lists devices whose
    predicted peak exceeds ``hbm_capacity`` (default: the TpuChipPerf
    capacity).
    """
    from flexflow_tpu.sim.cost_model import TpuChipPerf

    machine = machine or getattr(model, "machine", None)
    n_dev = machine.num_devices if machine is not None else 1
    config = getattr(model, "config", None)
    pscale = param_byte_scale(config)
    mixed = pscale != 1.0
    act_bytes = dtype_bytes(
        getattr(config, "compute_dtype", "float32") or "float32")
    if hbm_capacity is None:
        hbm_capacity = TpuChipPerf().hbm_capacity

    act_factor = 1.0 if forward_only else ACTIVATION_FACTOR
    zero = {"params": 0.0, "opt": 0.0, "grads": 0.0,
            "activations": 0.0, "inputs": 0.0, "kv_cache": 0.0}
    per: Dict[int, Dict[str, float]] = {d: dict(zero) for d in range(n_dev)}

    seen_param_keys = set()
    for op in getattr(model, "layers", []):
        pc = _effective_pc(op, strategy)
        # -- params / opt state / grads (once per shared param_key) ----
        pb = float(op.param_bytes())
        if pb and op.param_key not in seen_param_keys:
            seen_param_keys.add(op.param_key)
            frac = param_shard_fraction(op, pc)
            # normalized/canonical realizations alike leave every device
            # holding (a replica of) one shard-fraction of the param
            for d in range(n_dev):
                per[d]["params"] += pb * pscale * frac
                if not forward_only:
                    per[d]["opt"] += pb * frac * (2.0 if mixed else 1.0)
                    per[d]["grads"] += pb * pscale * frac
        # -- activation residual (saved for backward; forward-only keeps
        # just the live inter-op tiles) --------------------------------
        for d, elems in _per_device_out_tiles(op, pc, n_dev).items():
            per[d]["activations"] += elems * act_bytes * act_factor
    if forward_only and kv_cache_bytes:
        for d in range(n_dev):
            per[d]["kv_cache"] += float(kv_cache_bytes)
    # -- batch shards --------------------------------------------------
    for t in getattr(model, "_inputs", []):
        shard = math.ceil(t.size() / max(n_dev, 1)) * dtype_bytes(t.dtype)
        for d in range(n_dev):
            per[d]["inputs"] += shard

    over: List[tuple] = []
    for d in sorted(per):
        b = per[d]
        b["total"] = sum(b.values())
        if not donated:
            # un-donated step: the updated params+opt are fresh outputs
            # living alongside their inputs
            b["total"] += b["params"] + b["opt"]
        if b["total"] > hbm_capacity:
            over.append((d, b["total"]))
    return {
        "per_device": per,
        "capacity": float(hbm_capacity),
        "over": over,
        "assumptions": {
            "param_dtype": getattr(model.config, "param_dtype",
                                   "float32"),
            "param_byte_scale": pscale,
            "activation_dtype_bytes": act_bytes,
            "activation_factor": act_factor,
            "donated": donated,
            "opt_levels": 0 if forward_only else (2 if mixed else 1),
            "forward_only": forward_only,
            "kv_cache_bytes_per_device": float(kv_cache_bytes),
        },
    }


def format_over_report(report: dict) -> str:
    """Human rendering of the over-budget devices with their breakdown —
    what the drivers print before refusing an OOM plan."""
    lines = []
    cap = report["capacity"]
    for dev, total in report["over"]:
        b = report["per_device"][dev]
        kv = b.get("kv_cache", 0.0)
        kv_part = f" + kv_cache {kv / 1e9:.2f}" if kv else ""
        lines.append(
            f"device {dev}: predicted peak {total / 1e9:.2f} GB exceeds "
            f"{cap / 1e9:.2f} GB HBM (params {b['params'] / 1e9:.2f} + "
            f"opt {b['opt'] / 1e9:.2f} + grads {b['grads'] / 1e9:.2f} + "
            f"activations {b['activations'] / 1e9:.2f} + inputs "
            f"{b['inputs'] / 1e9:.2f}{kv_part} GB)")
    return "\n".join(lines)
