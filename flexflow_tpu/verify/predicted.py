"""Pass 3 — predicted-time grounded accept as a lint pass.

Runs the compiled-HLO audit (searched strategy vs pure DP) in-process on
the virtual mesh and judges the strategy's own claim (its
``__predicted__`` block, or an explicit ``--claimed-speedup``) with
``audit_consistent_time`` — predicted seconds from the calibrated
two-tier ring formulas, not byte counts.  A strategy that carries no
claim gets the no-win rule (the plan may not pay more predicted comm
time than DP) at warning level: there is no simulated number to
contradict, only a smell.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from flexflow_tpu.verify.findings import Finding


def predicted_findings(model_name: str, devices: int, ici_group: int,
                       strategy_path: str,
                       batch_size: Optional[int] = None,
                       seed: int = 3, dtype: str = "float32",
                       dcn_calibration: str = "",
                       overrides: Optional[dict] = None,
                       claimed_speedup: Optional[float] = None,
                       ) -> Tuple[List[Finding], dict]:
    """(findings, audit_summary) of the predicted-time pass."""
    from flexflow_tpu.machine import Topology
    from flexflow_tpu.strategy import Strategy
    from flexflow_tpu.utils.hlo_audit import (audit_consistent_time,
                                              audit_in_process)

    claim_src = "flag"
    if claimed_speedup is None:
        pred = getattr(Strategy.load(strategy_path), "predicted",
                       None) or {}
        claimed_speedup = pred.get("speedup_vs_dp")
        claim_src = "__predicted__" if claimed_speedup else "none"
    topo = (Topology.from_calibration(dcn_calibration,
                                      devices_per_ici_group=ici_group)
            if dcn_calibration
            else Topology(devices_per_ici_group=ici_group))
    audit = audit_in_process(model_name, devices, ici_group,
                             strategy_path, batch_size, seed, dtype,
                             dcn_calibration=dcn_calibration,
                             overrides=overrides)
    verdict = audit_consistent_time(audit, claimed_speedup or 1.0, topo)
    summary = {
        "claimed_speedup": claimed_speedup, "claim_source": claim_src,
        "searched_pred_s": verdict.get("searched_pred_s"),
        "dp_pred_s": verdict.get("dp_pred_s"),
        "searched_cross_mb": round(audit["searched_cross_bytes"] / 1e6, 3),
        "dp_cross_mb": round(audit["dp_cross_bytes"] / 1e6, 3),
        "mode": verdict["mode"], "consistent": verdict["consistent"],
    }
    findings: List[Finding] = []
    where = f"{model_name}:{strategy_path}"
    if verdict["consistent"]:
        findings.append(Finding(
            "predicted", "consistent", "info", where,
            f"predicted comm {verdict.get('searched_pred_s')} s vs DP "
            f"{verdict.get('dp_pred_s')} s supports the "
            f"{'claimed %.2fx' % claimed_speedup if claimed_speedup else 'no-win'}"
            f" plan ({verdict['mode']} mode)"))
    else:
        findings.append(Finding(
            "predicted", "inconsistent",
            "error" if claimed_speedup else "warning", where,
            f"compiled program's predicted comm "
            f"({verdict.get('searched_pred_s')} s) contradicts "
            f"{'the claimed %.2fx win over' % claimed_speedup if claimed_speedup else 'parity with'}"
            f" DP ({verdict.get('dp_pred_s')} s, {verdict['mode']} mode)"))
    return findings, summary
