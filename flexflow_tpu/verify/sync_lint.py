"""Pass 1 — sync-freedom / host-transfer lint.

Three views of the same invariant ("the per-step hot path makes zero
host round-trips"), because each catches what the others cannot:

* the **jaxpr** of the traced step sees host callbacks staged into the
  program (``debug_callback`` / ``pure_callback`` / ``io_callback``)
  before XLA rewrites them;
* the **compiled HLO** sees what actually lowered: callback
  custom-calls, ``infeed``/``outfeed``, host-transfer send/recv;
* the **source AST** of the fit hot path sees Python-side syncs the
  trace never contains (``device_get``, ``block_until_ready``,
  ``.item()``, implicit ``float()`` concretization of device values) —
  flagged unless the statement carries an approved boundary marker
  ``# sync-ok: <reason>`` on its own lines or the line above.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence

from flexflow_tpu.verify.findings import Finding

# jaxpr primitives that stage a host round-trip into the step
JAXPR_HOST_PRIMS = ("debug_callback", "pure_callback", "io_callback",
                    "infeed", "outfeed")

# HLO custom-call targets that are python/host callbacks
_HLO_CALLBACK = re.compile(
    r'custom_call_target="([^"]*(?:callback|host)[^"]*)"', re.I)

# Python calls that synchronize with the device unconditionally
_ALWAYS_SYNC = ("device_get", "block_until_ready", "item")

# float()/int()/bool() only syncs when its argument is a device value;
# config/shape conversions are host-side and must not be flagged
_DEVICE_VALUE = re.compile(r"loss|grad|param|logit|metric|sig\b")

_MARKER = re.compile(r"#\s*sync-ok\s*:?\s*(.*)")


def jaxpr_sync_findings(jaxpr, label: str = "step") -> List[Finding]:
    """Walk a (Closed)Jaxpr recursively for host-round-trip primitives."""
    out: List[Finding] = []
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr

    def walk(j):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if any(p in name for p in JAXPR_HOST_PRIMS):
                out.append(Finding(
                    "sync", "jaxpr_host_prim", "error",
                    f"{label}:jaxpr:{name}",
                    f"traced step stages host primitive {name!r} — a "
                    f"per-step host round-trip"))
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        walk(inner)
                    elif hasattr(sub, "eqns"):
                        walk(sub)

    walk(jaxpr)
    return out


def hlo_sync_findings(hlo: str, label: str = "step") -> List[Finding]:
    """Scan compiled HLO text for host transfers the program would pay
    every step."""
    out: List[Finding] = []
    for m in _HLO_CALLBACK.finditer(hlo):
        out.append(Finding(
            "sync", "hlo_callback", "error",
            f"{label}:hlo:{m.group(1)}",
            f"compiled program calls host callback {m.group(1)!r}"))
    for op in ("infeed", "outfeed"):
        for _ in re.finditer(rf"(?<=[\s(]){op}\(", hlo):
            out.append(Finding(
                "sync", "hlo_" + op, "error", f"{label}:hlo:{op}",
                f"compiled program contains {op} — a host transfer in "
                f"the step"))
    for m in re.finditer(r"(?<=[\s(])(send|recv)\([^\n]*"
                         r"is_host_transfer=true", hlo):
        out.append(Finding(
            "sync", "hlo_host_transfer", "error",
            f"{label}:hlo:{m.group(1)}",
            f"compiled program {m.group(1)}s to the host every step"))
    return out


def _marked_ok(lines: Sequence[str], lineno: int,
               end_lineno: int) -> Optional[str]:
    """The ``# sync-ok: reason`` marker on any physical line of the
    enclosing statement or in the contiguous comment block above it;
    returns the reason, '' when the marker has none (itself a finding),
    None when unmarked."""
    hi = min(end_lineno, len(lines))
    for i in range(max(lineno - 1, 0), hi):   # the statement's own lines
        m = _MARKER.search(lines[i])
        if m:
            return m.group(1).strip()
    i = lineno - 2                            # comment block above
    while i >= 0 and lines[i].strip().startswith("#"):
        m = _MARKER.search(lines[i])
        if m:
            return m.group(1).strip()
        i -= 1
    return None


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _touches_device_value(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name and _DEVICE_VALUE.search(name):
            return True
    return False


def source_sync_findings(source: str, filename: str = "model.py",
                         funcs: Sequence[str] = ("fit", "_fit"),
                         ) -> List[Finding]:
    """AST pass over the per-step region: flag Python-side sync calls in
    the named functions unless bracketed by ``# sync-ok: reason``."""
    tree = ast.parse(source, filename=filename)
    lines = source.splitlines()
    out: List[Finding] = []

    def scan(fn: ast.FunctionDef):
        stmts = [n for n in ast.walk(fn) if isinstance(n, ast.stmt)]

        def enclosing(call):
            """Innermost statement containing the call — its span (plus
            the comment block above it) is where the marker may live."""
            best = None
            ce = call.end_lineno or call.lineno
            for st in stmts:
                se = st.end_lineno or st.lineno
                if st.lineno <= call.lineno and se >= ce:
                    if best is None or se - st.lineno <= \
                            (best.end_lineno or best.lineno) - best.lineno:
                        best = st
            return best or call

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            syncs = False
            if name in _ALWAYS_SYNC:
                syncs = True
            elif name in ("float", "int", "bool") and node.args \
                    and _touches_device_value(node.args[0]):
                syncs = True
            if not syncs:
                continue
            stmt = enclosing(node)
            reason = _marked_ok(lines, stmt.lineno,
                                stmt.end_lineno or stmt.lineno)
            where = f"{filename}:{fn.name}:{name}"
            if reason is None:
                out.append(Finding(
                    "sync", name, "error", where,
                    f"{filename}:{node.lineno}: per-step region calls "
                    f"{name}() with no '# sync-ok: reason' marker — a "
                    f"Python-side device sync"))
            elif not reason:
                out.append(Finding(
                    "sync", name, "error", where,
                    f"{filename}:{node.lineno}: '# sync-ok' marker has "
                    f"no reason — every approved sync must say why"))
            else:
                out.append(Finding(
                    "sync", name, "info", where,
                    f"{filename}:{node.lineno}: approved sync ({reason})",
                    exempted=True, reason=reason))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in funcs:
            scan(node)
    return out
