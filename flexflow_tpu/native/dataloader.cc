// Native data loader: threaded JPEG decode -> nearest-neighbor resize ->
// ImageNet normalization, with an asynchronous batch pipeline.
//
// TPU-native equivalent of the reference's CPU-side loader tasks
// (/root/reference/model.cu:97-211: load_images_task jpeg decode +
// nearest_neighbor resize; apply_normalize kernel (u8/256 - mean)/std), with
// the Legion "loader CPU processors" replaced by an in-process thread pool
// and the zero-copy staging memory replaced by caller-provided host buffers
// that Python hands straight to jax.device_put.
//
// Differences from the reference (deliberate):
//   * output layout is NHWC float32 (TPU conv layout), not NCHW;
//   * grayscale JPEGs are promoted to RGB via libjpeg out_color_space
//     instead of being skipped;
//   * decode errors leave the slot zero-filled with label preserved instead
//     of aborting the run.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr float kMean[3] = {0.485f, 0.456f, 0.406f};
constexpr float kStd[3] = {0.229f, 0.224f, 0.225f};

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode one JPEG file into normalized float NHWC at (height, width).
// Returns 0 on success; on failure `out` is zero-filled.
int decode_one(const char* path, int height, int width, float* out) {
  std::memset(out, 0, sizeof(float) * 3 * height * width);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;

  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  std::vector<unsigned char> rgb;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return -2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // promotes grayscale; CMYK will fail out
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return -3;
  }
  const int ow = cinfo.output_width, oh = cinfo.output_height;
  const int row_stride = ow * 3;
  rgb.resize(static_cast<size_t>(oh) * row_stride);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* rowp = rgb.data() +
        static_cast<size_t>(cinfo.output_scanline) * row_stride;
    jpeg_read_scanlines(&cinfo, &rowp, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);

  // Nearest-neighbor resize (reference index rule: round(y*scale), clamped
  // — model.cu:74-90) fused with (u8/256 - mean)/std into NHWC floats.
  const float hs = static_cast<float>(oh) / height;
  const float ws = static_cast<float>(ow) / width;
  for (int y = 0; y < height; y++) {
    int y0 = static_cast<int>(y * hs + 0.5f);
    if (y0 > oh - 1) y0 = oh - 1;
    const unsigned char* row = rgb.data() + static_cast<size_t>(y0) * row_stride;
    float* orow = out + static_cast<size_t>(y) * width * 3;
    for (int x = 0; x < width; x++) {
      int x0 = static_cast<int>(x * ws + 0.5f);
      if (x0 > ow - 1) x0 = ow - 1;
      const unsigned char* px = row + x0 * 3;
      for (int c = 0; c < 3; c++) {
        orow[x * 3 + c] = (px[c] / 256.0f - kMean[c]) / kStd[c];
      }
    }
  }
  return 0;
}

struct Batch {
  std::vector<std::string> files;
  std::vector<int> labels;
  std::vector<float> img;     // n * h * w * 3
  std::atomic<int> remaining{0};
};

struct Loader {
  int height, width;
  std::mutex mu;
  std::condition_variable cv_work;   // workers wait for work
  std::condition_variable cv_done;   // consumer waits for front batch
  std::deque<std::shared_ptr<Batch>> fifo;          // submit order
  std::deque<std::pair<std::shared_ptr<Batch>, int>> work;  // (batch, idx)
  std::vector<std::thread> workers;
  bool stop = false;

  explicit Loader(int h, int w, int nthreads) : height(h), width(w) {
    for (int i = 0; i < nthreads; i++) {
      workers.emplace_back([this] { run(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  void run() {
    for (;;) {
      std::pair<std::shared_ptr<Batch>, int> item;
      {
        std::unique_lock<std::mutex> g(mu);
        cv_work.wait(g, [this] { return stop || !work.empty(); });
        if (stop && work.empty()) return;
        item = work.front();
        work.pop_front();
      }
      Batch& b = *item.first;
      const int i = item.second;
      decode_one(b.files[i].c_str(), height, width,
                 b.img.data() + static_cast<size_t>(i) * height * width * 3);
      if (b.remaining.fetch_sub(1) == 1) {
        // take mu so the notify can't slip between the consumer's predicate
        // check and its wait (lost-wakeup)
        std::lock_guard<std::mutex> g(mu);
        cv_done.notify_all();
      }
    }
  }

  void submit(const char** files, const int32_t* labels, int n) {
    auto b = std::make_shared<Batch>();
    b->files.reserve(n);
    b->labels.assign(labels, labels + n);
    for (int i = 0; i < n; i++) b->files.emplace_back(files[i]);
    b->img.resize(static_cast<size_t>(n) * height * width * 3);
    b->remaining.store(n);
    {
      std::lock_guard<std::mutex> g(mu);
      fifo.push_back(b);
      for (int i = 0; i < n; i++) work.emplace_back(b, i);
    }
    cv_work.notify_all();
  }

  // Blocks until the oldest submitted batch is fully decoded; copies it out.
  int next(float* img, int32_t* lbl) {
    std::shared_ptr<Batch> b;
    {
      std::unique_lock<std::mutex> g(mu);
      if (fifo.empty()) return -1;
      b = fifo.front();
      fifo.pop_front();
    }
    {
      std::unique_lock<std::mutex> g(mu);
      cv_done.wait(g, [&b] { return b->remaining.load() == 0; });
    }
    std::memcpy(img, b->img.data(), b->img.size() * sizeof(float));
    std::memcpy(lbl, b->labels.data(), b->labels.size() * sizeof(int32_t));
    return static_cast<int>(b->labels.size());
  }
};

}  // namespace

extern "C" {

void* ffdata_create(int height, int width, int nthreads) {
  if (height <= 0 || width <= 0 || nthreads <= 0) return nullptr;
  return new Loader(height, width, nthreads);
}

void ffdata_destroy(void* h) { delete static_cast<Loader*>(h); }

void ffdata_submit(void* h, const char** files, const int32_t* labels,
                   int n) {
  static_cast<Loader*>(h)->submit(files, labels, n);
}

int ffdata_next(void* h, float* img, int32_t* lbl) {
  return static_cast<Loader*>(h)->next(img, lbl);
}

// Synchronous single-image decode (tests / fallback path).
int ffdata_decode(const char* path, int height, int width, float* out) {
  return decode_one(path, height, width, out);
}

}  // extern "C"
