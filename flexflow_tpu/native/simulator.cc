// Task-graph execution simulator + Metropolis MCMC strategy search.
//
// Native core of the strategy-search subsystem (the role of the reference's
// scripts/simulator.cc, re-designed): Python precomputes, for every op and
// every candidate ParallelConfig, the per-shard compute cost and the shard
// rectangles (output tile + input footprint per grid point, each pinned to a
// device).  This C++ library owns the hot loop: rectangle-intersection
// derived communication, two-tier (ICI/DCN) transfer costing, greedy
// list-scheduling by per-device ready time, parameter-sync costing, and the
// MCMC search over per-op config assignments.
//
// Per-proposal cost is ~O(affected ops), not O(whole graph):
//   * edge plans — the rectangle-intersection derived dependency/transfer
//     list of every (consumer, input, src_cfg, dst_cfg) pair — are computed
//     once per pair and memoized for the lifetime of the handle (shared by
//     all chains under a read/write lock);
//   * DeltaState caches an accepted assignment's full schedule (per-point
//     finish times, per-device free times before each op, per-op sync and
//     makespan contributions) and re-propagates a single-op proposal
//     forward from the changed op only, skipping ops whose producers and
//     devices are untouched and early-exiting once no dirty producer has a
//     consumer ahead and the device-free vector re-converges;
//   * the reclaimed budget funds N independent Metropolis chains on
//     std::thread (ffsim_mcmc_chains / ffsim_mcmc_chains_run) with
//     deterministic, barrier-synchronized best-state exchange.
// Delta results are bit-identical to full simulate() by construction
// (skipped ops reuse cached values, recomputed ops see bitwise-identical
// inputs, and the sync term is re-summed in full-path order); a cross-check
// mode (ffsim_set_crosscheck) verifies every delta against a full
// re-simulation and aborts on divergence.
//
// Exposed as a C ABI consumed via ctypes (flexflow_tpu/sim/native.py).
//
// Serialized input schema (two flat buffers):
//   ints:
//     n_devices, group_size,
//     n_ops,
//     per op:
//       n_inputs, producer_op_id[n_inputs] (-1 = graph input),
//       n_configs,
//       per config:
//         n_points,
//         per point:
//           device_id,
//           out_rect[8]   (lo0,hi0,...,lo3,hi3; hi exclusive; unused dims 0/1)
//           in_rect[8] x n_inputs
//   doubles:
//     intra_bw, cross_bw, latency,          (bytes/sec, sec)
//     per op: param_bytes,
//     per op, per config: compute_cost,     (sec, fwd+bwd per step)
//     per op, per config: param_replicas,   (gradient copies to merge)
//     per op, per config: collective_cost   (sec; in-op collectives — ring
//                                            rotation, MoE all-to-all, TP
//                                            grad all-reduce; sim/collectives.py)

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <thread>
#include <vector>

namespace {

struct Rect {
  int64_t lo[4], hi[4];  // hi exclusive
  int64_t volume() const {
    int64_t v = 1;
    for (int d = 0; d < 4; d++) {
      int64_t e = hi[d] - lo[d];
      if (e <= 0) return 0;
      v *= e;
    }
    return v;
  }
};

inline int64_t intersect_volume(const Rect& a, const Rect& b) {
  int64_t v = 1;
  for (int d = 0; d < 4; d++) {
    int64_t lo = a.lo[d] > b.lo[d] ? a.lo[d] : b.lo[d];
    int64_t hi = a.hi[d] < b.hi[d] ? a.hi[d] : b.hi[d];
    if (hi <= lo) return 0;
    v *= hi - lo;
  }
  return v;
}

struct Point {
  int device;
  Rect out;
  std::vector<Rect> in;  // one footprint per op input
};

struct Config {
  std::vector<Point> points;
  double compute_cost = 0.0;
  double param_replicas = 1.0;
  double collective_cost = 0.0;
};

struct OpNode {
  std::vector<int> producers;  // per input: producer op id or -1
  std::vector<Config> configs;
  double param_bytes = 0.0;
};

// One scheduling constraint from a producer shard to a consumer shard:
// cost == 0 -> same-device dependency (producer must finish first);
// cost > 0  -> cross-device transfer, latency + bytes/bw precomputed so
// neither the full nor the delta path re-derives rectangle intersections.
// `bytes` is the transfer payload (intersection volume * 4), kept for the
// trace exporter; the hot paths read only `cost`.
struct Hop {
  int src_point, dst_point;
  double cost;
  double bytes;
};

struct Simulator {
  int n_devices = 1, group_size = 1;
  double intra_bw = 1.0, cross_bw = 1.0, latency = 0.0;
  std::vector<OpNode> ops;
  std::vector<int> last_consumer;  // per op: largest consumer op id, -1 none
  // memoized edge plans: per (op, input), one slot per (src_cfg, dst_cfg)
  // pair, filled on first use and shared by every chain.  Readers take the
  // shared lock; a miss computes the plan outside any lock (read-only op
  // data) and publishes it under the unique lock.
  std::vector<std::vector<std::vector<std::unique_ptr<std::vector<Hop>>>>>
      edges;
  mutable std::shared_mutex edge_mu;
  bool use_delta = true;    // ffsim_set_delta
  bool crosscheck = false;  // ffsim_set_crosscheck: delta vs full, abort

  double bw(int da, int db) const {
    if (da / group_size == db / group_size) return intra_bw;
    return cross_bw;
  }

  const std::vector<Hop>& edge_plan(int dst_op, int inp, int src_cfg,
                                    int dst_cfg) {
    auto& slots = edges[dst_op][inp];
    size_t idx = (size_t)src_cfg * ops[dst_op].configs.size() + dst_cfg;
    {
      std::shared_lock<std::shared_mutex> rl(edge_mu);
      if (slots[idx]) return *slots[idx];
    }
    int src_op = ops[dst_op].producers[inp];
    auto plan = std::make_unique<std::vector<Hop>>();
    const auto& sp = ops[src_op].configs[src_cfg].points;
    const auto& dp = ops[dst_op].configs[dst_cfg].points;
    for (size_t j = 0; j < dp.size(); j++) {
      const Rect& need = dp[j].in[inp];
      for (size_t i = 0; i < sp.size(); i++) {
        int64_t v = intersect_volume(sp[i].out, need);
        if (v <= 0) continue;
        if (sp[i].device == dp[j].device)
          plan->push_back({(int)i, (int)j, 0.0, 0.0});
        else
          plan->push_back({(int)i, (int)j,
                           latency + (double)v * 4.0 /
                               bw(sp[i].device, dp[j].device),
                           (double)v * 4.0});
      }
    }
    std::unique_lock<std::shared_mutex> wl(edge_mu);
    if (!slots[idx]) slots[idx] = std::move(plan);
    return *slots[idx];
  }

  // Schedule one op: producer-driven ready times via the memoized edge
  // plans, then greedy list scheduling by per-device free time.  Returns
  // the op's max finish.  `finish_of(src)` yields a producer's finish
  // array, `cfg_of(src)` its config index — callbacks so the delta path
  // can splice in recomputed/proposed values.
  template <class FinishOf, class CfgOf>
  double run_op(int o, int ci, FinishOf&& finish_of, CfgOf&& cfg_of,
                std::vector<double>& dev_free, std::vector<double>& ready,
                std::vector<double>& out_finish) {
    const Config& cfg = ops[o].configs[ci];
    size_t np = cfg.points.size();
    ready.assign(np, 0.0);
    for (size_t inp = 0; inp < ops[o].producers.size(); inp++) {
      int src = ops[o].producers[inp];
      if (src < 0) continue;
      const std::vector<double>& sf = finish_of(src);
      for (const Hop& h : edge_plan(o, (int)inp, cfg_of(src), ci)) {
        double t = sf[h.src_point] + h.cost;
        if (t > ready[h.dst_point]) ready[h.dst_point] = t;
      }
    }
    // per-shard compute + in-op collective time, serialized per device
    double per_point = cfg.compute_cost + cfg.collective_cost;
    out_finish.resize(np);
    double op_max = 0.0;
    for (size_t j = 0; j < np; j++) {
      int d = cfg.points[j].device;
      double start = ready[j] > dev_free[d] ? ready[j] : dev_free[d];
      double end = start + per_point;
      dev_free[d] = end;
      out_finish[j] = end;
      if (end > op_max) op_max = end;
    }
    return op_max;
  }

  // Parameter synchronization of ONE op: merging gradient replicas,
  // two-tier (reference update() models, scripts-equivalent semantics).
  double sync_of(int o, int ci) const {
    if (ops[o].param_bytes <= 0.0) return 0.0;
    const Config& cfg = ops[o].configs[ci];
    double r = cfg.param_replicas;
    if (r <= 1.0) return 0.0;
    // devices of this config grouped by node
    std::vector<char> dev_seen(n_devices, 0);
    std::vector<char> grp_seen(n_devices / group_size + 1, 0);
    int ndev = 0, ngrp = 0;
    for (const Point& p : cfg.points) {
      if (!dev_seen[p.device]) { dev_seen[p.device] = 1; ndev++; }
      int g = p.device / group_size;
      if (!grp_seen[g]) { grp_seen[g] = 1; ngrp++; }
    }
    double shard_bytes = ops[o].param_bytes / ((double)cfg.points.size() / r);
    int intra_cnt = ndev > ngrp ? ndev - ngrp : 0;
    double sync = 0.0;
    sync += intra_cnt > 0 ? shard_bytes * intra_cnt / ((double)intra_cnt + 1)
                                * 2.0 / intra_bw : 0.0;
    sync += ngrp > 1 ? shard_bytes * 2.0 * (ngrp - 1) / ngrp / cross_bw : 0.0;
    return sync;
  }

  // Makespan + sync of one training step under `assign` (config index per
  // op).  Ops arrive in topological order (graph is built front-to-back).
  double simulate(const std::vector<int>& assign) {
    size_t n = ops.size();
    std::vector<std::vector<double>> finish(n);
    std::vector<double> dev_free(n_devices, 0.0), ready;
    double makespan = 0.0;
    for (size_t o = 0; o < n; o++) {
      double m = run_op(
          (int)o, assign[o],
          [&](int s) -> const std::vector<double>& { return finish[s]; },
          [&](int s) { return assign[s]; }, dev_free, ready, finish[o]);
      if (m > makespan) makespan = m;
    }
    double sync = 0.0;
    for (size_t o = 0; o < n; o++) sync += sync_of((int)o, assign[o]);
    return makespan + sync;
  }

  // One exported timeline record (ffsim_simulate_trace).  Flat doubles so
  // the ctypes consumer reshapes to (n, TRACE_STRIDE) without a struct
  // mirror.  kind 0 = compute interval of one grid point; kind 1 = a
  // cross-device transfer (hop with cost > 0); kind 2 = the op's
  // parameter-sync term (laid after the makespan — it overlaps all
  // devices, so it gets no device lane).
  static constexpr int TRACE_STRIDE = 8;
  enum { TRACE_COMPUTE = 0, TRACE_XFER = 1, TRACE_SYNC = 2 };

  // Full simulation of `assign` that exports the schedule: same greedy
  // list-scheduling arithmetic as simulate()/run_op (kept separate so the
  // MCMC hot path stays untouched), but every scheduled interval is
  // emitted.  Writes at most `cap` records into `out` (records beyond the
  // capacity are counted, not written — callers probe with cap = 0, then
  // allocate); returns the total record count and stores makespan + sync
  // in *total_s.  Record layout per TRACE_STRIDE doubles:
  //   [0] kind  [1] op id  [2] point (compute) / src device (xfer) / -1
  //   [3] device (compute) / dst device (xfer) / -1
  //   [4] start sec  [5] duration sec  [6] payload bytes (xfer only)
  //   [7] the op's config index under `assign`
  int64_t simulate_trace(const std::vector<int>& assign, double* out,
                         int64_t cap, double* total_s) {
    size_t n = ops.size();
    std::vector<std::vector<double>> finish(n);
    std::vector<double> dev_free(n_devices, 0.0);
    double makespan = 0.0;
    int64_t cnt = 0;
    auto emit = [&](double kind, double op, double a, double b,
                    double start, double dur, double bytes, double cfg) {
      if (cnt < cap) {
        double* r = out + cnt * TRACE_STRIDE;
        r[0] = kind; r[1] = op; r[2] = a; r[3] = b;
        r[4] = start; r[5] = dur; r[6] = bytes; r[7] = cfg;
      }
      cnt++;
    };
    for (size_t o = 0; o < n; o++) {
      int ci = assign[o];
      const Config& cfg = ops[o].configs[ci];
      size_t np = cfg.points.size();
      std::vector<double> ready(np, 0.0);
      for (size_t inp = 0; inp < ops[o].producers.size(); inp++) {
        int src = ops[o].producers[inp];
        if (src < 0) continue;
        const std::vector<double>& sf = finish[src];
        const auto& sp = ops[src].configs[assign[src]].points;
        for (const Hop& h : edge_plan((int)o, (int)inp, assign[src], ci)) {
          double t = sf[h.src_point] + h.cost;
          if (t > ready[h.dst_point]) ready[h.dst_point] = t;
          if (h.cost > 0.0)  // the transfer occupies [src finish, +cost)
            emit(TRACE_XFER, (double)o, (double)sp[h.src_point].device,
                 (double)cfg.points[h.dst_point].device, sf[h.src_point],
                 h.cost, h.bytes, (double)ci);
        }
      }
      double per_point = cfg.compute_cost + cfg.collective_cost;
      finish[o].resize(np);
      for (size_t j = 0; j < np; j++) {
        int d = cfg.points[j].device;
        double start = ready[j] > dev_free[d] ? ready[j] : dev_free[d];
        double end = start + per_point;
        dev_free[d] = end;
        finish[o][j] = end;
        if (end > makespan) makespan = end;
        emit(TRACE_COMPUTE, (double)o, (double)j, (double)d, start,
             per_point, 0.0, (double)ci);
      }
    }
    double sync = 0.0, at = makespan;
    for (size_t o = 0; o < n; o++) {
      double s = sync_of((int)o, assign[o]);
      if (s > 0.0) {  // serialized after the makespan, full-path order
        emit(TRACE_SYNC, (double)o, -1.0, -1.0, at, s, 0.0,
             (double)assign[o]);
        at += s;
      }
      sync += s;
    }
    if (total_s) *total_s = makespan + sync;
    return cnt;
  }
};

// Cached schedule of one accepted assignment, supporting O(affected ops)
// re-simulation of single-op proposals (the SysML'19 delta simulation
// algorithm, re-derived for list scheduling).  Kept: per-(op, point)
// finish times, the device-free vector observed just before each op was
// scheduled, and per-op sync/makespan contributions.  propose() walks
// forward from the changed op; an op is recomputed only when a producer's
// finish times changed or the free time of one of its devices differs
// from the cached schedule, and the walk stops once no changed op has a
// consumer ahead and the device-free vector re-converges.  All arithmetic
// matches the full path bit-for-bit: skipped ops reuse cached values,
// recomputed ops see bitwise-identical inputs, and the sync term is
// re-summed in full-path order (incremental +/- updates would drift by
// ulps and could flip borderline Metropolis decisions).
struct DeltaState {
  std::vector<int> assign;
  std::vector<std::vector<double>> finish;   // per (op, point)
  std::vector<std::vector<double>> before;   // [n+1] dev-free before op o
  std::vector<double> op_sync, op_max;       // per-op contributions
  std::vector<double> prefix_max, suffix_max;
  double makespan = 0.0;
  bool valid = false;
  int64_t delta_evals = 0, full_evals = 0;
  // pending proposal (propose fills, commit applies)
  int p_op = -1, p_cfg = -1, p_exit = -1;
  double p_makespan = 0.0, p_sync = 0.0, p_total = 0.0;
  std::vector<std::vector<double>> s_finish, s_before;
  std::vector<double> s_opmax, s_devfree, s_ready;
  std::vector<char> s_recomputed, s_dirty;

  // Full simulation that also (re)builds the cached schedule.  Returns
  // makespan + sync, bitwise-equal to Simulator::simulate.
  double init(Simulator* sim, const std::vector<int>& a) {
    size_t n = sim->ops.size();
    assign = a;
    finish.resize(n);
    before.assign(n + 1, std::vector<double>(sim->n_devices, 0.0));
    op_sync.resize(n);
    op_max.resize(n);
    prefix_max.resize(n + 1);
    suffix_max.resize(n + 1);
    s_finish.resize(n);
    s_before.resize(n + 1);
    s_opmax.resize(n);
    s_recomputed.resize(n);
    s_dirty.resize(n);
    std::vector<double> dev_free(sim->n_devices, 0.0);
    makespan = 0.0;
    double sync = 0.0;
    for (size_t o = 0; o < n; o++) {
      before[o] = dev_free;
      op_max[o] = sim->run_op(
          (int)o, assign[o],
          [&](int s) -> const std::vector<double>& { return finish[s]; },
          [&](int s) { return assign[s]; }, dev_free, s_ready, finish[o]);
      if (op_max[o] > makespan) makespan = op_max[o];
      op_sync[o] = sim->sync_of((int)o, assign[o]);
    }
    before[n] = dev_free;
    for (size_t o = 0; o < n; o++) sync += op_sync[o];
    rebuild_extrema();
    valid = true;
    p_op = -1;
    full_evals++;
    return makespan + sync;
  }

  void rebuild_extrema() {
    size_t n = op_max.size();
    prefix_max[0] = 0.0;
    for (size_t o = 0; o < n; o++)
      prefix_max[o + 1] = std::max(prefix_max[o], op_max[o]);
    suffix_max[n] = 0.0;
    for (size_t o = n; o-- > 0;)
      suffix_max[o] = std::max(suffix_max[o + 1], op_max[o]);
  }

  // Cost of changing op `c` to config `cfg`, leaving the cached schedule
  // untouched until commit().  NaN if the state was never initialized.
  // `th` is an optional rejection threshold (Metropolis bound): the walk
  // aborts with +inf as soon as its makespan lower bound proves the total
  // must exceed `th` — the running max only grows and the sync term is
  // summed exactly upfront, so an abort implies t > th bit-for-bit and
  // the accept/reject decision is identical to a completed evaluation.
  double propose(Simulator* sim, int c, int cfg,
                 double th = std::numeric_limits<double>::infinity()) {
    size_t n = sim->ops.size();
    if (!valid || assign.size() != n) return std::nan("");
    if (sim->crosscheck)  // verify every delta in full, no shortcuts
      th = std::numeric_limits<double>::infinity();
    delta_evals++;
    // the proposal's sync term, re-summed in full-path order so completed
    // totals stay bitwise-identical to simulate() (incremental +/- updates
    // would drift by ulps and could flip borderline Metropolis decisions)
    double new_sync = sim->sync_of(c, cfg);
    double sync = 0.0;
    for (size_t o = 0; o < n; o++)
      sync += ((int)o == c) ? new_sync : op_sync[o];
    std::fill(s_recomputed.begin(), s_recomputed.end(), 0);
    std::fill(s_dirty.begin(), s_dirty.end(), 0);
    s_devfree = before[c];
    int last_dirty = -1;  // largest consumer index of any dirty op
    double run_max = prefix_max[c];
    int exit_at = (int)n;
    auto finish_of = [&](int s) -> const std::vector<double>& {
      return s_recomputed[s] ? s_finish[s] : finish[s];
    };
    auto cfg_of = [&](int s) { return s == c ? cfg : assign[s]; };
    for (int o = c; o < (int)n; o++) {
      if (o > c && last_dirty < o && s_devfree == before[o]) {
        exit_at = o;  // downstream re-converged: suffix is the cached one
        break;
      }
      int ci = (o == c) ? cfg : assign[o];
      const Config& cc = sim->ops[o].configs[ci];
      bool need = (o == c);
      if (!need)
        for (int src : sim->ops[o].producers)
          if (src >= 0 && s_dirty[src]) { need = true; break; }
      if (!need)
        for (const Point& p : cc.points)
          if (s_devfree[p.device] != before[o][p.device]) {
            need = true;
            break;
          }
      s_before[o] = s_devfree;
      if (!need) {
        // untouched: identical to the cached run — fast-forward its
        // devices to their cached post-op free times
        for (const Point& p : cc.points)
          s_devfree[p.device] = before[o + 1][p.device];
        if (op_max[o] > run_max) run_max = op_max[o];
      } else {
        s_recomputed[o] = 1;
        s_opmax[o] = sim->run_op(o, ci, finish_of, cfg_of, s_devfree,
                                 s_ready, s_finish[o]);
        if (s_opmax[o] > run_max) run_max = s_opmax[o];
        if (o == c || s_finish[o] != finish[o]) {
          s_dirty[o] = 1;
          if (sim->last_consumer[o] > last_dirty)
            last_dirty = sim->last_consumer[o];
        }
      }
      if (run_max + sync > th) {  // rejection certain: t >= run_max + sync
        p_op = -1;                // nothing committable
        return std::numeric_limits<double>::infinity();
      }
    }
    if (exit_at == (int)n) s_before[n] = s_devfree;
    p_makespan = exit_at < (int)n ? std::max(run_max, suffix_max[exit_at])
                                  : run_max;
    p_op = c;
    p_cfg = cfg;
    p_exit = exit_at;
    p_sync = new_sync;
    p_total = p_makespan + sync;
    if (sim->crosscheck) {
      std::vector<int> a = assign;
      a[c] = cfg;
      double full = sim->simulate(a);
      if (!(std::fabs(full - p_total) <= 1e-9)) {
        std::fprintf(stderr,
                     "ffsim delta cross-check FAILED: op %d cfg %d delta "
                     "%.17g vs full %.17g (|diff| %.3g)\n",
                     c, cfg, p_total, full, std::fabs(full - p_total));
        std::abort();
      }
    }
    return p_total;
  }

  // Adopt the last proposal into the cached schedule.
  void commit(Simulator* sim) {
    if (p_op < 0 || !valid) return;
    size_t n = sim->ops.size();
    assign[p_op] = p_cfg;
    for (int o = p_op; o < p_exit; o++) {
      before[o].swap(s_before[o]);
      if (s_recomputed[o]) {
        finish[o].swap(s_finish[o]);
        op_max[o] = s_opmax[o];
      }
    }
    if (p_exit == (int)n) before[n].swap(s_before[n]);
    op_sync[p_op] = p_sync;
    makespan = p_makespan;
    rebuild_extrema();
    p_op = -1;
  }
};

struct McmcCounters {
  int64_t accepted = 0, proposed = 0, delta_evals = 0, full_evals = 0;
};

// Advance one Metropolis chain by `iters` proposals: re-randomize one op's
// config, accept better moves always and worse moves with prob
// exp(-beta * delta) (reference: scripts/simulator.cc:1444-1471).  The
// acceptance draw happens BEFORE evaluation and is folded into a cost
// threshold th = cur_t - ln(u)/beta — accept iff t < th, the same decision
// as the textbook form (exp/log are strictly monotone), which lets the
// delta path abort a walk as soon as rejection is certain.  The RNG draw
// order is identical on the delta and full paths, so a fixed seed yields
// the same accepted sequence either way (delta totals are bitwise equal
// to full ones by construction).
void mcmc_advance(Simulator* sim, std::vector<int>& cur,
                  std::vector<int>& best, double& cur_t, double& best_t,
                  int64_t iters, double beta, std::mt19937_64& rng,
                  DeltaState* st, McmcCounters& k) {
  size_t n = sim->ops.size();
  std::uniform_real_distribution<double> unif(0.0, 1.0);
  for (int64_t it = 0; it < iters; it++) {
    size_t o = rng() % n;
    size_t nc = sim->ops[o].configs.size();
    if (nc <= 1) continue;
    int old = cur[o];
    int prop = (int)(rng() % nc);
    if (prop == old) continue;
    k.proposed++;
    // u == 0 -> ln(u) = -inf -> th = +inf: accept anything, like exp > 0
    double th = cur_t - std::log(unif(rng)) / beta;
    double t;
    bool via_delta = st != nullptr && st->valid;
    if (via_delta) {
      t = st->propose(sim, (int)o, prop, th);
      k.delta_evals++;
    } else {
      cur[o] = prop;
      t = sim->simulate(cur);
      cur[o] = old;
      k.full_evals++;
    }
    if (t < th) {
      k.accepted++;
      if (via_delta) st->commit(sim);
      cur[o] = prop;
      cur_t = t;
      if (t < best_t) {
        best_t = t;
        best = cur;
      }
    }
  }
}

uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// Chain 0 uses the base seed verbatim so chains=1 reproduces the
// single-chain entry points; further chains derive via splitmix64.
uint64_t chain_seed(uint64_t base, int i) {
  return i == 0 ? base
                : splitmix64(base ^ (0x9E3779B97F4A7C15ull * (uint64_t)i));
}

struct ChainT {
  std::vector<int> cur, best;
  double cur_t = -1.0, best_t = -1.0;
  std::mt19937_64 rng;
  DeltaState st;
  McmcCounters k;
};

void write_chain_stats(const std::vector<ChainT>& chains, int64_t* stats) {
  if (!stats) return;
  for (size_t i = 0; i < chains.size(); i++) {
    stats[i * 4 + 0] += chains[i].k.accepted;
    stats[i * 4 + 1] += chains[i].k.proposed;
    stats[i * 4 + 2] += chains[i].k.delta_evals;
    stats[i * 4 + 3] += chains[i].k.full_evals;
  }
}

// One chunk of every chain, concurrently; join before returning.
void run_chains_round(Simulator* sim, std::vector<ChainT>& chains,
                      int64_t iters, double beta) {
  std::vector<std::thread> ts;
  ts.reserve(chains.size());
  for (size_t i = 0; i < chains.size(); i++)
    ts.emplace_back([sim, iters, beta, &chains, i]() {
      ChainT& ch = chains[i];
      if (sim->use_delta) {
        if (!ch.st.valid) {
          double t = ch.st.init(sim, ch.cur);
          ch.k.full_evals++;
          if (ch.cur_t < 0.0) ch.cur_t = t;
        }
      } else if (ch.cur_t < 0.0) {
        ch.cur_t = sim->simulate(ch.cur);
        ch.k.full_evals++;
      }
      if (ch.best_t < 0.0) ch.best_t = ch.cur_t;
      mcmc_advance(sim, ch.cur, ch.best, ch.cur_t, ch.best_t, iters, beta,
                   ch.rng, sim->use_delta ? &ch.st : nullptr, ch.k);
    });
  for (auto& t : ts) t.join();
}

int64_t read_i(const int64_t*& p) { return *p++; }

}  // namespace

extern "C" {

// Build a simulator from the serialized buffers. Returns opaque handle.
void* ffsim_create(const int64_t* ints, int64_t n_ints, const double* dbls,
                   int64_t n_dbls) {
  (void)n_ints;
  Simulator* sim = new Simulator();
  const int64_t* ip = ints;
  sim->n_devices = (int)read_i(ip);
  sim->group_size = (int)read_i(ip);
  if (sim->group_size <= 0) sim->group_size = sim->n_devices;
  int64_t n_ops = read_i(ip);
  sim->ops.resize(n_ops);
  const double* dp = dbls;
  sim->intra_bw = *dp++;
  sim->cross_bw = *dp++;
  sim->latency = *dp++;
  (void)n_dbls;
  for (int64_t o = 0; o < n_ops; o++) {
    OpNode& op = sim->ops[o];
    int64_t n_inputs = read_i(ip);
    op.producers.resize(n_inputs);
    for (int64_t i = 0; i < n_inputs; i++)
      op.producers[i] = (int)read_i(ip);
    int64_t n_configs = read_i(ip);
    op.configs.resize(n_configs);
    for (int64_t c = 0; c < n_configs; c++) {
      Config& cfg = op.configs[c];
      int64_t n_points = read_i(ip);
      cfg.points.resize(n_points);
      for (int64_t pt = 0; pt < n_points; pt++) {
        Point& point = cfg.points[pt];
        point.device = (int)read_i(ip);
        for (int d = 0; d < 4; d++) {
          point.out.lo[d] = read_i(ip);
          point.out.hi[d] = read_i(ip);
        }
        point.in.resize(n_inputs);
        for (int64_t i = 0; i < n_inputs; i++) {
          for (int d = 0; d < 4; d++) {
            point.in[i].lo[d] = read_i(ip);
            point.in[i].hi[d] = read_i(ip);
          }
        }
      }
    }
  }
  for (int64_t o = 0; o < n_ops; o++) sim->ops[o].param_bytes = *dp++;
  for (int64_t o = 0; o < n_ops; o++)
    for (auto& cfg : sim->ops[o].configs) cfg.compute_cost = *dp++;
  for (int64_t o = 0; o < n_ops; o++)
    for (auto& cfg : sim->ops[o].configs) cfg.param_replicas = *dp++;
  for (int64_t o = 0; o < n_ops; o++)
    for (auto& cfg : sim->ops[o].configs) cfg.collective_cost = *dp++;
  // edge-plan tables + consumer index for the delta walk's early exit
  sim->last_consumer.assign(n_ops, -1);
  sim->edges.resize(n_ops);
  for (int64_t o = 0; o < n_ops; o++) {
    OpNode& op = sim->ops[o];
    sim->edges[o].resize(op.producers.size());
    for (size_t i = 0; i < op.producers.size(); i++) {
      int src = op.producers[i];
      if (src < 0) continue;
      sim->edges[o][i].resize(sim->ops[src].configs.size() *
                              op.configs.size());
      if ((int)o > sim->last_consumer[src]) sim->last_consumer[src] = (int)o;
    }
  }
  return sim;
}

void ffsim_destroy(void* handle) { delete (Simulator*)handle; }

// Handle-level switches: delta re-simulation on/off (default on) and the
// debug cross-check (every delta verified against a full re-simulation;
// divergence > 1e-9 aborts the process).
void ffsim_set_delta(void* handle, int32_t on) {
  ((Simulator*)handle)->use_delta = on != 0;
}

void ffsim_set_crosscheck(void* handle, int32_t on) {
  ((Simulator*)handle)->crosscheck = on != 0;
}

double ffsim_simulate(void* handle, const int32_t* assign) {
  Simulator* sim = (Simulator*)handle;
  std::vector<int> a(sim->ops.size());
  for (size_t i = 0; i < a.size(); i++) a[i] = assign[i];
  return sim->simulate(a);
}

// Full simulate of `assign` that exports the per-op/per-point/per-hop
// timeline (the Perfetto trace source — obs/trace.py).  Two-call
// protocol: cap = 0 probes the record count, the second call fills
// `out` (Simulator::TRACE_STRIDE doubles per record; layout documented
// there).  `total_s` (optional) receives makespan + sync, equal to
// ffsim_simulate on the same assignment.
int64_t ffsim_simulate_trace(void* handle, const int32_t* assign,
                             double* out, int64_t cap, double* total_s) {
  Simulator* sim = (Simulator*)handle;
  std::vector<int> a(sim->ops.size());
  for (size_t i = 0; i < a.size(); i++) a[i] = assign[i];
  return sim->simulate_trace(a, out, cap, total_s);
}

// Delta-state lifecycle for callers that drive proposals themselves (the
// Python property tests; any future search variant).
void* ffsim_state_create(void* handle) {
  (void)handle;
  return new DeltaState();
}

void ffsim_state_destroy(void* state) { delete (DeltaState*)state; }

double ffsim_state_init(void* handle, void* state, const int32_t* assign) {
  Simulator* sim = (Simulator*)handle;
  std::vector<int> a(sim->ops.size());
  for (size_t i = 0; i < a.size(); i++) a[i] = assign[i];
  return ((DeltaState*)state)->init(sim, a);
}

double ffsim_state_propose(void* handle, void* state, int32_t op,
                           int32_t cfg) {
  return ((DeltaState*)state)->propose((Simulator*)handle, op, cfg);
}

void ffsim_state_commit(void* handle, void* state) {
  ((DeltaState*)state)->commit((Simulator*)handle);
}

// Metropolis MCMC (reference: scripts/simulator.cc:1444-1471): start from
// `assign`, `iters` proposals re-randomizing one op's config, accept better
// moves always and worse moves with prob exp(-beta * delta).  Writes the
// best assignment back into `assign`; returns its simulated time.
double ffsim_mcmc(void* handle, int32_t* assign, int64_t iters, double beta,
                  uint64_t seed) {
  Simulator* sim = (Simulator*)handle;
  size_t n = sim->ops.size();
  std::vector<int> cur(n), best(n);
  for (size_t i = 0; i < n; i++) cur[i] = best[i] = assign[i];
  std::mt19937_64 rng(seed);
  DeltaState st;
  double cur_t = sim->use_delta ? st.init(sim, cur) : sim->simulate(cur);
  double best_t = cur_t;
  McmcCounters k;
  mcmc_advance(sim, cur, best, cur_t, best_t, iters, beta, rng,
               sim->use_delta ? &st : nullptr, k);
  for (size_t i = 0; i < n; i++) assign[i] = best[i];
  return best_t;
}

// Chunk-resumable Metropolis MCMC with acceptance accounting (the obs
// subsystem's trajectory source).  The caller owns the chain: `cur` and
// `best` are the current and best assignments, `times[0]`/`times[1]` their
// simulated costs (pass times[0] < 0 on the first chunk to compute it).
// Runs `iters` proposals continuing that chain, writes the advanced state
// back, and adds the chunk's counts to stats[0] (accepted moves), stats[1]
// (evaluated proposals; self/singleton proposals are skipped and not
// counted), stats[2] (delta evaluations) and stats[3] (full simulations,
// including the per-chunk schedule re-anchor) — the caller's stats buffer
// must hold 4 int64.  Semantics per proposal are identical to ffsim_mcmc;
// a chunked run differs from one long call only in re-seeding per chunk.
// Returns the best cost.
double ffsim_mcmc_run(void* handle, int32_t* cur, int32_t* best,
                      double* times, int64_t iters, double beta,
                      uint64_t seed, int64_t* stats) {
  Simulator* sim = (Simulator*)handle;
  size_t n = sim->ops.size();
  std::vector<int> c(n), b(n);
  for (size_t i = 0; i < n; i++) { c[i] = cur[i]; b[i] = best[i]; }
  std::mt19937_64 rng(seed);
  DeltaState st;
  McmcCounters k;
  double cur_t;
  if (sim->use_delta) {
    double t = st.init(sim, c);
    k.full_evals++;
    cur_t = times[0] >= 0.0 ? times[0] : t;
  } else {
    cur_t = times[0] >= 0.0 ? times[0] : sim->simulate(c);
  }
  double best_t = times[1] >= 0.0 ? times[1] : cur_t;
  mcmc_advance(sim, c, b, cur_t, best_t, iters, beta, rng,
               sim->use_delta ? &st : nullptr, k);
  for (size_t i = 0; i < n; i++) { cur[i] = c[i]; best[i] = b[i]; }
  times[0] = cur_t;
  times[1] = best_t;
  stats[0] += k.accepted;
  stats[1] += k.proposed;
  stats[2] += k.delta_evals;
  stats[3] += k.full_evals;
  return best_t;
}

// N independent Metropolis chains on std::thread, all starting from
// `assign`, each with its own RNG (chain 0 = base seed, others derived by
// splitmix64) and its own delta state.  Chains run in barrier-synchronized
// rounds of `exchange_every` proposals; after each round every chain whose
// current cost is worse than the global best adopts it (ties break to the
// lowest chain id), so the result is reproducible for a fixed base seed
// regardless of thread scheduling.  Writes the global best assignment back
// into `assign`; `stats` (optional, n_chains x 4 int64) receives per-chain
// accepted/proposed/delta-eval/full-eval counts.  Returns the best cost.
double ffsim_mcmc_chains(void* handle, int32_t* assign, int64_t iters,
                         double beta, uint64_t seed, int32_t n_chains,
                         int64_t exchange_every, int64_t* stats) {
  Simulator* sim = (Simulator*)handle;
  size_t n = sim->ops.size();
  int nch = n_chains < 1 ? 1 : n_chains;
  if (iters <= 0) {
    std::vector<int> a(assign, assign + n);
    return sim->simulate(a);
  }
  if (exchange_every <= 0) exchange_every = iters;
  std::vector<ChainT> chains(nch);
  for (int i = 0; i < nch; i++) {
    chains[i].cur.assign(assign, assign + n);
    chains[i].best = chains[i].cur;
    chains[i].rng.seed(chain_seed(seed, i));
  }
  for (int64_t done = 0; done < iters; done += exchange_every) {
    int64_t step = std::min(exchange_every, iters - done);
    run_chains_round(sim, chains, step, beta);
    int gb = 0;
    for (int i = 1; i < nch; i++)
      if (chains[i].best_t < chains[gb].best_t) gb = i;
    for (int i = 0; i < nch; i++) {
      if (i == gb) continue;
      if (chains[gb].best_t < chains[i].cur_t) {
        chains[i].cur = chains[gb].best;
        chains[i].cur_t = chains[gb].best_t;
        chains[i].st.valid = false;  // re-anchored at next round start
      }
    }
  }
  int gb = 0;
  for (int i = 1; i < nch; i++)
    if (chains[i].best_t < chains[gb].best_t) gb = i;
  for (size_t i = 0; i < n; i++) assign[i] = chains[gb].best[i];
  write_chain_stats(chains, stats);
  return chains[gb].best_t;
}

// Chunk-resumable multi-chain variant (the obs subsystem's multi-chain
// trajectory source): the caller owns every chain's state — `curs` and
// `bests` are chain-major int32[n_chains * n_ops], `times` holds per-chain
// {cur_t, best_t} (pass cur_t < 0 on the first chunk) — and the per-chunk
// base seed.  Runs `iters` proposals on EACH chain concurrently (no
// internal exchange: the caller exchanges best states between chunks,
// deterministically, and emits one search_chunk record per chain per
// chunk).  `stats` (n_chains x 4 int64) accumulates per-chain counters as
// in ffsim_mcmc_run.  Returns the global best cost.
double ffsim_mcmc_chains_run(void* handle, int32_t* curs, int32_t* bests,
                             double* times, int64_t iters, double beta,
                             uint64_t seed, int32_t n_chains,
                             int64_t* stats) {
  Simulator* sim = (Simulator*)handle;
  size_t n = sim->ops.size();
  int nch = n_chains < 1 ? 1 : n_chains;
  std::vector<ChainT> chains(nch);
  for (int i = 0; i < nch; i++) {
    chains[i].cur.assign(curs + (size_t)i * n, curs + (size_t)(i + 1) * n);
    chains[i].best.assign(bests + (size_t)i * n,
                          bests + (size_t)(i + 1) * n);
    chains[i].cur_t = times[i * 2];
    chains[i].best_t = times[i * 2 + 1];
    chains[i].rng.seed(chain_seed(seed, i));
  }
  run_chains_round(sim, chains, iters, beta);
  int gb = 0;
  for (int i = 0; i < nch; i++) {
    ChainT& ch = chains[i];
    for (size_t j = 0; j < n; j++) {
      curs[(size_t)i * n + j] = ch.cur[j];
      bests[(size_t)i * n + j] = ch.best[j];
    }
    times[i * 2] = ch.cur_t;
    times[i * 2 + 1] = ch.best_t;
    if (ch.best_t < chains[gb].best_t) gb = i;
  }
  write_chain_stats(chains, stats);
  return chains[gb].best_t;
}

}  // extern "C"
